"""Collective-contract checks (rules TPL001-TPL006).

The contract every SPMD program implicitly signs: all ranks of a
communicator issue the *same* collective sequence (else the world
desyncs — the exact bug shape the runtime flight-recorder analyzer
diagnoses post-mortem), every async handle is eventually waited (else
completion is silently unordered and backpressure accounting leaks),
donated device buffers are dead after the donating call, and no
collective runs outside the ``start()``/``stop()`` window.

All checks are intraprocedural and deliberately conservative: a handle
that *escapes* (returned, stored, passed to any call) is assumed
waited by someone; only provably-dropped handles are flagged.
"""

from __future__ import annotations

import ast
from typing import Dict, List, Optional, Sequence, Set, Tuple

from .core import Finding, SourceFile, attr_chain, expr_source, walk_scope

# Names whose call (or bare variable read) makes an expression
# rank-dependent. process_count()/size() are NOT here: they evaluate the
# same on every rank.
RANK_SOURCES = {"rank", "local_ranks", "process_index"}

# The public collective surface (collectives/__init__.py) plus the eager
# entry points. Terminal attribute/name matches: `mpi.allreduce_tensor`,
# `mpi.ring.allreduce_tensor`, bare `allreduce_tensor` after a
# from-import all count.
COLLECTIVE_NAMES = {
    "broadcast_tensor", "reduce_tensor", "allreduce_tensor",
    "allgather_tensor", "allgatherv_tensor", "sendreceive_tensor",
    "reducescatter_tensor", "alltoall_tensor",
    "broadcast_scalar", "allreduce_scalar", "reduce_scalar",
    "sendreceive_scalar", "barrier",
    "run", "run_async", "run_fused", "run_allgatherv",
    "synchronize_gradients", "synchronize_parameters",
    "check_with_allreduce", "allreduce_async",
}
# `run`/`barrier` as a BARE name is too generic to claim; require an
# attribute chain for these (eager.run / mpi.barrier).
_ATTR_ONLY = {"run", "barrier"}

# Calls that produce SyncHandles: anything reached through the async_
# namespace, eager.run_async, and GradientBuckets.allreduce_async.
ASYNC_TERMINALS = {"run_async", "allreduce_async"}

_WAIT_NAMES = {"wait", "sync_all", "wait_and_unflatten"}


def _is_collective_call(node: ast.Call) -> Optional[str]:
    chain = attr_chain(node.func)
    if not chain:
        return None
    name = chain[-1]
    if name not in COLLECTIVE_NAMES:
        return None
    if len(chain) == 1 and name in _ATTR_ONLY:
        return None
    return name


def _is_async_call(node: ast.Call) -> bool:
    chain = attr_chain(node.func)
    if not chain:
        return False
    if chain[-1] in ASYNC_TERMINALS:
        return True
    # mpi.async_.allreduce_tensor / async_.ring.allreduce_tensor
    return "async_" in chain[:-1] and chain[-1] in COLLECTIVE_NAMES


def _is_rank_dependent(expr: ast.AST) -> bool:
    for node in ast.walk(expr):
        if isinstance(node, ast.Call):
            chain = attr_chain(node.func)
            if chain and chain[-1] in RANK_SOURCES:
                return True
        elif isinstance(node, ast.Name) and node.id == "rank":
            # the `rank = mpi.rank(); if rank == 0:` idiom
            return True
    return False


def _collective_sequence(body: Sequence[ast.stmt]) -> List[Tuple[str, int]]:
    """Ordered (op, line) sequence of collective calls in a statement
    list, recursing into nested control flow but not nested defs."""
    out: List[Tuple[str, int]] = []
    for stmt in body:
        for node in walk_scope(stmt):
            if isinstance(node, ast.Call):
                op = _is_collective_call(node)
                if op:
                    out.append((op, node.lineno))
    return out


def _terminates(body: Sequence[ast.stmt]) -> bool:
    """Does the block end control flow (return/raise/continue/break)?"""
    return bool(body) and isinstance(
        body[-1], (ast.Return, ast.Raise, ast.Continue, ast.Break)
    )


class _FunctionScopes(ast.NodeVisitor):
    """Collect every function body (plus the module body) as a scope."""

    def __init__(self, tree: ast.AST):
        self.scopes: List[Tuple[str, Sequence[ast.stmt]]] = [
            ("<module>", tree.body)
        ]
        self.visit(tree)

    def visit_FunctionDef(self, node):
        self.scopes.append((node.name, node.body))
        self.generic_visit(node)

    visit_AsyncFunctionDef = visit_FunctionDef


def check_rank_divergence(sf: SourceFile) -> List[Finding]:
    """TPL001/TPL002: collectives under rank-dependent control flow."""
    findings: List[Finding] = []
    for node in ast.walk(sf.tree):
        if not isinstance(node, (ast.If, ast.While)):
            continue
        if not _is_rank_dependent(node.test):
            continue
        body_seq = _collective_sequence(node.body)
        else_seq = _collective_sequence(node.orelse)
        if isinstance(node, ast.While):
            if body_seq:
                op, line = body_seq[0]
                findings.append(Finding(
                    "TPL001", sf.display, line,
                    f"collective '{op}' issued inside a while-loop whose "
                    f"condition depends on the rank "
                    f"({expr_source(node.test)}): ranks iterate different "
                    "numbers of times and desync",
                    hint="make the loop bound rank-invariant, or hoist the "
                    "collective out of the loop",
                ))
            continue
        body_ops = [op for op, _ in body_seq]
        else_ops = [op for op, _ in else_seq]
        if body_ops == else_ops:
            continue  # both arms issue the identical sequence: legal
        if body_ops and else_ops:
            op, line = (body_seq or else_seq)[0]
            findings.append(Finding(
                "TPL002", sf.display, line,
                f"rank-dependent branch ({expr_source(node.test)}) arms "
                f"issue mismatched collective sequences "
                f"{body_ops} vs {else_ops}",
                hint="all ranks must issue the same collective sequence; "
                "restructure so both arms match, or hoist the collectives "
                "out of the branch",
            ))
        else:
            seq = body_seq or else_seq
            op, line = seq[0]
            findings.append(Finding(
                "TPL001", sf.display, line,
                f"collective '{op}' issued only when "
                f"{expr_source(node.test)} — other ranks never enter this "
                "collective and the world desyncs",
                hint="issue the collective unconditionally on every rank "
                "(guard only the rank-local work, not the collective)",
            ))
    # early-exit divergence: `if rank() != 0: return` followed by
    # collectives in the enclosing block
    for fname, body in _FunctionScopes(sf.tree).scopes:
        findings.extend(_check_early_exit(sf, body))
    return findings


def _check_early_exit(sf: SourceFile, body: Sequence[ast.stmt]) -> List[Finding]:
    findings: List[Finding] = []
    for i, stmt in enumerate(body):
        if (
            isinstance(stmt, ast.If)
            and _is_rank_dependent(stmt.test)
            and _terminates(stmt.body)
            and not stmt.orelse
            and not _collective_sequence(stmt.body)
        ):
            after = _collective_sequence(body[i + 1:])
            if after:
                op, line = after[0]
                findings.append(Finding(
                    "TPL001", sf.display, line,
                    f"collective '{op}' is unreachable for ranks taking "
                    f"the early exit at line {stmt.lineno} "
                    f"({expr_source(stmt.test)})",
                    hint="every rank must reach the collective; move the "
                    "rank-guarded early exit below it",
                ))
        # recurse into nested blocks so guarded regions are checked too
        for sub in getattr(stmt, "body", []), getattr(stmt, "orelse", []):
            if sub and not isinstance(stmt, (ast.FunctionDef,
                                             ast.AsyncFunctionDef)):
                findings.extend(_check_early_exit(sf, sub))
    return findings


# ---------------------------------------------------------------------------
# TPL003: leaked SyncHandles
# ---------------------------------------------------------------------------


def _parent_map(root: ast.AST) -> Dict[int, ast.AST]:
    parents: Dict[int, ast.AST] = {}
    for node in ast.walk(root):
        for child in ast.iter_child_nodes(node):
            parents[id(child)] = node
    return parents


def _name_is_waited(name: str, scope: ast.AST, after_line: int) -> bool:
    """Does `name` escape or get waited anywhere after ``after_line``?

    Conservative: ANY use other than a bare read absolves it — returned,
    yielded, stored, subscripted, passed to a call, iterated, waited.
    Only a handle that is never touched again is a leak.
    """
    for node in walk_scope(scope):
        if isinstance(node, ast.Call):
            chain = attr_chain(node.func)
            if chain and chain[-1] in _WAIT_NAMES and not node.args \
                    and chain[:-1] != [name]:
                # a bare sync_all() drains the global handle table
                if chain[-1] == "sync_all":
                    return True
        if (
            isinstance(node, ast.Name)
            and node.id == name
            and isinstance(node.ctx, ast.Load)
            and node.lineno > after_line
        ):
            return True
    return False


def check_leaked_handles(sf: SourceFile) -> List[Finding]:
    findings: List[Finding] = []
    parents = _parent_map(sf.tree)
    for fname, body in _FunctionScopes(sf.tree).scopes:
        scope_root = ast.Module(body=list(body), type_ignores=[])
        for node in walk_scope(scope_root):
            if not (isinstance(node, ast.Call) and _is_async_call(node)):
                continue
            parent = parents.get(id(node))
            if isinstance(parent, ast.Expr):
                findings.append(Finding(
                    "TPL003", sf.display, node.lineno,
                    f"result of async collective "
                    f"'{expr_source(node.func)}' is discarded — the "
                    "SyncHandle is never waited",
                    hint="assign the handle and wait() it (or call "
                    "sync_all() before results are consumed)",
                ))
                continue
            if isinstance(parent, ast.Assign) and all(
                isinstance(t, ast.Name) for t in parent.targets
            ):
                for t in parent.targets:
                    if not _name_is_waited(t.id, scope_root, parent.lineno):
                        findings.append(Finding(
                            "TPL003", sf.display, node.lineno,
                            f"SyncHandle '{t.id}' from async collective is "
                            "never waited, returned, or stored",
                            hint=f"call {t.id}.wait() (or mpi.wait/"
                            "sync_all) before the function exits",
                        ))
    return findings


# ---------------------------------------------------------------------------
# TPL004: donated buffers read after donation
# ---------------------------------------------------------------------------


def _donated_positions(call: ast.Call) -> Optional[Tuple[int, ...]]:
    """For `jax.jit(f, donate_argnums=...)`: the donated positions."""
    chain = attr_chain(call.func)
    if not chain or chain[-1] != "jit":
        return None
    for kw in call.keywords:
        if kw.arg == "donate_argnums":
            try:
                val = ast.literal_eval(kw.value)
            except ValueError:
                return None
            if isinstance(val, int):
                return (val,)
            if isinstance(val, (tuple, list)):
                return tuple(int(v) for v in val)
    return None


def check_donated_reuse(sf: SourceFile) -> List[Finding]:
    findings: List[Finding] = []
    for fname, body in _FunctionScopes(sf.tree).scopes:
        scope_root = ast.Module(body=list(body), type_ignores=[])
        jitted: Dict[str, Tuple[int, ...]] = {}
        for node in walk_scope(scope_root):
            if isinstance(node, ast.Assign) and isinstance(
                node.value, ast.Call
            ):
                pos = _donated_positions(node.value)
                if pos is not None:
                    for t in node.targets:
                        if isinstance(t, ast.Name):
                            jitted[t.id] = pos
        if not jitted:
            continue
        parents = _parent_map(scope_root)
        for node in walk_scope(scope_root):
            if not (
                isinstance(node, ast.Call)
                and isinstance(node.func, ast.Name)
                and node.func.id in jitted
            ):
                continue
            for pos in jitted[node.func.id]:
                if pos >= len(node.args):
                    continue
                arg = node.args[pos]
                if not isinstance(arg, ast.Name):
                    continue
                parent = parents.get(id(node))
                if isinstance(parent, ast.Assign) and any(
                    isinstance(t, ast.Name) and t.id == arg.id
                    for t in parent.targets
                ):
                    continue  # `buf = fn(buf, ...)`: immediate rebind
                leak = _read_after(scope_root, arg.id, node.lineno)
                if leak is not None:
                    findings.append(Finding(
                        "TPL004", sf.display, leak,
                        f"'{arg.id}' is read at line {leak} after being "
                        f"donated to jitted '{node.func.id}' at line "
                        f"{node.lineno} — the donated buffer is dead "
                        "(XLA may have aliased its memory)",
                        hint="use the function's result instead of the "
                        "donated input, or drop donate_argnums",
                    ))
    return findings


def _read_after(scope: ast.AST, name: str, line: int) -> Optional[int]:
    """First Load of ``name`` after ``line`` with no intervening rebind."""
    events: List[Tuple[int, str]] = []
    for node in walk_scope(scope):
        if isinstance(node, ast.Name) and node.id == name:
            if node.lineno <= line:
                continue
            kind = "store" if isinstance(node.ctx, (ast.Store, ast.Del)) \
                else "load"
            events.append((node.lineno, kind))
    for ln, kind in sorted(events):
        if kind == "store":
            return None  # rebound before any read: fresh value
        return ln
    return None


# ---------------------------------------------------------------------------
# TPL006: literal routing kwarg outside schedule/
# ---------------------------------------------------------------------------

# the legacy escape hatches the schedule compiler absorbed: routing is a
# PLAN attribute now, decided by the compiler (cost model + autotuner
# overrides), not a per-call-site kwarg
_ROUTING_KWARGS = {"impl", "staged_intra", "ring_impl"}

# callees the rule applies to: the collective surface plus the
# generator-pinning wrappers that still accept routing kwargs —
# `impl=` on an unrelated library call is not our business, and the
# compiler's own pin surface (compile_collective / pinned_plan, the
# sanctioned mechanism) is not in this set
_ROUTED_CALLEES = COLLECTIVE_NAMES | {
    "run_hierarchical_allreduce",
    "run_hierarchical_collective",
    "run_tree_hierarchical_allreduce",
}


def _in_schedule_package(sf: SourceFile) -> bool:
    parts = sf.display.replace("\\", "/").split("/")
    return "schedule" in parts


def check_literal_routing(sf: SourceFile) -> List[Finding]:
    """TPL006: a call passing a literal routing kwarg (``impl='pallas'``,
    ``staged_intra='ring'``, ``ring_impl=...``) outside ``schedule/``.

    The schedule compiler owns routing: flat/hierarchical/staged/tree is
    a cost-modeled (and autotunable) plan decision, and a call site that
    pins it with a string literal silently bypasses the cost model, the
    measured ``tune_plan`` overrides, AND the plan cache keying — the
    exact escape hatch the compiler deleted. Passing a *variable*
    through (plumbing someone else's decision) is fine; hardcoding the
    schedule family at a call site is not. The generator-pinning
    wrappers delegate to the compiler's pin surface
    (``compile_collective``/``pinned_plan``), which is exempt."""
    if _in_schedule_package(sf):
        return []
    findings: List[Finding] = []
    for node in ast.walk(sf.tree):
        if not isinstance(node, ast.Call):
            continue
        chain = attr_chain(node.func)
        if not chain or chain[-1] not in _ROUTED_CALLEES:
            continue
        for kw in node.keywords:
            if kw.arg in _ROUTING_KWARGS and isinstance(
                kw.value, ast.Constant
            ):
                findings.append(Finding(
                    "TPL006", sf.display, node.lineno,
                    f"collective call passes literal routing kwarg "
                    f"{kw.arg}={kw.value.value!r} outside schedule/ — "
                    "the schedule compiler owns this decision (cost "
                    "model + tune_plan overrides), and a hardcoded "
                    "family bypasses both",
                    hint="drop the kwarg and let schedule.compile() "
                    "choose, or plumb a variable through; pin a "
                    "generator only via the run_hierarchical_* wrappers "
                    "/ compile_collective",
                ))
    return findings


# ---------------------------------------------------------------------------
# TPL005: collectives outside the start()/stop() window
# ---------------------------------------------------------------------------


def _lifecycle_aliases(tree: ast.AST) -> Set[str]:
    """Module aliases that refer to the torchmpi_tpu package."""
    aliases = set()
    for node in ast.walk(tree):
        if isinstance(node, ast.Import):
            for a in node.names:
                if a.name == "torchmpi_tpu":
                    aliases.add(a.asname or a.name)
        elif isinstance(node, ast.ImportFrom):
            if node.module == "torchmpi_tpu":
                for a in node.names:
                    if a.name in ("start", "stop"):
                        aliases.add("<bare>")
    return aliases


def _lifecycle_calls(body: Sequence[ast.stmt], aliases: Set[str], which: str
                     ) -> List[int]:
    lines = []
    for stmt in body:
        for node in walk_scope(stmt):
            if not isinstance(node, ast.Call):
                continue
            chain = attr_chain(node.func)
            if chain == [which] and "<bare>" in aliases:
                lines.append(node.lineno)
            elif (
                len(chain) == 2 and chain[1] == which and chain[0] in aliases
            ):
                lines.append(node.lineno)
    return lines


def check_lifecycle(sf: SourceFile) -> List[Finding]:
    findings: List[Finding] = []
    aliases = _lifecycle_aliases(sf.tree)
    if not aliases:
        return findings
    for fname, body in _FunctionScopes(sf.tree).scopes:
        starts = _lifecycle_calls(body, aliases, "start")
        stops = _lifecycle_calls(body, aliases, "stop")
        if not starts and not stops:
            continue
        # collectives directly in this scope (nested defs run later, at an
        # unknowable time — skip them)
        seq = []
        for stmt in body:
            for node in walk_scope(stmt):
                if isinstance(node, ast.Call):
                    op = _is_collective_call(node)
                    if op:
                        seq.append((op, node.lineno))
        for op, line in seq:
            if starts and line < min(starts):
                findings.append(Finding(
                    "TPL005", sf.display, line,
                    f"collective '{op}' invoked before start() "
                    f"(line {min(starts)})",
                    hint="move the collective after torchmpi_tpu.start()",
                ))
            elif stops and line > max(stops):
                findings.append(Finding(
                    "TPL005", sf.display, line,
                    f"collective '{op}' invoked after stop() "
                    f"(line {max(stops)})",
                    hint="move the collective before torchmpi_tpu.stop()",
                ))
    return findings


def check_file(sf: SourceFile) -> List[Finding]:
    out: List[Finding] = []
    out.extend(check_rank_divergence(sf))
    out.extend(check_leaked_handles(sf))
    out.extend(check_donated_reuse(sf))
    out.extend(check_lifecycle(sf))
    out.extend(check_literal_routing(sf))
    return out
