"""Shared analysis infrastructure: findings, rules, suppressions, baseline.

A :class:`Finding` is one diagnostic; every rule in the table below
produces them. Suppressions are source comments; the baseline is a
checked-in JSON list of accepted findings matched by (rule, file,
message) — line numbers are deliberately excluded so unrelated edits
above a baselined finding don't un-baseline it.
"""

from __future__ import annotations

import ast
import json
import re
from dataclasses import dataclass, field
from pathlib import Path
from typing import Dict, Iterable, List, Optional, Sequence, Tuple

# rule id -> (slug, one-line description, default fix hint)
RULES: Dict[str, Tuple[str, str]] = {
    "TPL001": (
        "rank-divergent-collective",
        "collective issued under rank-dependent control flow",
    ),
    "TPL002": (
        "mismatched-collective-branches",
        "rank-dependent branch arms issue different collective sequences",
    ),
    "TPL003": (
        "leaked-sync-handle",
        "async collective handle escapes scope without wait()/sync_all()",
    ),
    "TPL004": (
        "donated-buffer-reuse",
        "buffer read after being donated to a jitted function",
    ),
    "TPL005": (
        "collective-outside-lifecycle",
        "collective invoked before start() or after stop()",
    ),
    "TPL006": (
        "literal-routing-kwarg",
        "literal routing kwarg (impl=/staged_intra=/ring_impl=) outside "
        "schedule/ bypasses the schedule compiler",
    ),
    "TPL007": (
        "stale-world-cache",
        "cache keyed on world-size-derived state without a generation()/"
        "resize_epoch re-read — stale across a live resize epoch",
    ),
    "TPL101": (
        "lock-order-cycle",
        "cycle in the static lock acquisition graph",
    ),
    "TPL102": (
        "blocking-call-under-lock",
        "blocking call (join/result/wait/shutdown/sleep) while holding a lock",
    ),
    "TPL103": (
        "nested-self-acquisition",
        "non-reentrant lock re-acquired while already held",
    ),
    "TPL201": (
        "knob-unread",
        "constants knob is never read outside constants.py",
    ),
    "TPL202": (
        "knob-not-startable",
        "constants knobs are not settable via start(**kwargs)",
    ),
    "TPL203": (
        "knob-undocumented",
        "constants knob is not mentioned in README or docs/PARITY.md",
    ),
    "TPL204": (
        "metric-undocumented",
        "registered tm_* metric family is not mentioned in README or "
        "docs/PARITY.md",
    ),
    "TPL205": (
        "frame-field-undocumented",
        "PS wire-frame header field is not documented in the PARITY "
        "frame-format table",
    ),
}

_SLUG_TO_ID = {slug: rid for rid, (slug, _) in RULES.items()}


def canonical_rule(name: str) -> Optional[str]:
    """Accept either the id ('TPL001') or the slug; returns the id."""
    name = name.strip()
    if name in RULES:
        return name
    return _SLUG_TO_ID.get(name)


@dataclass
class Finding:
    rule: str  # TPLxxx
    file: str  # path as given (repo-relative when possible)
    line: int
    message: str
    hint: str = ""

    @property
    def slug(self) -> str:
        return RULES[self.rule][0]

    def key(self) -> Tuple[str, str, str]:
        """Baseline identity: line-number-free so edits above a finding
        don't churn the baseline."""
        return (self.rule, self.file.replace("\\", "/"), self.message)

    def render(self) -> str:
        hint = f"  [hint: {self.hint}]" if self.hint else ""
        return (
            f"{self.file}:{self.line}: {self.rule} ({self.slug}) "
            f"{self.message}{hint}"
        )

    def as_dict(self) -> dict:
        return {
            "rule": self.rule,
            "slug": self.slug,
            "file": self.file.replace("\\", "/"),
            "line": self.line,
            "message": self.message,
            "hint": self.hint,
        }


# ---------------------------------------------------------------------------
# suppressions: `# tpu-lint: disable=rule1,rule2` on the flagged line or the
# line directly above; `# tpu-lint: disable-file=rule1,...` anywhere in the
# file (use `all` to match every rule).
# ---------------------------------------------------------------------------

_SUPPRESS_RE = re.compile(r"#\s*tpu-lint:\s*disable=([\w\-, ]+)")
_SUPPRESS_FILE_RE = re.compile(r"#\s*tpu-lint:\s*disable-file=([\w\-, ]+)")


def _parse_rule_list(raw: str) -> set:
    out = set()
    for tok in raw.split(","):
        tok = tok.strip()
        if not tok:
            continue
        if tok == "all":
            out.update(RULES)
            continue
        rid = canonical_rule(tok)
        if rid:
            out.add(rid)
    return out


class SuppressionIndex:
    """Per-file map of line -> suppressed rule ids (plus file-wide set)."""

    def __init__(self, source: str):
        self.by_line: Dict[int, set] = {}
        self.file_wide: set = set()
        for i, text in enumerate(source.splitlines(), start=1):
            m = _SUPPRESS_RE.search(text)
            if m:
                self.by_line[i] = _parse_rule_list(m.group(1))
            m = _SUPPRESS_FILE_RE.search(text)
            if m:
                self.file_wide |= _parse_rule_list(m.group(1))

    def suppressed(self, rule: str, line: int) -> bool:
        if rule in self.file_wide:
            return True
        for ln in (line, line - 1):
            if rule in self.by_line.get(ln, ()):
                return True
        return False


# ---------------------------------------------------------------------------
# baseline
# ---------------------------------------------------------------------------


def load_baseline(path) -> set:
    """Accepted-finding keys from a baseline JSON file ([] when absent)."""
    p = Path(path)
    if not p.exists():
        return set()
    data = json.loads(p.read_text() or "[]")
    if isinstance(data, dict):
        data = data.get("findings", [])
    out = set()
    for item in data:
        out.add(
            (
                str(item.get("rule", "")),
                str(item.get("file", "")).replace("\\", "/"),
                str(item.get("message", "")),
            )
        )
    return out


def write_baseline(path, findings: Sequence[Finding]) -> None:
    payload = [
        {"rule": f.rule, "file": f.file.replace("\\", "/"),
         "message": f.message}
        for f in sorted(findings, key=lambda f: (f.file, f.rule, f.message))
    ]
    Path(path).write_text(json.dumps(payload, indent=2) + "\n")


# ---------------------------------------------------------------------------
# source loading
# ---------------------------------------------------------------------------


@dataclass(eq=False)  # identity hash: used as a dict key by the CLI
class SourceFile:
    path: Path  # resolved on disk
    display: str  # path string used in findings (relative when possible)
    source: str
    tree: ast.AST
    suppressions: SuppressionIndex = field(init=False)

    def __post_init__(self):
        self.suppressions = SuppressionIndex(self.source)


def iter_python_files(paths: Iterable) -> List[Path]:
    out: List[Path] = []
    for raw in paths:
        p = Path(raw)
        if p.is_dir():
            out.extend(sorted(q for q in p.rglob("*.py")
                              if "__pycache__" not in q.parts))
        elif p.suffix == ".py":
            out.append(p)
    # stable order, no duplicates
    seen, uniq = set(), []
    for p in out:
        r = p.resolve()
        if r not in seen:
            seen.add(r)
            uniq.append(p)
    return uniq


def load_source(path: Path, root: Optional[Path] = None) -> Optional[SourceFile]:
    """Parse one file; syntax errors yield None (reported by the CLI as a
    warning, not a crash — the linter must not die on one bad file)."""
    try:
        src = path.read_text()
        tree = ast.parse(src, filename=str(path))
    except (OSError, SyntaxError, ValueError):
        return None
    display = str(path)
    if root is not None:
        try:
            display = str(path.resolve().relative_to(root.resolve()))
        except ValueError:
            pass
    return SourceFile(path=path, display=display.replace("\\", "/"),
                      source=src, tree=tree)


def attr_chain(node: ast.AST) -> List[str]:
    """['mpi', 'async_', 'allreduce_tensor'] for mpi.async_.allreduce_tensor;
    [] when the expression is not a plain name/attribute chain."""
    parts: List[str] = []
    while isinstance(node, ast.Attribute):
        parts.append(node.attr)
        node = node.value
    if isinstance(node, ast.Name):
        parts.append(node.id)
        return parts[::-1]
    return []


def expr_source(node: ast.AST) -> str:
    try:
        return ast.unparse(node)
    except Exception:  # pragma: no cover - unparse of exotic nodes
        return "<expr>"


def walk_scope(root: ast.AST, include_root: bool = True):
    """Pre-order walk that does NOT descend into nested function/lambda
    bodies (``ast.walk`` has no pruning). Child order follows the AST
    field order, so statement lists come back in source order."""
    if include_root:
        yield root
    if isinstance(root, (ast.FunctionDef, ast.AsyncFunctionDef, ast.Lambda)):
        return  # a def IS the boundary, whether met as root or child
    for child in ast.iter_child_nodes(root):
        if isinstance(child, (ast.FunctionDef, ast.AsyncFunctionDef,
                              ast.Lambda)):
            continue
        yield from walk_scope(child)
