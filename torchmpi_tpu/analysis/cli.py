"""tpu-lint command line.

    python -m torchmpi_tpu.analysis <paths...> [options]

Exit codes (the contract CI composes with):

- ``0`` — no non-baselined, non-suppressed findings (or not --strict)
- ``1`` — findings remain under ``--strict``
- ``2`` — usage / input error (no Python files found, bad rule name)

This module is stdlib-only and never initializes an accelerator
backend; the ``-m`` entry point still imports the ``torchmpi_tpu``
parent package (Python's ``-m`` semantics), so jax must be importable.
"""

from __future__ import annotations

import argparse
import json
import sys
from pathlib import Path
from typing import List, Optional, Sequence

from . import contracts, epoch as epoch_mod, knobs as knobs_mod, locks
from .core import (
    Finding,
    RULES,
    canonical_rule,
    iter_python_files,
    load_baseline,
    load_source,
    write_baseline,
)


def run_analysis(
    paths: Sequence,
    rules: Optional[Sequence[str]] = None,
    root: Optional[Path] = None,
    doc_paths: Optional[Sequence[Path]] = None,
) -> List[Finding]:
    """Analyze files/dirs; returns suppression-filtered findings.

    ``rules``: restrict to these rule ids (default: all).
    ``root``: base for display paths and for locating README/docs
    (default: the common parent — the current directory).
    """
    root = Path(root) if root is not None else Path.cwd()
    files = iter_python_files(paths)
    sources = []
    for f in files:
        sf = load_source(f, root=root)
        if sf is None:
            print(f"tpu-lint: skipping unparseable {f}", file=sys.stderr)
            continue
        sources.append(sf)

    wanted = set(rules) if rules else set(RULES)
    findings: List[Finding] = []
    per_file = {}
    for sf in sources:
        per_file[sf] = []
        per_file[sf].extend(contracts.check_file(sf))
        per_file[sf].extend(locks.check_file(sf))
        per_file[sf].extend(epoch_mod.check_file(sf))

    if doc_paths is None:
        doc_paths = [root / "README.md", root / "docs" / "PARITY.md"]
    owner = {sf.display: sf for sf in sources}

    def _attribute(repo_findings):
        for f in repo_findings:
            sf = owner.get(f.file)
            if sf is not None:
                per_file.setdefault(sf, []).append(f)
            else:  # pragma: no cover - finding on an unscanned file
                findings.append(f)

    # repo-level knob rules: keyed off a scanned constants.py that
    # defines _Constants
    constants_sf = next(
        (sf for sf in sources
         if sf.path.name == "constants.py" and knobs_mod.knob_fields(sf)),
        None,
    )
    if constants_sf is not None:
        runtime_state_sf = next(
            (sf for sf in sources if sf.path.name == "runtime_state.py"),
            None,
        )
        _attribute(knobs_mod.check_knobs(
            constants_sf, sources, doc_paths, runtime_state_sf
        ))

    # repo-level metric documentation rule (TPL204): every registered
    # tm_* family must be in the docs table — the metrics mirror of
    # TPL203, and not gated on constants.py being in the scan set
    _attribute(knobs_mod.check_metrics_docs(sources, doc_paths))

    # repo-level wire-contract rule (TPL205): every PS frame header
    # field must be in the PARITY frame-format table
    _attribute(knobs_mod.check_frame_docs(sources, doc_paths))

    for sf, flist in per_file.items():
        for f in flist:
            if f.rule not in wanted:
                continue
            if sf.suppressions.suppressed(f.rule, f.line):
                continue
            findings.append(f)
    findings.sort(key=lambda f: (f.file, f.line, f.rule))
    return findings


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(
        prog="python -m torchmpi_tpu.analysis",
        description="tpu-lint: static collective-contract checker and "
        "lock-order analyzer",
    )
    ap.add_argument("paths", nargs="*", help="files or directories to lint")
    ap.add_argument("--strict", action="store_true",
                    help="exit 1 when non-baselined findings remain")
    ap.add_argument("--baseline", default=None, metavar="FILE",
                    help="JSON baseline of accepted findings (matched by "
                    "rule+file+message, line-free)")
    ap.add_argument("--write-baseline", action="store_true",
                    help="write the current findings to --baseline and "
                    "exit 0")
    ap.add_argument("--rules", default=None,
                    help="comma-separated rule ids/slugs to run "
                    "(default: all)")
    ap.add_argument("--json", action="store_true", dest="as_json",
                    help="machine-readable JSON output")
    ap.add_argument("--root", default=None,
                    help="repo root for display paths and README/docs "
                    "lookup (default: cwd)")
    ap.add_argument("--list-rules", action="store_true",
                    help="print the rule table and exit")
    args = ap.parse_args(argv)

    if args.list_rules:
        for rid, (slug, desc) in sorted(RULES.items()):
            print(f"{rid}  {slug:32s} {desc}")
        return 0

    rules = None
    if args.rules:
        rules = []
        for tok in args.rules.split(","):
            rid = canonical_rule(tok)
            if rid is None:
                print(f"tpu-lint: unknown rule {tok!r}", file=sys.stderr)
                return 2
            rules.append(rid)

    root = Path(args.root) if args.root else None
    # walk the tree ONCE; the expanded file list feeds run_analysis
    # directly (iter_python_files on plain files is a no-op expansion)
    files = iter_python_files(args.paths) if args.paths else []
    if not files:
        print("tpu-lint: no Python files under the given paths",
              file=sys.stderr)
        return 2

    findings = run_analysis(files, rules=rules, root=root)

    if args.write_baseline:
        path = args.baseline or "tpu_lint_baseline.json"
        write_baseline(path, findings)
        print(f"tpu-lint: wrote {len(findings)} finding(s) to {path}")
        return 0

    baselined = load_baseline(args.baseline) if args.baseline else set()
    fresh = [f for f in findings if f.key() not in baselined]

    if args.as_json:
        print(json.dumps(
            {
                "findings": [f.as_dict() for f in fresh],
                "baselined": len(findings) - len(fresh),
            },
            indent=2,
        ))
    else:
        for f in fresh:
            print(f.render())
        known = len(findings) - len(fresh)
        tail = f" ({known} baselined)" if known else ""
        print(f"tpu-lint: {len(fresh)} finding(s){tail}")
    if fresh and args.strict:
        return 1
    return 0
