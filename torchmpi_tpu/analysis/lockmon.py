"""Opt-in instrumented-lock runtime monitor (``TORCHMPI_TPU_LOCK_MONITOR=1``).

The static analyzer (:mod:`.locks`) derives the lock-order graph from
the source; this module validates that graph against *reality*: when
armed, every lock the threaded modules create through
:func:`make_lock` / :func:`make_condition` is a :class:`MonitoredLock`
that records the actual acquisition order (per thread, by lock *name*)
into a process-global order table. The first time two locks are
observed in both orders, the second acquisition **fails** with
:class:`LockOrderInversion` and the violation is recorded — sanitizer
wiring for a language TSan can't reach. Tier-1 runs once under the
monitor in CI (``scripts/ci.sh``); the conftest gate fails the session
if any inversion was recorded, even one swallowed by a worker thread.

Disarmed (the default), :func:`make_lock` returns a plain
``threading.Lock`` — zero overhead, byte-identical hot paths.

Same-name pairs are never flagged: a name covers every instance of a
lock *definition* (e.g. the per-rank mailbox locks
``server.py:_Instance.locks[]``), and instances of one definition may
legitimately interleave.
"""

from __future__ import annotations

import os
import threading
from typing import Dict, List, Optional, Tuple

__all__ = [
    "LockOrderInversion", "MonitoredLock", "make_lock", "make_condition",
    "enabled", "violations", "order_table", "reset",
]


def _env_true(name: str) -> bool:
    return os.environ.get(name, "").lower() in ("1", "true", "yes", "on")


_MONITOR = _env_true("TORCHMPI_TPU_LOCK_MONITOR")

# guards the order table + violation list (a plain lock: monitor
# internals are never themselves monitored)
_guard = threading.Lock()
# (first, second) -> "thread/site" of the first observation
_order: Dict[Tuple[str, str], str] = {}
_violations: List[dict] = []
_held = threading.local()


class LockOrderInversion(RuntimeError):
    """Two locks were acquired in both orders — a potential deadlock."""


def enabled() -> bool:
    return _MONITOR


def set_enabled(on: bool) -> None:
    """Test hook: arm/disarm for locks created AFTER this call."""
    global _MONITOR
    _MONITOR = bool(on)


def violations() -> List[dict]:
    with _guard:
        return list(_violations)


def order_table() -> Dict[Tuple[str, str], str]:
    """The observed acquired-while-held pairs (for introspection and for
    diffing against the static graph)."""
    with _guard:
        return dict(_order)


def reset() -> None:
    with _guard:
        _order.clear()
        del _violations[:]


def snapshot_state():
    """(order table, violations) — pair with :func:`restore_state` so a
    test that provokes a DELIBERATE inversion can put the global tables
    back exactly as it found them, instead of reset()-ing away any real
    violations recorded earlier in the session (which would blind the
    session-end gate)."""
    with _guard:
        return (dict(_order), [dict(v) for v in _violations])


def restore_state(state) -> None:
    order, viols = state
    with _guard:
        _order.clear()
        _order.update(order)
        del _violations[:]
        _violations.extend(viols)


def _held_stack() -> list:
    stack = getattr(_held, "stack", None)
    if stack is None:
        stack = _held.stack = []
    return stack


class MonitoredLock:
    """``threading.Lock`` wrapper recording acquisition order by name.

    Duck-types the Lock API (acquire/release/locked/context manager)
    plus ``_is_owned`` so ``threading.Condition`` can use it as its
    underlying lock (its wait() release/re-acquire flows through this
    wrapper, keeping the held-stack exact)."""

    __slots__ = ("name", "_lock", "_owner")

    def __init__(self, name: str):
        self.name = name
        self._lock = threading.Lock()
        self._owner: Optional[int] = None

    # -- Lock protocol ------------------------------------------------------
    def acquire(self, blocking: bool = True, timeout: float = -1) -> bool:
        got = self._lock.acquire(blocking, timeout)
        if not got:
            return False
        stack = _held_stack()
        bad = self._record(stack)
        if bad is not None:
            self._lock.release()
            raise LockOrderInversion(bad)
        self._owner = threading.get_ident()
        stack.append(self.name)
        return True

    def release(self) -> None:
        stack = _held_stack()
        if self.name in stack:
            # remove the most recent hold of this name
            for i in range(len(stack) - 1, -1, -1):
                if stack[i] == self.name:
                    del stack[i]
                    break
        self._owner = None
        self._lock.release()

    def locked(self) -> bool:
        return self._lock.locked()

    def __enter__(self):
        self.acquire()
        return self

    def __exit__(self, *exc) -> None:
        self.release()

    def _is_owned(self) -> bool:  # Condition support
        return self._owner == threading.get_ident()

    def __repr__(self) -> str:
        return f"MonitoredLock({self.name!r})"

    # -- order recording ----------------------------------------------------
    def _record(self, stack: list) -> Optional[str]:
        if not stack:
            return None
        me = self.name
        site = f"thread {threading.current_thread().name}"
        with _guard:
            for h in stack:
                if h == me:
                    continue  # same definition: instances may interleave
                rev = _order.get((me, h))
                if rev is not None:
                    record = {
                        "pair": (h, me),
                        "first_order": f"{me} -> {h}",
                        "first_site": rev,
                        "second_order": f"{h} -> {me}",
                        "second_site": site,
                    }
                    _violations.append(record)
                    return (
                        f"lock-order inversion: acquiring {me!r} while "
                        f"holding {h!r}, but the opposite order was "
                        f"observed earlier ({rev})"
                    )
                _order.setdefault((h, me), site)
        return None


def make_lock(name: str):
    """A plain ``threading.Lock`` — or, under the monitor, a
    :class:`MonitoredLock` keyed by ``name`` (use the static analyzer's
    naming, ``module.py:Class.attr``, so the runtime table diffs
    directly against the static graph)."""
    if _MONITOR:
        return MonitoredLock(name)
    return threading.Lock()


def make_condition(name: str) -> threading.Condition:
    """A Condition over a (possibly monitored) lock."""
    return threading.Condition(make_lock(name))
