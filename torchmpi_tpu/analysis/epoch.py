"""Resize-epoch cache-coherence lint (rule TPL007).

Live elastic resharding (``torchmpi_tpu/reshard``) can change the world
size WITHOUT restarting the process: ``engine.resize``, an elastic
membership change, or a PS chain re-formation all bump the
``resize_epoch`` constant — which advances ``constants.generation()``,
the monotone counter every world-derived cache is expected to embed in
its keys (the dispatch memos, the plan cache, the compiled-reshard
cache all do). A cache whose key bakes in a world-size-derived value
(``comm.size``, ``world``, ``process_count()``) *without* a
``generation()`` / ``resize_epoch`` component keeps serving entries
compiled for the OLD world after a resize — the silent-staleness bug
class this rule makes structural.

Heuristic (intraprocedural, deliberately conservative):

- a **cache access** is a subscript store/load or a ``.get`` /
  ``.setdefault`` / ``.pop`` call on a name matching ``cache``/``memo``
  (suffix-insensitive);
- its **key expression** (simple ``name = (...)`` assignments in the
  same scope are resolved one hop) is world-derived when it reads a
  ``.size`` attribute, a name containing ``world``, or calls
  ``size()`` / ``process_count()`` / ``num_processes()``;
- the access is CLEAN when the key also calls ``generation()`` or
  reads ``resize_epoch`` (either literally in a ``get``/``set`` string
  or as an attribute).

Passing a variable that happens to hold a world size through a
non-cache-named dict is out of scope — naming the container is the
opt-in, same as the reference's ``_cache`` suffix conventions.
"""

from __future__ import annotations

import ast
import re
from typing import Dict, List, Optional

from .core import Finding, SourceFile, attr_chain, expr_source, walk_scope

_CACHE_NAME = re.compile(r"(cache|memo)s?(\b|_|$)", re.IGNORECASE)
_WORLD_NAME = re.compile(r"world", re.IGNORECASE)
_WORLD_CALLS = {"size", "process_count", "num_processes"}
_EPOCH_NAMES = {"generation", "resize_epoch"}


def _cache_target(node: ast.AST) -> Optional[str]:
    """The cache-ish name a subscript/get call operates on, or None."""
    chain = attr_chain(node)
    if not chain:
        return None
    name = chain[-1]
    return name if _CACHE_NAME.search(name) else None


def _mentions_world(expr: ast.AST) -> bool:
    for node in ast.walk(expr):
        if isinstance(node, ast.Attribute) and node.attr == "size":
            return True
        if isinstance(node, ast.Name) and _WORLD_NAME.search(node.id):
            return True
        if isinstance(node, ast.Call):
            chain = attr_chain(node.func)
            if chain and chain[-1] in _WORLD_CALLS:
                return True
    return False


def _mentions_epoch(expr: ast.AST) -> bool:
    for node in ast.walk(expr):
        if isinstance(node, ast.Call):
            chain = attr_chain(node.func)
            if chain and chain[-1] in _EPOCH_NAMES:
                return True
            # constants.get("resize_epoch")
            if (
                chain
                and chain[-1] == "get"
                and node.args
                and isinstance(node.args[0], ast.Constant)
                and node.args[0].value == "resize_epoch"
            ):
                return True
        if isinstance(node, ast.Attribute) and node.attr in _EPOCH_NAMES:
            return True
        if isinstance(node, ast.Name) and node.id in _EPOCH_NAMES:
            return True
    return False


class _Scopes(ast.NodeVisitor):
    def __init__(self, tree: ast.AST):
        self.scopes = [list(tree.body)] if hasattr(tree, "body") else []
        self.visit(tree)

    def visit_FunctionDef(self, node):
        self.scopes.append(list(node.body))
        self.generic_visit(node)

    visit_AsyncFunctionDef = visit_FunctionDef


def check_stale_world_cache(sf: SourceFile) -> List[Finding]:
    findings: List[Finding] = []
    for body in _Scopes(sf.tree).scopes:
        scope = ast.Module(body=body, type_ignores=[])
        # one-hop key resolution: `key = (...)` then `cache.get(key)`
        assigns: Dict[str, ast.AST] = {}
        for node in walk_scope(scope):
            if isinstance(node, ast.Assign) and len(node.targets) == 1 and (
                isinstance(node.targets[0], ast.Name)
            ):
                assigns[node.targets[0].id] = node.value

        def key_expr(expr: ast.AST) -> ast.AST:
            if isinstance(expr, ast.Name) and expr.id in assigns:
                return assigns[expr.id]
            return expr

        seen = set()
        for node in walk_scope(scope):
            target = key = None
            if isinstance(node, ast.Subscript):
                target = _cache_target(node.value)
                key = key_expr(node.slice)
            elif isinstance(node, ast.Call):
                chain = attr_chain(node.func)
                if (
                    chain
                    and len(chain) >= 2
                    and chain[-1] in ("get", "setdefault", "pop")
                    and node.args
                ):
                    target = (
                        chain[-2]
                        if _CACHE_NAME.search(chain[-2]) else None
                    )
                    key = key_expr(node.args[0])
            if target is None or key is None:
                continue
            if not _mentions_world(key) or _mentions_epoch(key):
                continue
            if (target, node.lineno) in seen:
                continue
            seen.add((target, node.lineno))
            findings.append(Finding(
                "TPL007", sf.display, node.lineno,
                f"cache '{target}' is keyed on world-size-derived state "
                f"({expr_source(key)}) without a generation()/"
                "resize_epoch component — entries go stale across a "
                "live resize epoch",
                hint="append constants.generation() (or the resize_epoch "
                "knob) to the cache key so a resize invalidates it "
                "coherently",
            ))
    return findings


def check_file(sf: SourceFile) -> List[Finding]:
    return check_stale_world_cache(sf)
