"""Static lock-order analyzer (rules TPL101-TPL103).

Builds, per module, the static lock-acquisition graph: nodes are lock
*definitions* (``threading.Lock()`` / ``RLock`` / ``Condition`` or the
:mod:`.lockmon` factories, assigned to a module global, a ``self``
attribute, or a list/dict of locks), edges are "B acquired while A is
held" — from lexical ``with`` nesting plus an intraprocedural
same-module call graph (method/function calls propagate their callees'
acquisitions to the caller's held-set). A cycle in that graph is a
potential deadlock (TPL101); re-acquiring a held non-reentrant lock is
a guaranteed one (TPL103); and a blocking call — ``join``, ``result``,
``wait`` on a foreign object, ``shutdown(wait=True)``, ``sleep`` —
under any lock is a stall amplifier at best and a deadlock at worst
(TPL102).

The companion runtime monitor (:mod:`.lockmon`,
``TORCHMPI_TPU_LOCK_MONITOR=1``) records *actual* acquisition orders
during the test suite and fails on inversion, validating this static
graph against reality.

Explicit ``lock.release()`` inside a ``with`` block is honored: the
bounded-inflight pattern in ``parameterserver/server.py`` drops its
lock around a blocking drain and re-acquires — the walker tracks that.
"""

from __future__ import annotations

import ast
from typing import Dict, List, Optional, Sequence, Set, Tuple

from .core import Finding, SourceFile, attr_chain, expr_source

_LOCK_CTORS = {"Lock", "RLock", "Condition", "make_lock", "make_rlock",
               "make_condition"}
_BLOCKING_ATTRS = {"join", "result", "exception", "sleep"}
_WAITY_ATTRS = {"wait", "wait_for"}


def _creates_lock(value: ast.AST) -> Optional[str]:
    """'' for a single lock, '[]' for a collection of locks, None else."""
    if isinstance(value, ast.Call):
        chain = attr_chain(value.func)
        if chain and chain[-1] in _LOCK_CTORS:
            return ""
    if isinstance(value, (ast.List, ast.Tuple)):
        for elt in value.elts:
            if _creates_lock(elt) == "":
                return "[]"
    if isinstance(value, ast.ListComp):
        if _creates_lock(value.elt) == "":
            return "[]"
    if isinstance(value, ast.DictComp):
        if _creates_lock(value.value) == "":
            return "[]"
    return None


class _FuncInfo:
    def __init__(self, node, cls: Optional[str]):
        self.node = node
        self.cls = cls
        # lock keys this function acquires anywhere in its body (direct)
        self.direct_acquires: Set[str] = set()
        # same-module callees: (cls, name) tuples
        self.calls: Set[Tuple[Optional[str], str]] = set()


class ModuleLockGraph:
    """One module's lock definitions, acquisition edges, and findings."""

    def __init__(self, sf: SourceFile):
        self.sf = sf
        self.prefix = sf.display.rsplit("/", 1)[-1]  # e.g. transport.py
        self.module_locks: Dict[str, str] = {}  # name -> key
        self.class_locks: Dict[Tuple[str, str], str] = {}  # (cls,attr)->key
        self.funcs: Dict[Tuple[Optional[str], str], _FuncInfo] = {}
        self.classes: Set[str] = set()
        # (a, b) -> (display, line, context) of the first site where b was
        # acquired while a was held
        self.edges: Dict[Tuple[str, str], Tuple[str, int, str]] = {}
        self.findings: List[Finding] = []
        self._collect_defs()
        self._collect_funcs()
        self._transitive = self._fixpoint_acquires()
        for info in self.funcs.values():
            self._walk_function(info)

    # -- definitions --------------------------------------------------------
    def _key(self, cls: Optional[str], name: str, suffix: str) -> str:
        if cls:
            return f"{self.prefix}:{cls}.{name}{suffix}"
        return f"{self.prefix}:{name}{suffix}"

    def _collect_defs(self) -> None:
        for node in ast.walk(self.sf.tree):
            if isinstance(node, ast.ClassDef):
                self.classes.add(node.name)
        # module-level lock names
        for stmt in self.sf.tree.body:
            if isinstance(stmt, ast.Assign):
                suffix = _creates_lock(stmt.value)
                if suffix is not None:
                    for t in stmt.targets:
                        if isinstance(t, ast.Name):
                            self.module_locks[t.id] = self._key(
                                None, t.id, suffix
                            )
        # self.<attr> lock assignments anywhere inside a class
        for cls in ast.walk(self.sf.tree):
            if not isinstance(cls, ast.ClassDef):
                continue
            for node in ast.walk(cls):
                if not isinstance(node, ast.Assign):
                    continue
                suffix = _creates_lock(node.value)
                if suffix is None:
                    continue
                for t in node.targets:
                    if (
                        isinstance(t, ast.Attribute)
                        and isinstance(t.value, ast.Name)
                        and t.value.id == "self"
                    ):
                        self.class_locks[(cls.name, t.attr)] = self._key(
                            cls.name, t.attr, suffix
                        )
                    elif (
                        isinstance(t, ast.Subscript)
                        and isinstance(t.value, ast.Attribute)
                        and isinstance(t.value.value, ast.Name)
                        and t.value.value.id == "self"
                    ):
                        # self._delta_locks[key] = Lock()
                        self.class_locks[(cls.name, t.value.attr)] = (
                            self._key(cls.name, t.value.attr, "[]")
                        )

    def _collect_funcs(self) -> None:
        def visit(node, cls):
            for child in ast.iter_child_nodes(node):
                if isinstance(child, ast.ClassDef):
                    visit(child, child.name)
                elif isinstance(child, (ast.FunctionDef,
                                        ast.AsyncFunctionDef)):
                    self.funcs[(cls, child.name)] = _FuncInfo(child, cls)
                    visit(child, cls)  # nested defs keep the class context
                else:
                    visit(child, cls)

        visit(self.sf.tree, None)

    # -- lock-expression resolution ----------------------------------------
    def resolve(self, expr: ast.AST, cls: Optional[str],
                local_locks: Dict[str, str]) -> Optional[str]:
        if isinstance(expr, ast.Name):
            if expr.id in local_locks:
                return local_locks[expr.id]
            return self.module_locks.get(expr.id)
        if isinstance(expr, ast.Attribute):
            if isinstance(expr.value, ast.Name) and expr.value.id == "self":
                if cls and (cls, expr.attr) in self.class_locks:
                    return self.class_locks[(cls, expr.attr)]
            return None
        if isinstance(expr, ast.Subscript):
            base = self.resolve(expr.value, cls, local_locks)
            if base is not None and not base.endswith("[]"):
                return None
            if base is None and isinstance(expr.value, ast.Attribute):
                return None
            return base
        if isinstance(expr, ast.Call):
            # a with-item calling a lock-returning helper, e.g.
            # `with self._delta_lock_for(key):` — a distinct stable node
            chain = attr_chain(expr.func)
            if chain and "lock" in chain[-1].lower():
                owner = cls if chain[0] == "self" else None
                return self._key(owner, chain[-1] + "()", "")
        return None

    # -- call graph ---------------------------------------------------------
    def _callee(self, call: ast.Call, cls: Optional[str]
                ) -> Optional[Tuple[Optional[str], str]]:
        chain = attr_chain(call.func)
        if not chain:
            return None
        if len(chain) == 1:
            name = chain[0]
            if (None, name) in self.funcs:
                return (None, name)
            if name in self.classes and (name, "__init__") in self.funcs:
                return (name, "__init__")
            return None
        if chain[0] == "self" and len(chain) == 2 and cls:
            if (cls, chain[1]) in self.funcs:
                return (cls, chain[1])
        if chain[0] in self.classes and len(chain) == 2:
            if (chain[0], chain[1]) in self.funcs:
                return (chain[0], chain[1])
        return None

    def _fixpoint_acquires(self) -> Dict[Tuple[Optional[str], str], Set[str]]:
        # first pass: record direct acquisitions + callee lists
        for info in self.funcs.values():
            self._scan_direct(info)
        acquires = {k: set(i.direct_acquires) for k, i in self.funcs.items()}
        changed = True
        while changed:
            changed = False
            for k, info in self.funcs.items():
                for callee in info.calls:
                    extra = acquires.get(callee, set()) - acquires[k]
                    if extra:
                        acquires[k] |= extra
                        changed = True
        return acquires

    def _scan_direct(self, info: _FuncInfo) -> None:
        local_locks: Dict[str, str] = {}
        for node in ast.walk(info.node):
            if isinstance(node, ast.Assign):
                suffix = _creates_lock(node.value)
                if suffix is not None:
                    for t in node.targets:
                        if isinstance(t, ast.Name):
                            local_locks[t.id] = self._key(
                                info.cls, f"<local {t.id}>", suffix
                            )
            if isinstance(node, ast.With):
                for item in node.items:
                    key = self.resolve(item.context_expr, info.cls,
                                       local_locks)
                    if key:
                        info.direct_acquires.add(key)
            if isinstance(node, ast.Call):
                chain = attr_chain(node.func)
                if chain and chain[-1] == "acquire":
                    key = self.resolve(
                        _strip_last(node.func), info.cls, local_locks
                    )
                    if key:
                        info.direct_acquires.add(key)
                callee = self._callee(node, info.cls)
                if callee and callee != (info.cls, info.node.name):
                    info.calls.add(callee)

    # -- the walk -----------------------------------------------------------
    def _walk_function(self, info: _FuncInfo) -> None:
        local_locks: Dict[str, str] = {}
        # pre-scan local lock assignments (they may precede the with)
        for node in ast.walk(info.node):
            if isinstance(node, ast.Assign):
                suffix = _creates_lock(node.value)
                if suffix is not None:
                    for t in node.targets:
                        if isinstance(t, ast.Name):
                            local_locks[t.id] = self._key(
                                info.cls, f"<local {t.id}>", suffix
                            )
        self._walk_stmts(info.node.body, [], info, local_locks)

    def _walk_stmts(self, stmts: Sequence[ast.stmt], held: List[str],
                    info: _FuncInfo, local_locks: Dict[str, str]) -> None:
        for stmt in stmts:
            if isinstance(stmt, (ast.FunctionDef, ast.AsyncFunctionDef)):
                continue  # analyzed as their own function
            if isinstance(stmt, (ast.With, ast.AsyncWith)):
                keys = []
                for item in stmt.items:
                    # the context expression runs BEFORE the acquisition
                    self._scan_exprs(item.context_expr, held, info,
                                     local_locks)
                    key = self.resolve(item.context_expr, info.cls,
                                       local_locks)
                    if key:
                        self._acquire(key, held, stmt, info)
                        keys.append(key)
                self._walk_stmts(stmt.body, held, info, local_locks)
                for key in reversed(keys):
                    if key in held:
                        held.remove(key)
                continue
            # explicit acquire()/release() calls toggle the held set
            handled = False
            if isinstance(stmt, ast.Expr) and isinstance(stmt.value, ast.Call):
                chain = attr_chain(stmt.value.func)
                if chain and chain[-1] in ("acquire", "release"):
                    key = self.resolve(
                        _strip_last(stmt.value.func), info.cls, local_locks
                    )
                    if key:
                        handled = True
                        if chain[-1] == "acquire":
                            self._acquire(key, held, stmt, info)
                        elif key in held:
                            held.remove(key)
            if handled:
                continue
            self._scan_stmt_exprs(stmt, held, info, local_locks)
            for field in ("body", "orelse", "finalbody"):
                sub = getattr(stmt, field, None)
                if sub:
                    self._walk_stmts(sub, held, info, local_locks)
            handlers = getattr(stmt, "handlers", None)
            if handlers:
                for h in handlers:
                    self._walk_stmts(h.body, held, info, local_locks)

    def _scan_stmt_exprs(self, stmt: ast.stmt, held: List[str],
                         info: _FuncInfo, local_locks) -> None:
        for field, value in ast.iter_fields(stmt):
            if field in ("body", "orelse", "finalbody", "handlers"):
                continue
            for v in value if isinstance(value, list) else [value]:
                if isinstance(v, ast.AST):
                    self._scan_exprs(v, held, info, local_locks)

    def _scan_exprs(self, expr: ast.AST, held: List[str], info: _FuncInfo,
                    local_locks) -> None:
        for node in ast.walk(expr):
            if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef,
                                 ast.Lambda)):
                continue
            if not isinstance(node, ast.Call):
                continue
            if held:
                self._check_blocking(node, held, info, local_locks)
                callee = self._callee(node, info.cls)
                if callee:
                    for k in self._transitive.get(callee, ()):
                        self._edge(held, k, node, info, via=callee)

    def _acquire(self, key: str, held: List[str], stmt, info) -> None:
        if key in held and not key.endswith("[]") and not key.endswith("()"):
            self.findings.append(Finding(
                "TPL103", self.sf.display, stmt.lineno,
                f"lock {key} re-acquired while already held in "
                f"{_fq(info)} — threading.Lock is not reentrant, this "
                "self-deadlocks",
                hint="use one critical section, or an RLock if re-entry "
                "is intended",
            ))
        self._edge(held, key, stmt, info)
        held.append(key)

    def _edge(self, held: List[str], key: str, node, info,
              via: Optional[Tuple[Optional[str], str]] = None) -> None:
        for h in held:
            if h == key:
                continue
            if (h, key) not in self.edges:
                ctx = _fq(info) + (f" -> {_fq_name(via)}" if via else "")
                self.edges[(h, key)] = (self.sf.display, node.lineno, ctx)

    def _check_blocking(self, call: ast.Call, held: List[str],
                        info: _FuncInfo, local_locks) -> None:
        chain = attr_chain(call.func)
        if not chain:
            return
        name = chain[-1]
        blocking = None
        if name in _BLOCKING_ATTRS and len(chain) > 1:
            blocking = f".{name}()"
        elif name == "sleep":
            blocking = "sleep()"
        elif name == "shutdown" and len(chain) > 1:
            wait_kw = next(
                (kw for kw in call.keywords if kw.arg == "wait"), None
            )
            if wait_kw is None or not (
                isinstance(wait_kw.value, ast.Constant)
                and wait_kw.value.value is False
            ):
                blocking = ".shutdown(wait=True)"
        elif name in _WAITY_ATTRS and len(chain) > 1:
            # waiting on the condition variable you hold is the cv
            # protocol (it releases internally) — only foreign waits block
            owner = self.resolve(_strip_last(call.func), info.cls,
                                 local_locks)
            if owner is None or owner not in held:
                blocking = f".{name}()"
        if blocking:
            self.findings.append(Finding(
                "TPL102", self.sf.display, call.lineno,
                f"blocking call {expr_source(call.func)} while holding "
                f"{held[-1]} in {_fq(info)}",
                hint="release the lock before blocking (copy state out, "
                "block, re-acquire) — a blocked holder wedges every "
                "other acquirer",
            ))

    # -- graph analysis -----------------------------------------------------
    def cycle_findings(self) -> List[Finding]:
        out: List[Finding] = []
        graph: Dict[str, Set[str]] = {}
        for (a, b) in self.edges:
            graph.setdefault(a, set()).add(b)
            graph.setdefault(b, set())
        seen_cycles: Set[Tuple[str, ...]] = set()

        def dfs(start: str):
            stack = [(start, [start])]
            while stack:
                node, path = stack.pop()
                for nxt in graph.get(node, ()):
                    if nxt == start and len(path) > 1:
                        canon = tuple(sorted(path))
                        if canon not in seen_cycles:
                            seen_cycles.add(canon)
                            yield path + [start]
                    elif nxt not in path:
                        stack.append((nxt, path + [nxt]))

        for start in sorted(graph):
            for cycle in dfs(start):
                sites = []
                for a, b in zip(cycle, cycle[1:]):
                    f, ln, ctx = self.edges[(a, b)]
                    sites.append(f"{a} -> {b} at {f}:{ln} ({ctx})")
                f, ln, _ = self.edges[(cycle[0], cycle[1])]
                out.append(Finding(
                    "TPL101", self.sf.display, ln,
                    "lock-order cycle: " + "; ".join(sites),
                    hint="impose one global acquisition order (acquire "
                    "the locks in a fixed order everywhere, or merge "
                    "the critical sections)",
                ))
        return out


def _strip_last(attr_node: ast.Attribute) -> ast.AST:
    return attr_node.value


def _fq(info: _FuncInfo) -> str:
    return _fq_name((info.cls, info.node.name))


def _fq_name(key: Tuple[Optional[str], str]) -> str:
    cls, name = key
    return f"{cls}.{name}" if cls else name


def check_file(sf: SourceFile) -> List[Finding]:
    g = ModuleLockGraph(sf)
    return g.findings + g.cycle_findings()
