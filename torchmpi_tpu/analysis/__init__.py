"""tpu-lint: static collective-contract + concurrency analysis.

The runtime observability stack (flight recorder, hang watchdog,
cross-rank analyzer — PR 6) tells you *which* rank issued a mismatched
collective or deadlocked the world, after the job already burned the
chips. The same bug classes are statically detectable before a single
chip is allocated: this package walks Python ASTs and checks the
*collective contract* (every rank must issue the same collective
sequence; async handles must be waited; donated buffers must not be
read back; collectives live between ``start()`` and ``stop()``) plus
the *concurrency contract* of the threaded host modules (a consistent
lock acquisition order, no blocking calls under a lock). MPI-Checker
(LLVM) is the classic static formulation of the desync check; GC3
(PAPERS.md) makes the case for treating communication as analyzable
program structure — a pass that understands collective call sites well
enough to *check* them is the front half of one that can *compile*
them (ROADMAP item 1).

CLI::

    python -m torchmpi_tpu.analysis <paths> [--strict] [--baseline F]

Findings carry ``file:line``, a rule id, and a fix hint. Suppress a
judged false positive with ``# tpu-lint: disable=<rule>`` on (or just
above) the flagged line; ``--baseline`` names a checked-in JSON file of
accepted findings (shipped empty — see ``scripts/tpu_lint_baseline.json``).

The static lock graph is validated against reality by the opt-in
instrumented-lock runtime monitor (:mod:`.lockmon`,
``TORCHMPI_TPU_LOCK_MONITOR=1``): the threaded modules create their
locks through :func:`lockmon.make_lock`, which — only when armed —
records actual acquisition orders and fails on inversion. Sanitizer
wiring for a language TSan can't reach.

The analysis modules themselves are stdlib-only (``ast``-based, no jax
imports, no accelerator state touched — linting never initializes a
backend). Note that running via ``python -m torchmpi_tpu.analysis``
still imports the parent package (Python imports it before the
submodule), which does require jax to be importable.
"""

from .core import Finding, RULES, iter_python_files  # noqa: F401


def run(paths, **kw):
    """Analyze ``paths`` (files or directories); returns a list of
    :class:`Finding`. Keyword args as :func:`.cli.run_analysis`."""
    from .cli import run_analysis

    return run_analysis(paths, **kw)


def main(argv=None) -> int:
    from .cli import main as _main

    return _main(argv)
