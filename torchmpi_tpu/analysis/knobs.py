"""Knob/metric-consistency lint (rules TPL201-TPL205).

``constants.py`` is the single source of truth for every tunable knob.
Three invariants keep it honest:

- **TPL201 knob-unread** — a knob nobody reads is dead configuration:
  either wire it up or delete it. Reads are ``constants.get("name")``,
  attribute access ``constants.name``, and composed f-string reads like
  ``constants.get(f"small_allreduce_size_{suffix}")`` (the
  platform-suffix idiom), matched as a pattern.
- **TPL202 knob-not-startable** — every knob must be settable at the
  single user entry point, ``start(**kwargs)``; a knob that can only be
  set by importing ``constants`` and calling ``set()`` before start is
  a foot-gun (tuned-constant loading may clobber it).
- **TPL203 knob-undocumented** — every knob must appear in README.md or
  docs/PARITY.md (suffix pairs like ``_cpu``/``_tpu`` may be documented
  by their base name).
- **TPL204 metric-undocumented** — every registered ``tm_*`` metric
  family (a ``counter(...)`` / ``gauge(...)`` / ``histogram(...)`` call
  with a ``tm_``-prefixed literal name) must appear in the metrics
  documentation table (README.md or docs/PARITY.md), same shape as
  TPL203 for knobs: an undocumented family is an operator surface
  nobody can discover.
- **TPL205 frame-field-undocumented** — every PS wire-frame header
  field (the ``name uN`` tokens of the ``# frame:`` doc comment that
  precedes ``_HEADER = struct.Struct(...)`` in the transport) must
  appear as a backticked token in the documented frame-format table
  (README.md / docs/PARITY.md). The wire layout is a cross-version
  compatibility contract; a field that ships undocumented (the fate the
  ``trace``/``span`` trace-context fields would otherwise share with
  ``oseq`` before it) cannot be audited against peers.
"""

from __future__ import annotations

import ast
import re
from pathlib import Path
from typing import Dict, List, Optional, Sequence, Tuple

from .core import Finding, SourceFile, attr_chain


def knob_fields(constants_sf: SourceFile) -> Dict[str, int]:
    """name -> definition line of every _Constants dataclass field."""
    out: Dict[str, int] = {}
    for node in ast.walk(constants_sf.tree):
        if isinstance(node, ast.ClassDef) and node.name == "_Constants":
            for stmt in node.body:
                if isinstance(stmt, ast.AnnAssign) and isinstance(
                    stmt.target, ast.Name
                ):
                    out[stmt.target.id] = stmt.lineno
    return out


def _read_patterns(sf: SourceFile) -> List[re.Pattern]:
    """Regexes matching knob names this file reads.

    Besides direct ``constants.get("name")`` / ``constants.name`` reads
    and composed f-string reads, any bare string literal equal to a knob
    name counts: the pools pass the knob name to a reader at
    construction (``_Pool("tm-ps", "parameterserver_thread_pool_size")``)
    and the autotuner templates names as ``"small_{op}_size_{s}"`` —
    knob names are distinctive enough that a matching literal IS a
    reference."""
    pats: List[re.Pattern] = []
    for node in ast.walk(sf.tree):
        if isinstance(node, ast.Constant) and isinstance(node.value, str):
            if "_" in node.value and node.value.isidentifier():
                pats.append(re.compile(re.escape(node.value) + r"\Z"))
        if isinstance(node, ast.Call):
            chain = attr_chain(node.func)
            if chain and chain[-1] in ("get", "set") and node.args:
                arg = node.args[0]
                if isinstance(arg, ast.Constant) and isinstance(
                    arg.value, str
                ):
                    pats.append(re.compile(re.escape(arg.value) + r"\Z"))
                elif isinstance(arg, ast.JoinedStr):
                    parts = []
                    for v in arg.values:
                        if isinstance(v, ast.Constant):
                            parts.append(re.escape(str(v.value)))
                        else:
                            parts.append(r"\w+")
                    pats.append(re.compile("".join(parts) + r"\Z"))
        elif isinstance(node, ast.Attribute) and isinstance(
            node.ctx, ast.Load
        ):
            base = attr_chain(node)
            if base and len(base) >= 2 and "constants" in base[-2].lower():
                pats.append(re.compile(re.escape(node.attr) + r"\Z"))
    return pats


def _start_accepts_kwargs(runtime_state_sf: SourceFile) -> Optional[int]:
    """Line of ``def start`` if it lacks a ``**kwargs``; None when fine
    (or when there is no start() to check)."""
    for node in ast.walk(runtime_state_sf.tree):
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)) and (
            node.name == "start"
        ):
            if node.args.kwarg is None:
                return node.lineno
            return None
    return None


def check_knobs(
    constants_sf: SourceFile,
    package_files: Sequence[SourceFile],
    doc_paths: Sequence[Path],
    runtime_state_sf: Optional[SourceFile],
) -> List[Finding]:
    knobs = knob_fields(constants_sf)
    if not knobs:
        return []
    findings: List[Finding] = []

    pats: List[re.Pattern] = []
    for sf in package_files:
        if sf.path.resolve() == constants_sf.path.resolve():
            continue
        pats.extend(_read_patterns(sf))

    docs = ""
    for p in doc_paths:
        try:
            docs += Path(p).read_text()
        except OSError:
            pass

    for name, line in sorted(knobs.items(), key=lambda kv: kv[1]):
        if not any(p.fullmatch(name) for p in pats):
            findings.append(Finding(
                "TPL201", constants_sf.display, line,
                f"knob '{name}' is never read outside constants.py",
                hint="wire the knob into the code path it claims to "
                "control, or delete it",
            ))
        base = re.sub(r"_(cpu|tpu)$", "", name)
        if docs and name not in docs and base not in docs:
            findings.append(Finding(
                "TPL203", constants_sf.display, line,
                f"knob '{name}' is not mentioned in README.md or "
                "docs/PARITY.md",
                hint="add it to the README knob table",
            ))

    if runtime_state_sf is not None:
        bad_line = _start_accepts_kwargs(runtime_state_sf)
        if bad_line is not None:
            findings.append(Finding(
                "TPL202", runtime_state_sf.display, bad_line,
                f"start() accepts no **kwargs — none of the {len(knobs)} "
                "constants knobs are settable at the entry point",
                hint="add **constant_overrides to start() and forward "
                "each to constants.set()",
            ))
    return findings


_METRIC_REGISTRARS = ("counter", "gauge", "histogram")


def registered_metric_families(
    package_files: Sequence[SourceFile],
) -> Dict[str, Tuple[str, int]]:
    """Every ``tm_*`` family registered anywhere in the tree:
    name -> (file display path, first registration line)."""
    out: Dict[str, Tuple[str, int]] = {}
    for sf in package_files:
        for node in ast.walk(sf.tree):
            if not isinstance(node, ast.Call):
                continue
            chain = attr_chain(node.func)
            if not chain or chain[-1] not in _METRIC_REGISTRARS:
                continue
            if not node.args:
                continue
            arg = node.args[0]
            if isinstance(arg, ast.Constant) and isinstance(
                arg.value, str
            ) and arg.value.startswith("tm_"):
                if arg.value not in out:
                    out[arg.value] = (sf.display, node.lineno)
    return out


def check_metrics_docs(
    package_files: Sequence[SourceFile],
    doc_paths: Sequence[Path],
) -> List[Finding]:
    """TPL204: every registered ``tm_*`` metric family must appear in
    the metrics documentation (README.md / docs/PARITY.md)."""
    docs = ""
    for p in doc_paths:
        try:
            docs += Path(p).read_text()
        except OSError:
            pass
    findings: List[Finding] = []
    if not docs:
        return findings  # no docs to check against (same rule as TPL203)
    for name, (display, line) in sorted(
        registered_metric_families(package_files).items()
    ):
        if name not in docs:
            findings.append(Finding(
                "TPL204", display, line,
                f"metric family '{name}' is not mentioned in README.md "
                "or docs/PARITY.md",
                hint="add a row (name, type, labels, emitting module) "
                "to the metrics table",
            ))
    return findings


_FRAME_FIELD_RE = re.compile(r"\b([a-z_][a-z0-9_]*) u(?:8|16|32|64)\b")


def frame_header_fields(sf: SourceFile) -> Dict[str, int]:
    """The wire-frame header fields a transport declares: the ``name uN``
    tokens of the contiguous ``# frame:`` comment block (the field list
    ends at the first bare ``#`` line, where the semantic notes start).
    Returns name -> declaration line."""
    out: Dict[str, int] = {}
    in_block = False
    for i, line in enumerate(sf.source.splitlines(), 1):
        stripped = line.strip()
        if stripped.startswith("# frame:"):
            in_block = True
        elif in_block and (not stripped.startswith("#") or stripped == "#"):
            break
        if in_block:
            for m in _FRAME_FIELD_RE.finditer(stripped):
                out.setdefault(m.group(1), i)
    return out


def check_frame_docs(
    package_files: Sequence[SourceFile],
    doc_paths: Sequence[Path],
) -> List[Finding]:
    """TPL205: every PS wire-frame header field must appear as a
    backticked token in the documented frame-format table. Applies to
    any scanned file that both declares a ``# frame:`` field list and
    packs it (``_HEADER = struct.Struct``) — the wire contract and its
    documentation must move together."""
    docs = ""
    for p in doc_paths:
        try:
            docs += Path(p).read_text()
        except OSError:
            pass
    findings: List[Finding] = []
    if not docs:
        return findings  # no docs to check against (same rule as TPL203)
    for sf in package_files:
        if "_HEADER = struct.Struct(" not in sf.source:
            continue
        for name, line in sorted(
            frame_header_fields(sf).items(), key=lambda kv: kv[1]
        ):
            if f"`{name}`" not in docs:
                findings.append(Finding(
                    "TPL205", sf.display, line,
                    f"wire-frame header field '{name}' is not documented "
                    "in the frame-format table (README.md or "
                    "docs/PARITY.md)",
                    hint="add the field (backticked, with width and "
                    "meaning) to the PARITY frame-format table",
                ))
    return findings
