// Native runtime core for torchmpi_tpu.
//
// C++ equivalents of the reference's native components (SURVEY.md §2.1),
// exposed as a C API loaded from Python via ctypes:
//
//  - tunable-constants table with freeze semantics  (≅ lib/constants.cpp)
//  - condvar thread pool + bounded SPMC pool        (≅ lib/thread_pool-in.h,
//                                                      lib/spmc_thread_pool-in.h)
//  - future/handle registry with wait()             (≅ lib/resources.cpp
//                                                      request table + futures,
//                                                      SynchronizationHandle)
//  - memoized ring chunk plans                      (≅ lib/resources.cpp:582-672,
//                                                      lib/detail/README.md)
//  - parameter-server shard store with named update
//    rules applied outside the Python GIL           (≅ lib/parameterserver.cpp
//                                                      shard + rule core)
//  - POSIX named-semaphore local barrier            (≅ lib/barrier.cpp)
//
// The compute path (collectives) is XLA/Pallas; this library is the host
// runtime around it, mirroring where the reference spent native code.

#include <atomic>
#include <chrono>
#include <condition_variable>
#include <cstdint>
#include <cstring>
#include <deque>
#include <functional>
#include <future>
#include <map>
#include <memory>
#include <mutex>
#include <string>
#include <thread>
#include <unordered_map>
#include <vector>

#include <cerrno>
#include <fcntl.h>
#include <semaphore.h>
#include <sys/mman.h>
#include <unistd.h>

#define TPUMPI_API extern "C" __attribute__((visibility("default")))

// ---------------------------------------------------------------------------
// Constants table with freeze (≅ lib/constants.cpp:130-352)
// ---------------------------------------------------------------------------
namespace {

std::mutex g_const_mutex;
std::unordered_map<std::string, int64_t> g_constants;
std::atomic<bool> g_frozen{false};

}  // namespace

TPUMPI_API int tpumpi_set_constant(const char* name, int64_t value) {
  if (g_frozen.load()) return -1;  // immutableConstants check
  std::lock_guard<std::mutex> lock(g_const_mutex);
  g_constants[name] = value;
  return 0;
}

TPUMPI_API int64_t tpumpi_get_constant(const char* name, int64_t fallback) {
  std::lock_guard<std::mutex> lock(g_const_mutex);
  auto it = g_constants.find(name);
  return it == g_constants.end() ? fallback : it->second;
}

TPUMPI_API void tpumpi_freeze_constants() { g_frozen.store(true); }
TPUMPI_API int tpumpi_constants_frozen() { return g_frozen.load() ? 1 : 0; }

// test-only
TPUMPI_API void tpumpi_reset_constants() {
  std::lock_guard<std::mutex> lock(g_const_mutex);
  g_constants.clear();
  g_frozen.store(false);
}

// ---------------------------------------------------------------------------
// Thread pool (condvar, ≅ lib/thread_pool-in.h)
// ---------------------------------------------------------------------------
namespace {

class ThreadPool {
 public:
  explicit ThreadPool(size_t n) : stop_(false) {
    for (size_t i = 0; i < n; ++i) {
      workers_.emplace_back([this] {
        for (;;) {
          std::function<void()> task;
          {
            std::unique_lock<std::mutex> lock(mutex_);
            cv_.wait(lock, [this] { return stop_ || !tasks_.empty(); });
            if (stop_ && tasks_.empty()) return;
            task = std::move(tasks_.front());
            tasks_.pop_front();
          }
          task();
        }
      });
    }
  }

  ~ThreadPool() {
    {
      std::lock_guard<std::mutex> lock(mutex_);
      stop_ = true;
    }
    cv_.notify_all();
    for (auto& w : workers_) w.join();
  }

  bool enqueue(std::function<void()> fn) {
    {
      std::lock_guard<std::mutex> lock(mutex_);
      if (stop_) return false;  // defense-in-depth; see Registry comment
      tasks_.push_back(std::move(fn));
    }
    cv_.notify_one();
    return true;
  }

  size_t size() const { return workers_.size(); }

 private:
  std::vector<std::thread> workers_;
  std::deque<std::function<void()>> tasks_;
  std::mutex mutex_;
  std::condition_variable cv_;
  bool stop_;
};

// Shared id->object registry. INTENTIONALLY LEAKED (heap-allocated,
// accessor-scoped): worker threads of pools leaked at interpreter exit may
// still touch the handle registry, and C++ static destruction order would
// otherwise tear that registry down first (use-after-destruction). Leaked
// registries are immortal; live threads simply die with the process.
// shared_ptr holders: callers copy the pointer out under the (brief) map
// lock and operate outside it, so per-object work never contends the
// global lock. Destroy-vs-use safety: a caller's shared_ptr keeps the
// object alive past destroy(), and the pool destructors DRAIN their task
// queues before workers exit, so even an enqueue racing a destroy has its
// task completed (the stop-flag checks in the enqueue paths are pure
// defense-in-depth — unreachable while any caller holds a reference).
template <class T>
struct Registry {
  std::mutex m;
  std::unordered_map<int64_t, std::shared_ptr<T>> map;
  int64_t next = 0;

  int64_t insert(std::shared_ptr<T> obj) {
    std::lock_guard<std::mutex> lock(m);
    int64_t id = next++;
    map[id] = std::move(obj);
    return id;
  }

  std::shared_ptr<T> get(int64_t id) {
    std::lock_guard<std::mutex> lock(m);
    auto it = map.find(id);
    return it == map.end() ? nullptr : it->second;
  }

  // remove from the map; the object dies when the LAST holder (possibly a
  // caller mid-operation) drops its reference, outside this lock
  void destroy(int64_t id) {
    std::shared_ptr<T> dying;
    {
      std::lock_guard<std::mutex> lock(m);
      auto it = map.find(id);
      if (it == map.end()) return;
      dying = std::move(it->second);
      map.erase(it);
    }
  }

  size_t size() {
    std::lock_guard<std::mutex> lock(m);
    return map.size();
  }
};

Registry<ThreadPool>& pool_registry() {
  static Registry<ThreadPool>* r = new Registry<ThreadPool>();
  return *r;
}

}  // namespace

TPUMPI_API int64_t tpumpi_pool_create(int64_t num_threads) {
  if (num_threads <= 0) return -1;  // a worker-less pool would hang waits
  return pool_registry().insert(
      std::make_shared<ThreadPool>(static_cast<size_t>(num_threads)));
}

TPUMPI_API void tpumpi_pool_destroy(int64_t pool) {
  pool_registry().destroy(pool);
}

// forward decl (defined with the handle registry below)
TPUMPI_API void tpumpi_handle_complete(int64_t id, int64_t status);

// Enqueue a task that completes `handle` on a worker thread — the
// enqueue -> future contract of the reference pool (`ThreadPool::enqueue`
// returning std::future); the Python side waits the handle.
// Returns 0 ok, -2 unknown/destroyed pool (NOT retryable).
TPUMPI_API int tpumpi_pool_enqueue_signal(int64_t pool, int64_t handle) {
  auto p = pool_registry().get(pool);
  if (!p) return -2;
  return p->enqueue([handle] { tpumpi_handle_complete(handle, 0); }) ? 0 : -2;
}

// ---------------------------------------------------------------------------
// Bounded SPMC pool (≅ lib/spmc_thread_pool-in.h): fixed-capacity task
// ring, non-blocking enqueue (returns -1 when full), workers poll with the
// reference's 500µs sleep cadence instead of a condvar.
// ---------------------------------------------------------------------------
namespace {

class SpmcPool {
 public:
  SpmcPool(size_t threads, size_t capacity)
      : capacity_(capacity), stop_(false) {
    for (size_t i = 0; i < threads; ++i) {
      workers_.emplace_back([this] {
        for (;;) {
          int64_t handle = -1;
          {
            std::lock_guard<std::mutex> lock(mutex_);
            if (!queue_.empty()) {
              handle = queue_.front();
              queue_.pop_front();
            } else if (stop_.load()) {
              return;
            }
          }
          if (handle >= 0) {
            tpumpi_handle_complete(handle, 0);
          } else {
            std::this_thread::sleep_for(std::chrono::microseconds(500));
          }
        }
      });
    }
  }

  ~SpmcPool() {
    stop_.store(true);
    for (auto& w : workers_) w.join();
  }

  // 0 ok; -1 full (transient: back off and retry); -2 stopping
  // (defense-in-depth; see Registry comment)
  int try_enqueue(int64_t handle) {
    std::lock_guard<std::mutex> lock(mutex_);
    if (stop_.load()) return -2;
    if (queue_.size() >= capacity_) return -1;  // bounded: caller backs off
    queue_.push_back(handle);
    return 0;
  }

 private:
  size_t capacity_;
  std::atomic<bool> stop_;
  std::deque<int64_t> queue_;
  std::mutex mutex_;
  std::vector<std::thread> workers_;
};

Registry<SpmcPool>& spmc_registry() {
  static Registry<SpmcPool>* r = new Registry<SpmcPool>();
  return *r;
}

}  // namespace

TPUMPI_API int64_t tpumpi_spmc_create(int64_t threads, int64_t capacity) {
  if (threads <= 0 || capacity <= 0) return -1;
  return spmc_registry().insert(std::make_shared<SpmcPool>(
      static_cast<size_t>(threads), static_cast<size_t>(capacity)));
}

// 0 ok; -1 ring full (retryable); -2 unknown/destroyed pool (permanent)
TPUMPI_API int tpumpi_spmc_enqueue_signal(int64_t pool, int64_t handle) {
  auto p = spmc_registry().get(pool);
  if (!p) return -2;
  return p->try_enqueue(handle);
}

TPUMPI_API void tpumpi_spmc_destroy(int64_t pool) {
  spmc_registry().destroy(pool);
}

// ---------------------------------------------------------------------------
// Handle registry (≅ SynchronizationHandle + future/request tables,
// lib/resources.h:230-253, lib/resources.cpp:399-461,545-578)
// ---------------------------------------------------------------------------
namespace {

struct Handle {
  std::promise<int64_t> promise;
  std::shared_future<int64_t> future;
  std::atomic<bool> completed{false};
  Handle() : future(promise.get_future().share()) {}
};

// immortal (leaked) for the same reason as the pool registries: leaked
// pools' worker threads may complete handles during interpreter exit
Registry<Handle>& handle_registry() {
  static Registry<Handle>* r = new Registry<Handle>();
  return *r;
}

std::shared_ptr<Handle> take_handle(int64_t id) {
  return handle_registry().get(id);
}

}  // namespace

TPUMPI_API int64_t tpumpi_handle_create() {
  return handle_registry().insert(std::make_shared<Handle>());
}

// Idempotent: the second and later completes are no-ops (a throwing
// std::promise::set_value must never unwind across the C boundary).
TPUMPI_API void tpumpi_handle_complete(int64_t id, int64_t status) {
  auto h = take_handle(id);
  if (h && !h->completed.exchange(true)) h->promise.set_value(status);
}

// Blocks until complete; frees the slot; returns status (0 unknown-id, like
// the reference's wait-on-freed-handle no-op, resources.cpp:1226-1242).
TPUMPI_API int64_t tpumpi_handle_wait(int64_t id) {
  auto h = take_handle(id);
  if (!h) return 0;
  int64_t status = h->future.get();
  handle_registry().destroy(id);
  return status;
}

TPUMPI_API int64_t tpumpi_handles_outstanding() {
  return static_cast<int64_t>(handle_registry().size());
}

// ---------------------------------------------------------------------------
// Ring chunk plans (≅ lib/resources.cpp:582-672): for `chunks` chunks on a
// ring of `size` at position `rank`, the (p-1) reduce-scatter steps then
// (p-1) allgather steps, each step = (send_chunk, recv_chunk). Memoized.
// ---------------------------------------------------------------------------
namespace {

struct Plan {
  std::vector<int64_t> send;  // 2*(size-1) entries
  std::vector<int64_t> recv;
};

std::mutex g_plan_mutex;
std::map<std::tuple<int64_t, int64_t, int64_t>, Plan> g_plans;

const Plan& get_plan(int64_t rank, int64_t size) {
  std::lock_guard<std::mutex> lock(g_plan_mutex);
  auto key = std::make_tuple(int64_t(0), rank, size);
  auto it = g_plans.find(key);
  if (it != g_plans.end()) return it->second;
  Plan plan;
  auto mod = [](int64_t a, int64_t m) { return ((a % m) + m) % m; };
  // reduce-scatter phase: step s sends chunk (rank-s), receives (rank-s-1)
  for (int64_t s = 0; s < size - 1; ++s) {
    plan.send.push_back(mod(rank - s, size));
    plan.recv.push_back(mod(rank - s - 1, size));
  }
  // allgather phase: step s sends (rank+1-s), receives (rank-s)
  for (int64_t s = 0; s < size - 1; ++s) {
    plan.send.push_back(mod(rank + 1 - s, size));
    plan.recv.push_back(mod(rank - s, size));
  }
  return g_plans.emplace(key, std::move(plan)).first->second;
}

}  // namespace

// Fills out_send/out_recv (each 2*(size-1) int64 slots) with chunk indices
// in [0, size). A buffer of k*size chunks runs the same schedule per group
// of `size` chunks (offset j*size), exactly like the reference plan's
// repetition over chunk groups. Returns step count.
TPUMPI_API int64_t tpumpi_ring_plan(int64_t rank, int64_t size,
                                    int64_t* out_send, int64_t* out_recv) {
  if (size < 2 || rank < 0 || rank >= size) return -1;
  const Plan& plan = get_plan(rank, size);
  std::memcpy(out_send, plan.send.data(), plan.send.size() * sizeof(int64_t));
  std::memcpy(out_recv, plan.recv.data(), plan.recv.size() * sizeof(int64_t));
  return static_cast<int64_t>(plan.send.size());
}

// ---------------------------------------------------------------------------
// Parameter-server shard store (≅ lib/parameterserver.cpp shard + rules).
// Rules: 0=zero, 1=copy, 2=add (parameterserver.cpp:119-213). float32 (0)
// and float64 (1), matching the reference's Float/Double instantiation.
// Applies without holding the Python GIL (ctypes releases it around calls).
// ---------------------------------------------------------------------------
namespace {

struct Shard {
  std::vector<uint8_t> data;
  int dtype;  // 0 = f32, 1 = f64
  std::mutex mutex;
};

struct PSStore {
  std::vector<std::shared_ptr<Shard>> shards;
};

std::mutex g_ps_mutex;
std::unordered_map<int64_t, std::unique_ptr<PSStore>> g_ps;
int64_t g_next_ps = 0;

template <typename T>
void apply_rule_typed(uint8_t* shard, const uint8_t* incoming, int64_t n,
                      int64_t rule) {
  T* s = reinterpret_cast<T*>(shard);
  const T* in = reinterpret_cast<const T*>(incoming);
  switch (rule) {
    case 0:
      std::memset(shard, 0, n * sizeof(T));
      break;
    case 1:
      std::memcpy(shard, incoming, n * sizeof(T));
      break;
    case 2:
      for (int64_t i = 0; i < n; ++i) s[i] += in[i];
      break;
  }
}

// Returns a shared_ptr copy so a concurrent tpumpi_ps_free cannot destroy
// the shard (and its mutex) while a reader/writer still holds it.
std::shared_ptr<Shard> find_shard(int64_t store, int64_t shard_idx) {
  std::lock_guard<std::mutex> lock(g_ps_mutex);
  auto it = g_ps.find(store);
  if (it == g_ps.end()) return nullptr;
  auto& shards = it->second->shards;
  if (shard_idx < 0 || shard_idx >= (int64_t)shards.size()) return nullptr;
  return shards[shard_idx];
}

}  // namespace

// dtype: 0=f32, 1=f64. shard_sizes: element count per shard.
TPUMPI_API int64_t tpumpi_ps_create(const int64_t* shard_sizes,
                                    int64_t num_shards, int dtype,
                                    const uint8_t* initial_flat) {
  if (dtype != 0 && dtype != 1) return -1;
  size_t esize = dtype == 0 ? 4 : 8;
  auto store = std::make_unique<PSStore>();
  size_t offset = 0;
  for (int64_t i = 0; i < num_shards; ++i) {
    auto shard = std::make_shared<Shard>();
    shard->dtype = dtype;
    size_t bytes = shard_sizes[i] * esize;
    shard->data.resize(bytes);
    if (initial_flat) {
      std::memcpy(shard->data.data(), initial_flat + offset, bytes);
    }
    offset += bytes;
    store->shards.push_back(std::move(shard));
  }
  std::lock_guard<std::mutex> lock(g_ps_mutex);
  int64_t id = g_next_ps++;
  g_ps[id] = std::move(store);
  return id;
}

TPUMPI_API int tpumpi_ps_apply(int64_t store, int64_t shard_idx, int64_t rule,
                               const uint8_t* incoming, int64_t n_elements) {
  std::shared_ptr<Shard> shard = find_shard(store, shard_idx);
  if (!shard || rule < 0 || rule > 2) return -1;
  size_t esize = shard->dtype == 0 ? 4 : 8;
  if ((size_t)n_elements * esize != shard->data.size()) return -2;
  std::lock_guard<std::mutex> lock(shard->mutex);
  if (shard->dtype == 0) {
    apply_rule_typed<float>(shard->data.data(), incoming, n_elements, rule);
  } else {
    apply_rule_typed<double>(shard->data.data(), incoming, n_elements, rule);
  }
  return 0;
}

TPUMPI_API int tpumpi_ps_read(int64_t store, int64_t shard_idx, uint8_t* out,
                              int64_t n_elements) {
  std::shared_ptr<Shard> shard = find_shard(store, shard_idx);
  if (!shard) return -1;
  size_t esize = shard->dtype == 0 ? 4 : 8;
  if ((size_t)n_elements * esize != shard->data.size()) return -2;
  std::lock_guard<std::mutex> lock(shard->mutex);
  std::memcpy(out, shard->data.data(), shard->data.size());
  return 0;
}

TPUMPI_API void tpumpi_ps_free(int64_t store) {
  std::lock_guard<std::mutex> lock(g_ps_mutex);
  g_ps.erase(store);
}

TPUMPI_API int64_t tpumpi_ps_count() {
  std::lock_guard<std::mutex> lock(g_ps_mutex);
  return static_cast<int64_t>(g_ps.size());
}

// ---------------------------------------------------------------------------
// POSIX named-semaphore local barrier (≅ lib/barrier.cpp + resources.cpp:
// 486-539, which the reference left disabled; functional here).
// Classic two-phase (arrive + depart) so the barrier is reusable. The
// arrival count lives in a POSIX shared-memory int (mmap'd), mutated only
// under mutex_sem — a real cross-process counter, not the fragile
// sem_getvalue trick.
// ---------------------------------------------------------------------------
namespace {

struct Barrier {
  std::string name;
  sem_t* mutex_sem = SEM_FAILED;
  sem_t* turnstile1 = SEM_FAILED;
  sem_t* turnstile2 = SEM_FAILED;
  int* count = nullptr;  // shm-mapped arrival counter
  int shm_fd = -1;
  int size = 0;
  bool owner = false;

  // release happens in the destructor: waiters hold a shared_ptr, so a
  // concurrent destroy() cannot munmap/close under a blocked wait — the
  // LAST holder (which may be a waiter) tears down.
  ~Barrier() {
    if (mutex_sem != SEM_FAILED) sem_close(mutex_sem);
    if (turnstile1 != SEM_FAILED) sem_close(turnstile1);
    if (turnstile2 != SEM_FAILED) sem_close(turnstile2);
    if (count != nullptr) munmap(count, sizeof(int));
    if (shm_fd >= 0) close(shm_fd);
    if (owner) {
      for (const char* suffix : {"_m", "_t1", "_t2"}) {
        sem_unlink((std::string("/tpumpi_") + name + suffix).c_str());
      }
      shm_unlink((std::string("/tpumpi_") + name + "_c").c_str());
    }
  }
};

std::mutex g_barrier_mutex;
std::unordered_map<int64_t, std::shared_ptr<Barrier>> g_barriers;
int64_t g_next_barrier = 0;

// sem_wait restarted on signal interruption: an EINTR falling through
// would mutate the shm counter without holding the mutex (lost update ->
// permanent barrier hang for every process). Any OTHER failure (e.g.
// EINVAL from a concurrently-closed semaphore) returns -1 and the caller
// must bail out WITHOUT touching the counter.
int sem_wait_retry(sem_t* s) {
  int rc;
  while ((rc = sem_wait(s)) == -1 && errno == EINTR) {
  }
  return rc;
}

}  // namespace

// `owner` != 0: unlink any stale names from a crashed prior run before
// creating and initialize the shared counter (the creator process passes
// owner=1; joiners pass owner=0 and must be started after the owner).
TPUMPI_API int64_t tpumpi_barrier_create(const char* name, int size,
                                         int owner) {
  auto b = std::make_shared<Barrier>();
  b->name = name;
  b->size = size;
  b->owner = owner != 0;
  std::string n1 = std::string("/tpumpi_") + name + "_m";
  std::string n2 = std::string("/tpumpi_") + name + "_t1";
  std::string n3 = std::string("/tpumpi_") + name + "_t2";
  std::string nc = std::string("/tpumpi_") + name + "_c";
  if (owner) {
    for (const char* suffix : {"_m", "_t1", "_t2"}) {
      sem_unlink((std::string("/tpumpi_") + name + suffix).c_str());
    }
    shm_unlink(nc.c_str());
  }
  // Joiners attach WITHOUT O_CREAT: a joiner racing ahead of the owner
  // must fail (and retry) rather than create its own objects that the
  // owner's unlink+recreate would orphan (split-brain: both sides wait
  // on different kernel objects forever). Every failure path releases
  // whatever was opened so far (and, for the owner, unlinks the names so
  // a retry starts clean).
  int sflags = owner ? O_CREAT : 0;
  b->mutex_sem = sem_open(n1.c_str(), sflags, 0600, 1);
  if (b->mutex_sem == SEM_FAILED) return -1;  // dtor releases
  b->turnstile1 = sem_open(n2.c_str(), sflags, 0600, 0);
  if (b->turnstile1 == SEM_FAILED) return -1;
  b->turnstile2 = sem_open(n3.c_str(), sflags, 0600, 0);
  if (b->turnstile2 == SEM_FAILED) return -1;
  b->shm_fd = shm_open(nc.c_str(), (owner ? O_CREAT : 0) | O_RDWR, 0600);
  if (b->shm_fd < 0 || ftruncate(b->shm_fd, sizeof(int)) != 0) return -1;
  void* mem = mmap(nullptr, sizeof(int), PROT_READ | PROT_WRITE, MAP_SHARED,
                   b->shm_fd, 0);
  if (mem == MAP_FAILED) return -1;
  b->count = static_cast<int*>(mem);
  if (owner) *b->count = 0;
  std::lock_guard<std::mutex> lock(g_barrier_mutex);
  int64_t id = g_next_barrier++;
  g_barriers[id] = std::move(b);
  return id;
}

TPUMPI_API int tpumpi_barrier_wait(int64_t id) {
  std::shared_ptr<Barrier> b;  // keeps the mapping alive across the wait
  {
    std::lock_guard<std::mutex> lock(g_barrier_mutex);
    auto it = g_barriers.find(id);
    if (it == g_barriers.end()) return -1;
    b = it->second;
  }
  // every sem op error-checked: a failed FIRST wait (e.g. a concurrently
  // destroyed barrier) bails out without touching the counter; a failure
  // AFTER the phase-1 increment leaves the barrier poisoned for every
  // participant (peers must destroy + recreate) — the MPI model, where a
  // rank failure kills the communicator, and exactly what the reference's
  // job-wide failure semantics prescribe (SURVEY §5 failure detection)
  // phase 1: everyone arrives; the last arrival opens turnstile1
  if (sem_wait_retry(b->mutex_sem) != 0) return -1;
  if (++*b->count == b->size) {
    for (int i = 0; i < b->size; ++i) sem_post(b->turnstile1);
  }
  sem_post(b->mutex_sem);
  if (sem_wait_retry(b->turnstile1) != 0) return -1;
  // phase 2: everyone departs; the last departure opens turnstile2,
  // resetting the barrier for reuse
  if (sem_wait_retry(b->mutex_sem) != 0) return -1;
  if (--*b->count == 0) {
    for (int i = 0; i < b->size; ++i) sem_post(b->turnstile2);
  }
  sem_post(b->mutex_sem);
  if (sem_wait_retry(b->turnstile2) != 0) return -1;
  return 0;
}

TPUMPI_API void tpumpi_barrier_destroy(int64_t id) {
  std::shared_ptr<Barrier> dying;
  {
    std::lock_guard<std::mutex> lock(g_barrier_mutex);
    auto it = g_barriers.find(id);
    if (it == g_barriers.end()) return;
    dying = std::move(it->second);
    g_barriers.erase(it);
  }
  // release happens in ~Barrier when the LAST holder (possibly a still-
  // blocked waiter) drops its reference; the owner unlinks the names
  // there — a joiner's destroy never invalidates surviving processes
}

TPUMPI_API const char* tpumpi_version() { return "tpumpi-native-0.1.0"; }
