"""torchmpi_tpu — a TPU-native distributed training framework.

A brand-new framework with the capabilities of facebookresearch/TorchMPI,
re-designed for TPU: hierarchical named communicators over JAX device meshes
(ICI × DCN instead of MPI_COMM_WORLD splits and cudaIPC groups), a full
sync/async collectives surface with XLA-builtin and custom ring backends plus
a runtime selector, NN-level data-parallel helpers, an AllReduceSGD training
engine, and a host-side sharded parameter server (Downpour / EASGD / DSGD).

Public API shape follows the reference (``torchmpi/init.lua``):

    import torchmpi_tpu as mpi
    mpi.start()
    y = mpi.allreduce_tensor(x)           # selector-routed
    y = mpi.ring.allreduce_tensor(x)      # explicit custom-ring backend
    h = mpi.async_.allreduce_tensor(x)    # async -> SyncHandle
    mpi.wait(h)
    mpi.stop()
"""

from . import _compat

# Older jax spells shard_map differently; alias it FIRST so every
# submodule (and downstream user code) sees the current API surface.
_compat.install_jax_aliases()

from . import constants, telemetry
from .collectives import (
    allgather_tensor,
    allgatherv_tensor,
    allreduce_scalar,
    allreduce_tensor,
    async_,
    barrier,
    broadcast_scalar,
    broadcast_tensor,
    collective_availability,
    free_collective_resources,
    alltoall_tensor,
    pallas,
    reduce_scalar,
    reduce_tensor,
    reducescatter_tensor,
    ring,
    selector as collective_selector,
    sendreceive_scalar,
    sendreceive_tensor,
    wait,
    xla,
)
from .runtime.communicator import Communicator, split_by_keys
from .runtime.handles import SyncHandle, sync_all
from .runtime_state import (
    communicator_names,
    describe,
    current_communicator,
    num_nodes_in_communicator,
    num_processes,
    push_communicator,
    rank,
    set_collective_span,
    set_communicator,
    size,
    stack,
    start,
    started,
    stop,
)

# Submodules as attributes, matching the reference's surface (torchmpi.nn,
# torchmpi.parameterserver, ...): `import torchmpi_tpu as mpi; mpi.nn.*`
# must work without a separate import. Imported LAST — each pulls from
# `collectives`/`runtime_state` above, so the order avoids cycles.
from . import data, engine, nn, parallel, parameterserver, utils  # noqa: E402

__version__ = "0.5.0"

__all__ = [
    "start",
    "stop",
    "started",
    "rank",
    "size",
    "num_processes",
    "barrier",
    "push_communicator",
    "set_communicator",
    "set_collective_span",
    "communicator_names",
    "describe",
    "num_nodes_in_communicator",
    "current_communicator",
    "stack",
    "Communicator",
    "split_by_keys",
    "SyncHandle",
    "sync_all",
    "wait",
    "broadcast_tensor",
    "reduce_tensor",
    "allreduce_tensor",
    "allgather_tensor",
    "allgatherv_tensor",
    "sendreceive_tensor",
    "reducescatter_tensor",
    "alltoall_tensor",
    "broadcast_scalar",
    "allreduce_scalar",
    "reduce_scalar",
    "sendreceive_scalar",
    "xla",
    "ring",
    "pallas",
    "async_",
    "collective_selector",
    "collective_availability",
    "free_collective_resources",
    "constants",
    "telemetry",
    "__version__",
]
