"""Opaque synchronization handles for async work.

Analog of the reference ``SynchronizationHandle`` — a tagged union over
{MPI request index, thread-pool future index, CUDA stream} with a single
``wait`` entry point (``lib/resources.h:230-253``,
``lib/resources.cpp:1173-1242``). On TPU the three variants map to:

- ``arrays``: in-flight ``jax.Array`` results — XLA dispatch is already
  asynchronous, so the "stream" variant becomes the arrays themselves and
  ``wait`` is ``jax.block_until_ready`` on them.
- ``future``: a ``concurrent.futures.Future`` from the host offload pools
  (parameter-server clients, host-staged collectives) — the thread-pool
  future variant.
- ``native``: an integer request id owned by the C++ runtime extension.

Handles are registered in a table and identified by index, preserving the
reference's C-API shape where handles cross the FFI boundary by pointer and
are freed by ``wait`` (``resources.cpp:1212-1242``).
"""

from __future__ import annotations

import threading
from concurrent.futures import Future
from typing import Any, Dict, List, Optional

import jax

from ..telemetry import flightrecorder as _flight
from ..analysis import lockmon as _lockmon


class SyncHandle:
    """Tagged union: exactly one of arrays / future / native_id is set."""

    __slots__ = ("arrays", "future", "native_id", "_result", "_done", "_table_index")

    def __init__(
        self,
        arrays: Optional[Any] = None,
        future: Optional[Future] = None,
        native_id: Optional[int] = None,
    ):
        populated = sum(x is not None for x in (arrays, future, native_id))
        if populated != 1:
            raise ValueError(
                "SyncHandle requires exactly one of arrays/future/native_id"
            )
        self.arrays = arrays
        self.future = future
        self.native_id = native_id
        self._result = None
        self._done = False
        self._table_index = None

    def wait(self) -> Any:
        """Block until the work completes; returns the result (if any).

        Idempotent, like the reference's ``wait`` which frees the slot and
        turns subsequent waits into no-ops (``resources.cpp:1226-1242``).

        This is the point where DEVICE-side completion is actually
        awaited (XLA dispatch is async, so a collective's flight-recorder
        entry completes at dispatch): when the flight recorder is on,
        the blocking region records its own ``wait.*`` entry — a
        desynced peer wedges THIS call, and the entry stuck at
        ``issued`` is what the hang watchdog flags.
        """
        if self._done:
            return self._result
        entry = None
        if _flight.enabled():
            kind = (
                "arrays" if self.arrays is not None
                else "future" if self.future is not None
                else "native"
            )
            entry = _flight.recorder.record(
                "handles", f"wait.{kind}", backend=kind
            )
        try:
            if self.arrays is not None:
                self._result = jax.block_until_ready(self.arrays)
            elif self.future is not None:
                self._result = self.future.result()
            else:
                from . import native  # local import: extension is optional

                native.wait_request(self.native_id)
                self._result = None
        except BaseException:
            if entry is not None:
                _flight.FlightRecorder.fail(entry)
            raise
        if entry is not None:
            _flight.FlightRecorder.complete(entry)
        self._done = True
        if self._table_index is not None:
            handles._discard(self._table_index)
            self._table_index = None
        return self._result

    @property
    def done(self) -> bool:
        if self._done:
            return True
        if self.future is not None:
            return self.future.done()
        return False

    def __repr__(self) -> str:
        kind = (
            "arrays"
            if self.arrays is not None
            else "future"
            if self.future is not None
            else f"native:{self.native_id}"
        )
        return f"SyncHandle<{kind}{', done' if self._done else ''}>"


class _HandleTable:
    """Index-addressed handle registry (reference ``resources.cpp:545-578``,
    the MPI request table, and the future queues at ``:399-461``)."""

    def __init__(self):
        self._lock = _lockmon.make_lock("handles.py:_HandleTable._lock")
        self._handles: Dict[int, SyncHandle] = {}
        self._kinds: Dict[int, str] = {}
        self._next = 0

    def register(self, handle: SyncHandle, kind: str = "") -> int:
        with self._lock:
            idx = self._next
            self._next += 1
            self._handles[idx] = handle
            if kind:
                self._kinds[idx] = kind
            handle._table_index = idx
            return idx

    def outstanding_kind(self, kind: str) -> int:
        """Count unwaited handles registered under ``kind`` (backpressure
        accounting for the num_async_*_in_flight bounds)."""
        with self._lock:
            return sum(1 for i in self._handles if self._kinds.get(i) == kind)

    def wait_oldest(self, kind: str) -> bool:
        """Drain the oldest outstanding handle of ``kind``; False if none."""
        with self._lock:
            idxs = sorted(i for i in self._handles if self._kinds.get(i) == kind)
            if not idxs:
                return False
            handle = self._handles.pop(idxs[0], None)
            self._kinds.pop(idxs[0], None)
        if handle is not None:
            handle.wait()
        return True

    def _discard(self, idx: int) -> None:
        """Drop a handle that completed via a direct wait() call."""
        with self._lock:
            self._handles.pop(idx, None)
            self._kinds.pop(idx, None)

    def wait_index(self, idx: int) -> Any:
        with self._lock:
            handle = self._handles.pop(idx, None)
            self._kinds.pop(idx, None)
        if handle is None:
            return None  # already waited: no-op, as in the reference
        return handle.wait()

    def sync_all(self) -> None:
        """Drain every outstanding handle (``resources.cpp:463-481``)."""
        with self._lock:
            pending = list(self._handles.values())
            self._handles.clear()
            self._kinds.clear()
        for h in pending:
            h.wait()

    @property
    def outstanding(self) -> int:
        with self._lock:
            return len(self._handles)


handles = _HandleTable()


def wait(handle_or_index) -> Any:
    """`mpi.syncHandle` equivalent: wait on a handle or a registry index."""
    if isinstance(handle_or_index, SyncHandle):
        return handle_or_index.wait()
    if isinstance(handle_or_index, int):
        return handles.wait_index(handle_or_index)
    if handle_or_index is None:
        return None
    raise TypeError(f"cannot wait on {type(handle_or_index).__name__}")


def sync_all() -> None:
    handles.sync_all()
