from .communicator import Communicator, CommunicatorStack, split_by_keys
from .handles import SyncHandle, handles, sync_all, wait

__all__ = [
    "Communicator",
    "CommunicatorStack",
    "split_by_keys",
    "SyncHandle",
    "handles",
    "sync_all",
    "wait",
]
