"""ctypes bridge to the native runtime (csrc/tpumpi.cpp).

Loads ``libtpumpi.so`` (building it with the bundled Makefile on first use
when a toolchain exists) and exposes the C API. Everything degrades
gracefully: ``available()`` is False when no compiler/library is present and
callers fall back to the pure-Python implementations — the analog of the
reference's optional NCCL/Gloo feature detection (``lib/CMakeLists.txt``).

The constants table is mirrored into C++ through a listener (the C getters
are then the native code's source of truth, like the reference's C
getter/setter pairs).
"""

from __future__ import annotations

import ctypes
import os
import subprocess
import threading
from ..analysis import lockmon as _lockmon
from pathlib import Path
from typing import Optional

import numpy as np

_CSRC = Path(__file__).resolve().parent.parent / "csrc"
_SO = _CSRC / "libtpumpi.so"

_lib: Optional[ctypes.CDLL] = None
_load_lock = _lockmon.make_lock("native.py:_load_lock")
_load_attempted = False


def _build() -> bool:
    try:
        subprocess.run(
            ["make", "-s"],
            cwd=_CSRC,
            check=True,
            capture_output=True,
            timeout=120,
        )
        return _SO.exists()
    except Exception:
        return False


def _declare(lib: ctypes.CDLL) -> None:
    c = ctypes
    lib.tpumpi_set_constant.argtypes = [c.c_char_p, c.c_int64]
    lib.tpumpi_set_constant.restype = c.c_int
    lib.tpumpi_get_constant.argtypes = [c.c_char_p, c.c_int64]
    lib.tpumpi_get_constant.restype = c.c_int64
    lib.tpumpi_freeze_constants.restype = None
    lib.tpumpi_constants_frozen.restype = c.c_int
    lib.tpumpi_reset_constants.restype = None

    lib.tpumpi_pool_create.argtypes = [c.c_int64]
    lib.tpumpi_pool_create.restype = c.c_int64
    lib.tpumpi_pool_destroy.argtypes = [c.c_int64]
    lib.tpumpi_pool_enqueue_signal.argtypes = [c.c_int64, c.c_int64]
    lib.tpumpi_pool_enqueue_signal.restype = c.c_int

    lib.tpumpi_spmc_create.argtypes = [c.c_int64, c.c_int64]
    lib.tpumpi_spmc_create.restype = c.c_int64
    lib.tpumpi_spmc_enqueue_signal.argtypes = [c.c_int64, c.c_int64]
    lib.tpumpi_spmc_enqueue_signal.restype = c.c_int
    lib.tpumpi_spmc_destroy.argtypes = [c.c_int64]

    lib.tpumpi_handle_create.restype = c.c_int64
    lib.tpumpi_handle_complete.argtypes = [c.c_int64, c.c_int64]
    lib.tpumpi_handle_wait.argtypes = [c.c_int64]
    lib.tpumpi_handle_wait.restype = c.c_int64
    lib.tpumpi_handles_outstanding.restype = c.c_int64

    i64p = np.ctypeslib.ndpointer(np.int64, flags="C_CONTIGUOUS")
    lib.tpumpi_ring_plan.argtypes = [c.c_int64, c.c_int64, i64p, i64p]
    lib.tpumpi_ring_plan.restype = c.c_int64

    u8p = c.POINTER(c.c_uint8)
    lib.tpumpi_ps_create.argtypes = [i64p, c.c_int64, c.c_int, u8p]
    lib.tpumpi_ps_create.restype = c.c_int64
    lib.tpumpi_ps_apply.argtypes = [c.c_int64, c.c_int64, c.c_int64, u8p, c.c_int64]
    lib.tpumpi_ps_apply.restype = c.c_int
    lib.tpumpi_ps_read.argtypes = [c.c_int64, c.c_int64, u8p, c.c_int64]
    lib.tpumpi_ps_read.restype = c.c_int
    lib.tpumpi_ps_free.argtypes = [c.c_int64]
    lib.tpumpi_ps_count.restype = c.c_int64

    lib.tpumpi_barrier_create.argtypes = [c.c_char_p, c.c_int, c.c_int]
    lib.tpumpi_barrier_create.restype = c.c_int64
    lib.tpumpi_barrier_wait.argtypes = [c.c_int64]
    lib.tpumpi_barrier_wait.restype = c.c_int
    lib.tpumpi_barrier_destroy.argtypes = [c.c_int64]

    lib.tpumpi_version.restype = c.c_char_p


def get_lib() -> Optional[ctypes.CDLL]:
    global _lib, _load_attempted
    with _load_lock:
        if _lib is not None or _load_attempted:
            return _lib
        _load_attempted = True
        if not _SO.exists() and not _build():
            return None
        try:
            lib = ctypes.CDLL(str(_SO))
            _declare(lib)
            _lib = lib
            _mirror_constants(lib)
        except (OSError, AttributeError):
            # AttributeError: a stale .so missing a newly-added symbol —
            # degrade to the pure-Python fallbacks rather than raising
            # from available().
            _lib = None
            return None
        return _lib


def available() -> bool:
    return get_lib() is not None


def _mirror_constants(lib: ctypes.CDLL) -> None:
    from .. import constants

    def listener(name: str, value) -> None:
        if isinstance(value, bool):
            value = int(value)
        if isinstance(value, int):
            rc = lib.tpumpi_set_constant(name.encode(), value)
            if rc != 0:
                # The native table refused (frozen there but not here):
                # surface the divergence instead of silently disagreeing.
                raise RuntimeError(
                    f"native constants table rejected {name!r} "
                    "(frozen out-of-band?)"
                )

    constants.register_listener(listener)
    constants.register_freeze_listener(
        lambda: lib.tpumpi_freeze_constants()
    )
    if constants.constants_frozen():
        lib.tpumpi_freeze_constants()


# ---------------------------------------------------------------------------
# typed wrappers
# ---------------------------------------------------------------------------


def wait_request(request_id: int) -> int:
    """Wait a native handle (SyncHandle.native_id backend)."""
    lib = get_lib()
    if lib is None:
        raise RuntimeError("native runtime not available")
    return int(lib.tpumpi_handle_wait(request_id))


def ring_plan(rank: int, size: int):
    """(send, recv) chunk-index schedules (values in [0, size)) for the
    2(p-1) ring steps (the memoized plan of resources.cpp:582-672). Buffers
    with k*size chunks run the same schedule per group of ``size`` chunks."""
    lib = get_lib()
    if lib is None:
        raise RuntimeError("native runtime not available")
    steps = 2 * (size - 1)
    send = np.zeros(steps, np.int64)
    recv = np.zeros(steps, np.int64)
    n = lib.tpumpi_ring_plan(rank, size, send, recv)
    if n < 0:
        raise ValueError(f"invalid plan request ({rank=}, {size=})")
    return send, recv


class NativeShardStore:
    """C++-side PS shard storage: rules applied outside the GIL (the hybrid
    split of the reference — protocol in the scripting layer, byte-crunching
    in C++)."""

    RULES = {"zero": 0, "copy": 1, "add": 2}

    def __init__(self, shard_sizes, dtype, initial_flat: np.ndarray):
        lib = get_lib()
        if lib is None:
            raise RuntimeError("native runtime not available")
        self._lib = lib
        self.dtype = np.dtype(dtype)
        code = {np.dtype(np.float32): 0, np.dtype(np.float64): 1}.get(self.dtype)
        if code is None:
            raise TypeError(f"native PS supports f32/f64, got {self.dtype}")
        sizes = np.asarray(shard_sizes, np.int64)
        flat = np.ascontiguousarray(initial_flat, self.dtype)
        self.shard_sizes = [int(s) for s in sizes]
        self._id = lib.tpumpi_ps_create(
            sizes,
            len(sizes),
            code,
            flat.ctypes.data_as(ctypes.POINTER(ctypes.c_uint8)),
        )
        if self._id < 0:
            raise RuntimeError("native PS creation failed")
        self._freed = False

    def apply(self, shard_idx: int, rule: str, incoming: np.ndarray) -> None:
        if self._freed:
            raise RuntimeError("native shard store freed")
        buf = np.ascontiguousarray(incoming, self.dtype)
        rc = self._lib.tpumpi_ps_apply(
            self._id,
            shard_idx,
            self.RULES[rule],
            buf.ctypes.data_as(ctypes.POINTER(ctypes.c_uint8)),
            buf.size,
        )
        if rc != 0:
            raise RuntimeError(f"native ps_apply failed rc={rc}")

    def read(self, shard_idx: int) -> np.ndarray:
        if self._freed:
            raise RuntimeError("native shard store freed")
        out = np.empty(self.shard_sizes[shard_idx], self.dtype)
        rc = self._lib.tpumpi_ps_read(
            self._id,
            shard_idx,
            out.ctypes.data_as(ctypes.POINTER(ctypes.c_uint8)),
            out.size,
        )
        if rc != 0:
            raise RuntimeError(f"native ps_read failed rc={rc}")
        return out

    def free(self) -> None:
        if not self._freed:
            self._lib.tpumpi_ps_free(self._id)
            self._freed = True


class NativeBarrier:
    """POSIX named-semaphore intra-host barrier (lib/barrier.cpp analog)."""

    def __init__(self, name: str, size: int, owner: bool = True):
        lib = get_lib()
        if lib is None:
            raise RuntimeError("native runtime not available")
        self._lib = lib
        # owner=True unlinks stale semaphores from crashed prior runs;
        # joiner processes pass owner=False and start after the owner.
        self._id = lib.tpumpi_barrier_create(name.encode(), size, int(owner))
        if self._id < 0:
            raise RuntimeError("barrier creation failed")

    def wait(self) -> None:
        rc = self._lib.tpumpi_barrier_wait(self._id)
        if rc != 0:
            raise RuntimeError("barrier wait failed")

    def destroy(self) -> None:
        self._lib.tpumpi_barrier_destroy(self._id)
