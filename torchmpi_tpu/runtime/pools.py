"""Host-side offload thread pools.

Analog of the reference's two dedicated pools — one for collective offload,
one for parameter-server client ops — plus their in-flight caps
(``lib/resources.cpp:399-461``, ``lib/thread_pool-in.h``). When the native
C++ runtime is built, these delegate to its pools; otherwise a
``ThreadPoolExecutor`` provides the same future-based contract.
"""

from __future__ import annotations

import threading
from concurrent.futures import Future, ThreadPoolExecutor
from typing import Callable, Optional

from .. import constants
from ..analysis import lockmon as _lockmon


class _Pool:
    def __init__(self, name: str, size_constant: str):
        self._name = name
        self._size_constant = size_constant
        self._executor: Optional[ThreadPoolExecutor] = None
        self._lock = _lockmon.make_lock("pools.py:_Pool._lock")

    def _get(self) -> ThreadPoolExecutor:
        with self._lock:
            if self._executor is None:
                self._executor = ThreadPoolExecutor(
                    max_workers=constants.get(self._size_constant),
                    thread_name_prefix=self._name,
                )
            return self._executor

    def submit(self, fn: Callable, /, *args, **kwargs) -> Future:
        return self._get().submit(fn, *args, **kwargs)

    def shutdown(self) -> None:
        # Detach under the lock, JOIN outside it: shutdown(wait=True)
        # blocks until every worker drains, and a worker that calls
        # submit() (-> _get -> self._lock) while we hold the lock would
        # deadlock the teardown. Found by tpu-lint TPL102.
        with self._lock:
            executor, self._executor = self._executor, None
        if executor is not None:
            executor.shutdown(wait=True)


collective_pool = _Pool("tm-collective", "collective_thread_pool_size")
parameterserver_pool = _Pool("tm-ps", "parameterserver_thread_pool_size")


def shutdown_all() -> None:
    collective_pool.shutdown()
    parameterserver_pool.shutdown()
