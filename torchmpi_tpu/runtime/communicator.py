"""Hierarchical named communicator stack over JAX device meshes.

TPU-native re-design of the reference's communicator machinery
(``lib/torch_mpi.cpp:38-41,233-306`` and ``lib/resources.cpp:187-350``):

- The reference builds a stack of ``Communicator``s, each created by
  Allgathering a per-rank *key string*, sorting by ``(key, rank)``, and
  ``MPI_Comm_split``-ing ranks with equal keys into *intra* groups; a second
  split links same-intra-rank peers across groups (*cartesian*, requires all
  groups equal-sized — ``resources.cpp:266-280``) or group roots only
  (*tree*) into the *inter* communicator.
- Here, a :class:`Communicator` is a named, ordered grouping of JAX devices.
  "Rank" is a *device rank*: the index of a device in the communicator's
  device list. Key-splitting groups devices (not processes) so a single
  controller can express the same hierarchical topologies the reference builds
  with one process per GPU; under multi-controller JAX the same construction
  runs unchanged over the global device list.
- The intra × inter structure materialises as a 2-D
  :class:`jax.sharding.Mesh` with axes ``('inter', 'intra')`` when cartesian;
  non-cartesian (ragged) splits keep per-group 1-D meshes plus a roots mesh,
  exactly the tree topology of the reference.

The stack itself (push / current level / collective span) mirrors
``mainThreadCommunicators`` + ``setCollectiveSpan``
(``lib/torch_mpi.cpp:38-41,84-90``).
"""

from __future__ import annotations

import threading
from dataclasses import dataclass
from typing import Callable, Dict, List, Optional, Sequence, Tuple, Union

import jax

from ..analysis import lockmon as _lockmon
import numpy as np
from jax.sharding import Mesh

from .. import constants

KeySpec = Union[Sequence[str], Callable[[int], str]]


class CommunicatorError(RuntimeError):
    pass


@dataclass(frozen=True)
class _Member:
    """Per-device placement inside a communicator (one reference rank)."""

    global_rank: int  # rank in the communicator this was split from
    intra_group: int  # which key-group this device landed in
    intra_rank: int  # rank within the key-group
    inter_rank: int  # rank in the inter communicator (-1 if not a member)


class Communicator:
    """One level of the hierarchical communicator stack.

    Construction follows ``resources.cpp:187-350``: stable-sort members by
    ``(key, rank)``, group equal keys into intra groups, mark cartesian iff
    every group has the same size (and cartesian mode is on), and form the
    inter communicator from same-intra-rank peers (cartesian) or group roots
    (tree).
    """

    def __init__(
        self,
        devices: Sequence[jax.Device],
        keys: Optional[Sequence[str]] = None,
        name: str = "global",
        cartesian: Optional[bool] = None,
    ):
        if keys is None:
            keys = [""] * len(devices)
        if len(keys) != len(devices):
            raise CommunicatorError(
                f"got {len(keys)} keys for {len(devices)} devices"
            )
        keys = [str(k) for k in keys]
        for k in keys:
            if len(k.encode()) >= 1024:
                # reference: keys are fixed 1KB buffers (resources.cpp:203-213)
                raise CommunicatorError("communicator key must be < 1024 bytes")
        self.name = name
        self._devices = list(devices)
        self._keys = keys

        # Stable sort by (key, original rank) — resources.cpp:236-244.
        order = sorted(range(len(devices)), key=lambda r: (self._keys[r], r))
        groups: List[List[int]] = []
        group_keys: List[str] = []
        for r in order:
            if not groups or self._keys[r] != group_keys[-1]:
                groups.append([])
                group_keys.append(self._keys[r])
            groups[-1].append(r)
        self._groups = groups
        self._group_keys = group_keys

        sizes = {len(g) for g in groups}
        if cartesian is None:
            cartesian = constants.get("use_cartesian_communicator")
        # cartesian iff requested AND all intra groups equal size
        # (resources.cpp:266-280).
        self.cartesian = bool(cartesian) and len(sizes) == 1

        self._members: List[_Member] = [None] * len(devices)  # type: ignore
        for gi, g in enumerate(groups):
            for ir, r in enumerate(g):
                if self.cartesian:
                    inter_rank = gi  # every device joins an inter ring of peers
                else:
                    inter_rank = gi if ir == 0 else -1  # roots only (tree)
                self._members[r] = _Member(r, gi, ir, inter_rank)

        # Mesh materialisation.
        if self.cartesian:
            arr = np.empty((len(groups), len(groups[0])), dtype=object)
            for gi, g in enumerate(groups):
                for ir, r in enumerate(g):
                    arr[gi, ir] = self._devices[r]
            self.mesh = Mesh(arr, ("inter", "intra"))
            self.intra_meshes = [
                Mesh(arr[gi : gi + 1, :].reshape(-1), ("intra",))
                for gi in range(len(groups))
            ]
            self.inter_meshes = [
                Mesh(arr[:, ir], ("inter",)) for ir in range(len(groups[0]))
            ]
        else:
            self.mesh = None  # ragged: no single dense mesh exists
            self.intra_meshes = [
                Mesh(
                    np.array([self._devices[r] for r in g], dtype=object),
                    ("intra",),
                )
                for g in groups
            ]
            roots = [self._devices[g[0]] for g in groups]
            self.inter_meshes = [Mesh(np.array(roots, dtype=object), ("inter",))]

    # ------------------------------------------------------------------
    # introspection (reference lib/torch_mpi.cpp:105-127,257-280)
    # ------------------------------------------------------------------
    @property
    def devices(self) -> List[jax.Device]:
        return list(self._devices)

    @property
    def size(self) -> int:
        return len(self._devices)

    @property
    def num_intra_groups(self) -> int:
        return len(self._groups)

    def intra_size(self, group: int = 0) -> int:
        return len(self._groups[group])

    @property
    def has_intra_collective(self) -> bool:
        """True when intra groups have more than one member."""
        return any(len(g) > 1 for g in self._groups)

    @property
    def has_inter_collective(self) -> bool:
        return len(self._groups) > 1

    def member(self, rank: int) -> _Member:
        return self._members[rank]

    def intra_rank_of(self, rank: int) -> int:
        return self._members[rank].intra_rank

    def inter_rank_of(self, rank: int) -> int:
        return self._members[rank].inter_rank

    def num_nodes(self) -> int:
        """Distinct host processes spanned (``torch_mpi.cpp:321-350``).

        The reference Allgathers hostnames and counts distinct values; the
        JAX client already knows every device's owning process. Memoized:
        the device list is immutable.
        """
        if not hasattr(self, "_num_nodes"):
            self._num_nodes = len({d.process_index for d in self._devices})
        return self._num_nodes

    def flat_mesh(self, axis_name: str = "mpi") -> Mesh:
        """A 1-D mesh over all member devices in rank order."""
        return Mesh(np.array(self._devices, dtype=object), (axis_name,))

    def describe(self) -> str:
        """Topology string (analog of the startup dump, init.lua:456-459)."""
        lines = [
            f"Communicator '{self.name}': size={self.size} "
            f"groups={self.num_intra_groups} "
            f"{'cartesian' if self.cartesian else 'tree'} "
            f"nodes={self.num_nodes()}"
        ]
        for gi, g in enumerate(self._groups):
            ids = ",".join(str(self._devices[r].id) for r in g)
            lines.append(
                f"  intra[{gi}] key={self._group_keys[gi]!r} devices=[{ids}]"
            )
        return "\n".join(lines)

    def __repr__(self) -> str:
        return (
            f"Communicator({self.name!r}, size={self.size}, "
            f"groups={self.num_intra_groups}, cartesian={self.cartesian})"
        )


class CommunicatorStack:
    """The mutable stack of communicators + collective span.

    Mirrors ``mainThreadCommunicators`` and the ``(begin, end)`` collective
    span cursor (``lib/torch_mpi.cpp:38-41,84-103``): collectives act on the
    communicator at ``current`` (the span end), and hierarchical collectives
    compose levels ``[span_begin, span_end]``.
    """

    def __init__(self, root: Communicator):
        self._stack: List[Communicator] = [root]
        self._span = (0, 0)
        self._lock = _lockmon.make_lock(
            "communicator.py:CommunicatorStack._lock"
        )

    # --- push/set (torch_mpi.cpp:251-268) ---
    def push(self, comm: Communicator) -> int:
        with self._lock:
            self._stack.append(comm)
            level = len(self._stack) - 1
            self._span = (level, level)
            return level

    def set_current(self, level: int) -> None:
        with self._lock:
            if not 0 <= level < len(self._stack):
                raise CommunicatorError(f"no communicator at level {level}")
            self._span = (level, level)

    def set_span(self, begin: int, end: int) -> None:
        with self._lock:
            if not (0 <= begin <= end < len(self._stack)):
                raise CommunicatorError(
                    f"invalid span ({begin}, {end}) for stack depth "
                    f"{len(self._stack)}"
                )
            self._span = (begin, end)

    @property
    def span(self) -> Tuple[int, int]:
        return self._span

    @property
    def current(self) -> Communicator:
        return self._stack[self._span[1]]

    @property
    def depth(self) -> int:
        return len(self._stack)

    def at(self, level: int) -> Communicator:
        return self._stack[level]

    def names(self) -> List[str]:
        return [c.name for c in self._stack]


def split_by_keys(
    parent: Communicator,
    keys: KeySpec,
    name: Optional[str] = None,
    cartesian: Optional[bool] = None,
) -> Communicator:
    """Create a child communicator by key-splitting the parent's devices.

    ``keys`` is either one key string per parent rank or a callable
    ``rank -> key`` (the analog of each reference rank passing its own key to
    ``torchmpi_push_communicator``, ``torch_mpi.cpp:251-255``). Devices with
    equal keys form intra groups of the child.

    The reference pushes splits of the *current intra* communicator
    (``torch_mpi.cpp:75-79``), so a nested split subdivides existing groups
    rather than regrouping across them. We express that by compounding each
    key with the parent's group index: devices in different parent intra
    groups can never share a child group.
    """
    if callable(keys):
        key_list = [str(keys(r)) for r in range(parent.size)]
    else:
        key_list = [str(k) for k in keys]
    if len(key_list) != parent.size:
        raise CommunicatorError(
            f"got {len(key_list)} keys for communicator of size {parent.size}"
        )
    if parent.num_intra_groups > 1:
        key_list = [
            f"{parent.member(r).intra_group:06d}|{k}"
            for r, k in enumerate(key_list)
        ]
    return Communicator(
        parent.devices,
        key_list,
        name=name or f"{parent.name}/split",
        cartesian=cartesian,
    )
