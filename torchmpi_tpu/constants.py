"""Tunable communication constants with freeze semantics.

TPU-native analog of the reference flag system (reference:
``lib/constants.cpp:130-352``, ``lib/constants.h:40-80``): every knob that
shapes collective routing lives here as a mutable module-level value behind
typed ``get_*`` / ``set_*`` accessors, and the whole table can be frozen
(``freeze_constants``) after which every setter raises — mirroring the
reference's ``immutableConstants`` flag which each setter checks
(``lib/constants.cpp:163-168``).

The *meaning* of the knobs is re-grounded in TPU/XLA terms:

- "staged vs direct" cross-node transport (``kUseStagedCollectives``) becomes a
  choice between host-staged DCN transfers and direct ICI/DCN device
  collectives.
- small-message cutoffs switch from the bandwidth-optimised chunked ring to the
  latency path (a single fused XLA collective), the analog of falling back to
  stock MPI below ``kSmallBcastSize``/``kSmallAllreduceSize``
  (``lib/constants.cpp:136-141``).
- chunk min/max sizes bound the per-step message size of the custom ring
  backends (``lib/constants.cpp:142-145``).
- thread-pool sizes control the host-side async offload pools used by the
  parameter server and host collectives (``lib/constants.cpp:152-155``).

When the native runtime extension is available the values are mirrored into it
so C++ code observes the same configuration (see ``runtime/native.py``).
"""

from __future__ import annotations

import threading
from .analysis import lockmon as _lockmon
from dataclasses import dataclass, field, fields
from typing import Any, Callable, Dict, List


class FrozenConstantsError(RuntimeError):
    """Raised when mutating a constant after :func:`freeze_constants`."""


@dataclass
class _Constants:
    # --- transport/routing policy (reference lib/constants.cpp:132-141) ---
    # Stage cross-slice (DCN) traffic through host memory instead of direct
    # device collectives (analog of staged-via-pinned-CPU vs GDR-direct).
    use_staged_collectives: bool = False
    # Compose collectives hierarchically (intra-slice ICI ring/reduce + inter
    # -slice exchange) instead of one flat collective over all devices.
    use_hierarchical_collectives: bool = True
    # Build cartesian communicators (equal-size intra groups linked peer-to-
    # peer) rather than tree communicators (roots only) when splitting.
    use_cartesian_communicator: bool = True
    # Let the schedule compiler race plans SYNTHESIZED from the composition
    # algebra (schedule/algebra.py: recursive halving, torus-axis rings,
    # multi-ring striping) alongside the four hand-written families.
    use_plan_synthesis: bool = False

    # --- small-message latency cutoffs, in ELEMENTS (constants.cpp:136-141) ---
    small_broadcast_size_cpu: int = 1 << 13
    small_allreduce_size_cpu: int = 1 << 16
    small_broadcast_size_tpu: int = 1 << 13
    small_allreduce_size_tpu: int = 1 << 16

    # --- ring chunking, in BYTES (constants.cpp:142-147) ---
    min_buffer_size_cpu: int = 1 << 17
    max_buffer_size_cpu: int = 1 << 20
    min_buffer_size_tpu: int = 1 << 17
    max_buffer_size_tpu: int = 1 << 20
    # tree -> pipelined broadcast switch-over, in bytes (constants.cpp:146-147)
    broadcast_size_tree_based_cpu: int = 1 << 22
    broadcast_size_tree_based_tpu: int = 1 << 22

    # --- in-flight buffering (constants.cpp:149-150, constants.h:77-78) ---
    num_buffers_per_collective_cpu: int = 3
    num_buffers_per_collective_tpu: int = 3
    max_num_buffers_per_collective: int = 16

    # --- host-side async offload pools (constants.cpp:152-155) ---
    collective_thread_pool_size: int = 4
    parameterserver_thread_pool_size: int = 4
    num_async_collectives_in_flight: int = 1 << 20
    num_async_parameterservers_in_flight: int = 1 << 20

    # --- TPU-specific additions (no reference analog; new capability) ---
    # Preferred backend order is handled by the selector; this picks the
    # default custom-ring implementation: 'ppermute' (pure XLA, portable) or
    # 'pallas' (ICI RDMA kernels, TPU only).
    ring_implementation: str = "ppermute"
    # Bound on cached compiled executables per communicator (LRU evicted).
    # The reference frees per-size IPC descriptors between tester sweeps
    # (cache.lua:19-61, tester.lua:131-133); compiled XLA executables are
    # this design's per-size resource, so they get the same lifecycle:
    # bounded while live, freed wholesale by free_collective_resources/stop.
    collective_cache_max_entries: int = 256
    # Deadlock watchdog for host-side waits (parameter-server client ops):
    # seconds before a blocked wait aborts with a diagnostic. 0 disables.
    # Analog of the reference's 10s spin-acquire abort (resources.cpp:
    # 124-133), its only runtime failure detector.
    deadlock_timeout_seconds: int = 0
    # Use the native C++ runtime (csrc/libtpumpi.so) for PS shard storage,
    # handle registry, and plans when it is available; pure-Python fallback
    # otherwise (analog of the reference's optional-backend detection).
    use_native_runtime: bool = True
    # Donate input buffers to eager collectives (strict in-place semantics,
    # like the reference's inplace collective variants). Off by default:
    # JAX users expect value semantics, and donation invalidates reuse of
    # the input array.
    donate_eager_buffers: bool = False

    # --- wire format for the bandwidth-path reductions (EQuARX-style) ---
    # Default on-wire encoding for ring allreduce / reduce-scatter of
    # float32 payloads: 'full' (ship fp32 verbatim), 'bf16' (cast on
    # send, accumulate in f32), or 'int8' (block-quantized with a
    # per-block scale, f32 accumulate, requantize per hop). Opt-in
    # per-call via wire_dtype=; the autotuner measures and persists the
    # winner per (platform, world size).
    wire_dtype: str = "full"
    # Elements per quantization block (one shared scale each) for the
    # ppermute ring. The Pallas kernels always quantize per 128-lane row
    # (the sublane layout IS the block grid there); the default of 128
    # keeps both backends on the same grid.
    wire_quant_block_size: int = 128
    # Per-rank element count below which compressed wire formats are
    # bypassed: small payloads are latency-bound (op_route sends them to
    # the fused XLA path anyway) and the scale overhead erodes the win.
    wire_quant_min_elements: int = 1 << 16
    # Error-feedback compression (1-bit SGD / QSGD lineage behind
    # EQuARX): when a gradient bucket ships on a lossy wire ('int8' /
    # 'bf16'), keep the per-bucket quantization residual in an f32
    # buffer and add it back before the NEXT quantization, so the
    # compression error is fed forward instead of lost — int8 wire
    # stays convergent at scales where plain quantization drifts.
    # Residuals ride the persistent flat buckets (fusion_buffer_bytes),
    # one f32 buffer per bucket.
    wire_error_feedback: bool = False

    # --- parameter-server data path (wire format + overlap) ---
    # On-wire encoding for PS client<->server exchanges (updates, shard
    # fetches): 'full' (fp32 verbatim), 'bf16', or 'int8' (block-
    # quantized, per-block f32 scales on the wire_quant_block_size grid).
    # Server shards stay f32 master copies — decode reconstructs f32
    # before any update rule accumulates, so only the exchange is lossy
    # (the 1-bit-SGD/QSGD framing). The in-process transport honors the
    # same precision (encode->decode roundtrip), keeping single-process
    # convergence evidence faithful to the distributed deployment.
    parameterserver_wire_dtype: str = "full"
    # Chunk size (BYTES) for streaming PS shard payloads: encode of chunk
    # k+1 overlaps wire I/O of chunk k (sendmsg scatter-gather), decode
    # of chunk k overlaps the recv of chunk k+1 (recv_into, preallocated
    # buffers). 0 ships each payload as one monolithic frame.
    # tune_ps_chunk_bytes measures and persists the best value.
    ps_chunk_bytes: int = 1 << 18
    # Client-side prefetch: Update schedules (downpour/EASGD) issue the
    # next center fetch right after consuming the current one, so the
    # receive() at the next integration finds its data already in flight
    # (double-buffered per PS instance). Adds up to one send-interval of
    # staleness to the fetched center when the schedule's own `prefetch`
    # distance is 0 — the classic Downpour overlap-vs-freshness trade.
    ps_prefetch: bool = True
    # Delta-encoded fetches: receive() ships only the since-last-fetch
    # difference against a per-(shard, client) version vector; unchanged
    # shards answer with an empty 'same' frame, changed ones with a
    # delta (which int8-quantizes on far smaller scales than the full
    # tensor). Off by default: costs one shard-sized snapshot per active
    # (shard, client) pair server-side.
    parameterserver_delta_encoding: bool = False

    # --- parameter-server fabric (event-multiplexed listener) ---
    # TCP accept backlog of the PS listener socket. The event loop
    # accepts promptly, so the backlog only has to absorb connect bursts
    # (a fleet of clients starting at once); raise it for synthetic
    # fleets or mass worker restarts.
    ps_listen_backlog: int = 64
    # Admission budget: max decoded frames a listener may have admitted
    # to the apply stage (queued or applying, reply not yet sent) before
    # new UPDATE/TRIGGER frames are answered with a BUSY/retry-after
    # reply instead of being queued. The client channel retries BUSY
    # frames with jittered exponential backoff, so overload degrades to
    # bounded queue depth + retry latency instead of unbounded memory
    # growth. Control frames (barrier/gather) are always admitted.
    # 0 disables admission control.
    ps_pending_frame_budget: int = 4096
    # Base retry-after hint (milliseconds) carried on BUSY replies; the
    # client channel backs off base * 2^attempt with +-50% jitter
    # (capped at 2s) before replaying the rejected frame.
    ps_busy_retry_ms: int = 20
    # Replica-chain length per shard: each shard rank's updates are
    # chain-forwarded to the next (ps_replication - 1) distinct owner
    # processes (ack after chain-apply; fetches served by the head), so
    # one server process death no longer loses PS state — clients fail
    # over to the next live chain member (addresses already known from
    # the bootstrap exchange) and the survivor's per-(shard, client)
    # seq high-water dedups replays. 1 disables replication. Takes
    # effect for instances whose owners span >= 2 processes.
    ps_replication: int = 1
    # Seconds a chain member observed dead (ConnectionError after the
    # channel's replay budget) stays skipped by failover routing before
    # it is re-probed. Expiry bounds the split-brain window a TRANSIENT
    # stall can open: without it one client would route to the replica
    # forever while everyone else still talks to the recovered head.
    # 0 makes dead-marks permanent (until restart).
    ps_dead_peer_retry_s: float = 5.0
    # Read-path routing policy for SHARD/delta fetches against a
    # replicated shard: 'owner' fetches from the chain head (legacy
    # failover walk), 'replica' round-robins fetches across the live
    # chain members (the read-scaling mode: a read-heavy fleet spreads
    # off the owner hot spot), 'adaptive' prefers the owner until it
    # shows backpressure (a recent BUSY or an active dead-mark), then
    # spreads like 'replica' until the pressure clears. Replica-served
    # fetches carry the client's read-session floor (last-ACKED origin
    # seq minus ps_read_staleness); a member whose applied high-water
    # has not covered it answers 'stale:<hw>' and the client falls back
    # to the owner — read-your-writes holds under every policy.
    ps_read_policy: str = "owner"
    # Allowed replica lag for replica-served fetches, in ACKED origin
    # seqs per (instance, rank, client) session. 0 = strict
    # read-your-writes (a replica must have applied every update this
    # client was acked for); N > 0 trades N acked updates of session
    # staleness for replica availability. Pure readers (no acked writes)
    # are served by any live member regardless.
    ps_read_staleness: int = 0
    # Zero-copy shared-memory fetch lane: shard owners publish each
    # applied shard into a per-(instance, rank) shared-memory segment
    # (seqlock-versioned; published BEFORE the update's ack, so owner
    # shm reads are read-your-writes by construction), and co-located
    # clients map the segment and fetch without touching the socket or
    # the event loop. Torn concurrent writes are detected by the seqlock
    # and retried (bounded spins), then the fetch falls back to the
    # socket path. Off by default: costs one shard-sized segment per
    # locally-owned shard.
    ps_shm_lane: bool = False
    # Seqlock read attempts before the shm lane gives up on a torn /
    # unpublished segment and the fetch falls back to the socket path.
    ps_shm_spin_limit: int = 64

    # --- distributed flight recorder / hang watchdog ---
    # Seconds a collective dispatch or PS RPC may stay in flight (or a
    # peer's heartbeat stay stale) before the watchdog dumps a structured
    # hang report (flight recorder + spans + metrics + all-thread stacks)
    # to the telemetry dir. 0 disables. start() arms the watchdog when
    # set; `launch --watchdog-timeout N` arms it per rank via the
    # TORCHMPI_TPU_WATCHDOG env var instead (pre-start() coverage).
    watchdog_timeout_seconds: int = 0
    # Watchdog poll + heartbeat-file period, in seconds.
    watchdog_interval_seconds: int = 1

    # --- live telemetry plane (telemetry/live.py) ---
    # Export period of the per-rank live exporter: every interval one
    # bounded frame (metric-family delta, flight seq high-waters, flight
    # tail) streams to the fleet aggregator (`launch --telemetry-live`).
    # Also sets the aggregator's default staleness bound (3 intervals
    # without a frame = a stale rank).
    telemetry_live_interval_s: float = 1.0
    # Newest flight-recorder entries shipped per frame. Bounds the frame
    # size and the aggregator's per-(rank, comm) rolling window the
    # incremental desync/straggler detectors diff.
    telemetry_live_tail_entries: int = 128
    # Minimum measured dispatch samples per (op, comm, wire, payload
    # bucket, plan) key before schedule.calibrate() counts the key's
    # median as a fit point (a single noisy dispatch must not bend the
    # calibrated cost model).
    plan_calibration_min_samples: int = 3
    # Cap on Perfetto flow arrows (cross-rank causal edges: collective
    # joins and PS span->parent hops) the offline analyzer's merged
    # trace and the aggregator's /criticalpath view emit, earliest
    # first. Bounds merged-trace size on long journals; 0 removes the
    # cap.
    trace_max_flow_events: int = 512

    # --- schedule-compiler cost model (alpha-beta per link class) ---
    # Per-hop launch latency (alpha, µs) and per-MiB transfer time
    # (beta, µs/MiB) for each link class a plan step can ride: 'ici'
    # (intra-island fast fabric), 'dcn' (inter-island), 'host' (host-
    # staged device<->host<->socket hop). Plus a quantize/dequantize
    # throughput term and a per-dispatch overhead. These order candidate
    # plans analytically between measurements; tune_plan measures real
    # candidates and persists the winner per plan-cache key, which
    # overrides the analytic pick.
    plan_cost_alpha_ici_us: float = 1.0
    plan_cost_beta_ici_us_per_mib: float = 10.0
    plan_cost_alpha_dcn_us: float = 25.0
    plan_cost_beta_dcn_us_per_mib: float = 120.0
    plan_cost_alpha_host_us: float = 50.0
    plan_cost_beta_host_us_per_mib: float = 300.0
    plan_cost_quantize_us_per_mib: float = 8.0
    plan_cost_dispatch_us: float = 5.0

    # --- chunk-pipelined plan execution (schedule IR pipeline depth) ---
    # Pipeline depth policy for the ppermute-ring plan families: 0 lets
    # the (calibrated) cost model choose the depth per request among
    # power-of-two candidates; 1 pins pipelining OFF; >1 pins that depth
    # for every eligible plan. tune_pipeline_depth measures the depths
    # on the live communicator and persists the winner here (re-applied
    # by start(), like every tuned knob).
    plan_pipeline_depth: int = 0
    # Largest depth the compiler's candidate enumeration considers
    # (depths are 2, 4, ... up to this cap).
    plan_pipeline_max_depth: int = 8
    # Per-chunk LOGICAL payload floor (bytes): a depth whose chunks
    # would fall below this is not a candidate — small chunks are
    # alpha-dominated and the per-hop launch overhead eats the overlap.
    plan_pipeline_min_chunk_bytes: int = 1 << 18

    # --- gradient-overlap scheduling (bucket flush order) ---
    # How GradientBuckets / FusionBuffer order bucket flushes against
    # the backward pass: 'none' packs everything and dispatches+waits
    # each bucket serially (the all-at-once baseline), 'reverse' keeps
    # the reverse-layer bucket order (bucket 0 = last layers = first
    # gradients ready) and dispatches every bucket async before any
    # wait, so bucket k's wire time overlaps bucket k+1's quantize/pack.
    # The order is stamped into the schedule IR as per-bucket plan
    # priorities; the overlap ledger (telemetry.analyze) measures the
    # realized overlap fraction per scheduled flush.
    overlap_schedule: str = "none"

    # --- streaming input pipeline (torchmpi_tpu.data) ---
    # Bounded depth of the host-side batch ring AND the device prefetch
    # window: producer threads stay at most this many batches ahead of
    # the consumer, and the pipeline keeps the next batch's
    # host-to-device transfer in flight while the current one trains
    # (double-buffered like the PS ps_prefetch path).
    input_prefetch_batches: int = 2
    # Background producer threads assembling host batches. More than one
    # helps when per-batch assembly (decode, augment, memmap reads) is
    # the bottleneck; batches are re-sequenced by a reorder window so
    # delivery order is deterministic regardless of worker count.
    input_workers: int = 1

    # --- live elastic resharding (reshard/ subsystem) ---
    # Chunk size (BYTES) for redistribution transfers: the reshard
    # executor moves state between (world size, sharding) layouts
    # through one reusable scratch buffer of at most this many bytes,
    # so redistribution peak memory is bounded regardless of array size
    # (the "memory-efficient array redistribution" contract; asserted
    # < 2x the largest single shard in tests). 0 disables chunking
    # (one piece per transfer).
    reshard_chunk_bytes: int = 1 << 20
    # Monotone resize-epoch marker: bumped (via constants.set, which
    # advances generation()) every time the world is resized — engine
    # in-place resize, elastic membership change, PS chain re-formation.
    # Caches keyed on world-size-derived state must embed generation()
    # (or re-read this knob) so a resize invalidates them coherently;
    # tpu-lint TPL007 flags caches that do not.
    resize_epoch: int = 0
    # Elastic membership heartbeat period, seconds: members report to
    # the resize coordinator at this cadence, and a member silent for
    # 5 heartbeats is declared dead (epoch bump -> survivors reshard).
    elastic_heartbeat_seconds: float = 0.5
    # Seconds a resize barrier may wait for the slowest member before
    # the coordinator answers it stale (members retry after the next
    # epoch). Bounds how long one wedged survivor can stall a resize;
    # the member's control RPC allows 30s of slack on top. The SAME
    # bound also caps the post-barrier redistribution wait (how long a
    # member waits for its transfer frames), so tune it to the slower
    # of barrier skew and state-transfer time.
    elastic_barrier_timeout_s: float = 300.0

    # --- recovery supervisor (supervise/ subsystem; launch --supervise) ---
    # Consecutive live-aggregation windows a streaming verdict must
    # persist before the supervisor acts on it. 1 acts on the first
    # window (no hysteresis) — a single noisy window can then evict a
    # healthy rank, so keep >= 2 in production.
    supervisor_hysteresis_windows: int = 3
    # Bounded attempts per escalation-ladder rung: after this many
    # failed/uncleared attempts of a verdict's primary action, the
    # supervisor escalates (evict -> checkpoint rollback) or holds.
    supervisor_max_retries: int = 3
    # Jittered exponential backoff between attempts of one rung:
    # base * 2^attempt seconds, +-50% seeded jitter, capped below.
    supervisor_backoff_base_s: float = 1.0
    supervisor_backoff_cap_s: float = 30.0
    # Seconds a quarantined (straggler-evicted) rank stays on the
    # rejoin denylist; grow-back will not re-admit capacity while the
    # denylist covers it.
    supervisor_quarantine_cooldown_s: float = 60.0
    # Opt-in grow-back rung: once the fleet has been clean for the
    # hysteresis window and the world is below its observed high-water
    # (minus quarantined ranks), request an elastic grow. Off by
    # default: shrink-and-continue is the conservative posture.
    supervisor_grow_back: bool = False
    # Consecutive overloaded windows before the scale-up rung fires
    # (the load analog of supervisor_hysteresis_windows; scale-up reacts
    # faster than scale-down on purpose: adding capacity is cheap to
    # undo, shedding users is not).
    supervisor_scale_up_hysteresis: int = 3
    # Consecutive underloaded windows before the scale-down rung
    # retires the highest rank. Keep well above the scale-up hysteresis:
    # asymmetric thresholds are the first line of flap damping.
    supervisor_scale_down_hysteresis: int = 8
    # Minimum seconds between ANY two applied scale actions (up or
    # down): the second line of flap damping. An oscillating arrival
    # trace can satisfy both hysteresis counters in turn; the cooldown
    # bounds the resize rate regardless.
    supervisor_scale_cooldown_s: float = 30.0
    # Hard ceiling on the world size the scale-up rung will request
    # (0 = unbounded). At the ceiling the supervisor holds and the
    # serving tier's brownout ladder degrades instead of collapsing.
    supervisor_scale_max_world: int = 0
    # Floor below which scale-down never shrinks the world.
    supervisor_scale_min_world: int = 1

    # --- fleet simulation (torchmpi_tpu.sim: modeled network, real
    # --- control plane; see README "Fleet simulation") ---
    # Modeled wall-clock period of one training step in the simulated
    # fleet (compute + dispatch; the collective itself is priced by the
    # plan cost model on top).
    sim_step_seconds: float = 0.25
    # Fractional latency jitter the modeled network draws per event
    # (uniform in [1-j, 1+j], from the scenario's seeded RNG): 0 makes
    # every latency exactly the cost-model value.
    sim_jitter_pct: float = 0.05
    # Modeled member<->coordinator control round trip (µs) for joins,
    # barrier arrivals and view fetches in the simulated fleet.
    sim_control_rtt_us: float = 500.0

    # --- serving tier (torchmpi_tpu.serve; README "Serving & autoscaling") ---
    # Per-server cap on queued inference requests before the local
    # brownout ladder engages (distinct from ps_pending_frame_budget,
    # which is the transport-level admission budget shared with
    # training traffic).
    serve_queue_budget: int = 256
    # Service-level objective on per-request latency, milliseconds.
    # Replies slower than this count as SLO breaches; the load verdict's
    # burn rate is breaches/requests per aggregation window.
    serve_slo_ms: float = 50.0
    # Number of QoS levels carried on REQUEST frames (0 = lowest).
    # Brownout shedding drops the lowest level first.
    serve_qos_levels: int = 3
    # Retry-after hint (ms) carried on shed replies, mirroring
    # ps_busy_retry_ms for BUSY frames.
    serve_shed_retry_ms: int = 50
    # Seconds between background weight-refresh fetches (the PR 5
    # delta-fetch path); each fetch that lands a newer version swaps
    # the serving weights atomically.
    serve_refresh_interval_s: float = 2.0
    # Read-routing policy for the background weight refresher's fetches
    # ('' inherits ps_read_policy). Default 'replica': a serving tier's
    # weight refreshes spread across the replica chain instead of
    # competing with training updates at the shard owner; freshness is
    # preserved by the read-session staleness bound + the version-vector
    # swap (a stale-identical fetch is a no-op swap, never a regression).
    serve_refresh_read_policy: str = "replica"
    # Staleness bound: a server whose weights are older than this warns
    # (and the brownout ladder may widen it; see the factor below).
    serve_refresh_staleness_s: float = 30.0
    # Brownout level 2 multiplies both the refresh interval and the
    # staleness bound by this factor: under pressure, serving slightly
    # staler weights beats missing the latency SLO.
    serve_brownout_staleness_factor: float = 4.0
    # Load-verdict thresholds (FleetAggregator): fraction of a window's
    # requests that breached the SLO before the window counts as
    # overloaded...
    serve_slo_burn_threshold: float = 0.1
    # ...or fleet-wide BUSY/shed rejects per second per rank...
    serve_overload_busy_rate: float = 1.0
    # ...or sustained queue growth per second per rank (trend, not
    # level: a full-but-draining queue is not overload).
    serve_queue_growth_per_s: float = 1.0
    # Underload: fleet-wide requests per second per rank below which a
    # window counts toward scale-down (with zero breaches/rejects).
    serve_underload_qps: float = 1.0

    # --- coalescing dispatch (latency path; GC3-style fused plans) ---
    # Capacity of the flat fusion buffer: pending same-(op, dtype, comm,
    # wire) async collectives pack into one contiguous buffer and flush
    # as a SINGLE collective when the per-rank payload reaches this many
    # bytes (or on wait()/sync_all()). 0 disables coalescing entirely —
    # every submit dispatches immediately, the pre-fusion behavior.
    fusion_buffer_bytes: int = 4 << 20
    # Minimum pending tensors for a flush to dispatch FUSED: below this,
    # packing overhead (the gather executable) exceeds the saved
    # dispatches, so the flush falls back to one collective per tensor.
    fusion_min_tensors: int = 2


_frozen = False
_lock = _lockmon.make_lock("constants.py:_lock")
_values = _Constants()
_listeners: List[Callable[[str, Any], None]] = []
# bumped on every successful set(): dispatch fast paths embed the value in
# their memo keys so a constants change invalidates them without a
# subscription per call site
_generation = 0

_FIELD_NAMES = {f.name for f in fields(_Constants)}


def register_listener(fn: Callable[[str, Any], None]) -> None:
    """Register a callback invoked as ``fn(name, value)`` on every set.

    Used by the native runtime bridge to mirror values into C++ (the analog of
    the reference's C getter/setter pairs being the single source of truth).
    Callbacks run outside the module lock so a listener may itself call
    :func:`set` without deadlocking.
    """
    with _lock:
        _listeners.append(fn)
        replay = [(f.name, getattr(_values, f.name)) for f in fields(_Constants)]
    for name, value in replay:
        fn(name, value)


def platform_suffix(platform: str) -> str:
    """Map a jax platform string to the cutoff-constant suffix (the
    reference's CPU/GPU constant pairs; any accelerator takes 'tpu')."""
    return "cpu" if platform == "cpu" else "tpu"


def get(name: str) -> Any:
    if name not in _FIELD_NAMES:
        raise KeyError(f"unknown constant: {name}")
    return getattr(_values, name)


def set(name: str, value: Any) -> None:  # noqa: A001 - parity with C setters
    if name not in _FIELD_NAMES:
        raise KeyError(f"unknown constant: {name}")
    with _lock:
        if _frozen:
            raise FrozenConstantsError(
                f"constants are frozen; cannot set {name!r} (freeze_constants "
                "was called, matching the reference immutableConstants check)"
            )
        current = getattr(_values, name)
        # bool is a subclass of int: require the bool-ness of value and field
        # to match exactly, then ordinary type compatibility.
        if isinstance(current, bool) != isinstance(value, bool) or not isinstance(
            value, type(current)
        ):
            raise TypeError(
                f"constant {name!r} expects {type(current).__name__}, "
                f"got {type(value).__name__}"
            )
        setattr(_values, name, value)
        global _generation
        _generation += 1
        listeners = list(_listeners)
    for fn in listeners:
        fn(name, value)


def generation() -> int:
    """Monotone counter incremented by every :func:`set`. Cache a value
    alongside this to notice any later constants change in O(1)."""
    return _generation


_freeze_listeners: List[Callable[[], None]] = []


def register_freeze_listener(fn: Callable[[], None]) -> None:
    """Called when the table freezes (mirrors the freeze into native code)."""
    with _lock:
        _freeze_listeners.append(fn)
        frozen = _frozen
    if frozen:
        fn()


def freeze_constants() -> None:
    """Permanently freeze the table (reference ``lib/constants.cpp:130,163``)."""
    global _frozen
    with _lock:
        _frozen = True
        listeners = list(_freeze_listeners)
    for fn in listeners:
        fn()


def constants_frozen() -> bool:
    return _frozen


def snapshot() -> Dict[str, Any]:
    """A plain-dict view of every constant (for introspection dumps)."""
    return {f.name: getattr(_values, f.name) for f in fields(_Constants)}


def _reset_for_tests() -> None:
    """Unfreeze and restore defaults. Test-only."""
    global _frozen, _values, _generation
    with _lock:
        _frozen = False
        _values = _Constants()
        _generation += 1
        listeners = list(_listeners)
        replay = [(f.name, getattr(_values, f.name)) for f in fields(_Constants)]
    # unfreeze the native mirror too, else replay below would raise
    try:
        from .runtime import native as _native

        lib = _native._lib
        if lib is not None:
            lib.tpumpi_reset_constants()
    except Exception:
        pass
    for fn in listeners:
        for name, value in replay:
            fn(name, value)


def __getattr__(name: str):
    # Allow `constants.small_allreduce_size_tpu` style reads.
    if name in _FIELD_NAMES:
        return getattr(_values, name)
    raise AttributeError(name)
