"""Pallas ring-attention kernel: double-buffered K/V RDMA ring with the
streaming-softmax merge fused in-kernel.

The sequence-parallel capability extension (SURVEY.md §5: "ICI ring =
natural fit for ring attention") taken down to the transport the custom
ring collectives already use: queries stay resident in VMEM, K/V blocks
rotate around the mesh axis via inter-chip RDMA
(``pltpu.make_async_remote_copy``) into double-buffered VMEM slots, and
each ring step's block attention + flash-style online-softmax merge
(running max ``m``, normalizer ``l``, f32 accumulator ``o``) executes
while the next block is in flight — the same communication/compute
overlap the XLA ``ppermute`` path (``parallel/ring_attention.py``) asks
the compiler for, made explicit.

Transport discipline mirrors ``ring_kernels._ring_phases_kernel`` (the
reference's receive-centric ring, ``lib/detail/collectives_cuda.cpp:
202-388``): a neighbor barrier before the first push, per-step
``copy.wait()`` (send landed + symmetric incoming block arrived), and a
capacity semaphore closing the fast-sender/slow-consumer race — slot
``s%2`` is re-written by the LEFT neighbor at step s+1, so the consumer
signals left after its step-s compute and a sender waits for that signal
before pushing (signals stop two steps early so every semaphore ends the
kernel drained).

Numerics are the flash-attention contract: scores and accumulators in
float32 regardless of input dtype; outputs cast back. Causal masking
uses the static ring schedule — the block visiting at step s originated
on rank ``(r - s) mod p``, so global key positions are known in-kernel.

Differentiation: ``pallas_call`` has no autodiff, so the public
:func:`ring_attention` wraps the kernel in a ``jax.custom_vjp``. The
kernel saves the flash residuals — the output and the global
log-sum-exp — and the backward is the ANALYTIC flash-attention gradient
over a second K/V ring (``_ring_attention_bwd_xla``, ppermute
transport): ``P = exp(S - lse)``, ``dS = P (dP - rowsum(dO∘O))``, with
dK/dV accumulators riding the ring home. No forward recompute on the
gradient path — training with the pallas backend costs one kernel
forward plus one analytic backward, the same step economics as the XLA
ring's autodiff.

With one local chip this path cannot execute on hardware; correctness is
validated in TPU interpret mode on the virtual CPU mesh (p = 2..8,
causal x dtypes, vs gathered-sequence full attention), the same evidence
discipline as the ring collectives.
"""

from __future__ import annotations

import functools
import math
from typing import Optional

import jax
import jax.numpy as jnp
from jax import lax
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from .._compat import (
    dma_device_id,
    interpret_params,
    kernel_flow_control,
    tpu_compiler_params,
)

NEG_INF = -1e30

# VMEM footprint bound for one kernel invocation (q/k/v/o + 2x2 kv slots
# + f32 accumulators must fit well under the ~16MB/core VMEM).
_VMEM_BUDGET_BYTES = 10 * 1024 * 1024


def _flash_merge_cells(
    bh, n, my, src, causal, scale, q_ref, kbuf, vbuf, slot,
    oacc, macc, lacc,
):
    """Merge the K/V block in ``(kbuf, vbuf)[slot]`` (originating on rank
    ``src``) into the running flash accumulators, one 2D MXU step per
    (b, h) cell. Shared by the uni- and bidirectional forward kernels —
    the merge is order-independent, which is what makes the bidir
    schedule valid."""

    def cell(i, _):
        qi = q_ref[i].astype(jnp.float32)  # [n, d]
        ki = kbuf[slot, i].astype(jnp.float32)
        vi = vbuf[slot, i].astype(jnp.float32)
        sij = (
            lax.dot_general(
                qi, ki, (((1,), (1,)), ((), ())),
                preferred_element_type=jnp.float32,
            )
            * scale
        )  # [n(q), n(k)]
        if causal:
            qpos = lax.broadcasted_iota(jnp.int32, (n, n), 0) + my * n
            kpos = lax.broadcasted_iota(jnp.int32, (n, n), 1) + src * n
            sij = jnp.where(qpos >= kpos, sij, NEG_INF)
        mb = jnp.max(sij, axis=1, keepdims=True)  # [n, 1]
        pexp = jnp.exp(sij - mb)
        lb = jnp.sum(pexp, axis=1, keepdims=True)  # [n, 1]
        ob = lax.dot_general(
            pexp, vi, (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32,
        )  # [n, d]
        m_old = macc[i]  # [n, 1]
        m_new = jnp.maximum(m_old, mb)
        alpha = jnp.exp(m_old - m_new)
        beta = jnp.exp(mb - m_new)
        lacc[i] = lacc[i] * alpha + lb * beta
        oacc[i] = oacc[i] * alpha + ob * beta
        macc[i] = m_new
        return 0

    lax.fori_loop(0, bh, cell, 0)


def _ring_attn_kernel(
    p: int,
    axis: str,
    causal: bool,
    scale: float,
    n: int,
    fc: bool,
    my_ref,
    q_ref,
    k_ref,
    v_ref,
    o_ref,
    lse_ref,
    kbuf,
    vbuf,
    oacc,
    macc,
    lacc,
    send_k,
    recv_k,
    send_v,
    recv_v,
    cap_sem,
):
    """One device's program. ``q/k/v/o_ref``: [bh, n, d] VMEM (batch*heads
    flattened to the leading dim; every cell's math is 2D for the MXU).
    ``lse_ref``: [bh, n, 1] f32 log-sum-exp of the global scores — the
    residual the analytic backward needs. ``kbuf/vbuf``: [2, bh, n, d]
    double-buffered ring slots. ``oacc``: [bh, n, d] f32; ``macc/lacc``:
    [bh, n, 1] f32 (2D per cell)."""
    my = my_ref[0]
    right = lax.rem(my + 1, p)
    left = lax.rem(my + p - 1, p)
    bh = q_ref.shape[0]

    oacc[:] = jnp.zeros_like(oacc)
    macc[:] = jnp.full_like(macc, NEG_INF)
    lacc[:] = jnp.zeros_like(lacc)
    kbuf[0] = k_ref[:]
    vbuf[0] = v_ref[:]

    # neighbor barrier: nobody pushes until both neighbors arrived
    # (skipped, with the capacity semaphores, under the legacy lockstep
    # interpreter — _compat.kernel_flow_control)
    if fc:
        barrier = pltpu.get_barrier_semaphore()
        for nbr in (left, right):
            pltpu.semaphore_signal(
                barrier,
                inc=1,
                device_id={axis: nbr},
                device_id_type=pltpu.DeviceIdType.MESH,
            )
        pltpu.semaphore_wait(barrier, 2)

    def block_merge(s: int, slot: int):
        """Attention of resident q against the slot's K/V block, merged
        into the running (o, m, l) — one 2D flash step per (b, h) cell."""
        src = lax.rem(my - s + p, p)  # rank whose shard this block is
        _flash_merge_cells(
            bh, n, my, src, causal, scale, q_ref, kbuf, vbuf, slot,
            oacc, macc, lacc,
        )

    for s in range(p):
        slot = s % 2
        nslot = 1 - slot
        copies = ()
        if s < p - 1:
            # the RIGHT neighbor computes on its slot ``nslot`` at step
            # s-1; wait for its consumed-signal before overwriting
            if fc and s >= 1:
                pltpu.semaphore_wait(cap_sem.at[nslot], 1)
            copies = tuple(
                pltpu.make_async_remote_copy(
                    src_ref=buf.at[slot],
                    dst_ref=buf.at[nslot],
                    send_sem=ssem.at[slot],
                    recv_sem=rsem.at[slot],
                    device_id=dma_device_id(axis, right, not fc),
                    device_id_type=pltpu.DeviceIdType.MESH,
                )
                for buf, ssem, rsem in (
                    (kbuf, send_k, recv_k),
                    (vbuf, send_v, recv_v),
                )
            )
            for c in copies:
                c.start()
        block_merge(s, slot)  # compute overlaps the in-flight DMA
        for c in copies:
            c.wait()  # our send landed + next block fully arrived
        if fc and s < p - 2:
            # tell LEFT our slot is consumed (left overwrites it at its
            # step s+1). Strictly after the wait above: the outgoing DMA
            # reads this slot until the send completes, so an earlier
            # signal would let left clobber bytes still in flight. No
            # signal for the last two steps so cap_sem ends drained.
            pltpu.semaphore_signal(
                cap_sem.at[slot],
                inc=1,
                device_id={axis: left},
                device_id_type=pltpu.DeviceIdType.MESH,
            )

    def finalize(i, _):
        li = jnp.maximum(lacc[i], 1e-30)
        o_ref[i] = (oacc[i] / li).astype(o_ref.dtype)
        lse_ref[i] = macc[i] + jnp.log(li)
        return 0

    lax.fori_loop(0, bh, finalize, 0)


def _sequence_after(x, dep):
    """Give ``x`` a data dependency on ``dep`` so XLA cannot overlap two
    ring kernels that share a ``collective_id`` (and thus barrier/DMA
    semaphore state) — chunked sub-calls must run strictly one after
    another."""
    return lax.optimization_barrier((x, dep))[0]


def _chunk_plan(b, h, fits) -> Optional[tuple]:
    """(b_chunk, h_chunk) making ``fits(b_chunk, h_chunk)`` true, halving
    heads first (keeps batches coherent), or None when even a single
    (batch, head) cell is too large."""
    hh = h
    while hh > 1 and not fits(b, hh):
        hh = (hh + 1) // 2
    bb = b
    while bb > 1 and not fits(bb, hh):
        bb = (bb + 1) // 2
    return (bb, hh) if fits(bb, hh) else None


def _run_chunked(b, h, fits, sub, concat_axes, cell_bytes, budget, what):
    """Shared dispatch for VMEM auto-chunking (forward AND backward use
    it — the plan heuristic, sequencing scheme, and error text must never
    diverge between them). ``sub(bi, bb, hi, hh, prev)`` runs one chunk
    (applying its own slicing and the ``prev`` sequencing dependency) and
    returns a tuple of outputs; chunks are concatenated along
    ``concat_axes`` over heads, then axis 0 over batches."""
    plan = _chunk_plan(b, h, fits)
    if plan is None:
        raise ValueError(
            f"one {what} (batch, head) cell of {cell_bytes} B exceeds "
            f"the VMEM envelope {budget} B; shard the sequence further "
            "or use the XLA ppermute backend"
        )
    bb, hh = plan
    prev = None
    out_rows: Optional[list] = None
    for bi in range(0, b, bb):
        row: Optional[list] = None
        for hi in range(0, h, hh):
            outs = sub(bi, bb, hi, hh, prev)
            prev = outs[0]
            if row is None:
                row = [[] for _ in outs]
            for acc, t in zip(row, outs):
                acc.append(t)
        merged = [
            jnp.concatenate(acc, axis=ax)
            for acc, ax in zip(row, concat_axes)
        ]
        if out_rows is None:
            out_rows = [[] for _ in merged]
        for acc, t in zip(out_rows, merged):
            acc.append(t)
    return tuple(jnp.concatenate(acc, axis=0) for acc in out_rows)


def _ring_attention_fwd_xla(q, k, v, axis, causal, p, return_lse):
    """ppermute-ring forward with the lse residual — the stand-in the
    kernel wrappers use when the LEGACY pallas interpreter cannot run
    remote DMA on a multi-axis mesh (``ring_kernels._legacy_multiaxis``).
    Same streaming-softmax math as the kernels; XLA transport."""
    b, n, h, d = q.shape
    r = lax.axis_index(axis)
    perm = [(i, (i + 1) % p) for i in range(p)]
    scale = 1.0 / math.sqrt(d)
    q_pos = r * n + jnp.arange(n)
    qf = q.astype(jnp.float32)

    def step(s, carry):
        o, m, l, kb, vb = carry
        src = lax.rem(r - s + p, p)
        k_pos = src * n + jnp.arange(n)
        sij = (
            jnp.einsum("bqhd,bkhd->bhqk", qf, kb.astype(jnp.float32))
            * scale
        )
        if causal:
            mask = q_pos[:, None] >= k_pos[None, :]
            sij = jnp.where(mask[None, None], sij, NEG_INF)
        mb = sij.max(-1)  # [b, h, q]
        pexp = jnp.exp(sij - mb[..., None])
        lb = pexp.sum(-1)
        ob = jnp.einsum("bhqk,bkhd->bqhd", pexp, vb.astype(jnp.float32))
        m_new = jnp.maximum(m, mb)
        alpha = jnp.exp(m - m_new)
        beta = jnp.exp(mb - m_new)
        l_new = l * alpha + lb * beta
        o_new = (
            o * alpha.transpose(0, 2, 1)[..., None]
            + ob * beta.transpose(0, 2, 1)[..., None]
        )
        return (
            o_new, m_new, l_new,
            lax.ppermute(kb, axis, perm), lax.ppermute(vb, axis, perm),
        )

    o0 = jnp.zeros((b, n, h, d), jnp.float32)
    m0 = jnp.full((b, h, n), NEG_INF, jnp.float32)
    l0 = jnp.zeros((b, h, n), jnp.float32)
    o, m, l, _, _ = lax.fori_loop(0, p, step, (o0, m0, l0, k, v))
    l = jnp.maximum(l, 1e-30)
    out = (o / l.transpose(0, 2, 1)[..., None]).astype(q.dtype)
    if return_lse:
        return out, m + jnp.log(l)
    return out


def _make_fwd(kernel_fn, vmem_bytes_fn, scratch_fn, collective_id, what):
    """Build a forward-ring entry point: ONE wrapper body (p == 1
    degenerate, batch/head auto-chunking, cell layout, pallas_call
    scaffolding) shared by the uni- and bidirectional kernels, so the
    chunk-plan/sequencing discipline can never diverge between them.
    ``scratch_fn(bh, n, d, k_dtype, v_dtype)`` returns the kernel's
    scratch list."""

    def fwd(
        q,
        k,
        v,
        axis: str = "sp",
        causal: bool = False,
        axis_size: Optional[int] = None,
        interpret: bool = False,
        return_lse: bool = False,
        vmem_budget_bytes: Optional[int] = None,
    ):
        p = axis_size or lax.axis_size(axis)
        b, n, h, d = q.shape
        if p == 1:
            if return_lse:
                # one score matrix serves both the output and the residual
                return _full_attention_with_lse(q, k, v, causal)
            from ..parallel.ring_attention import full_self_attention

            return full_self_attention(q, k, v, causal=causal)
        from .ring_kernels import _legacy_multiaxis

        if _legacy_multiaxis(interpret):
            return _ring_attention_fwd_xla(
                q, k, v, axis, causal, p, return_lse
            )
        budget = vmem_budget_bytes or _VMEM_BUDGET_BYTES
        if vmem_bytes_fn(q.shape, q.dtype) > budget:
            def sub(bi, bb, hi, hh, prev):
                qs = q[bi:bi + bb, :, hi:hi + hh]
                if prev is not None:
                    qs = _sequence_after(qs, prev)
                return fwd(
                    qs,
                    k[bi:bi + bb, :, hi:hi + hh],
                    v[bi:bi + bb, :, hi:hi + hh],
                    axis=axis, causal=causal, axis_size=axis_size,
                    interpret=interpret, return_lse=True,
                    vmem_budget_bytes=budget,
                )

            out, lse = _run_chunked(
                b, h,
                lambda bb, hh: vmem_bytes_fn(
                    (bb, n, hh, d), q.dtype
                ) <= budget,
                sub, (2, 1),
                vmem_bytes_fn((1, n, 1, d), q.dtype), budget, what,
            )
            return (out, lse) if return_lse else out
        bh = b * h
        # [b, n, h, d] -> [bh, n, d]: per-cell 2D math on the MXU
        to_cells = lambda t: t.transpose(0, 2, 1, 3).reshape(bh, n, d)  # noqa: E731
        scale = 1.0 / math.sqrt(d)
        my = lax.axis_index(axis).astype(jnp.int32).reshape(1)
        kernel = functools.partial(
            kernel_fn, p, axis, causal, scale, n,
            kernel_flow_control(interpret),
        )
        out, lse = pl.pallas_call(
            kernel,
            out_shape=(
                jax.ShapeDtypeStruct((bh, n, d), q.dtype),
                jax.ShapeDtypeStruct((bh, n, 1), jnp.float32),
            ),
            in_specs=[
                pl.BlockSpec(memory_space=pltpu.SMEM),
                pl.BlockSpec(memory_space=pltpu.VMEM),
                pl.BlockSpec(memory_space=pltpu.VMEM),
                pl.BlockSpec(memory_space=pltpu.VMEM),
            ],
            out_specs=(
                pl.BlockSpec(memory_space=pltpu.VMEM),
                pl.BlockSpec(memory_space=pltpu.VMEM),
            ),
            scratch_shapes=scratch_fn(bh, n, d, k.dtype, v.dtype),
            compiler_params=tpu_compiler_params(
                collective_id=collective_id
            ),
            interpret=interpret_params() if interpret else False,
        )(my, to_cells(q), to_cells(k), to_cells(v))
        out = out.reshape(b, h, n, d).transpose(0, 2, 1, 3)
        if return_lse:
            return out, lse.reshape(b, h, n)
        return out

    return fwd


def _uni_scratch(bh, n, d, k_dtype, v_dtype):
    return [
        pltpu.VMEM((2, bh, n, d), k_dtype),
        pltpu.VMEM((2, bh, n, d), v_dtype),
        pltpu.VMEM((bh, n, d), jnp.float32),
        pltpu.VMEM((bh, n, 1), jnp.float32),
        pltpu.VMEM((bh, n, 1), jnp.float32),
        pltpu.SemaphoreType.DMA((2,)),
        pltpu.SemaphoreType.DMA((2,)),
        pltpu.SemaphoreType.DMA((2,)),
        pltpu.SemaphoreType.DMA((2,)),
        pltpu.SemaphoreType.REGULAR((2,)),
    ]


def ring_attention_vmem_bytes(local_shape, dtype) -> int:
    """Kernel working-set estimate for the given local q shape: q/k/v/o
    plus the 2x2 double-buffered slots in ``dtype``, the f32 accumulator,
    and the [.., n, 1] m/l columns."""
    b, n, h, d = local_shape
    cells = b * h * n * d
    itemsize = jnp.dtype(dtype).itemsize
    return cells * (8 * itemsize + 4) + 2 * 4 * b * h * n


ring_attention_pallas = _make_fwd(
    _ring_attn_kernel, ring_attention_vmem_bytes, _uni_scratch, 11,
    "ring-attention",
)
ring_attention_pallas.__doc__ = """Forward ring attention via the RDMA
kernel. Call inside ``shard_map``; q/k/v are the local shards
``[b, n_local, h, d]``. Not differentiable — training uses
:func:`ring_attention` (custom VJP). ``return_lse=True`` additionally
returns the global log-sum-exp ``[b, h, n_local]`` f32 (the backward's
residual).

A working set over the VMEM envelope is AUTO-CHUNKED over batch and
heads (attention is independent across both): each chunk runs its own
full K/V ring, so total wire traffic is unchanged — every head's K/V
still crosses each link exactly once per step — while per-call VMEM
fits. Only a single (batch, head) cell too large for the envelope
raises; sequence length then needs more sp shards or the XLA backend."""


def _l_hop_needed(s, p: int, nL: int):
    """Whether the bidirectional kernel's L-chain hop carrying UNWRAPPED
    source index ``s`` (= sender rank + step; >= p once the block crossed
    rank 0) does any work under causal masking.

    Under causal, a block from source rank ``src`` is merged only by
    receivers that see it as a PAST rank — on the L chain (blocks moving
    toward lower ranks) that happens only after the block wraps past
    rank 0. Pre-wrap hops are pure transport toward the wrap point. So
    the hop matters iff the block already wrapped (``s >= p``) or still
    can within the chain's ``nL`` distances (``s < nL``); otherwise the
    block is strictly-future for every receiver it can reach, all its
    merges are beta=0, and the send is wire spent on provably-zero
    contributions (ADVICE r5 ``ops/ring_attention_kernel.py:520``).

    Pairing invariant (what keeps the semaphores drained): sender rank
    ``r+1`` and receiver ``r`` evaluate the SAME unwrapped index for one
    hop — send gate ``_l_hop_needed((r+1) + t)`` vs recv gate
    ``_l_hop_needed(r + 1 + t)`` — and the capacity signal at ``(r, t)``
    matches the upstream's wait before its ``t+1`` send (both index
    ``r + t + 2``). ``tests/test_fusion.py`` checks the pairing
    exhaustively over p/t/rank."""
    return (s >= p) | (s < nL)


def _ring_attn_bidir_kernel(
    p: int,
    axis: str,
    causal: bool,
    scale: float,
    n: int,
    fc: bool,
    my_ref,
    q_ref,
    k_ref,
    v_ref,
    o_ref,
    lse_ref,
    kbufR,
    vbufR,
    kbufL,
    vbufL,
    oacc,
    macc,
    lacc,
    sendR_k,
    recvR_k,
    sendR_v,
    recvR_v,
    sendL_k,
    recvL_k,
    sendL_v,
    recvL_v,
    capR,
    capL,
):
    """Bidirectional forward: TWO independent K/V chains rotate in
    opposite ICI directions (the torus has a link each way), so the ring
    finishes in ceil((p-1)/2) + 1 steps instead of p — total wire bytes
    unchanged, wall-clock halved when both link directions run at full
    rate (the same trade as ``ring_allreduce_bidir_pallas``). The
    streaming-softmax merge is order-independent, so visiting sources as
    {my, my±1, my±2, ...} instead of {my, my-1, my-2, ...} is exact.

    Per loop step t (t also = block distance): the R chain's slot holds
    the block from rank (my - t), the L chain's from (my + t). The R
    chain delivers distances 1..ceil((p-1)/2); the L chain distances
    1..floor((p-1)/2) — at t = 0 both slots hold the LOCAL block and it
    is merged exactly once. Each chain runs the unidirectional kernel's
    transport discipline (prefetch-send, per-step wait, capacity
    semaphores toward its upstream neighbor) with its own buffers and
    semaphores."""
    my = my_ref[0]
    right = lax.rem(my + 1, p)
    left = lax.rem(my + p - 1, p)
    bh = q_ref.shape[0]

    oacc[:] = jnp.zeros_like(oacc)
    macc[:] = jnp.full_like(macc, NEG_INF)
    lacc[:] = jnp.zeros_like(lacc)
    kbufR[0] = k_ref[:]
    vbufR[0] = v_ref[:]
    kbufL[0] = k_ref[:]
    vbufL[0] = v_ref[:]

    if fc:
        barrier = pltpu.get_barrier_semaphore()
        for nbr in (left, right):
            pltpu.semaphore_signal(
                barrier,
                inc=1,
                device_id={axis: nbr},
                device_id_type=pltpu.DeviceIdType.MESH,
            )
        pltpu.semaphore_wait(barrier, 2)

    # distances delivered per chain; nR >= nL, nR + nL = p - 1
    nR = (p - 1 + 1) // 2
    nL = (p - 1) // 2

    def l_needed(s):
        return _l_hop_needed(s, p, nL)

    chains = (
        # (buffers, sems, cap, dst neighbor, cap-signal target,
        #  #distances, is_l_chain)
        ((kbufR, vbufR), (sendR_k, recvR_k, sendR_v, recvR_v), capR,
         right, left, nR, False),
        ((kbufL, vbufL), (sendL_k, recvL_k, sendL_v, recvL_v), capL,
         left, right, nL, True),
    )

    for t in range(nR + 1):
        slot = t % 2
        nslot = 1 - slot
        all_copies = []
        for (bufs, sems, cap, dst, cap_to, ndist, is_l) in chains:
            if t < ndist:  # this chain still has a farther block to push
                # causal L-chain hops that can never contribute are
                # skipped — but only where the flow-control machinery
                # runs (hardware / modern interpreter): the LEGACY
                # interpreter cannot discharge DMAs under
                # device-divergent pl.when (each remote copy lowers to
                # an all_gather that deadlocks inside a divergent cond,
                # see ring_kernels._legacy_interpret), so it keeps the
                # unconditional schedule (its transport is simulated;
                # the merge skip below still carries the numerics).
                gated = causal and is_l and fc
                # gates agree pairwise across neighbors: my send at t is
                # my-1's recv at t (both l_needed(my + t) from the
                # sender's frame); my cap signal at t enables my+1's
                # send at t+1 (both l_needed(my + t + 2))
                p_out = l_needed(my + t) if gated else None
                if fc and t >= 1:
                    if p_out is None:
                        pltpu.semaphore_wait(cap.at[nslot], 1)
                    else:
                        @pl.when(p_out)
                        def _():
                            pltpu.semaphore_wait(cap.at[nslot], 1)
                sk, rk, sv, rv = sems
                copies = tuple(
                    pltpu.make_async_remote_copy(
                        src_ref=buf.at[slot],
                        dst_ref=buf.at[nslot],
                        send_sem=ssem.at[slot],
                        recv_sem=rsem.at[slot],
                        device_id=dma_device_id(axis, dst, not fc),
                        device_id_type=pltpu.DeviceIdType.MESH,
                    )
                    for buf, ssem, rsem in (
                        (bufs[0], sk, rk),
                        (bufs[1], sv, rv),
                    )
                )
                if p_out is None:
                    for c in copies:
                        c.start()
                else:
                    @pl.when(p_out)
                    def _():
                        for c in copies:
                            c.start()
                all_copies.append((copies, gated, cap, cap_to, ndist))
        # merge this step's visiting block(s); t = 0 merges the local
        # block exactly once (both chains hold it)
        if t == 0:
            _flash_merge_cells(
                bh, n, my, my, causal, scale, q_ref, kbufR, vbufR, 0,
                oacc, macc, lacc,
            )
        else:
            # the R chain reaches every loop step (nR >= nL); the L
            # chain stops one distance short when p is even
            _flash_merge_cells(
                bh, n, my, lax.rem(my - t + p, p), causal, scale,
                q_ref, kbufR, vbufR, slot, oacc, macc, lacc,
            )
            if t <= nL:
                if causal:
                    # The L chain's block at step t originated on rank
                    # my + t. Without wraparound (my + t < p) that rank
                    # is strictly FUTURE, so every (q, k) pair is masked
                    # and the merge is a numerical no-op (its beta
                    # underflows to exactly 0) — skip the matmuls. Only
                    # wrapped sources (my + t - p < my: past blocks)
                    # contribute.
                    @pl.when(my + t >= p)
                    def _():
                        _flash_merge_cells(
                            bh, n, my, lax.rem(my + t, p), causal, scale,
                            q_ref, kbufL, vbufL, slot, oacc, macc, lacc,
                        )
                else:
                    _flash_merge_cells(
                        bh, n, my, lax.rem(my + t, p), causal, scale,
                        q_ref, kbufL, vbufL, slot, oacc, macc, lacc,
                    )
        for copies, gated, cap, cap_to, ndist in all_copies:
            if not gated:
                for c in copies:
                    c.wait()
            else:
                # decoupled waits (the causal-gated L chain): my own send
                # completed iff I sent (l_needed(my + t)); the incoming
                # block from my+1 landed iff IT sent, which from my frame
                # is l_needed(my + t + 1). The copy descriptor's recv
                # semaphore is the SPMD-symmetric one the incoming copy
                # signals, so wait_recv on it observes the inbound DMA.
                @pl.when(l_needed(my + t))
                def _():
                    for c in copies:
                        c.wait_send()

                @pl.when(l_needed(my + t + 1))
                def _():
                    for c in copies:
                        c.wait_recv()
            # slot consumed + our outgoing read landed: upstream may
            # overwrite it at its next send. Its sends stop at t = ndist-1,
            # so signals stop one step earlier (semaphores end drained).
            if fc and t < ndist - 1:
                if not gated:
                    pltpu.semaphore_signal(
                        cap.at[slot],
                        inc=1,
                        device_id={axis: cap_to},
                        device_id_type=pltpu.DeviceIdType.MESH,
                    )
                else:
                    # pairs with my+1's cap wait before its t+1 send,
                    # which carries source my + t + 2 — same gate
                    @pl.when(l_needed(my + t + 2))
                    def _():
                        pltpu.semaphore_signal(
                            cap.at[slot],
                            inc=1,
                            device_id={axis: cap_to},
                            device_id_type=pltpu.DeviceIdType.MESH,
                        )

    def finalize(i, _):
        li = jnp.maximum(lacc[i], 1e-30)
        o_ref[i] = (oacc[i] / li).astype(o_ref.dtype)
        lse_ref[i] = macc[i] + jnp.log(li)
        return 0

    lax.fori_loop(0, bh, finalize, 0)


def ring_attention_bidir_vmem_bytes(local_shape, dtype) -> int:
    """Bidir working set: the unidirectional envelope plus the second
    chain's 2x2 K/V slots."""
    b, n, h, d = local_shape
    cells = b * h * n * d
    itemsize = jnp.dtype(dtype).itemsize
    return cells * (12 * itemsize + 4) + 2 * 4 * b * h * n


def _bidir_scratch(bh, n, d, k_dtype, v_dtype):
    return [
        pltpu.VMEM((2, bh, n, d), k_dtype),
        pltpu.VMEM((2, bh, n, d), v_dtype),
        pltpu.VMEM((2, bh, n, d), k_dtype),
        pltpu.VMEM((2, bh, n, d), v_dtype),
        pltpu.VMEM((bh, n, d), jnp.float32),
        pltpu.VMEM((bh, n, 1), jnp.float32),
        pltpu.VMEM((bh, n, 1), jnp.float32),
        pltpu.SemaphoreType.DMA((2,)),
        pltpu.SemaphoreType.DMA((2,)),
        pltpu.SemaphoreType.DMA((2,)),
        pltpu.SemaphoreType.DMA((2,)),
        pltpu.SemaphoreType.DMA((2,)),
        pltpu.SemaphoreType.DMA((2,)),
        pltpu.SemaphoreType.DMA((2,)),
        pltpu.SemaphoreType.DMA((2,)),
        pltpu.SemaphoreType.REGULAR((2,)),
        pltpu.SemaphoreType.REGULAR((2,)),
    ]


ring_attention_bidir_pallas = _make_fwd(
    _ring_attn_bidir_kernel, ring_attention_bidir_vmem_bytes,
    _bidir_scratch, 13, "bidirectional ring-attention",
)
ring_attention_bidir_pallas.__doc__ = """Forward ring attention with BOTH
ICI directions carrying K/V chains (~half the steps of
:func:`ring_attention_pallas`). Same call contract, residuals, and
batch/head auto-chunking.

Causal caveat: under ``causal=True`` the L chain mostly carries blocks
from strictly-future ranks (source ``my + t`` with no wraparound), whose
scores are fully masked. The kernel SKIPS both the merge compute for
those blocks AND — on hardware / the modern interpreter — their K/V
sends: an L-chain hop runs only when its block already wrapped past
rank 0 or still can within the chain (:func:`_l_hop_needed`), with
send / recv / capacity-semaphore gates matched pairwise across
neighbors so the transport discipline stays deadlock-free. Wire bytes
saved, not just FLOPs (ADVICE r5). The LEGACY pallas interpreter keeps
the unconditional schedule (conditional DMAs cannot discharge there;
its transport is simulated anyway). Even so, causal workloads get less
than the full ~2x: the R chain carries ``ceil((p-1)/2)`` useful blocks
regardless — measure (``utils.autotune``) rather than assume."""


def _full_attention_with_lse(q, k, v, causal):
    """Single-shard attention returning ``(out, lse[b, h, n])`` from ONE
    score matrix — the p == 1 degenerate of the kernel + its residual."""
    n = q.shape[1]
    s = jnp.einsum(
        "bqhd,bkhd->bhqk",
        q.astype(jnp.float32),
        k.astype(jnp.float32),
    ) / math.sqrt(q.shape[-1])
    if causal:
        mask = jnp.tril(jnp.ones((n, n), bool))
        s = jnp.where(mask[None, None], s, NEG_INF)
    lse = jax.nn.logsumexp(s, axis=-1)
    w = jnp.exp(s - lse[..., None])
    out = jnp.einsum("bhqk,bkhd->bqhd", w, v.astype(jnp.float32))
    return out.astype(q.dtype), lse


def _ring_attention_bwd_xla(q, k, v, o, lse, do, axis, causal, p):
    """Analytic flash-attention backward over a second K/V ring (XLA
    ppermute transport). The forward's residuals make recomputing the
    forward unnecessary: per visiting block, the true probabilities are
    ``P = exp(S - lse)`` and ``dS = P * (dP - D)`` with
    ``D = rowsum(dO * O)``; dK/dV accumulators ride the ring WITH their
    blocks and are home after the p-th rotation. All accumulation in f32.
    """
    b, n, h, d = q.shape
    r = lax.axis_index(axis)
    perm = [(i, (i + 1) % p) for i in range(p)]
    scale = 1.0 / math.sqrt(d)

    qf = q.astype(jnp.float32)
    dof = do.astype(jnp.float32)
    # D_i = sum_d dO * O  -> [b, h, n]
    D = jnp.einsum("bqhd,bqhd->bhq", dof, o.astype(jnp.float32))
    q_pos = r * n + jnp.arange(n)

    def step(s, carry):
        dq, kb, vb, dkb, dvb = carry
        src = (r - s) % p
        k_pos = src * n + jnp.arange(n)
        sij = jnp.einsum("bqhd,bkhd->bhqk", qf, kb) * scale
        if causal:
            mask = q_pos[:, None] >= k_pos[None, :]
            sij = jnp.where(mask[None, None], sij, NEG_INF)
        pij = jnp.exp(sij - lse[..., None])  # true softmax probs
        dvb = dvb + jnp.einsum("bhqk,bqhd->bkhd", pij, dof)
        dp = jnp.einsum("bqhd,bkhd->bhqk", dof, vb)
        ds = pij * (dp - D[..., None])
        dq = dq + jnp.einsum("bhqk,bkhd->bqhd", ds, kb) * scale
        dkb = dkb + jnp.einsum("bhqk,bqhd->bkhd", ds, qf) * scale
        rot = lambda t: lax.ppermute(t, axis, perm)  # noqa: E731
        return dq, rot(kb), rot(vb), rot(dkb), rot(dvb)

    zeros = jnp.zeros((b, n, h, d), jnp.float32)
    dq, _, _, dk, dv = lax.fori_loop(
        0,
        p,
        step,
        (zeros, k.astype(jnp.float32), v.astype(jnp.float32), zeros, zeros),
    )
    # p rotations = identity: dk/dv finished the loop back on the rank
    # that owns their block
    return dq.astype(q.dtype), dk.astype(k.dtype), dv.astype(v.dtype)


def _ring_attn_bwd_kernel(
    p: int,
    axis: str,
    causal: bool,
    scale: float,
    n: int,
    fc: bool,
    my_ref,
    q_ref,
    o_ref,
    do_ref,
    lse_ref,
    k_ref,
    v_ref,
    dq_ref,
    dk_ref,
    dv_ref,
    kbuf,
    vbuf,
    dkbuf,
    dvbuf,
    dqacc,
    dacc,
    send_k,
    recv_k,
    send_v,
    recv_v,
    send_dk,
    recv_dk,
    send_dv,
    recv_dv,
    cap_sem,
):
    """Backward ring program: the K/V blocks make a SECOND trip around the
    ring, this time carrying their dK/dV accumulators with them (the
    fused-transport philosophy of ``collectives_cuda.cpp:202-388``): each
    rank computes the analytic flash gradients against the visiting block
    from the saved (o, lse) residuals — no forward recompute — adds its
    contribution to the riding accumulators, THEN forwards the 4-tensor
    payload. p sends total, so the last hop is the homecoming: every
    block's finished dK/dV lands back on its owner.

    Transport discipline differs from the forward in one way: the forward
    pushes its (immutable) block while computing on it; here the payload
    is MUTATED by the compute, so the send follows the compute and the
    overlap is between this step's compute and the NEXT block's in-flight
    arrival. Capacity semaphores close the same fast-sender race: a send
    into the right neighbor's slot waits for that slot's consumed-signal.
    """
    my = my_ref[0]
    right = lax.rem(my + 1, p)
    left = lax.rem(my + p - 1, p)
    bh = q_ref.shape[0]

    kbuf[0] = k_ref[:]
    vbuf[0] = v_ref[:]
    dkbuf[0] = jnp.zeros_like(dkbuf[0])
    dvbuf[0] = jnp.zeros_like(dvbuf[0])
    dqacc[:] = jnp.zeros_like(dqacc)

    def dinit(i, _):
        # D = rowsum(dO ∘ O): the softmax-jacobian correction, f32
        dacc[i] = jnp.sum(
            do_ref[i].astype(jnp.float32) * o_ref[i].astype(jnp.float32),
            axis=1,
            keepdims=True,
        )
        return 0

    lax.fori_loop(0, bh, dinit, 0)

    if fc:
        barrier = pltpu.get_barrier_semaphore()
        for nbr in (left, right):
            pltpu.semaphore_signal(
                barrier,
                inc=1,
                device_id={axis: nbr},
                device_id_type=pltpu.DeviceIdType.MESH,
            )
        pltpu.semaphore_wait(barrier, 2)

    def block_grad(s: int, slot: int):
        """Analytic flash gradients of the visiting block, accumulated
        into dqacc (stays) and dkbuf/dvbuf[slot] (rides onward)."""
        src = lax.rem(my - s + p, p)

        def cell(i, _):
            qi = q_ref[i].astype(jnp.float32)  # [n, d]
            doi = do_ref[i].astype(jnp.float32)
            ki = kbuf[slot, i].astype(jnp.float32)
            vi = vbuf[slot, i].astype(jnp.float32)
            sij = (
                lax.dot_general(
                    qi, ki, (((1,), (1,)), ((), ())),
                    preferred_element_type=jnp.float32,
                )
                * scale
            )  # [n(q), n(k)]
            if causal:
                qpos = lax.broadcasted_iota(jnp.int32, (n, n), 0) + my * n
                kpos = lax.broadcasted_iota(jnp.int32, (n, n), 1) + src * n
                sij = jnp.where(qpos >= kpos, sij, NEG_INF)
            pij = jnp.exp(sij - lse_ref[i])  # true probs ([n,1] lse bcasts)
            dvbuf[slot, i] += lax.dot_general(
                pij, doi, (((0,), (0,)), ((), ())),
                preferred_element_type=jnp.float32,
            )  # [n(k), d]
            dp = lax.dot_general(
                doi, vi, (((1,), (1,)), ((), ())),
                preferred_element_type=jnp.float32,
            )  # [n(q), n(k)]
            ds = pij * (dp - dacc[i])
            dqacc[i] += (
                lax.dot_general(
                    ds, ki, (((1,), (0,)), ((), ())),
                    preferred_element_type=jnp.float32,
                )
                * scale
            )
            dkbuf[slot, i] += (
                lax.dot_general(
                    ds, qi, (((0,), (0,)), ((), ())),
                    preferred_element_type=jnp.float32,
                )
                * scale
            )
            return 0

        lax.fori_loop(0, bh, cell, 0)

    for s in range(p):
        slot = s % 2
        nslot = 1 - slot
        block_grad(s, slot)
        # forward the mutated payload; the right neighbor's slot must be
        # consumed (its step s-1 compute done AND its own send of that
        # slot landed — it signals after its c.wait())
        if fc and s >= 1:
            pltpu.semaphore_wait(cap_sem.at[nslot], 1)
        copies = tuple(
            pltpu.make_async_remote_copy(
                src_ref=buf.at[slot],
                dst_ref=buf.at[nslot],
                send_sem=ssem.at[slot],
                recv_sem=rsem.at[slot],
                device_id=dma_device_id(axis, right, not fc),
                device_id_type=pltpu.DeviceIdType.MESH,
            )
            for buf, ssem, rsem in (
                (kbuf, send_k, recv_k),
                (vbuf, send_v, recv_v),
                (dkbuf, send_dk, recv_dk),
                (dvbuf, send_dv, recv_dv),
            )
        )
        for c in copies:
            c.start()
        for c in copies:
            c.wait()  # our payload landed + next block fully arrived
        if fc and s < p - 1:
            # my slot is consumed and my outgoing read of it is complete:
            # left may overwrite it at its step s+1. No signal after the
            # last step so every semaphore ends the kernel drained.
            pltpu.semaphore_signal(
                cap_sem.at[slot],
                inc=1,
                device_id={axis: left},
                device_id_type=pltpu.DeviceIdType.MESH,
            )

    home = p % 2  # p sends: each block's accumulators are back home

    def fin(i, _):
        dq_ref[i] = dqacc[i].astype(dq_ref.dtype)
        dk_ref[i] = dkbuf[home, i].astype(dk_ref.dtype)
        dv_ref[i] = dvbuf[home, i].astype(dv_ref.dtype)
        return 0

    lax.fori_loop(0, bh, fin, 0)


def ring_attention_bwd_vmem_bytes(local_shape, dtype) -> int:
    """Backward working-set estimate: q/o/do/k/v inputs + dq/dk/dv outputs
    + 2x2 K/V slots in ``dtype``, 2x2 dK/dV slots + dq accumulator in f32,
    plus the [.., n, 1] lse/D columns."""
    b, n, h, d = local_shape
    cells = b * h * n * d
    itemsize = jnp.dtype(dtype).itemsize
    return cells * (12 * itemsize + 20) + 2 * 4 * b * h * n


def ring_attention_bwd_pallas(
    q, k, v, o, lse, do,
    axis: str = "sp",
    causal: bool = False,
    axis_size: Optional[int] = None,
    interpret: bool = False,
    vmem_budget_bytes: Optional[int] = None,
):
    """Analytic flash-attention backward on the RDMA ring (the transport
    symmetry the XLA-ppermute backward leaves on the table). ``lse`` is
    the forward's ``[b, h, n]`` residual. Returns (dq, dk, dv).
    Auto-chunks over batch/heads like the forward (each chunk rides its
    own ring; wire bytes unchanged)."""
    p = axis_size or lax.axis_size(axis)
    b, n, h, d = q.shape
    assert p > 1, "p == 1 has no ring; callers differentiate locally"
    from .ring_kernels import _legacy_multiaxis

    if _legacy_multiaxis(interpret):
        return _ring_attention_bwd_xla(q, k, v, o, lse, do, axis, causal, p)
    budget = vmem_budget_bytes or _VMEM_BUDGET_BYTES
    if ring_attention_bwd_vmem_bytes(q.shape, q.dtype) > budget:
        def sub(bi, bb, hi, hh, prev):
            qs = q[bi:bi + bb, :, hi:hi + hh]
            if prev is not None:
                qs = _sequence_after(qs, prev)
            return ring_attention_bwd_pallas(
                qs,
                k[bi:bi + bb, :, hi:hi + hh],
                v[bi:bi + bb, :, hi:hi + hh],
                o[bi:bi + bb, :, hi:hi + hh],
                lse[bi:bi + bb, hi:hi + hh],
                do[bi:bi + bb, :, hi:hi + hh],
                axis=axis, causal=causal, axis_size=axis_size,
                interpret=interpret, vmem_budget_bytes=budget,
            )

        return _run_chunked(
            b, h,
            lambda bb, hh: ring_attention_bwd_vmem_bytes(
                (bb, n, hh, d), q.dtype
            ) <= budget,
            sub, (2, 2, 2),
            ring_attention_bwd_vmem_bytes((1, n, 1, d), q.dtype), budget,
            "ring-attention backward",
        )
    bh = b * h
    to_cells = lambda t: t.transpose(0, 2, 1, 3).reshape(bh, n, d)  # noqa: E731
    scale = 1.0 / math.sqrt(d)
    my = lax.axis_index(axis).astype(jnp.int32).reshape(1)
    kernel = functools.partial(
        _ring_attn_bwd_kernel, p, axis, causal, scale, n,
        kernel_flow_control(interpret),
    )
    dq, dk, dv = pl.pallas_call(
        kernel,
        out_shape=(
            jax.ShapeDtypeStruct((bh, n, d), q.dtype),
            jax.ShapeDtypeStruct((bh, n, d), k.dtype),
            jax.ShapeDtypeStruct((bh, n, d), v.dtype),
        ),
        in_specs=[
            pl.BlockSpec(memory_space=pltpu.SMEM),
            pl.BlockSpec(memory_space=pltpu.VMEM),
            pl.BlockSpec(memory_space=pltpu.VMEM),
            pl.BlockSpec(memory_space=pltpu.VMEM),
            pl.BlockSpec(memory_space=pltpu.VMEM),
            pl.BlockSpec(memory_space=pltpu.VMEM),
            pl.BlockSpec(memory_space=pltpu.VMEM),
        ],
        out_specs=(
            pl.BlockSpec(memory_space=pltpu.VMEM),
            pl.BlockSpec(memory_space=pltpu.VMEM),
            pl.BlockSpec(memory_space=pltpu.VMEM),
        ),
        scratch_shapes=[
            pltpu.VMEM((2, bh, n, d), k.dtype),
            pltpu.VMEM((2, bh, n, d), v.dtype),
            pltpu.VMEM((2, bh, n, d), jnp.float32),
            pltpu.VMEM((2, bh, n, d), jnp.float32),
            pltpu.VMEM((bh, n, d), jnp.float32),
            pltpu.VMEM((bh, n, 1), jnp.float32),
            pltpu.SemaphoreType.DMA((2,)),
            pltpu.SemaphoreType.DMA((2,)),
            pltpu.SemaphoreType.DMA((2,)),
            pltpu.SemaphoreType.DMA((2,)),
            pltpu.SemaphoreType.DMA((2,)),
            pltpu.SemaphoreType.DMA((2,)),
            pltpu.SemaphoreType.DMA((2,)),
            pltpu.SemaphoreType.DMA((2,)),
            pltpu.SemaphoreType.REGULAR((2,)),
        ],
        compiler_params=tpu_compiler_params(collective_id=12),
        interpret=interpret_params() if interpret else False,
    )(
        my, to_cells(q), to_cells(o), to_cells(do),
        lse.reshape(bh, n, 1), to_cells(k), to_cells(v),
    )
    back = lambda t: t.reshape(b, h, n, d).transpose(0, 2, 1, 3)  # noqa: E731
    return back(dq), back(dk), back(dv)


@functools.partial(jax.custom_vjp, nondiff_argnums=(3, 4, 5, 6, 7, 8, 9))
def ring_attention(
    q, k, v, axis, causal=False, axis_size=None, interpret=False,
    bwd_kernel=False, vmem_budget_bytes=None, fwd_bidir=False,
):
    """Differentiable ring attention: RDMA-kernel forward (uni- or, with
    ``fwd_bidir=True``, bidirectional — both ICI directions carry K/V
    chains, ~half the ring steps), with the backward either the analytic
    XLA ppermute ring (default) or the RDMA backward kernel
    (``bwd_kernel=True``). Either way the saved (o, lse) residuals mean
    no forward recompute on the gradient path. ``vmem_budget_bytes``
    overrides the auto-chunking envelope for BOTH directions (None =
    module default)."""
    fwd = ring_attention_bidir_pallas if fwd_bidir else ring_attention_pallas
    return fwd(
        q, k, v, axis=axis, causal=causal, axis_size=axis_size,
        interpret=interpret, vmem_budget_bytes=vmem_budget_bytes,
    )


def _ra_fwd(q, k, v, axis, causal, axis_size, interpret, bwd_kernel,
            vmem_budget_bytes, fwd_bidir):
    fwd = ring_attention_bidir_pallas if fwd_bidir else ring_attention_pallas
    out, lse = fwd(
        q, k, v, axis=axis, causal=causal, axis_size=axis_size,
        interpret=interpret, return_lse=True,
        vmem_budget_bytes=vmem_budget_bytes,
    )
    return out, (q, k, v, out, lse)


def _ra_bwd(axis, causal, axis_size, interpret, bwd_kernel,
            vmem_budget_bytes, fwd_bidir, res, g):
    q, k, v, o, lse = res
    p = axis_size or lax.axis_size(axis)
    if p == 1:
        # no ring to walk: differentiate the local full attention
        from ..parallel.ring_attention import full_self_attention

        _, vjp = jax.vjp(
            lambda q, k, v: full_self_attention(q, k, v, causal=causal),
            q, k, v,
        )
        return vjp(g)
    if bwd_kernel:
        return ring_attention_bwd_pallas(
            q, k, v, o, lse, g, axis=axis, causal=causal,
            axis_size=axis_size, interpret=interpret,
            vmem_budget_bytes=vmem_budget_bytes,
        )
    return _ring_attention_bwd_xla(q, k, v, o, lse, g, axis, causal, p)


ring_attention.defvjp(_ra_fwd, _ra_bwd)
