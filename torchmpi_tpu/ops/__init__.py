"""Pallas TPU kernels: fused reduction, ring collectives over ICI RDMA."""

from .reduce_kernel import accumulate, scale_accumulate
from .ring_attention_kernel import (
    ring_attention,
    ring_attention_bidir_pallas,
    ring_attention_bwd_pallas,
    ring_attention_pallas,
)
from .ring_kernels import (
    available,
    ring_allgather_pallas,
    ring_allreduce_bidir_pallas,
    ring_allreduce_pallas,
    ring_broadcast_pallas,
    ring_reduce_pallas,
    ring_reduce_scatter_pallas,
    supports_dtype,
)

__all__ = [
    "accumulate",
    "scale_accumulate",
    "available",
    "ring_attention",
    "ring_attention_bidir_pallas",
    "ring_attention_bwd_pallas",
    "ring_attention_pallas",
    "ring_allgather_pallas",
    "ring_allreduce_bidir_pallas",
    "ring_allreduce_pallas",
    "ring_broadcast_pallas",
    "ring_reduce_pallas",
    "ring_reduce_scatter_pallas",
    "supports_dtype",
]
