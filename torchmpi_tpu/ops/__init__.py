"""Pallas TPU kernels: fused reduction, ring collectives over ICI RDMA."""
