"""Fused accumulation kernels (Pallas TPU).

Analog of the reference's CUDA reduce kernel (``lib/detail/reduce_kernel.cu``:
``out[i] += in[i]`` on a stream, vectorized float4 + __ldg, "2 SMs enough to
saturate BW"). On TPU the VPU is fed from VMEM, so the kernel is a chunked
grid over the flattened buffer with blocks sized to tile into (8, 128)
lanes; XLA fuses most elementwise adds already — this kernel exists for the
custom ring path, where the per-chunk accumulate must happen inside the
Pallas collective, and as the standalone fused-add primitive the reference
exposes.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
import numpy as np
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

# 2-D blocks tile the VPU lanes: (rows, 128) with f32-aligned sublanes.
_LANES = 128
_ROWS = 1024  # 512KB f32 per operand block


def _accumulate_kernel(out_ref, in_ref, result_ref):
    result_ref[:] = out_ref[:] + in_ref[:]


def _scale_add_kernel(alpha_ref, out_ref, in_ref, result_ref):
    result_ref[:] = out_ref[:] + alpha_ref[0] * in_ref[:]


def _to_rows(flat):
    """Pad + reshape a flat buffer to [rows, 128] with rows % _ROWS == 0."""
    n = flat.shape[0]
    per_block = _ROWS * _LANES
    padded = -(-n // per_block) * per_block
    if padded != n:
        flat = jnp.concatenate([flat, jnp.zeros(padded - n, flat.dtype)])
    return flat.reshape(-1, _LANES), n


@functools.partial(jax.jit, static_argnames=("interpret",))
def accumulate(out, inp, interpret: bool = False):
    """``out + inp`` through the Pallas kernel (chunked grid), any shape."""
    rows_out, n = _to_rows(out.reshape(-1))
    rows_in, _ = _to_rows(inp.reshape(-1).astype(out.dtype))
    grid = rows_out.shape[0] // _ROWS
    spec = pl.BlockSpec((_ROWS, _LANES), lambda i: (i, 0))
    res = pl.pallas_call(
        _accumulate_kernel,
        out_shape=jax.ShapeDtypeStruct(rows_out.shape, out.dtype),
        grid=(grid,),
        in_specs=[spec, spec],
        out_specs=spec,
        interpret=interpret,
    )(rows_out, rows_in)
    return res.reshape(-1)[:n].reshape(out.shape)


@functools.partial(jax.jit, static_argnames=("interpret",))
def scale_accumulate(out, inp, alpha, interpret: bool = False):
    """``out + alpha * inp`` (the PS 'add'-with-scale fused form)."""
    rows_out, n = _to_rows(out.reshape(-1))
    rows_in, _ = _to_rows(inp.reshape(-1).astype(out.dtype))
    grid = rows_out.shape[0] // _ROWS
    spec = pl.BlockSpec((_ROWS, _LANES), lambda i: (i, 0))
    alpha_arr = jnp.asarray([alpha], out.dtype)
    res = pl.pallas_call(
        _scale_add_kernel,
        out_shape=jax.ShapeDtypeStruct(rows_out.shape, out.dtype),
        grid=(grid,),
        in_specs=[
            pl.BlockSpec(memory_space=pltpu.SMEM),
            spec,
            spec,
        ],
        out_specs=spec,
        interpret=interpret,
    )(alpha_arr, rows_out, rows_in)
    return res.reshape(-1)[:n].reshape(out.shape)
