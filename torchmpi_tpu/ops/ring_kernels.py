"""Pallas ring collectives over ICI RDMA.

TPU-native re-design of the reference's custom cudaIPC/p2p rings
(``lib/detail/collectives_cuda.cpp:202-388``): the same receive-centric
chunked ring — (p-1) reduce-scatter steps, (p-1) all-gather steps — but the
transport is inter-chip RDMA (``pltpu.make_async_remote_copy``) instead of
cudaMemcpy over IPC pointers, the staging buffers are double-buffered VMEM
scratch (the reference's per-chunk GPU staging buffers + IPC events,
``:163-195``), and the per-chunk accumulate is the fused add that
``reduce_kernel.cu`` provided.

Step discipline: every step ends with ``copy.wait()`` (send done + the
symmetric incoming chunk arrived), which in lockstep SPMD guarantees the
neighbor consumed a slot two steps before it is overwritten — the
double-buffer capacity argument the reference enforced with interprocess
events and per-step MPI barriers (``:65-66,100-101``).

The kernel runs under ``shard_map`` (one program per device). With one local
chip this path cannot execute on hardware; correctness is validated in TPU
interpret mode (``pltpu.InterpretParams``) on the virtual CPU mesh, and
``available()`` gates the eager selector to real multi-chip TPU meshes.
"""

from __future__ import annotations

import functools
from typing import Optional

import jax
import jax.numpy as jnp
from jax import lax
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

_LANES = 128
_MIN_ROWS = 8  # f32 sublane tile


def available() -> bool:
    """True when the pallas ring can service eager collectives: a real TPU
    platform with more than one device."""
    try:
        devs = jax.devices()
    except Exception:
        return False
    return devs[0].platform == "tpu" and len(devs) > 1


def _ring_allreduce_kernel(
    p: int, axis: str, my_ref, x_ref, o_ref, comm_buf, send_sem, recv_sem, cap_sem
):
    """One device's program: x_ref/o_ref are [p, rows, 128]; comm_buf is
    [2, rows, 128] scratch; my_ref is the device's ring position (SMEM).

    Capacity discipline: ``copy.wait()`` proves our data LANDED in the right
    neighbor's slot, not that the neighbor CONSUMED it — a fast sender could
    clobber slot k at step t+2 while a slow receiver still reads step t's
    data. ``cap_sem[slot]`` closes that race: the consumer signals its LEFT
    neighbor after reading a slot, and a sender reusing a slot (t >= 2)
    waits for that signal first. Consumes at the last two steps don't
    signal, so all semaphores end the kernel drained (state persists across
    pallas invocations, incl. interpret mode — leftovers would poison the
    next collective).
    """
    my = my_ref[0]
    right = lax.rem(my + 1, p)
    left = lax.rem(my + p - 1, p)
    o_ref[:] = x_ref[:]

    # neighbor barrier: nobody starts pushing until both neighbors arrived
    # (the reference's per-collective MPI barrier before the IPC ring)
    barrier = pltpu.get_barrier_semaphore()
    pltpu.semaphore_signal(
        barrier,
        inc=1,
        device_id={axis: left},
        device_id_type=pltpu.DeviceIdType.MESH,
    )
    pltpu.semaphore_signal(
        barrier,
        inc=1,
        device_id={axis: right},
        device_id_type=pltpu.DeviceIdType.MESH,
    )
    pltpu.semaphore_wait(barrier, 2)

    total = 2 * (p - 1)

    def ring_step(t: int, send_idx, recv_idx, accumulate: bool):
        slot = t % 2
        if t >= 2:  # slot reuse: wait until right consumed our step t-2 data
            pltpu.semaphore_wait(cap_sem.at[slot], 1)
        copy = pltpu.make_async_remote_copy(
            src_ref=o_ref.at[send_idx],
            dst_ref=comm_buf.at[slot],
            send_sem=send_sem.at[slot],
            recv_sem=recv_sem.at[slot],
            device_id={axis: right},
            device_id_type=pltpu.DeviceIdType.MESH,
        )
        copy.start()
        copy.wait()
        if accumulate:
            o_ref[recv_idx] = o_ref[recv_idx] + comm_buf[slot]
        else:
            o_ref[recv_idx] = comm_buf[slot]
        if t < total - 2:  # tell LEFT its slot is free for step t+2
            pltpu.semaphore_signal(
                cap_sem.at[slot],
                inc=1,
                device_id={axis: left},
                device_id_type=pltpu.DeviceIdType.MESH,
            )

    # reduce-scatter: step s sends chunk (my - s), accumulates (my - s - 1)
    for s in range(p - 1):
        ring_step(
            s,
            lax.rem(my - s + p, p),
            lax.rem(my - s - 1 + p, p),
            accumulate=True,
        )

    # all-gather: step s sends (my + 1 - s) (fully reduced), installs (my - s)
    for s in range(p - 1):
        ring_step(
            p - 1 + s,
            lax.rem(my + 1 - s + 2 * p, p),
            lax.rem(my - s + p, p),
            accumulate=False,
        )


# VMEM budget per kernel invocation: x + o ([p, rows, 128] each) plus the
# [2, rows, 128] scratch must fit comfortably in ~16MB/core.
_VMEM_BUDGET_BYTES = 8 * 1024 * 1024

# test hook: force interpret mode for every call (lets the eager dispatch
# path be exercised on the CPU mesh)
_FORCE_INTERPRET = False


def _max_rows(p: int) -> int:
    per_row_bytes = (2 * p + 2) * _LANES * 4  # x + o + double buffer
    rows = _VMEM_BUDGET_BYTES // per_row_bytes
    return max(_MIN_ROWS, rows // _MIN_ROWS * _MIN_ROWS)


def _ring_allreduce_call(chunks, p, axis, rows, interpret):
    my = lax.axis_index(axis).astype(jnp.int32).reshape(1)
    kernel = functools.partial(_ring_allreduce_kernel, p, axis)
    return pl.pallas_call(
        kernel,
        out_shape=jax.ShapeDtypeStruct((p, rows, _LANES), jnp.float32),
        in_specs=[
            pl.BlockSpec(memory_space=pltpu.SMEM),
            pl.BlockSpec(memory_space=pltpu.VMEM),
        ],
        out_specs=pl.BlockSpec(memory_space=pltpu.VMEM),
        scratch_shapes=[
            pltpu.VMEM((2, rows, _LANES), jnp.float32),
            pltpu.SemaphoreType.DMA((2,)),
            pltpu.SemaphoreType.DMA((2,)),
            pltpu.SemaphoreType.REGULAR((2,)),
        ],
        compiler_params=pltpu.CompilerParams(collective_id=7),
        interpret=pltpu.InterpretParams() if interpret else False,
    )(my, chunks)


def ring_allreduce_pallas(
    x,
    axis: str = "mpi",
    axis_size: Optional[int] = None,
    interpret: bool = False,
):
    """Allreduce the per-device block ``x`` over mesh axis ``axis`` with the
    Pallas RDMA ring. Call inside ``shard_map`` (any mesh shape: devices are
    addressed by mesh coordinates along ``axis``). f32 math; any shape.
    Buffers larger than the VMEM budget are ring-reduced in sequential
    segments (the reference's kMin/kMaxBufferSize chunking, constants.cpp:
    142-145)."""
    p = axis_size or lax.axis_size(axis)
    if p == 1:
        return x
    interpret = interpret or _FORCE_INTERPRET
    orig_shape, orig_dtype = x.shape, x.dtype
    flat = x.reshape(-1).astype(jnp.float32)
    n = flat.shape[0]
    rows = -(-n // (p * _LANES))
    rows = -(-rows // _MIN_ROWS) * _MIN_ROWS  # sublane-align each chunk
    max_rows = _max_rows(p)
    seg_rows = min(rows, max_rows)
    padded = p * seg_rows * _LANES
    num_segments = -(-n // padded)
    total = num_segments * padded
    if total != n:
        flat = jnp.concatenate([flat, jnp.zeros(total - n, jnp.float32)])
    outs = []
    for seg in range(num_segments):
        chunk = flat[seg * padded : (seg + 1) * padded].reshape(
            p, seg_rows, _LANES
        )
        outs.append(_ring_allreduce_call(chunk, p, axis, seg_rows, interpret))
    out = jnp.concatenate([o.reshape(-1) for o in outs]) if len(outs) > 1 else outs[0].reshape(-1)
    return out[:n].reshape(orig_shape).astype(orig_dtype)
