"""Pallas ring collectives over ICI RDMA.

TPU-native re-design of the reference's custom cudaIPC/p2p rings
(``lib/detail/collectives_cuda.cpp:43-388``): the same receive-centric
chunked rings — allreduce = (p-1) reduce-scatter steps + (p-1) all-gather
steps, broadcast = pipelined chunk flow down the ring — but the transport
is inter-chip RDMA (``pltpu.make_async_remote_copy``) instead of cudaMemcpy
over IPC pointers, the staging buffers are double-buffered VMEM scratch
(the reference's per-chunk GPU staging buffers + IPC events, ``:163-195``),
and the per-chunk accumulate is the fused add that ``reduce_kernel.cu``
provided.

Kernels are **dtype-preserving**: the ring moves and reduces blocks in the
input dtype (float32/bfloat16/float16/int32/int8/uint8 natively, with
sublane tiling per dtype); other dtypes are routed through a same-kind
carrier by the wrappers. Round-1 cast everything to f32, which silently
corrupted int32 allreduces of values >= 2^24.

Step discipline (allreduce/reduce-scatter): every step ends with
``copy.wait()`` (send done + the symmetric incoming chunk arrived), which
in lockstep SPMD guarantees the neighbor consumed a slot two steps before
it is overwritten — the double-buffer capacity argument the reference
enforced with interprocess events and per-step MPI barriers
(``:65-66,100-101``). ``cap_sem`` closes the fast-sender/slow-receiver
race (see kernel docstring).

The kernels run under ``shard_map`` (one program per device). With one
local chip this path cannot execute on hardware; correctness is validated
in TPU interpret mode (``pltpu.InterpretParams``) on the virtual CPU mesh,
and ``available()`` gates the eager selector to real multi-chip TPU meshes.
"""

from __future__ import annotations

import functools
from typing import Optional

import jax
import jax.numpy as jnp
from jax import lax
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from .._compat import (
    HAS_TPU_INTERPRET,
    dma_device_id,
    interpret_params,
    kernel_flow_control,
    tpu_compiler_params,
)

_LANES = 128


def _legacy_interpret(interpret: bool) -> bool:
    """True when ``interpret`` would run on the LEGACY pallas interpreter
    (jax without the TPU interpret machinery). Kernels whose DMAs sit
    under device-divergent ``pl.when`` conditions (pipelined broadcast,
    root-directed gather) cannot discharge there — each remote copy
    lowers to an ``all_gather``, which deadlocks inside a divergent cond
    — so their wrappers substitute an equivalent transport. The
    unconditional-schedule kernels (allreduce/rs/ag phases, quantized
    ring) run fine."""
    return interpret and not HAS_TPU_INTERPRET


def _legacy_multiaxis(interpret: bool) -> bool:
    """True when the legacy interpreter additionally cannot run remote
    DMA AT ALL: its discharge rule rejects meshes with more than one
    named axis (hierarchical intra/inter compositions). Wrappers fall
    back to their ppermute equivalents — same results, XLA transport."""
    if not _legacy_interpret(interpret):
        return False
    try:
        from jax._src import core as _core

        names = [n for n in _core.get_axis_env().axis_sizes if n is not None]
    except Exception:  # noqa: BLE001 - private-API probe; assume 1 axis
        return False
    return len(names) > 1

# dtypes the kernels move/reduce natively; everything else is routed
# through a same-kind carrier (ints -> int32, floats -> float32) by the
# wrappers, preserving exactness for every dtype the platform can express.
_NATIVE_DTYPES = {
    jnp.dtype(jnp.float32),
    jnp.dtype(jnp.bfloat16),
    jnp.dtype(jnp.float16),
    jnp.dtype(jnp.int32),
    jnp.dtype(jnp.int8),
    jnp.dtype(jnp.uint8),
}


def _min_rows(dtype) -> int:
    """Sublane tile for the dtype: 8 rows at 4B, 16 at 2B, 32 at 1B."""
    return 8 * (4 // jnp.dtype(dtype).itemsize)


def _tile_rows(n: int, dtype) -> int:
    """Rows needed for ``n`` elements, rounded up to whole
    (min_rows, LANES) sublane tiles — the single source of the padding
    rule for every kernel wrapper."""
    min_rows = _min_rows(dtype)
    raw_rows = -(-n // _LANES)  # ceil(n / lanes)
    return max(min_rows, -(-raw_rows // min_rows) * min_rows)


def _pad_to_tile(flat):
    """Zero-pad a flat buffer to whole tiles; returns (rows, padded_flat)."""
    rows = _tile_rows(flat.shape[0], flat.dtype)
    padded = rows * _LANES
    if padded != flat.shape[0]:
        flat = jnp.concatenate(
            [flat, jnp.zeros(padded - flat.shape[0], flat.dtype)]
        )
    return rows, flat


def supports_dtype(dtype) -> bool:
    """True when the pallas ring preserves this dtype exactly (native or
    losslessly carried)."""
    d = jnp.dtype(dtype)
    if d in _NATIVE_DTYPES:
        return True
    # lossless carriers
    return d in (jnp.dtype(jnp.int16), jnp.dtype(jnp.uint16), jnp.dtype(bool))


def _carrier_dtype(dtype):
    """Arithmetic carrier for reductions. Raises on dtypes a carrier would
    silently degrade (f64, 32/64-bit unsigned/long ints): the eager path
    gates those to the ppermute ring via :func:`supports_dtype`; direct
    kernel callers get a loud error instead of corrupted sums."""
    d = jnp.dtype(dtype)
    if d in _NATIVE_DTYPES:
        return d
    if d in (jnp.dtype(jnp.int16), jnp.dtype(jnp.uint16), jnp.dtype(bool)):
        return jnp.dtype(jnp.int32)  # lossless carrier
    raise ValueError(
        f"dtype {d} is not supported by the pallas ring reduction (a carrier "
        "cast would lose precision); use the ppermute ring backend instead"
    )


def _bitcast_to_bytes(flat, force: bool = False):
    """Lossless byte view of any dtype (for data-movement kernels): returns
    (int8 view, restore_fn). bool rides as uint8 (bitcast rejects it);
    complex is rejected loudly (no TPU support). ``force=True`` bitcasts
    even kernel-native dtypes — for paths whose zero-padding arithmetic
    must be bit-exact (e.g. the allgather identity-sum would flip a float
    -0.0 to +0.0)."""
    d = jnp.dtype(flat.dtype)
    # NB: ml_dtypes floats (bfloat16) have numpy kind 'V' — test float-ness
    # via issubdtype, never d.kind
    is_float = jnp.issubdtype(d, jnp.floating)
    if d in _NATIVE_DTYPES and not (force and is_float):
        return flat, lambda out: out
    if d == jnp.dtype(bool):
        return flat.astype(jnp.uint8), lambda out: out.astype(bool)
    if d.kind == "c":
        raise ValueError(
            "complex dtypes are not supported by the pallas ring; use the "
            "ppermute ring backend instead"
        )
    bits = jax.lax.bitcast_convert_type(flat, jnp.int8).reshape(-1)
    return bits, lambda out: jax.lax.bitcast_convert_type(
        out.reshape(-1, jnp.dtype(d).itemsize), d
    ).reshape(-1)


def available() -> bool:
    """True when the pallas ring can service eager collectives: a real TPU
    platform with more than one device."""
    try:
        devs = jax.devices()
    except Exception:
        return False
    return devs[0].platform == "tpu" and len(devs) > 1


# VMEM budget per kernel invocation: x + o ([p, rows, 128] each) plus the
# [2, rows, 128] scratch must fit comfortably in ~16MB/core.
_VMEM_BUDGET_BYTES = 8 * 1024 * 1024

# test hook: force interpret mode for every call (lets the eager dispatch
# path be exercised on the CPU mesh)
_FORCE_INTERPRET = False

# introspection: per-op ring-step count of the most recent wrapper call
# (static schedule, recorded at trace time) — lets tests assert the
# (p-1)-vs-2(p-1) step economics without instrumenting the kernels.
_LAST_STEP_COUNTS: dict = {}


# ---------------------------------------------------------------------------
# allreduce / reduce-scatter
# ---------------------------------------------------------------------------


def _ring_phases_kernel(
    p: int,
    axis: str,
    mode: str,
    fc: bool,
    my_ref,
    x_ref,
    o_ref,
    comm_buf,
    send_sem,
    recv_sem,
    cap_sem,
):
    """One device's program: x_ref/o_ref are [p, rows, 128]; comm_buf is
    [2, rows, 128] scratch; my_ref is the device's ring position (SMEM).
    ``mode`` selects the phase set:

    - ``'allreduce'``: (p-1) reduce-scatter steps + (p-1) all-gather steps;
    - ``'rs'``: reduce-scatter only (the pallas psum_scatter block);
    - ``'ag'``: all-gather only — the SAME (p-1)-step send/recv schedule as
      the reduce-scatter phase but forwarding instead of accumulating
      (device my starts owning chunk my; after step s it has installed
      chunk my-s-1), so a standalone allgather costs (p-1) steps, not the
      2(p-1) of the round-2 zero-padded allreduce trick.

    Capacity discipline: ``copy.wait()`` proves our data LANDED in the right
    neighbor's slot, not that the neighbor CONSUMED it — a fast sender could
    clobber slot k at step t+2 while a slow receiver still reads step t's
    data. ``cap_sem[slot]`` closes that race: the consumer signals its LEFT
    neighbor after reading a slot, and a sender reusing a slot (t >= 2)
    waits for that signal first. Consumes at the last two steps don't
    signal, so all semaphores end the kernel drained (state persists across
    pallas invocations, incl. interpret mode — leftovers would poison the
    next collective).
    """
    my = my_ref[0]
    right = lax.rem(my + 1, p)
    left = lax.rem(my + p - 1, p)
    o_ref[:] = x_ref[:]

    # neighbor barrier: nobody starts pushing until both neighbors arrived
    # (the reference's per-collective MPI barrier before the IPC ring).
    # ``fc`` gates all flow control — off only under the legacy lockstep
    # interpreter, which cannot express remote signals (_compat).
    if fc:
        barrier = pltpu.get_barrier_semaphore()
        pltpu.semaphore_signal(
            barrier,
            inc=1,
            device_id={axis: left},
            device_id_type=pltpu.DeviceIdType.MESH,
        )
        pltpu.semaphore_signal(
            barrier,
            inc=1,
            device_id={axis: right},
            device_id_type=pltpu.DeviceIdType.MESH,
        )
        pltpu.semaphore_wait(barrier, 2)

    total = 2 * (p - 1) if mode == "allreduce" else (p - 1)

    def ring_step(t: int, send_idx, recv_idx, accumulate: bool):
        slot = t % 2
        if fc and t >= 2:  # slot reuse: wait until right consumed t-2 data
            pltpu.semaphore_wait(cap_sem.at[slot], 1)
        copy = pltpu.make_async_remote_copy(
            src_ref=o_ref.at[send_idx],
            dst_ref=comm_buf.at[slot],
            send_sem=send_sem.at[slot],
            recv_sem=recv_sem.at[slot],
            device_id=dma_device_id(axis, right, not fc),
            device_id_type=pltpu.DeviceIdType.MESH,
        )
        copy.start()
        copy.wait()
        if accumulate:
            o_ref[recv_idx] = o_ref[recv_idx] + comm_buf[slot]
        else:
            o_ref[recv_idx] = comm_buf[slot]
        if fc and t < total - 2:  # tell LEFT its slot frees for step t+2
            pltpu.semaphore_signal(
                cap_sem.at[slot],
                inc=1,
                device_id={axis: left},
                device_id_type=pltpu.DeviceIdType.MESH,
            )

    if mode == "ag":
        # standalone all-gather: step s sends chunk (my - s), installs
        # (my - s - 1) — the reduce-scatter schedule, forwarding-only
        for s in range(p - 1):
            ring_step(
                s,
                lax.rem(my - s + p, p),
                lax.rem(my - s - 1 + p, p),
                accumulate=False,
            )
        return

    # reduce-scatter: step s sends chunk (my - s), accumulates (my - s - 1)
    for s in range(p - 1):
        ring_step(
            s,
            lax.rem(my - s + p, p),
            lax.rem(my - s - 1 + p, p),
            accumulate=True,
        )
    if mode == "rs":
        return

    # all-gather: step s sends (my + 1 - s) (fully reduced), installs (my - s)
    for s in range(p - 1):
        ring_step(
            p - 1 + s,
            lax.rem(my + 1 - s + 2 * p, p),
            lax.rem(my - s + p, p),
            accumulate=False,
        )


def _max_rows(p: int, itemsize: int, min_rows: int) -> int:
    per_row_bytes = (2 * p + 2) * _LANES * itemsize  # x + o + double buffer
    rows = _VMEM_BUDGET_BYTES // per_row_bytes
    return max(min_rows, rows // min_rows * min_rows)


def _ring_phases_call(chunks, p, axis, rows, dtype, mode, interpret):
    my = lax.axis_index(axis).astype(jnp.int32).reshape(1)
    kernel = functools.partial(
        _ring_phases_kernel, p, axis, mode, kernel_flow_control(interpret)
    )
    return pl.pallas_call(
        kernel,
        out_shape=jax.ShapeDtypeStruct((p, rows, _LANES), dtype),
        in_specs=[
            pl.BlockSpec(memory_space=pltpu.SMEM),
            pl.BlockSpec(memory_space=pltpu.VMEM),
        ],
        out_specs=pl.BlockSpec(memory_space=pltpu.VMEM),
        scratch_shapes=[
            pltpu.VMEM((2, rows, _LANES), dtype),
            pltpu.SemaphoreType.DMA((2,)),
            pltpu.SemaphoreType.DMA((2,)),
            pltpu.SemaphoreType.REGULAR((2,)),
        ],
        compiler_params=tpu_compiler_params(collective_id=7),
        interpret=interpret_params() if interpret else False,
    )(my, chunks)


def _segmented(flat, p, dtype, call, row_align: Optional[int] = None,
               max_seg_rows: Optional[int] = None):
    """Pad/segment a flat buffer into [p, seg_rows, 128] VMEM-sized pieces
    and run ``call(chunks, seg_rows)`` per segment (the reference's
    kMin/kMaxBufferSize chunking, constants.cpp:142-145). ``row_align`` /
    ``max_seg_rows`` override the dtype-derived tile rounding and VMEM
    bound (the quantized kernels need 128-row alignment so per-row scales
    reshape into whole scale rows)."""
    n = flat.shape[0]
    if row_align is not None:
        raw = -(-(-(-n // p)) // _LANES)
        rows = max(row_align, -(-raw // row_align) * row_align)
        seg_rows = min(rows, max_seg_rows or rows)
    else:
        min_rows = _min_rows(dtype)
        # per-chunk rows for p ring chunks (nested-ceil identity keeps this
        # equal to ceil(n / (p * LANES)) rounded to tiles)
        rows = _tile_rows(-(-n // p), dtype)
        seg_rows = min(rows, _max_rows(p, jnp.dtype(dtype).itemsize, min_rows))
    padded = p * seg_rows * _LANES
    num_segments = -(-n // padded)
    total = num_segments * padded
    if total != n:
        flat = jnp.concatenate([flat, jnp.zeros(total - n, dtype)])
    outs = []
    for seg in range(num_segments):
        chunk = flat[seg * padded : (seg + 1) * padded].reshape(
            p, seg_rows, _LANES
        )
        outs.append(call(chunk, seg_rows))
    return outs, n


def ring_allreduce_pallas(
    x,
    axis: str = "mpi",
    axis_size: Optional[int] = None,
    interpret: bool = False,
    wire_dtype: Optional[str] = None,
):
    """Allreduce the per-device block ``x`` over mesh axis ``axis`` with the
    Pallas RDMA ring. Call inside ``shard_map`` (any mesh shape: devices are
    addressed by mesh coordinates along ``axis``). Dtype-preserving; any
    shape. Buffers larger than the VMEM budget are ring-reduced in
    sequential segments. ``wire_dtype`` ('int8' | 'bf16') engages the
    block-quantized wire kernel for f32 payloads above the
    ``wire_quant_min_elements`` cutoff (f32 accumulate either way)."""
    p = axis_size or lax.axis_size(axis)
    if p == 1:
        return x
    if _legacy_multiaxis(interpret or _FORCE_INTERPRET):
        from ..collectives import primitives as _prim

        # same ring economics; record the schedule for introspection
        _LAST_STEP_COUNTS["allreduce"] = 2 * (p - 1)
        return _prim.ring_allreduce(
            x, axis, axis_size=axis_size, wire_dtype=wire_dtype
        )
    wire = _wire_requested(x, wire_dtype)
    if wire is not None:
        return ring_allreduce_quant_pallas(
            x, wire, axis, axis_size=axis_size, interpret=interpret
        )
    interpret = interpret or _FORCE_INTERPRET
    orig_shape, orig_dtype = x.shape, x.dtype
    carrier = _carrier_dtype(orig_dtype)
    flat = x.reshape(-1).astype(carrier)
    _LAST_STEP_COUNTS["allreduce"] = 2 * (p - 1)

    outs, n = _segmented(
        flat,
        p,
        carrier,
        lambda chunk, rows: _ring_phases_call(
            chunk, p, axis, rows, carrier, "allreduce", interpret
        ),
    )
    out = (
        jnp.concatenate([o.reshape(-1) for o in outs])
        if len(outs) > 1
        else outs[0].reshape(-1)
    )
    return out[:n].reshape(orig_shape).astype(orig_dtype)


def ring_reduce_scatter_pallas(
    x,
    axis: str = "mpi",
    axis_size: Optional[int] = None,
    interpret: bool = False,
    wire_dtype: Optional[str] = None,
):
    """Reduce-scatter along dim 0 (``lax.psum_scatter`` tiled semantics:
    device r receives the sum of every device's segment r). The pallas
    analog of the reference ring's reduce-scatter phase
    (``detail/collectives_cuda.cpp:202-330``), exposed standalone.
    ``wire_dtype`` engages the block-quantized wire kernel (same contract
    as :func:`ring_allreduce_pallas`).

    Requires ``x.shape[0] % p == 0``.
    """
    p = axis_size or lax.axis_size(axis)
    if p == 1:
        return x
    if _legacy_multiaxis(interpret or _FORCE_INTERPRET):
        from ..collectives import primitives as _prim

        _LAST_STEP_COUNTS["reduce_scatter"] = p - 1
        return _prim.ring_reduce_scatter(
            x, axis, dim=0, axis_size=axis_size, wire_dtype=wire_dtype
        )
    wire = _wire_requested(x, wire_dtype)
    if wire is not None:
        return ring_reduce_scatter_quant_pallas(
            x, wire, axis, axis_size=axis_size, interpret=interpret
        )
    if x.shape[0] % p != 0:
        raise ValueError(
            f"reduce_scatter dim 0 ({x.shape[0]}) must be divisible by the "
            f"axis size ({p})"
        )
    interpret = interpret or _FORCE_INTERPRET
    orig_dtype = x.dtype
    carrier = _carrier_dtype(orig_dtype)
    seg_shape = (x.shape[0] // p,) + x.shape[1:]
    seg_n = 1
    for d in seg_shape:
        seg_n *= d
    # [p, seg_n]: segment s flattened per row; pad rows to tile shape.
    segs = x.reshape((p, seg_n)).astype(carrier)
    min_rows = _min_rows(carrier)
    rows = _tile_rows(seg_n, carrier)
    padded = rows * _LANES
    if padded != seg_n:
        segs = jnp.concatenate(
            [segs, jnp.zeros((p, padded - seg_n), carrier)], axis=1
        )
    chunks = segs.reshape(p, rows, _LANES)
    # Pre-roll so the standard schedule (rank ends owning kernel chunk
    # (r+1) mod p) delivers original segment r to rank r.
    chunks = jnp.roll(chunks, 1, axis=0)
    # VMEM budget: slice the row dimension into sequential kernel calls
    # (each element reduces independently, so row slices compose).
    seg_rows = min(rows, _max_rows(p, jnp.dtype(carrier).itemsize, min_rows))
    my = lax.axis_index(axis)
    owned_idx = lax.rem(my + 1, p)
    _LAST_STEP_COUNTS["reduce_scatter"] = p - 1
    outs = []
    for r0 in range(0, rows, seg_rows):
        # rows and seg_rows are both min_rows-aligned: every slice tiles
        r1 = min(rows, r0 + seg_rows)
        piece = chunks[:, r0:r1, :]
        out = _ring_phases_call(
            piece, p, axis, r1 - r0, carrier, "rs", interpret
        )
        owned = lax.dynamic_index_in_dim(out, owned_idx, 0, keepdims=False)
        outs.append(owned)
    full = jnp.concatenate(outs) if len(outs) > 1 else outs[0]
    return full.reshape(-1)[:seg_n].reshape(seg_shape).astype(orig_dtype)


# ---------------------------------------------------------------------------
# block-quantized wire format (EQuARX-style): int8 / bf16 on the wire,
# fp32 accumulate, requantize per hop — fused into the ring schedule
# ---------------------------------------------------------------------------

# the quantized kernels tile chunks to whole 128-row groups so the
# per-row scales ([rows] f32) reshape into whole [rows/128, 128] scale
# rows for their own DMA stream
_QUANT_ROW_ALIGN = 128


def _quant_rows(nchunk: int) -> int:
    """Rows for an ``nchunk``-element ring chunk, 128-row aligned."""
    raw = -(-nchunk // _LANES)
    return max(
        _QUANT_ROW_ALIGN, -(-raw // _QUANT_ROW_ALIGN) * _QUANT_ROW_ALIGN
    )


def _quant_srows(rows: int):
    """(scale buffer rows, used scale rows) for a [rows, 128] chunk: one
    f32 scale per value row, packed 128 per scale row, padded to the f32
    sublane tile."""
    nsr = rows // _QUANT_ROW_ALIGN
    return max(8, -(-nsr // 8) * 8), nsr


def _max_rows_quant(p: int, wire: str) -> int:
    """VMEM bound for the quantized kernels: x + o are [p, rows, 128] f32,
    plus the double-buffered wire slots, staging, and scales."""
    wire_itemsize = 1 if wire == "int8" else 2
    per_row = (2 * p * 4 + 3 * wire_itemsize) * _LANES + 16
    rows = _VMEM_BUDGET_BYTES // per_row
    return max(
        _QUANT_ROW_ALIGN, rows // _QUANT_ROW_ALIGN * _QUANT_ROW_ALIGN
    )


def _ring_quant_kernel(
    p: int,
    axis: str,
    mode: str,
    wire: str,
    fc: bool,
    nsr: int,
    my_ref,
    x_ref,
    o_ref,
    *scratch,
):
    """Block-quantized variant of :func:`_ring_phases_kernel` (same step
    schedule, same capacity discipline): x_ref/o_ref are [p, rows, 128]
    float32 — o_ref doubles as the HIGHER-PRECISION accumulator — and
    every hop ships the wire encoding instead of the raw chunk:

    - ``wire='int8'``: the outgoing chunk is quantized per 128-lane row
      (symmetric, scale = rowmax/127) into an int8 staging buffer, the
      row scales pack into a second f32 buffer ([nsr, 128], own DMA
      stream + semaphores), the receiver dequantizes into f32 and
      accumulates; the next hop REQUANTIZES the running partial. The
      all-gather phase forwards reduced chunks the same way — re-encoding
      a just-decoded chunk reproduces the same code points, so AG
      forwarding is lossless up to fp rounding.
    - ``wire='bf16'``: the staging/wire buffers are bf16 casts, no
      scales; accumulation still f32.

    Wire bytes per hop: rows*128 + 4*rows (int8 + scales) vs rows*128*4
    for the fp32 kernel — ~3.9x less on the bandwidth-bound links.
    """
    if wire == "int8":
        (comm_q, comm_s, qstage, sstage,
         send_q, recv_q, send_s, recv_s, cap_sem) = scratch
    else:
        comm_q, qstage, send_q, recv_q, cap_sem = scratch
        comm_s = sstage = send_s = recv_s = None
    my = my_ref[0]
    right = lax.rem(my + 1, p)
    left = lax.rem(my + p - 1, p)
    rows = o_ref.shape[1]
    o_ref[:] = x_ref[:]
    if wire == "int8":
        # deterministic bytes in the padded scale rows (never read back)
        sstage[...] = jnp.zeros_like(sstage)

    if fc:
        barrier = pltpu.get_barrier_semaphore()
        pltpu.semaphore_signal(
            barrier, inc=1, device_id={axis: left},
            device_id_type=pltpu.DeviceIdType.MESH,
        )
        pltpu.semaphore_signal(
            barrier, inc=1, device_id={axis: right},
            device_id_type=pltpu.DeviceIdType.MESH,
        )
        pltpu.semaphore_wait(barrier, 2)

    total = 2 * (p - 1) if mode == "allreduce" else (p - 1)

    def encode(idx):
        xv = o_ref[idx]  # [rows, 128] f32
        if wire == "int8":
            scale = jnp.maximum(
                jnp.max(jnp.abs(xv), axis=1, keepdims=True), 1e-30
            ) / 127.0
            qstage[...] = jnp.round(xv / scale).astype(jnp.int8)
            sstage[0:nsr] = scale.reshape(nsr, _LANES)
        else:
            qstage[...] = xv.astype(jnp.bfloat16)

    def decode(slot: int):
        if wire == "int8":
            sc = comm_s[slot, 0:nsr].reshape(rows, 1)
            return comm_q[slot].astype(jnp.float32) * sc
        return comm_q[slot].astype(jnp.float32)

    def ring_step(t: int, send_idx, recv_idx, accumulate: bool):
        slot = t % 2
        # staging reuse is safe: step t-1's copy.wait() proved the
        # previous staging bytes left the chip
        encode(send_idx)
        if fc and t >= 2:
            pltpu.semaphore_wait(cap_sem.at[slot], 1)
        copies = [
            pltpu.make_async_remote_copy(
                src_ref=qstage,
                dst_ref=comm_q.at[slot],
                send_sem=send_q.at[slot],
                recv_sem=recv_q.at[slot],
                device_id=dma_device_id(axis, right, not fc),
                device_id_type=pltpu.DeviceIdType.MESH,
            )
        ]
        if wire == "int8":
            copies.append(
                pltpu.make_async_remote_copy(
                    src_ref=sstage,
                    dst_ref=comm_s.at[slot],
                    send_sem=send_s.at[slot],
                    recv_sem=recv_s.at[slot],
                    device_id=dma_device_id(axis, right, not fc),
                    device_id_type=pltpu.DeviceIdType.MESH,
                )
            )
        for c in copies:
            c.start()
        for c in copies:
            c.wait()
        val = decode(slot)
        if accumulate:
            o_ref[recv_idx] = o_ref[recv_idx] + val
        else:
            o_ref[recv_idx] = val
        if fc and t < total - 2:
            pltpu.semaphore_signal(
                cap_sem.at[slot], inc=1, device_id={axis: left},
                device_id_type=pltpu.DeviceIdType.MESH,
            )

    # reduce-scatter: step s sends chunk (my - s), accumulates (my - s - 1)
    for s in range(p - 1):
        ring_step(
            s,
            lax.rem(my - s + p, p),
            lax.rem(my - s - 1 + p, p),
            accumulate=True,
        )
    if mode == "rs":
        return

    # all-gather: step s sends (my + 1 - s) (fully reduced), installs (my - s)
    for s in range(p - 1):
        ring_step(
            p - 1 + s,
            lax.rem(my + 1 - s + 2 * p, p),
            lax.rem(my - s + p, p),
            accumulate=False,
        )


def _ring_quant_call(chunks, p, axis, rows, mode, wire, interpret):
    my = lax.axis_index(axis).astype(jnp.int32).reshape(1)
    srows, nsr = _quant_srows(rows)
    if wire == "int8":
        scratch = [
            pltpu.VMEM((2, rows, _LANES), jnp.int8),
            pltpu.VMEM((2, srows, _LANES), jnp.float32),
            pltpu.VMEM((rows, _LANES), jnp.int8),
            pltpu.VMEM((srows, _LANES), jnp.float32),
            pltpu.SemaphoreType.DMA((2,)),
            pltpu.SemaphoreType.DMA((2,)),
            pltpu.SemaphoreType.DMA((2,)),
            pltpu.SemaphoreType.DMA((2,)),
            pltpu.SemaphoreType.REGULAR((2,)),
        ]
    else:
        scratch = [
            pltpu.VMEM((2, rows, _LANES), jnp.bfloat16),
            pltpu.VMEM((rows, _LANES), jnp.bfloat16),
            pltpu.SemaphoreType.DMA((2,)),
            pltpu.SemaphoreType.DMA((2,)),
            pltpu.SemaphoreType.REGULAR((2,)),
        ]
    kernel = functools.partial(
        _ring_quant_kernel, p, axis, mode, wire,
        kernel_flow_control(interpret), nsr,
    )
    return pl.pallas_call(
        kernel,
        out_shape=jax.ShapeDtypeStruct((p, rows, _LANES), jnp.float32),
        in_specs=[
            pl.BlockSpec(memory_space=pltpu.SMEM),
            pl.BlockSpec(memory_space=pltpu.VMEM),
        ],
        out_specs=pl.BlockSpec(memory_space=pltpu.VMEM),
        scratch_shapes=scratch,
        compiler_params=tpu_compiler_params(collective_id=14),
        interpret=interpret_params() if interpret else False,
    )(my, chunks)


def _wire_requested(x, wire_dtype: Optional[str]) -> Optional[str]:
    """Resolve a wrapper's wire_dtype argument against the engagement
    gates (f32 payload, min-elements cutoff); None = ship verbatim."""
    if wire_dtype not in ("int8", "bf16"):
        return None
    from ..collectives.primitives import wire_engages

    n = 1
    for d in x.shape:
        n *= d
    return wire_dtype if wire_engages(wire_dtype, x.dtype, n) else None


def ring_allreduce_quant_pallas(
    x,
    wire: str,
    axis: str = "mpi",
    axis_size: Optional[int] = None,
    interpret: bool = False,
):
    """Block-quantized allreduce on the Pallas RDMA ring: ``wire`` bytes
    on every hop, f32 accumulation, dequantized once at the end. Same
    shard_map/segmentation contract as :func:`ring_allreduce_pallas`
    (which routes here when its ``wire_dtype`` engages)."""
    p = axis_size or lax.axis_size(axis)
    if p == 1:
        return x
    interpret = interpret or _FORCE_INTERPRET
    orig_shape, orig_dtype = x.shape, x.dtype
    flat = x.reshape(-1).astype(jnp.float32)
    _LAST_STEP_COUNTS["allreduce"] = 2 * (p - 1)
    outs, n = _segmented(
        flat,
        p,
        jnp.float32,
        lambda chunk, rows: _ring_quant_call(
            chunk, p, axis, rows, "allreduce", wire, interpret
        ),
        row_align=_QUANT_ROW_ALIGN,
        max_seg_rows=_max_rows_quant(p, wire),
    )
    out = (
        jnp.concatenate([o.reshape(-1) for o in outs])
        if len(outs) > 1
        else outs[0].reshape(-1)
    )
    return out[:n].reshape(orig_shape).astype(orig_dtype)


def ring_reduce_scatter_quant_pallas(
    x,
    wire: str,
    axis: str = "mpi",
    axis_size: Optional[int] = None,
    interpret: bool = False,
):
    """Block-quantized reduce-scatter (dim 0, psum_scatter tiled
    semantics) on the Pallas ring — the 'rs' phase of the quantized
    kernel, standalone."""
    p = axis_size or lax.axis_size(axis)
    if p == 1:
        return x
    if x.shape[0] % p != 0:
        raise ValueError(
            f"reduce_scatter dim 0 ({x.shape[0]}) must be divisible by the "
            f"axis size ({p})"
        )
    interpret = interpret or _FORCE_INTERPRET
    orig_dtype = x.dtype
    seg_shape = (x.shape[0] // p,) + x.shape[1:]
    seg_n = 1
    for d in seg_shape:
        seg_n *= d
    segs = x.reshape((p, seg_n)).astype(jnp.float32)
    rows = _quant_rows(seg_n)
    padded = rows * _LANES
    if padded != seg_n:
        segs = jnp.concatenate(
            [segs, jnp.zeros((p, padded - seg_n), jnp.float32)], axis=1
        )
    chunks = segs.reshape(p, rows, _LANES)
    # pre-roll: the kernel leaves rank r owning chunk (r+1) mod p
    chunks = jnp.roll(chunks, 1, axis=0)
    seg_rows = min(rows, _max_rows_quant(p, wire))
    my = lax.axis_index(axis)
    owned_idx = lax.rem(my + 1, p)
    _LAST_STEP_COUNTS["reduce_scatter"] = p - 1
    outs = []
    for r0 in range(0, rows, seg_rows):
        r1 = min(rows, r0 + seg_rows)
        piece = chunks[:, r0:r1, :]
        out = _ring_quant_call(piece, p, axis, r1 - r0, "rs", wire, interpret)
        owned = lax.dynamic_index_in_dim(out, owned_idx, 0, keepdims=False)
        outs.append(owned)
    full = jnp.concatenate(outs) if len(outs) > 1 else outs[0]
    return full.reshape(-1)[:seg_n].reshape(seg_shape).astype(orig_dtype)


def ring_allgather_pallas(
    x,
    axis: str = "mpi",
    axis_size: Optional[int] = None,
    interpret: bool = False,
):
    """All-gather along a new leading ring dimension: every device ends
    with ``[p, *x.shape]`` stacked in rank order — the pallas analog of the
    allgather phase of the reference ring (``detail/collectives_cuda.cpp:
    330-388``), standalone. Data-movement only: any real dtype rides as a
    lossless byte view.

    Implementation: a dedicated forwarding-only (p-1)-step schedule (the
    phases kernel in ``'ag'`` mode) — device r starts owning chunk r and
    each step forwards its newest chunk rightward, so the op costs exactly
    (p-1) steps and (p-1)/p of the buffer in wire bytes. (Round 2 reused
    the allreduce kernel over a zero-padded layout, burning 2(p-1) steps;
    the round-2 verdict called that out and this replaces it.)
    """
    p = axis_size or lax.axis_size(axis)
    if p == 1:
        return x[None]
    interpret = interpret or _FORCE_INTERPRET
    if _legacy_multiaxis(interpret):
        # XLA transport stand-in (legacy interpreter, multi-axis mesh):
        # same stacked-[p, ...] contract
        return lax.all_gather(x, axis, axis=0)
    orig_shape, orig_dtype = x.shape, x.dtype
    flat, restore = _bitcast_to_bytes(x.reshape(-1))
    carrier = flat.dtype
    n = flat.shape[0]
    min_rows = _min_rows(carrier)
    rows, flat = _pad_to_tile(flat)
    padded = rows * _LANES
    my = lax.axis_index(axis)
    # VMEM budget: row slices run as sequential kernel calls
    seg_rows = min(rows, _max_rows(p, jnp.dtype(carrier).itemsize, min_rows))
    grid = flat.reshape(rows, _LANES)
    _LAST_STEP_COUNTS["allgather"] = p - 1
    outs = []
    for r0 in range(0, rows, seg_rows):
        r1 = min(rows, r0 + seg_rows)
        # chunk layout [p, slice_rows, LANES]: my own block at slot my
        # (the 'ag' schedule overwrites every other slot — device my
        # receives chunks my-1 .. my-p+1 over the p-1 steps)
        chunks = jnp.zeros((p, r1 - r0, _LANES), carrier)
        chunks = lax.dynamic_update_index_in_dim(
            chunks, grid[r0:r1], my, 0
        )
        out = _ring_phases_call(
            chunks, p, axis, r1 - r0, carrier, "ag", interpret
        )
        outs.append(out)
    full = jnp.concatenate(outs, axis=1) if len(outs) > 1 else outs[0]
    gathered = full.reshape(p, padded)[:, :n]
    # one flat restore over the whole buffer (every restore branch is
    # elementwise on a multiple-of-itemsize buffer)
    restored = restore(gathered.reshape(-1))
    return restored.reshape((p,) + orig_shape).astype(orig_dtype)


# ---------------------------------------------------------------------------
# bidirectional ring allreduce: two half-buffers, opposite directions
# ---------------------------------------------------------------------------


def _ring_bidir_kernel(
    p: int,
    axis: str,
    fc: bool,
    my_ref,
    xa_ref,
    xb_ref,
    oa_ref,
    ob_ref,
    comm_a,
    comm_b,
    send_a,
    recv_a,
    send_b,
    recv_b,
    cap_a,
    cap_b,
):
    """Bidirectional ring allreduce: half A runs the standard rightward
    RS+AG schedule, half B the mirrored leftward one, both DMAs issued
    per step before either wait — so each step drives BOTH directions of
    every ICI link and the wire time per link halves versus the
    unidirectional ring (the full-bisection-bandwidth variant the
    reference never built; its cudaIPC ring was unidirectional).

    Direction generalization (d = +1 right, -1 left): RS step s sends
    chunk ``my - d*s`` to neighbor ``my + d`` and accumulates
    ``my - d*(s+1)``; AG step s sends ``my - d*(s-1)`` and installs
    ``my - d*s``. Capacity semaphores follow the same slot discipline as
    the unidirectional kernel, one set per direction.
    """
    my = my_ref[0]
    right = lax.rem(my + 1, p)
    left = lax.rem(my + p - 1, p)
    oa_ref[:] = xa_ref[:]
    ob_ref[:] = xb_ref[:]

    if fc:
        barrier = pltpu.get_barrier_semaphore()
        pltpu.semaphore_signal(
            barrier, inc=1, device_id={axis: left},
            device_id_type=pltpu.DeviceIdType.MESH,
        )
        pltpu.semaphore_signal(
            barrier, inc=1, device_id={axis: right},
            device_id_type=pltpu.DeviceIdType.MESH,
        )
        pltpu.semaphore_wait(barrier, 2)

    total = 2 * (p - 1)

    def dir_step(t, d, o_ref, comm_buf, send_sem, recv_sem, cap_sem,
                 send_idx, recv_idx, accumulate):
        """One direction's slice of step t (start+wait split by caller)."""
        slot = t % 2
        to = right if d == 1 else left
        frm = left if d == 1 else right
        if fc and t >= 2:
            pltpu.semaphore_wait(cap_sem.at[slot], 1)
        copy = pltpu.make_async_remote_copy(
            src_ref=o_ref.at[send_idx],
            dst_ref=comm_buf.at[slot],
            send_sem=send_sem.at[slot],
            recv_sem=recv_sem.at[slot],
            device_id=dma_device_id(axis, to, not fc),
            device_id_type=pltpu.DeviceIdType.MESH,
        )
        copy.start()

        def finish():
            copy.wait()
            if accumulate:
                o_ref[recv_idx] = o_ref[recv_idx] + comm_buf[slot]
            else:
                o_ref[recv_idx] = comm_buf[slot]
            if fc and t < total - 2:
                pltpu.semaphore_signal(
                    cap_sem.at[slot], inc=1, device_id={axis: frm},
                    device_id_type=pltpu.DeviceIdType.MESH,
                )

        return finish

    for t in range(total):
        s = t if t < p - 1 else t - (p - 1)
        rs = t < p - 1
        if rs:
            ia_send = lax.rem(my - s + p, p)
            ia_recv = lax.rem(my - s - 1 + p, p)
            ib_send = lax.rem(my + s, p)
            ib_recv = lax.rem(my + s + 1, p)
        else:
            ia_send = lax.rem(my - s + 1 + p, p)
            ia_recv = lax.rem(my - s + p, p)
            ib_send = lax.rem(my + s - 1 + p, p)
            ib_recv = lax.rem(my + s, p)
        fin_a = dir_step(
            t, 1, oa_ref, comm_a, send_a, recv_a, cap_a,
            ia_send, ia_recv, rs,
        )
        fin_b = dir_step(
            t, -1, ob_ref, comm_b, send_b, recv_b, cap_b,
            ib_send, ib_recv, rs,
        )
        fin_a()
        fin_b()


def ring_allreduce_bidir_pallas(
    x,
    axis: str = "mpi",
    axis_size: Optional[int] = None,
    interpret: bool = False,
):
    """Bidirectional-ring allreduce: the buffer is split in two halves
    reduced simultaneously around the ring in opposite directions, using
    both directions of every ICI link — per-link wire time is half the
    unidirectional ring's. Same dtype/carrier rules and VMEM segmentation
    as :func:`ring_allreduce_pallas`. Selectable per-collective via the
    autotuner (``tune_ring_implementation`` measures it on hardware)."""
    p = axis_size or lax.axis_size(axis)
    if p == 1:
        return x
    if p == 2 or _legacy_multiaxis(interpret or _FORCE_INTERPRET):
        # two devices: both "directions" address the same single neighbor
        # link; the unidirectional kernel is the same schedule with half
        # the semaphore traffic. (The legacy multi-axis case delegates
        # for its ppermute fallback.)
        return ring_allreduce_pallas(
            x, axis, axis_size=axis_size, interpret=interpret
        )
    interpret = interpret or _FORCE_INTERPRET
    orig_shape, orig_dtype = x.shape, x.dtype
    carrier = _carrier_dtype(orig_dtype)
    flat = x.reshape(-1)
    n = flat.shape[0]
    half = -(-n // 2)
    _LAST_STEP_COUNTS["allreduce_bidir"] = 2 * (p - 1)

    def run_half(seg):
        return _segmented_pair_ready(seg.astype(carrier), p, carrier)

    (ca, rows_a), (cb, rows_b) = run_half(flat[:half]), run_half(
        jnp.concatenate([flat[half:], jnp.zeros(2 * half - n, flat.dtype)])
        if 2 * half != n
        else flat[half:]
    )
    # both halves are padded to the SAME tile geometry (equal half sizes)
    assert rows_a == rows_b
    my = lax.axis_index(axis).astype(jnp.int32).reshape(1)
    kernel = functools.partial(
        _ring_bidir_kernel, p, axis, kernel_flow_control(interpret)
    )
    outs = []
    for seg_a, seg_b in zip(ca, cb):
        rows = seg_a.shape[1]
        oa, ob = pl.pallas_call(
            kernel,
            out_shape=(
                jax.ShapeDtypeStruct((p, rows, _LANES), carrier),
                jax.ShapeDtypeStruct((p, rows, _LANES), carrier),
            ),
            in_specs=[
                pl.BlockSpec(memory_space=pltpu.SMEM),
                pl.BlockSpec(memory_space=pltpu.VMEM),
                pl.BlockSpec(memory_space=pltpu.VMEM),
            ],
            out_specs=(
                pl.BlockSpec(memory_space=pltpu.VMEM),
                pl.BlockSpec(memory_space=pltpu.VMEM),
            ),
            scratch_shapes=[
                pltpu.VMEM((2, rows, _LANES), carrier),
                pltpu.VMEM((2, rows, _LANES), carrier),
                pltpu.SemaphoreType.DMA((2,)),
                pltpu.SemaphoreType.DMA((2,)),
                pltpu.SemaphoreType.DMA((2,)),
                pltpu.SemaphoreType.DMA((2,)),
                pltpu.SemaphoreType.REGULAR((2,)),
                pltpu.SemaphoreType.REGULAR((2,)),
            ],
            compiler_params=tpu_compiler_params(collective_id=10),
            interpret=interpret_params() if interpret else False,
        )(my, seg_a, seg_b)
        outs.append((oa, ob))
    flat_a = jnp.concatenate([o.reshape(-1) for o, _ in outs])[:half]
    flat_b = jnp.concatenate([o.reshape(-1) for _, o in outs])[: n - half]
    return (
        jnp.concatenate([flat_a, flat_b])
        .reshape(orig_shape)
        .astype(orig_dtype)
    )


def _segmented_pair_ready(flat, p, dtype):
    """Pad/segment one half-buffer into [p, seg_rows, 128] pieces (shared
    geometry helper for the bidirectional kernel; mirrors
    :func:`_segmented` without invoking a call per segment)."""
    n = flat.shape[0]
    min_rows = _min_rows(dtype)
    rows = _tile_rows(-(-n // p), dtype)
    # bidir holds 2x (x + o + comm) in VMEM: halve the per-call budget
    seg_rows = min(
        rows, max(min_rows, _max_rows(p, jnp.dtype(dtype).itemsize,
                                      min_rows) // 2 // min_rows * min_rows)
    )
    padded = p * seg_rows * _LANES
    num_segments = -(-n // padded)
    total = num_segments * padded
    if total != n:
        flat = jnp.concatenate([flat, jnp.zeros(total - n, dtype)])
    segs = [
        flat[i * padded : (i + 1) * padded].reshape(p, seg_rows, _LANES)
        for i in range(num_segments)
    ]
    return segs, seg_rows


# ---------------------------------------------------------------------------
# reduce to root: reduce-scatter + chunk gather toward the root
# ---------------------------------------------------------------------------


def _ring_gather_root_kernel(
    p: int, axis: str, root: int, fc: bool, my_ref, x_ref, o_ref,
    send_sem, recv_sem, cap_sem
):
    """Gather every device's owned chunk to ``root`` along the ring — the
    second half of a ring reduce (the reference's reduce gathers the
    scattered partials back to the root GPU, ``detail/collectives_cuda.cpp``
    reduce path). Post-reduce-scatter ownership is assumed: device ``my``
    owns chunk ``(my+1) mod p`` (what the ``'rs'`` phases kernel leaves).

    Schedule (p-1 steps, root-directed — links past the root stay idle):
    with ``d = (my - root) mod p`` the ring distance to travel TO root
    going right, device my sends chunk ``(my+1-s) mod p`` at step s iff
    ``s < d`` (its own chunk first, then chunks passing through), and
    receives chunk ``(my-s) mod p`` from left iff ``s < left_d`` where
    ``left_d = (d-1) mod p`` (the root's left_d is p-1: the root receives
    every step, collecting all p-1 foreign chunks). Sender step s and
    receiver step s agree on semaphore slot s%2; ``cap_sem`` closes the
    slot-aliasing race exactly as in the broadcast kernel (consumer
    signals LEFT after consuming; a sender's 3rd+ use of a slot waits).
    Semaphores end drained: sender waits max(0, d-2) caps, its consumer
    signals for s+2 < d — the same count.
    """
    my = my_ref[0]
    d = lax.rem(my - root + p, p)
    left = lax.rem(my + p - 1, p)
    right = lax.rem(my + 1, p)
    left_d = lax.rem(d + p - 1, p)
    o_ref[:] = x_ref[:]

    if fc:
        barrier = pltpu.get_barrier_semaphore()
        pltpu.semaphore_signal(
            barrier, inc=1, device_id={axis: left},
            device_id_type=pltpu.DeviceIdType.MESH,
        )
        pltpu.semaphore_signal(
            barrier, inc=1, device_id={axis: right},
            device_id_type=pltpu.DeviceIdType.MESH,
        )
        pltpu.semaphore_wait(barrier, 2)

    for s in range(p - 1):
        slot = s % 2
        recv_now = s < left_d

        @pl.when(recv_now)
        def _():
            ridx = lax.rem(my - s + p, p)
            incoming = pltpu.make_async_remote_copy(
                src_ref=o_ref.at[ridx],
                dst_ref=o_ref.at[ridx],
                send_sem=send_sem.at[slot],
                recv_sem=recv_sem.at[slot],
                device_id=dma_device_id(axis, right, not fc),
                device_id_type=pltpu.DeviceIdType.MESH,
            )
            incoming.wait_recv()

        if fc:
            @pl.when(recv_now & (s + 2 < left_d))
            def _():
                pltpu.semaphore_signal(
                    cap_sem.at[slot], inc=1, device_id={axis: left},
                    device_id_type=pltpu.DeviceIdType.MESH,
                )

        send_now = s < d

        if fc:
            @pl.when(send_now & (s >= 2))
            def _():
                pltpu.semaphore_wait(cap_sem.at[slot], 1)

        @pl.when(send_now)
        def _():
            idx = lax.rem(my + 1 - s + p, p)
            copy = pltpu.make_async_remote_copy(
                src_ref=o_ref.at[idx],
                dst_ref=o_ref.at[idx],  # same slot in the consumer
                send_sem=send_sem.at[slot],
                recv_sem=recv_sem.at[slot],
                device_id=dma_device_id(axis, right, not fc),
                device_id_type=pltpu.DeviceIdType.MESH,
            )
            copy.start()
            copy.wait_send()


def _ring_gather_call(chunks, p, axis, root, rows, dtype, interpret):
    my = lax.axis_index(axis).astype(jnp.int32).reshape(1)
    kernel = functools.partial(
        _ring_gather_root_kernel, p, axis, root, kernel_flow_control(interpret)
    )
    return pl.pallas_call(
        kernel,
        out_shape=jax.ShapeDtypeStruct((p, rows, _LANES), dtype),
        in_specs=[
            pl.BlockSpec(memory_space=pltpu.SMEM),
            pl.BlockSpec(memory_space=pltpu.VMEM),
        ],
        out_specs=pl.BlockSpec(memory_space=pltpu.VMEM),
        scratch_shapes=[
            pltpu.SemaphoreType.DMA((2,)),
            pltpu.SemaphoreType.DMA((2,)),
            pltpu.SemaphoreType.REGULAR((2,)),
        ],
        compiler_params=tpu_compiler_params(collective_id=9),
        interpret=interpret_params() if interpret else False,
    )(my, chunks)


def ring_reduce_pallas(
    x,
    root: int = 0,
    axis: str = "mpi",
    axis_size: Optional[int] = None,
    interpret: bool = False,
):
    """Reduce the per-device blocks to ``root`` with the Pallas RDMA ring:
    (p-1) reduce-scatter steps + (p-1) root-directed gather steps (wire
    traffic past the root is skipped, unlike an allreduce whose all-gather
    phase loads every link). Non-root devices return their input unchanged
    — the eager ``reduce`` contract. Dtype-preserving via the same carrier
    rules as the allreduce."""
    p = axis_size or lax.axis_size(axis)
    if p == 1:
        return x
    interpret = interpret or _FORCE_INTERPRET
    if _legacy_interpret(interpret):
        # the root-directed gather's conditional DMAs cannot discharge on
        # the legacy interpreter: reduce = allreduce (same phases kernel)
        # masked to root — identical results, full-ring wire traffic
        total = ring_allreduce_pallas(
            x, axis, axis_size=axis_size, interpret=interpret
        )
        _LAST_STEP_COUNTS["reduce"] = 2 * (p - 1)
        return jnp.where(lax.axis_index(axis) == root, total, x)
    orig_shape, orig_dtype = x.shape, x.dtype
    carrier = _carrier_dtype(orig_dtype)
    flat = x.reshape(-1).astype(carrier)
    _LAST_STEP_COUNTS["reduce"] = 2 * (p - 1)

    def call(chunk, rows):
        reduced = _ring_phases_call(chunk, p, axis, rows, carrier, "rs", interpret)
        return _ring_gather_call(reduced, p, axis, root, rows, carrier, interpret)

    outs, n = _segmented(flat, p, carrier, call)
    out = (
        jnp.concatenate([o.reshape(-1) for o in outs])
        if len(outs) > 1
        else outs[0].reshape(-1)
    )
    assembled = out[:n].reshape(orig_shape).astype(orig_dtype)
    return jnp.where(lax.axis_index(axis) == root, assembled, x)


# ---------------------------------------------------------------------------
# pipelined ring broadcast
# ---------------------------------------------------------------------------


def _ring_broadcast_kernel(
    p: int, k: int, axis: str, root: int, fc: bool, my_ref, x_ref, o_ref,
    send_sem, recv_sem, cap_sem
):
    """Pipelined chunk flow down the ring (the reference's large-message
    GPU broadcast, ``detail/collectives_cuda.cpp:58-159``): x_ref/o_ref are
    [k, rows, 128]; chunk c reaches the device at ring distance d from root
    at step c + d - 1 and is forwarded at step c + d.

    Senders write a chunk directly into the consumer's ``o_ref[c]`` — each
    chunk location is written exactly once, so DATA cannot collide. The
    recv SEMAPHORE slots still alias (2 slots, k chunks) and RDMA delivery
    is not ordered: without flow control a fast sender's chunk c+2 signal
    can satisfy the receiver's wait for chunk c, which then forwards
    garbage (caught by interpret mode at p>=3). ``cap_sem`` closes it
    exactly as in the allreduce ring: a consumer signals its LEFT neighbor
    after consuming a slot, and a sender reusing a slot (its 3rd+ send)
    waits for that signal first — at most one outstanding signal per slot.
    All semaphores end drained: senders wait k-2 caps (c_send >= 2),
    consumers signal k-2 (c_recv <= k-3).
    """
    my = my_ref[0]
    d = lax.rem(my - root + p, p)
    right = lax.rem(my + 1, p)
    left = lax.rem(my + p - 1, p)

    @pl.when(d == 0)
    def _():
        o_ref[:] = x_ref[:]

    if fc:
        barrier = pltpu.get_barrier_semaphore()
        pltpu.semaphore_signal(
            barrier, inc=1, device_id={axis: left},
            device_id_type=pltpu.DeviceIdType.MESH,
        )
        pltpu.semaphore_signal(
            barrier, inc=1, device_id={axis: right},
            device_id_type=pltpu.DeviceIdType.MESH,
        )
        pltpu.semaphore_wait(barrier, 2)

    for t in range(k + p - 2):
        # receive chunk c_recv = t - d + 1 (sent by left at distance d-1):
        # construct the matching descriptor and wait_recv (DMA semaphores
        # cannot be waited directly; wait_recv blocks until the incoming
        # chunk's bytes have landed in o_ref[c_recv]).
        c_recv = t - d + 1
        recv_now = (d > 0) & (c_recv >= 0) & (c_recv < k)

        @pl.when(recv_now)
        def _():
            ridx = jnp.clip(c_recv, 0, k - 1)
            incoming = pltpu.make_async_remote_copy(
                src_ref=o_ref.at[ridx],
                dst_ref=o_ref.at[ridx],
                send_sem=send_sem.at[t % 2],
                recv_sem=recv_sem.at[t % 2],
                device_id=dma_device_id(axis, right, not fc),
                device_id_type=pltpu.DeviceIdType.MESH,
            )
            incoming.wait_recv()

        # free the consumed slot for the sender's next-but-one send
        if fc:
            @pl.when(recv_now & (c_recv <= k - 3))
            def _():
                pltpu.semaphore_signal(
                    cap_sem.at[t % 2],
                    inc=1,
                    device_id={axis: left},
                    device_id_type=pltpu.DeviceIdType.MESH,
                )

        # send chunk c_send = t - d to right (received at step t-1; root
        # sends its own chunks). The receiver at distance d+1 waits for it
        # in ITS iteration t (c_recv = t - (d+1) + 1 = c_send), so sender
        # and receiver agree on semaphore slot t % 2. The LAST device never
        # forwards.
        c_send = t - d
        send_now = (c_send >= 0) & (c_send < k) & (d < p - 1)

        # slot reuse (3rd+ send): wait until right consumed the chunk sent
        # two steps ago on this slot
        if fc:
            @pl.when(send_now & (c_send >= 2))
            def _():
                pltpu.semaphore_wait(cap_sem.at[t % 2], 1)

        @pl.when(send_now)
        def _():
            idx = jnp.clip(c_send, 0, k - 1)
            copy = pltpu.make_async_remote_copy(
                src_ref=o_ref.at[idx],
                dst_ref=o_ref.at[idx],  # same offset in the consumer
                send_sem=send_sem.at[t % 2],
                recv_sem=recv_sem.at[t % 2],
                device_id=dma_device_id(axis, right, not fc),
                device_id_type=pltpu.DeviceIdType.MESH,
            )
            copy.start()
            copy.wait_send()


def ring_broadcast_pallas(
    x,
    root: int = 0,
    axis: str = "mpi",
    axis_size: Optional[int] = None,
    num_chunks: Optional[int] = None,
    interpret: bool = False,
):
    """Broadcast the root's block down the ring in pipelined chunks with
    RDMA writes. ``num_chunks`` controls pipelining depth (default: one
    VMEM-tile per chunk up to 8, the reference's kNumBuffersPerCollective
    spirit). Pure data movement: every dtype is carried losslessly (non-
    native dtypes ride as a byte view). Messages beyond the VMEM budget
    (x + o in VMEM) run as sequential segmented broadcasts."""
    p = axis_size or lax.axis_size(axis)
    if p == 1:
        return x
    interpret = interpret or _FORCE_INTERPRET
    if _legacy_interpret(interpret):
        # (covers the multi-axis case too)
        # the pipelined chunk flow's conditional DMAs cannot discharge on
        # the legacy interpreter: ride the ppermute pipelined broadcast
        # (identical chunk schedule, XLA transport)
        from ..collectives.primitives import ring_broadcast as _ring_bcast

        k = num_chunks or min(8, max(1, p))
        _LAST_STEP_COUNTS["broadcast"] = k + p - 2
        return _ring_bcast(
            x, root, axis, axis_size=axis_size, num_chunks=num_chunks
        )
    orig_shape, orig_dtype = x.shape, x.dtype
    flat, restore = _bitcast_to_bytes(x.reshape(-1))
    carrier = flat.dtype
    total_n = flat.shape[0]
    min_rows = _min_rows(carrier)
    itemsize = jnp.dtype(carrier).itemsize
    # VMEM budget: x + o = 2 * k * rows * LANES * itemsize per call.
    max_total_rows = max(
        min_rows,
        (_VMEM_BUDGET_BYTES // (2 * _LANES * itemsize))
        // min_rows * min_rows,
    )

    def one_call(seg_flat):
        n = seg_flat.shape[0]
        k = num_chunks or min(8, max(1, -(-n // (min_rows * _LANES))))
        _LAST_STEP_COUNTS["broadcast"] = k + p - 2
        rows = _tile_rows(-(-n // k), carrier)  # per-chunk tile rows
        padded = k * rows * _LANES
        if padded != n:
            seg_flat = jnp.concatenate(
                [seg_flat, jnp.zeros(padded - n, carrier)]
            )
        chunks = seg_flat.reshape(k, rows, _LANES)
        my = lax.axis_index(axis).astype(jnp.int32).reshape(1)
        kernel = functools.partial(
            _ring_broadcast_kernel, p, k, axis, root,
            kernel_flow_control(interpret),
        )
        out = pl.pallas_call(
            kernel,
            out_shape=jax.ShapeDtypeStruct((k, rows, _LANES), carrier),
            in_specs=[
                pl.BlockSpec(memory_space=pltpu.SMEM),
                pl.BlockSpec(memory_space=pltpu.VMEM),
            ],
            out_specs=pl.BlockSpec(memory_space=pltpu.VMEM),
            scratch_shapes=[
                pltpu.SemaphoreType.DMA((2,)),
                pltpu.SemaphoreType.DMA((2,)),
                pltpu.SemaphoreType.REGULAR((2,)),
            ],
            compiler_params=tpu_compiler_params(collective_id=8),
            interpret=interpret_params() if interpret else False,
        )(my, chunks)
        return out.reshape(-1)[:n]

    seg_elems = max_total_rows * _LANES
    if total_n <= seg_elems:
        out = one_call(flat)
    else:
        outs = [
            one_call(flat[s : s + seg_elems])
            for s in range(0, total_n, seg_elems)
        ]
        out = jnp.concatenate(outs)
    return restore(out).reshape(orig_shape).astype(orig_dtype)
