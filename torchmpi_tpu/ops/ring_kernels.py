"""Pallas ICI-RDMA ring collectives (cudaIPC-ring analog). Placeholder:
implemented in ops/ring_kernels once the XLA paths are green."""

from __future__ import annotations


def available() -> bool:
    return False
