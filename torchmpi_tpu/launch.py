"""Multi-process launcher — the ``mpirun`` / ``scripts/wrap.sh`` analog.

The reference's whole UX is ``mpirun -n N wrap.sh luajit script.lua``
(``scripts/wrap.sh``, ``scripts/ompirun.sh``): N identical processes, the
world discovered from the environment, per-rank log redirection, and
manual ``pkill`` when a rank died (``dependencies/README.md:46-49``).
This is that launcher, TPU-native:

    python -m torchmpi_tpu.launch --nproc 4 examples/mnist_allreduce.py
    python -m torchmpi_tpu.launch --nproc 2 --cpu-devices 2 train.py -- --lr 0.1

- spawns ``--nproc`` copies of the script (or ``-m module``) with
  ``TORCHMPI_TPU_COORDINATOR/NUM_PROCESSES/PROCESS_ID`` set;
  ``mpi.start()`` reads them, so an unmodified script becomes rank i of N
  (the MPI_Init-reads-mpirun's-env contract);
- ``--cpu-devices K`` gives each process a K-device virtual CPU mesh
  (XLA_FLAGS + TORCHMPI_TPU_FORCE_CPU) — the "multi-node without a
  cluster" test mode (SURVEY.md §4);
- ``--log-dir DIR`` writes ``rank_<i>.log`` per process (wrap.sh's
  ``LOG_TO_FILE``); default streams every line prefixed ``[i]``;
- one rank failing kills the rest (no manual pkill) and the launcher
  exits with that rank's code; ``--nnodes/--node-rank/--coordinator``
  extend the same contract across hosts.
"""

from __future__ import annotations

import argparse
import os
import signal
import socket
import subprocess
import sys
import threading
from pathlib import Path
from typing import List, Optional


def arm_supervise_telemetry(args) -> Optional[str]:
    """``--supervise`` without ``--telemetry-live`` would silently
    starve the supervisor: its ONLY sensor is the launcher-resident
    aggregator's streaming verdicts, so a supervised job with the live
    plane dark observes nothing and never acts — the worst failure
    mode, an operator who BELIEVES recovery is armed. Auto-arm the
    plane and return the notice to print (the operator asked for one
    flag and got two, which must be visible in the job log); ``None``
    when nothing had to be armed."""
    if not getattr(args, "supervise", False) or args.telemetry_live:
        return None
    args.telemetry_live = True
    return (
        "[launch] --supervise needs the live telemetry plane (the "
        "streaming verdicts are the supervisor's only sensor): "
        "auto-arming --telemetry-live"
    )


def _free_port() -> int:
    with socket.socket() as s:
        s.bind(("localhost", 0))
        return s.getsockname()[1]


def _stream(proc: subprocess.Popen, rank: int) -> None:
    for line in proc.stdout:  # type: ignore[union-attr]
        sys.stdout.write(f"[{rank}] {line}")
        sys.stdout.flush()


def main(argv: Optional[List[str]] = None) -> int:
    ap = argparse.ArgumentParser(
        prog="python -m torchmpi_tpu.launch",
        description="spawn N torchmpi_tpu controller processes (mpirun analog)",
    )
    ap.add_argument("--nproc", type=int, required=True,
                    help="processes to launch on THIS host")
    ap.add_argument("--cpu-devices", type=int, default=0,
                    help="give each process a K-device virtual CPU mesh")
    ap.add_argument("--log-dir", default=None,
                    help="write rank_<i>.log files instead of streaming")
    ap.add_argument("--telemetry-dir", default=None,
                    help="enable telemetry in every rank and dump a "
                    "per-rank metrics snapshot + Perfetto trace JSON "
                    "(telemetry_rank_<i>.json / .trace.json) there on exit "
                    "— including abnormal exit (SIGTERM/SIGINT/fault "
                    "handlers); feed the dir to "
                    "`python -m torchmpi_tpu.telemetry.analyze`")
    ap.add_argument("--telemetry-live", action="store_true",
                    help="run a live telemetry aggregator in the launcher "
                    "and stream per-rank telemetry to it while the job "
                    "runs: every rank exports bounded metric/flight deltas "
                    "(over the elastic heartbeat when --elastic, a "
                    "dedicated socket otherwise) and the launcher serves "
                    "fleet-level /metrics (Prometheus), /health, /verdicts "
                    "(streaming desync/straggler/hang/PS verdicts) and "
                    "/calibration over HTTP; watch it with "
                    "`python -m torchmpi_tpu.telemetry.top <addr>`")
    ap.add_argument("--telemetry-live-port", type=int, default=0,
                    help="HTTP scrape port for --telemetry-live "
                    "(default: auto-chosen, printed at startup)")
    ap.add_argument("--telemetry-live-addr-file", default=None,
                    help="write the live plane's addresses here as JSON "
                    "{\"http\": ..., \"ingest\": ...} (atomic), for "
                    "operators and tests")
    ap.add_argument("--watchdog-timeout", type=float, default=0,
                    help="arm the per-rank hang watchdog: a collective or "
                    "PS RPC in flight (or a peer heartbeat stale) longer "
                    "than this many seconds dumps a structured hang report "
                    "(hang_rank_<i>.json, in --telemetry-dir when set)")
    ap.add_argument("--nnodes", type=int, default=1,
                    help="total hosts in the job")
    ap.add_argument("--node-rank", type=int, default=0,
                    help="this host's index in [0, nnodes)")
    ap.add_argument("--coordinator", default=None,
                    help="host:port of process 0 (required when nnodes > 1; "
                    "default: localhost:<free port>)")
    ap.add_argument("--set-constant", action="append", default=[],
                    metavar="NAME=VALUE",
                    help="override a torchmpi_tpu.constants knob in every "
                    "rank (repeatable), e.g. --set-constant ps_replication=2 "
                    "--set-constant parameterserver_wire_dtype=int8. "
                    "Applied by start() before the runtime bootstraps "
                    "(and re-applied over persisted tuned values), so "
                    "fabric knobs like the PS replica-chain length are "
                    "deployable without editing the training script.")
    ap.add_argument("--max-restarts", type=int, default=0,
                    help="full-job restarts: when the world dies, relaunch "
                    "ALL ranks up to this many times (scripts see "
                    "TORCHMPI_TPU_RESTART_COUNT and should resume from "
                    "their last checkpoint). Without --elastic, ANY rank "
                    "death triggers the relaunch (the pre-elastic model). "
                    "COMPOSED with --elastic, restart is the LAST "
                    "escalation rung, not an alternative: single deaths "
                    "are survived live by the membership layer, and the "
                    "world only relaunches when live recovery is "
                    "exhausted — every worker dead, or the --supervise "
                    "policy engine decides a checkpoint rollback "
                    "(resize-torn, desync, exhausted single-fault "
                    "contract). Multi-node jobs (--nnodes > 1) negotiate "
                    "the per-attempt coordinator WITHOUT communication: "
                    "attempt k uses --coordinator's port + k, so reserve "
                    "max-restarts consecutive ports above it on the "
                    "coordinator host.")
    ap.add_argument("--elastic", action="store_true",
                    help="LIVE elasticity: run an elastic membership "
                    "coordinator in the launcher, export "
                    "TORCHMPI_TPU_ELASTIC=host:port to every worker, and "
                    "keep the job alive across rank deaths — survivors "
                    "redistribute state through torchmpi_tpu.reshard and "
                    "training continues (no world relaunch). An operator "
                    "`python -m torchmpi_tpu.reshard.elastic grow <addr>` "
                    "spawns one more worker; `shrink` evicts one; `evict "
                    "--mid M` removes a specific member. The launcher "
                    "exits when every worker has; the exit code is the "
                    "LAST worker's. Composes with --max-restarts (the "
                    "checkpoint-rollback rung) and --supervise (autonomous "
                    "recovery). Single-node only.")
    ap.add_argument("--supervise", action="store_true",
                    help="run the verdict-driven recovery supervisor in "
                    "the launcher (requires --elastic; implies "
                    "--telemetry-live): streaming verdicts from the fleet "
                    "aggregator drive a policy table with hysteresis, "
                    "bounded jittered retries and an escalation ladder — "
                    "rank-dead/hang evicts the rank and commits a live "
                    "shrink, stragglers are quarantined (evict + rejoin "
                    "denylist), and resize-torn/desync/exhausted-contract "
                    "roll the world back to the last checkpoint_every "
                    "artifact (give the job restart budget with "
                    "--max-restarts). Actions serve on the live plane's "
                    "/actions endpoint and as tm_supervisor_* metrics; "
                    "knobs: the supervisor_* constants "
                    "(--set-constant supervisor_hysteresis_windows=2 ...)")
    ap.add_argument("--supervise-dry-run", action="store_true",
                    help="with --supervise: journal every recovery "
                    "decision (stderr, /actions, metrics) but actuate "
                    "nothing — the shadow-mode rollout posture. Implies "
                    "--supervise.")
    ap.add_argument("--elastic-addr-file", default=None,
                    help="write the elastic coordinator's host:port here "
                    "(atomic), for operators and tests")
    ap.add_argument("-m", "--module", default=None,
                    help="run a module (python -m) instead of a script")
    ap.add_argument("script", nargs="?", default=None,
                    help="script path (omit when using --module)")
    ap.add_argument("script_args", nargs=argparse.REMAINDER,
                    help="arguments passed through to the script")
    args = ap.parse_args(argv)

    if args.module is not None and args.script is not None:
        # with -m, the `script` positional greedily eats the first
        # passthrough token — everything positional belongs to the module
        args.script_args = [args.script] + args.script_args
        args.script = None
    if (args.script is None) == (args.module is None):
        ap.error("exactly one of a script path or --module is required")
    if args.nproc < 1:
        ap.error(f"--nproc must be >= 1, got {args.nproc}")
    if args.nnodes > 1 and args.coordinator is None:
        ap.error("--coordinator host:port is required when nnodes > 1")
    if not 0 <= args.node_rank < args.nnodes:
        ap.error(f"--node-rank {args.node_rank} outside [0, {args.nnodes})")
    if args.max_restarts < 0:
        ap.error(f"--max-restarts must be >= 0, got {args.max_restarts}")
    if args.elastic and args.nnodes > 1:
        ap.error("--elastic requires a single-node job (nnodes == 1)")
    if args.supervise_dry_run:
        args.supervise = True
    if args.supervise and not args.elastic:
        ap.error("--supervise requires --elastic (the supervisor drives "
                 "the elastic membership coordinator)")
    notice = arm_supervise_telemetry(args)
    if notice:
        print(notice, file=sys.stderr)
    if args.watchdog_timeout < 0:
        ap.error(
            f"--watchdog-timeout must be >= 0, got {args.watchdog_timeout}"
        )
    for spec in args.set_constant:
        if "=" not in spec:
            ap.error(f"--set-constant expects NAME=VALUE, got {spec!r}")

    target = (
        [sys.executable, "-m", args.module]
        if args.module
        else [sys.executable, args.script]
    )
    # argparse.REMAINDER keeps a leading "--" separator; drop it
    extra = args.script_args
    if extra and extra[0] == "--":
        extra = extra[1:]

    if args.elastic:
        # Live elasticity first, full-job restart LAST: single deaths
        # are survived in place by the membership layer, so an elastic
        # attempt only ends nonzero when live recovery is exhausted —
        # every worker dead, or the supervisor's rollback rung killed
        # the world on purpose. THAT is what --max-restarts now buys
        # under --elastic: relaunch from the last registered checkpoint
        # (scripts read TORCHMPI_TPU_RESTART_COUNT and the
        # TORCHMPI_TPU_CHECKPOINT_STATE registry to resume).
        # the cross-process last-checkpoint registry root is chosen ONCE,
        # outside the attempt loop: the registered artifact must survive
        # the very restart it exists to serve. A run-scoped temp root
        # (no --telemetry-dir) holds only the registry POINTER, not the
        # artifacts, so it is removed once the job is over.
        import shutil
        import tempfile

        tmp_root = None
        if args.telemetry_dir:
            state_root = Path(args.telemetry_dir)
        else:
            tmp_root = tempfile.mkdtemp(prefix="tm-elastic-state-")
            state_root = Path(tmp_root)
        try:
            for restart in range(args.max_restarts + 1):
                rc = _run_elastic(args, target, extra, restart,
                                  state_root)
                if rc == 0 or rc == 130 or restart == args.max_restarts:
                    return rc
                print(
                    f"[launch] elastic attempt {restart} ended with "
                    f"rc={rc}; relaunching the world from the last "
                    f"checkpoint ({args.max_restarts - restart} "
                    "restart(s) left)",
                    file=sys.stderr,
                )
            return rc
        finally:
            if tmp_root is not None:
                shutil.rmtree(tmp_root, ignore_errors=True)

    # Restart-style recovery = full-job relaunch from the last
    # checkpoint (a controller process cannot rejoin a running
    # jax.distributed job; the reference had no recovery at all — a dead
    # rank meant manual pkill, dependencies/README.md:46-49). Each
    # single-node attempt gets a FRESH auto-chosen coordinator port (the
    # old service's socket may linger); multi-node attempts derive it
    # with ZERO cross-host coordination — attempt k binds --coordinator's
    # port + k on every node, so the hosts re-agree by arithmetic.
    # Scripts read TORCHMPI_TPU_RESTART_COUNT to resume, not cold-start.
    for restart in range(args.max_restarts + 1):
        rc = _run_world(args, target, extra, restart)
        if rc == 0 or rc == 130 or restart == args.max_restarts:
            return rc  # success, operator interrupt, or budget spent
        print(
            f"[launch] attempt {restart} failed with rc={rc}; "
            f"restarting the world "
            f"({args.max_restarts - restart} restart(s) left)",
            file=sys.stderr,
        )
    return rc


def _constants_spec(set_constant) -> str:
    """Merge ``--set-constant`` overrides onto any operator-exported
    TORCHMPI_TPU_CONSTANTS (CLI overrides win: `_apply_env_constants`
    applies entries in order). Replacing instead of merging silently
    dropped the operator's env-specified knobs."""
    ambient = os.environ.get("TORCHMPI_TPU_CONSTANTS", "")
    parts = [s for s in (ambient,) if s] + list(set_constant)
    return ";".join(parts)


def _worker_env(args, rank: int, restart: int = 0) -> dict:
    """Per-rank environment (shared by the static and elastic paths)."""
    env = dict(
        os.environ,
        TORCHMPI_TPU_PROCESS_ID=str(rank),
        TORCHMPI_TPU_RESTART_COUNT=str(restart),
    )
    if args.set_constant:
        env["TORCHMPI_TPU_CONSTANTS"] = _constants_spec(args.set_constant)
    if args.watchdog_timeout:
        env["TORCHMPI_TPU_WATCHDOG"] = str(args.watchdog_timeout)
    if args.cpu_devices:
        env["XLA_FLAGS"] = (
            env.get("XLA_FLAGS", "")
            + f" --xla_force_host_platform_device_count={args.cpu_devices}"
        ).strip()
        env["TORCHMPI_TPU_FORCE_CPU"] = "1"
        env["JAX_PLATFORMS"] = "cpu"
    return env


def _start_live_aggregator(args, telemetry_dir):
    """``--telemetry-live``: start the launcher-resident fleet
    aggregator + scrape endpoints; returns it (or None when off)."""
    if not args.telemetry_live:
        return None
    from .telemetry.live import FleetAggregator

    if args.set_constant:
        # the aggregator reads fabric knobs (telemetry_live_interval_s
        # drives its staleness bound) from THIS process's constants —
        # apply the overrides here like _run_elastic does, or workers
        # framing at an overridden cadence read as stale to an
        # aggregator still assuming the default
        os.environ["TORCHMPI_TPU_CONSTANTS"] = _constants_spec(
            args.set_constant
        )
        from .runtime_state import _apply_env_constants

        _apply_env_constants()
    agg = FleetAggregator(
        mark_dir=telemetry_dir,
        # --watchdog-timeout reaches the WORKERS via env; hand it to the
        # aggregator explicitly so the live hang verdict uses the same
        # bound (None = fall back to the constants knob)
        hang_after_s=args.watchdog_timeout or None,
    )
    agg.serve(http_port=args.telemetry_live_port)
    print(
        f"[launch] live telemetry at http://127.0.0.1:{agg.http_port} "
        "(/metrics /health /verdicts /calibration) — watch with "
        f"`python -m torchmpi_tpu.telemetry.top 127.0.0.1:{agg.http_port}`",
        file=sys.stderr,
    )
    if args.telemetry_live_addr_file:
        import json

        path = Path(args.telemetry_live_addr_file)
        path.parent.mkdir(parents=True, exist_ok=True)
        tmp = path.with_name(path.name + ".tmp")
        tmp.write_text(json.dumps({
            "http": f"127.0.0.1:{agg.http_port}",
            "ingest": f"127.0.0.1:{agg.ingest_port}",
        }))
        os.replace(tmp, path)
    return agg


def _close_live_aggregator(agg, telemetry_dir) -> None:
    if agg is None:
        return
    if telemetry_dir is not None:
        try:
            # the calibration feed outlives the job: schedule.calibrate()
            # fits the persisted samples offline
            agg.save_samples(Path(telemetry_dir) / "live_samples.json")
        except OSError:
            pass
    agg.close()


def _run_elastic(args, target, extra, restart: int,
                 state_root) -> int:
    """Live-elastic supervision: one membership coordinator in THIS
    process, workers that survive each other's deaths, and an operator
    grow surface that spawns additional workers into the running job.
    Exits when every worker has; returns the last worker's exit code
    (survivors of tolerated deaths exit last, so a recovered job is 0).

    With ``--supervise``, a :class:`~.supervise.RecoverySupervisor`
    consumes the launcher aggregator's streaming verdicts and acts:
    evict (SIGKILL + the membership sweep commits the live shrink),
    grow, or — the last rung — kill the world so the surrounding
    ``--max-restarts`` loop relaunches attempt ``restart + 1`` from the
    last registered checkpoint."""
    from .analysis import lockmon as _lockmon
    from .reshard.elastic import ElasticCoordinator

    if args.set_constant:
        # the membership coordinator lives in THIS process and reads
        # fabric knobs (elastic_heartbeat_seconds, the barrier timeout)
        # from constants — apply the overrides here too, not only in the
        # worker envs, or `--set-constant elastic_heartbeat_seconds=...`
        # would tune the members' beat cadence but not the coordinator's
        # death-detection sweep. Merged onto any operator-exported spec
        # (workers re-merge; the duplicate entries are idempotent).
        os.environ["TORCHMPI_TPU_CONSTANTS"] = _constants_spec(
            args.set_constant
        )
        from .runtime_state import _apply_env_constants

        _apply_env_constants()

    lock = _lockmon.make_lock("launch.py:_run_elastic")
    procs: dict = {}
    readers: List[threading.Thread] = []
    logs = []
    next_rank = [0]
    log_dir = Path(args.log_dir) if args.log_dir else None
    if log_dir is not None:
        log_dir.mkdir(parents=True, exist_ok=True)
    telemetry_dir = Path(args.telemetry_dir) if args.telemetry_dir else None
    if telemetry_dir is not None:
        telemetry_dir.mkdir(parents=True, exist_ok=True)
        # clear liveness/hang artifacts from a PREVIOUS LAUNCH only
        # (attempt 0): on a restart attempt they are the failed
        # attempt's post-mortem — the evidence that explains the very
        # failure that consumed the restart
        if restart == 0:
            for pattern in ("heartbeat_rank_*.json", "hang_rank_*.json",
                            "dead_rank_*.json"):
                for stale in telemetry_dir.glob(pattern):
                    try:
                        stale.unlink()
                    except OSError:
                        pass
    # the cross-process last-checkpoint registry: workers register
    # every checkpoint_every artifact here; the supervisor's rollback
    # rung and a relaunched attempt both read it. The root comes from
    # main()'s restart loop (chosen once, so the registry SURVIVES
    # restart attempts — the artifact is the whole point of the
    # restart); exported into THIS process's env too, or the
    # launcher-resident supervisor could never see what the workers
    # registered.
    ckpt_state = Path(state_root) / "last_checkpoint.json"
    os.environ["TORCHMPI_TPU_CHECKPOINT_STATE"] = str(ckpt_state)
    live_agg = _start_live_aggregator(args, telemetry_dir)

    def spawn_locked(addr: str) -> None:
        rank = next_rank[0]
        next_rank[0] += 1
        env = _worker_env(args, rank, restart)
        env["TORCHMPI_TPU_ELASTIC"] = addr
        env["TORCHMPI_TPU_ELASTIC_RANK"] = str(rank)
        env["TORCHMPI_TPU_CHECKPOINT_STATE"] = str(ckpt_state)
        if rank >= args.nproc:
            # spawned by an operator grow INTO a running job: the worker
            # must attach to the live membership, not wait for formation
            env["TORCHMPI_TPU_ELASTIC_JOINER"] = "1"
        if telemetry_dir is not None:
            tname = (
                f"telemetry_rank_{rank}.json" if restart == 0
                else f"telemetry_rank_{rank}.restart{restart}.json"
            )
            env["TORCHMPI_TPU_TELEMETRY"] = "1"
            env["TORCHMPI_TPU_TELEMETRY_DUMP"] = str(telemetry_dir / tname)
        if live_agg is not None:
            # elastic workers piggyback their live frames on the
            # membership heartbeat instead of opening another socket;
            # the coordinator's on_telemetry hook feeds the aggregator
            env["TORCHMPI_TPU_TELEMETRY"] = "1"
            env["TORCHMPI_TPU_TELEMETRY_LIVE_VIA"] = "heartbeat"
        if log_dir is not None:
            out = open(log_dir / f"rank_{rank}.log", "w")
            logs.append(out)
            proc = subprocess.Popen(
                target + extra, env=env, stdout=out,
                stderr=subprocess.STDOUT,
            )
        else:
            proc = subprocess.Popen(
                target + extra, env=env, stdout=subprocess.PIPE,
                stderr=subprocess.STDOUT, text=True,
            )
            reader = threading.Thread(
                target=_stream, args=(proc, rank), daemon=True
            )
            reader.start()
            readers.append(reader)
        procs[rank] = proc

    coord_box = {}

    def on_grow():
        with lock:
            print("[launch] elastic grow: spawning one more worker",
                  file=sys.stderr)
            spawn_locked(coord_box["addr"])

    coord = ElasticCoordinator(
        on_grow=on_grow,
        on_telemetry=live_agg.ingest if live_agg is not None else None,
    )
    coord_box["addr"] = f"{coord.address[0]}:{coord.address[1]}"
    print(f"[launch] elastic coordinator at {coord_box['addr']}",
          file=sys.stderr)
    if args.elastic_addr_file:
        tmp = Path(args.elastic_addr_file).with_suffix(".tmp")
        tmp.parent.mkdir(parents=True, exist_ok=True)
        tmp.write_text(coord_box["addr"])
        os.replace(tmp, args.elastic_addr_file)

    rollback_box: dict = {}
    sup_stop = threading.Event()
    sup_thread = None
    if args.supervise:
        from . import constants
        from .supervise import RecoverySupervisor
        from .supervise import checkpoints as _ckpts

        class _Actuator:
            """The supervisor's levers over THIS launcher's job."""

            def evict(self, ranks, reason):
                with lock:
                    live = [r for r, p in procs.items()
                            if p.poll() is None]
                doomed = [r for r in ranks if r in live]
                if doomed and len(live) - len(doomed) < 1:
                    # cannot evict below 1 (the coordinator's own rule):
                    # a FAILED attempt — the bounded retries escalate to
                    # rollback instead of beheading the job
                    return False
                for r in ranks:
                    with lock:
                        p = procs.get(r)
                    if p is not None and p.poll() is None:
                        # SIGKILL, not SIGTERM: a wedged worker (the
                        # hang verdict) won't honor polite signals, and
                        # membership eviction follows from the silence
                        # (heartbeat sweep -> epoch bump -> live shrink)
                        p.kill()
                    # a deliberately evicted rank leaves the fleet view:
                    # the verdict must stop charging the job with it
                    live_agg.mark_evicted(r)
                return True

            def grow(self, reason):
                on_grow()
                return True

            def rollback(self, reason):
                if restart >= args.max_restarts:
                    # no restart budget left: killing the world would be
                    # a job death, not a rollback. Refuse (a counted
                    # FAILED attempt, journaled and bounded) — the
                    # survivors keep limping, which beats nothing.
                    print(
                        f"[supervise] rollback ({reason}) REFUSED: no "
                        "restart budget (give the job --max-restarts)",
                        file=sys.stderr,
                    )
                    return False
                rollback_box["reason"] = reason
                print(
                    f"[supervise] rollback ({reason}): killing the "
                    f"world — {_ckpts.describe_last()}",
                    file=sys.stderr,
                )
                with lock:
                    victims = list(procs.values())
                for p in victims:
                    if p.poll() is None:
                        p.kill()
                return True

        def _print_action(entry):
            print(
                "[supervise] action={action} verdict={verdict} "
                "ranks={ranks} windows={windows} attempt={attempt} "
                "result={result}".format(**entry),
                file=sys.stderr,
            )

        sup = RecoverySupervisor(
            _Actuator(), dry_run=args.supervise_dry_run,
            on_action=_print_action,
        )
        live_agg.attach_supervisor(sup)
        sup_interval = float(constants.get("telemetry_live_interval_s"))

        def _sup_loop():
            warned = False
            while not sup_stop.wait(sup_interval):
                try:
                    sup.observe(live_agg.evaluate())
                except Exception as e:  # noqa: BLE001 - one bad window
                    # must not end supervision, but a PERSISTENTLY
                    # broken sensor must not fail silent either
                    if not warned:
                        warned = True
                        print(
                            f"[supervise] verdict evaluation failed: "
                            f"{e!r} (supervision degraded; further "
                            "failures suppressed)",
                            file=sys.stderr,
                        )
        sup_thread = threading.Thread(
            target=_sup_loop, name="tm-supervisor", daemon=True
        )
        sup_thread.start()
        print(
            "[launch] recovery supervisor armed"
            + (" (dry-run)" if args.supervise_dry_run else "")
            + f" — actions at http://127.0.0.1:{live_agg.http_port}"
            "/actions",
            file=sys.stderr,
        )
        if not args.max_restarts and not args.supervise_dry_run:
            print(
                "[launch] note: --supervise without --max-restarts "
                "has no rollback budget — the rollback rung will "
                "refuse to fire (evict/quarantine still act)",
                file=sys.stderr,
            )

    with lock:
        for _ in range(args.nproc):
            spawn_locked(coord_box["addr"])

    rc = 0
    last_code = 0
    try:
        while True:
            with lock:
                live = {r: p for r, p in procs.items() if p.poll() is None}
                done = {r: p for r, p in procs.items() if p.poll() is not None}
                for r in done:
                    procs.pop(r, None)
            for r, p in sorted(done.items()):
                code = p.returncode
                last_code = 128 - code if code < 0 else code
                level = "exited" if code == 0 else "DIED"
                print(
                    f"[launch] elastic rank {r} {level} with {code}; "
                    f"{len(live)} worker(s) remain — continuing "
                    "(live elasticity: survivors reshard)",
                    file=sys.stderr,
                )
            if not live:
                rc = last_code
                break
            try:
                next(iter(live.values())).wait(timeout=0.2)
            except subprocess.TimeoutExpired:
                pass
    except KeyboardInterrupt:
        rc = 130
        with lock:
            remaining = list(procs.values())
        for p in remaining:
            if p.poll() is None:
                p.send_signal(signal.SIGTERM)
        for p in remaining:
            try:
                p.wait(timeout=10)
            except subprocess.TimeoutExpired:
                p.kill()
    finally:
        sup_stop.set()
        if sup_thread is not None:
            sup_thread.join(timeout=5)
        coord.close()
        for reader in readers:
            reader.join(timeout=5)
        for f in logs:
            f.close()
        _close_live_aggregator(live_agg, telemetry_dir)
    if rollback_box.get("reason") and rc == 0:
        # every worker exited 0 despite a rollback kill (a race on the
        # way down): the attempt must still read as failed so the
        # restart loop relaunches from the checkpoint
        rc = 1
    return rc


def _run_world(args, target, extra, restart: int) -> int:
    """Spawn the full world once and wait for it (one restart attempt)."""
    # Restart attempts need a coordinator port the failed attempt's
    # lingering socket cannot shadow. Single-node relaunches pick a
    # fresh free port; multi-node relaunches cannot communicate a fresh
    # choice, so every node derives the SAME next port by arithmetic:
    # attempt k = --coordinator's port + k (reserve the range).
    if args.coordinator and args.nnodes > 1 and restart:
        host, _, port = args.coordinator.rpartition(":")
        coordinator = f"{host}:{int(port) + restart}"
    else:
        coordinator = (
            args.coordinator if restart == 0 and args.coordinator else None
        ) or f"localhost:{_free_port()}"
    world = args.nnodes * args.nproc
    base = args.node_rank * args.nproc
    procs: List[subprocess.Popen] = []
    logs = []
    readers: List[threading.Thread] = []
    log_dir = Path(args.log_dir) if args.log_dir else None
    if log_dir is not None:
        log_dir.mkdir(parents=True, exist_ok=True)
    telemetry_dir = Path(args.telemetry_dir) if args.telemetry_dir else None
    if telemetry_dir is not None:
        telemetry_dir.mkdir(parents=True, exist_ok=True)
        # clear liveness/hang artifacts from a previous attempt or a
        # reused dir: a SIGKILL'd rank never retracts its heartbeat, and
        # a leftover hang report (or live-plane dead-rank marker) would
        # read as THIS run's diagnosis
        for pattern in ("heartbeat_rank_*.json", "hang_rank_*.json",
                        "dead_rank_*.json"):
            for stale in telemetry_dir.glob(pattern):
                try:
                    stale.unlink()
                except OSError:
                    pass
    live_agg = _start_live_aggregator(args, telemetry_dir)
    for i in range(args.nproc):
        rank = base + i
        # _worker_env: PROCESS_ID/RESTART_COUNT, --set-constant knob
        # overrides (applied by start() pre-bootstrap), watchdog arming,
        # and the virtual-CPU-mesh flags
        env = _worker_env(args, rank, restart)
        env["TORCHMPI_TPU_COORDINATOR"] = coordinator
        env["TORCHMPI_TPU_NUM_PROCESSES"] = str(world)
        if telemetry_dir is not None:
            # the env var both enables telemetry in the rank and registers
            # its atexit dump (torchmpi_tpu.telemetry import-time hook);
            # restart attempts keep distinct files like the logs do
            tname = (
                f"telemetry_rank_{rank}.json" if restart == 0
                else f"telemetry_rank_{rank}.restart{restart}.json"
            )
            env["TORCHMPI_TPU_TELEMETRY"] = "1"
            env["TORCHMPI_TPU_TELEMETRY_DUMP"] = str(telemetry_dir / tname)
        if live_agg is not None:
            # arm the per-rank live exporter (telemetry import-time
            # hook) streaming to the launcher's aggregator
            env["TORCHMPI_TPU_TELEMETRY"] = "1"
            env["TORCHMPI_TPU_TELEMETRY_LIVE"] = (
                f"127.0.0.1:{live_agg.ingest_port}"
            )
        if log_dir is not None:
            # restart attempts keep distinct logs: the failed attempt's
            # tail is the evidence worth reading
            name = (
                f"rank_{rank}.log" if restart == 0
                else f"rank_{rank}.restart{restart}.log"
            )
            out = open(log_dir / name, "w")
            logs.append(out)
            proc = subprocess.Popen(
                target + extra, env=env, stdout=out,
                stderr=subprocess.STDOUT,
            )
        else:
            proc = subprocess.Popen(
                target + extra, env=env, stdout=subprocess.PIPE,
                stderr=subprocess.STDOUT, text=True,
            )
            reader = threading.Thread(
                target=_stream, args=(proc, rank), daemon=True
            )
            reader.start()
            readers.append(reader)
        procs.append(proc)

    # one rank failing kills the rest (the reference needed manual pkill)
    rc = 0
    try:
        remaining = set(range(args.nproc))
        while remaining and rc == 0:
            for i in [i for i in remaining if procs[i].poll() is not None]:
                remaining.discard(i)
                code = procs[i].returncode
                if code != 0 and rc == 0:
                    # signal deaths (segfault/OOM-kill) surface as the
                    # conventional 128+signum, not Popen's negative code
                    # (sys.exit(-9) would report 247)
                    rc = 128 - code if code < 0 else code
                    print(
                        f"[launch] rank {base + i} exited with {code}; "
                        "terminating remaining ranks",
                        file=sys.stderr,
                    )
            if rc == 0 and remaining:
                try:
                    procs[sorted(remaining)[0]].wait(timeout=0.2)
                except subprocess.TimeoutExpired:
                    pass
    except KeyboardInterrupt:
        rc = rc or 130
    finally:
        for p in procs:
            if p.poll() is None:
                p.send_signal(signal.SIGTERM)
        for p in procs:
            try:
                p.wait(timeout=10)
            except subprocess.TimeoutExpired:
                p.kill()
        # drain the stream readers before returning: daemon threads die
        # with the interpreter, and the undrained tail of a failed rank's
        # output is exactly the part that explains the failure
        for reader in readers:
            reader.join(timeout=5)
        for f in logs:
            f.close()
        _close_live_aggregator(live_agg, telemetry_dir)
    return rc


if __name__ == "__main__":
    sys.exit(main())
