"""Multi-process launcher — the ``mpirun`` / ``scripts/wrap.sh`` analog.

The reference's whole UX is ``mpirun -n N wrap.sh luajit script.lua``
(``scripts/wrap.sh``, ``scripts/ompirun.sh``): N identical processes, the
world discovered from the environment, per-rank log redirection, and
manual ``pkill`` when a rank died (``dependencies/README.md:46-49``).
This is that launcher, TPU-native:

    python -m torchmpi_tpu.launch --nproc 4 examples/mnist_allreduce.py
    python -m torchmpi_tpu.launch --nproc 2 --cpu-devices 2 train.py -- --lr 0.1

- spawns ``--nproc`` copies of the script (or ``-m module``) with
  ``TORCHMPI_TPU_COORDINATOR/NUM_PROCESSES/PROCESS_ID`` set;
  ``mpi.start()`` reads them, so an unmodified script becomes rank i of N
  (the MPI_Init-reads-mpirun's-env contract);
- ``--cpu-devices K`` gives each process a K-device virtual CPU mesh
  (XLA_FLAGS + TORCHMPI_TPU_FORCE_CPU) — the "multi-node without a
  cluster" test mode (SURVEY.md §4);
- ``--log-dir DIR`` writes ``rank_<i>.log`` per process (wrap.sh's
  ``LOG_TO_FILE``); default streams every line prefixed ``[i]``;
- one rank failing kills the rest (no manual pkill) and the launcher
  exits with that rank's code; ``--nnodes/--node-rank/--coordinator``
  extend the same contract across hosts.
"""

from __future__ import annotations

import argparse
import os
import signal
import socket
import subprocess
import sys
import threading
from pathlib import Path
from typing import List, Optional


def _free_port() -> int:
    with socket.socket() as s:
        s.bind(("localhost", 0))
        return s.getsockname()[1]


def _stream(proc: subprocess.Popen, rank: int) -> None:
    for line in proc.stdout:  # type: ignore[union-attr]
        sys.stdout.write(f"[{rank}] {line}")
        sys.stdout.flush()


def main(argv: Optional[List[str]] = None) -> int:
    ap = argparse.ArgumentParser(
        prog="python -m torchmpi_tpu.launch",
        description="spawn N torchmpi_tpu controller processes (mpirun analog)",
    )
    ap.add_argument("--nproc", type=int, required=True,
                    help="processes to launch on THIS host")
    ap.add_argument("--cpu-devices", type=int, default=0,
                    help="give each process a K-device virtual CPU mesh")
    ap.add_argument("--log-dir", default=None,
                    help="write rank_<i>.log files instead of streaming")
    ap.add_argument("--telemetry-dir", default=None,
                    help="enable telemetry in every rank and dump a "
                    "per-rank metrics snapshot + Perfetto trace JSON "
                    "(telemetry_rank_<i>.json / .trace.json) there on exit "
                    "— including abnormal exit (SIGTERM/SIGINT/fault "
                    "handlers); feed the dir to "
                    "`python -m torchmpi_tpu.telemetry.analyze`")
    ap.add_argument("--watchdog-timeout", type=float, default=0,
                    help="arm the per-rank hang watchdog: a collective or "
                    "PS RPC in flight (or a peer heartbeat stale) longer "
                    "than this many seconds dumps a structured hang report "
                    "(hang_rank_<i>.json, in --telemetry-dir when set)")
    ap.add_argument("--nnodes", type=int, default=1,
                    help="total hosts in the job")
    ap.add_argument("--node-rank", type=int, default=0,
                    help="this host's index in [0, nnodes)")
    ap.add_argument("--coordinator", default=None,
                    help="host:port of process 0 (required when nnodes > 1; "
                    "default: localhost:<free port>)")
    ap.add_argument("--set-constant", action="append", default=[],
                    metavar="NAME=VALUE",
                    help="override a torchmpi_tpu.constants knob in every "
                    "rank (repeatable), e.g. --set-constant ps_replication=2 "
                    "--set-constant parameterserver_wire_dtype=int8. "
                    "Applied by start() before the runtime bootstraps "
                    "(and re-applied over persisted tuned values), so "
                    "fabric knobs like the PS replica-chain length are "
                    "deployable without editing the training script.")
    ap.add_argument("--max-restarts", type=int, default=0,
                    help="elastic full-job restarts: when a rank dies, kill "
                    "the survivors and relaunch ALL ranks up to this many "
                    "times (scripts see TORCHMPI_TPU_RESTART_COUNT and "
                    "should resume from their last checkpoint). Single-node "
                    "jobs only.")
    ap.add_argument("-m", "--module", default=None,
                    help="run a module (python -m) instead of a script")
    ap.add_argument("script", nargs="?", default=None,
                    help="script path (omit when using --module)")
    ap.add_argument("script_args", nargs=argparse.REMAINDER,
                    help="arguments passed through to the script")
    args = ap.parse_args(argv)

    if args.module is not None and args.script is not None:
        # with -m, the `script` positional greedily eats the first
        # passthrough token — everything positional belongs to the module
        args.script_args = [args.script] + args.script_args
        args.script = None
    if (args.script is None) == (args.module is None):
        ap.error("exactly one of a script path or --module is required")
    if args.nproc < 1:
        ap.error(f"--nproc must be >= 1, got {args.nproc}")
    if args.nnodes > 1 and args.coordinator is None:
        ap.error("--coordinator host:port is required when nnodes > 1")
    if not 0 <= args.node_rank < args.nnodes:
        ap.error(f"--node-rank {args.node_rank} outside [0, {args.nnodes})")
    if args.max_restarts < 0:
        ap.error(f"--max-restarts must be >= 0, got {args.max_restarts}")
    if args.max_restarts and args.nnodes > 1:
        # a restart needs a fresh coordinator port and a synchronized
        # world relaunch; across hosts that coordination does not exist
        ap.error("--max-restarts requires a single-node job (nnodes == 1)")
    if args.watchdog_timeout < 0:
        ap.error(
            f"--watchdog-timeout must be >= 0, got {args.watchdog_timeout}"
        )
    for spec in args.set_constant:
        if "=" not in spec:
            ap.error(f"--set-constant expects NAME=VALUE, got {spec!r}")

    target = (
        [sys.executable, "-m", args.module]
        if args.module
        else [sys.executable, args.script]
    )
    # argparse.REMAINDER keeps a leading "--" separator; drop it
    extra = args.script_args
    if extra and extra[0] == "--":
        extra = extra[1:]

    # Elastic recovery = full-job restart from the last checkpoint: the
    # practical TPU model (a controller process cannot rejoin a running
    # jax.distributed job; the reference had no recovery at all — a dead
    # rank meant manual pkill, dependencies/README.md:46-49). Each
    # attempt gets a FRESH auto-chosen coordinator port (the old
    # service's socket may linger); scripts read
    # TORCHMPI_TPU_RESTART_COUNT to resume instead of cold-start.
    for restart in range(args.max_restarts + 1):
        rc = _run_world(args, target, extra, restart)
        if rc == 0 or rc == 130 or restart == args.max_restarts:
            return rc  # success, operator interrupt, or budget spent
        print(
            f"[launch] attempt {restart} failed with rc={rc}; "
            f"restarting the world "
            f"({args.max_restarts - restart} restart(s) left)",
            file=sys.stderr,
        )
    return rc


def _run_world(args, target, extra, restart: int) -> int:
    """Spawn the full world once and wait for it (one elastic attempt)."""
    # restart attempts ignore an explicit --coordinator port: the failed
    # attempt's service socket can linger, and the fresh-port choice is
    # what the relaunch depends on (single-node only, so auto-choice is
    # always valid here)
    coordinator = (
        args.coordinator if restart == 0 and args.coordinator else None
    ) or f"localhost:{_free_port()}"
    world = args.nnodes * args.nproc
    base = args.node_rank * args.nproc
    procs: List[subprocess.Popen] = []
    logs = []
    readers: List[threading.Thread] = []
    log_dir = Path(args.log_dir) if args.log_dir else None
    if log_dir is not None:
        log_dir.mkdir(parents=True, exist_ok=True)
    telemetry_dir = Path(args.telemetry_dir) if args.telemetry_dir else None
    if telemetry_dir is not None:
        telemetry_dir.mkdir(parents=True, exist_ok=True)
        # clear liveness/hang artifacts from a previous attempt or a
        # reused dir: a SIGKILL'd rank never retracts its heartbeat, and
        # a leftover hang report would read as THIS run's diagnosis
        for pattern in ("heartbeat_rank_*.json", "hang_rank_*.json"):
            for stale in telemetry_dir.glob(pattern):
                try:
                    stale.unlink()
                except OSError:
                    pass
    for i in range(args.nproc):
        rank = base + i
        env = dict(
            os.environ,
            TORCHMPI_TPU_COORDINATOR=coordinator,
            TORCHMPI_TPU_NUM_PROCESSES=str(world),
            TORCHMPI_TPU_PROCESS_ID=str(rank),
            TORCHMPI_TPU_RESTART_COUNT=str(restart),
        )
        if telemetry_dir is not None:
            # the env var both enables telemetry in the rank and registers
            # its atexit dump (torchmpi_tpu.telemetry import-time hook);
            # restart attempts keep distinct files like the logs do
            tname = (
                f"telemetry_rank_{rank}.json" if restart == 0
                else f"telemetry_rank_{rank}.restart{restart}.json"
            )
            env["TORCHMPI_TPU_TELEMETRY"] = "1"
            env["TORCHMPI_TPU_TELEMETRY_DUMP"] = str(telemetry_dir / tname)
        if args.watchdog_timeout:
            # armed at telemetry import in the rank (pre-start coverage);
            # heartbeats + hang reports land beside the telemetry dumps
            env["TORCHMPI_TPU_WATCHDOG"] = str(args.watchdog_timeout)
        if args.set_constant:
            # applied by runtime_state.start() in the rank, before any
            # runtime state exists; explicit start(**overrides) still win
            env["TORCHMPI_TPU_CONSTANTS"] = ";".join(args.set_constant)
        if args.cpu_devices:
            env["XLA_FLAGS"] = (
                env.get("XLA_FLAGS", "")
                + f" --xla_force_host_platform_device_count={args.cpu_devices}"
            ).strip()
            env["TORCHMPI_TPU_FORCE_CPU"] = "1"
            env["JAX_PLATFORMS"] = "cpu"
        if log_dir is not None:
            # restart attempts keep distinct logs: the failed attempt's
            # tail is the evidence worth reading
            name = (
                f"rank_{rank}.log" if restart == 0
                else f"rank_{rank}.restart{restart}.log"
            )
            out = open(log_dir / name, "w")
            logs.append(out)
            proc = subprocess.Popen(
                target + extra, env=env, stdout=out,
                stderr=subprocess.STDOUT,
            )
        else:
            proc = subprocess.Popen(
                target + extra, env=env, stdout=subprocess.PIPE,
                stderr=subprocess.STDOUT, text=True,
            )
            reader = threading.Thread(
                target=_stream, args=(proc, rank), daemon=True
            )
            reader.start()
            readers.append(reader)
        procs.append(proc)

    # one rank failing kills the rest (the reference needed manual pkill)
    rc = 0
    try:
        remaining = set(range(args.nproc))
        while remaining and rc == 0:
            for i in [i for i in remaining if procs[i].poll() is not None]:
                remaining.discard(i)
                code = procs[i].returncode
                if code != 0 and rc == 0:
                    # signal deaths (segfault/OOM-kill) surface as the
                    # conventional 128+signum, not Popen's negative code
                    # (sys.exit(-9) would report 247)
                    rc = 128 - code if code < 0 else code
                    print(
                        f"[launch] rank {base + i} exited with {code}; "
                        "terminating remaining ranks",
                        file=sys.stderr,
                    )
            if rc == 0 and remaining:
                try:
                    procs[sorted(remaining)[0]].wait(timeout=0.2)
                except subprocess.TimeoutExpired:
                    pass
    except KeyboardInterrupt:
        rc = rc or 130
    finally:
        for p in procs:
            if p.poll() is None:
                p.send_signal(signal.SIGTERM)
        for p in procs:
            try:
                p.wait(timeout=10)
            except subprocess.TimeoutExpired:
                p.kill()
        # drain the stream readers before returning: daemon threads die
        # with the interpreter, and the undrained tail of a failed rank's
        # output is exactly the part that explains the failure
        for reader in readers:
            reader.join(timeout=5)
        for f in logs:
            f.close()
    return rc


if __name__ == "__main__":
    sys.exit(main())
