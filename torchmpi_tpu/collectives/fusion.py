"""Coalescing dispatch for the eager latency path.

The MNIST north-star is *latency-bound*: a step issues one eager
``run()``/``run_async()`` per gradient bucket, each paying Python-side
hashing, cache lookup and dispatch. GC3 (arXiv:2201.11840) compiles
collective *plans* once and replays them; the TF/CUDA-aware-MPI
characterization (arXiv:1810.11112) shows small-tensor coalescing into a
fused buffer is the biggest lever for latency-bound data-parallel
training. This module is both, for the eager surface:

- :class:`FusionBuffer` packs pending same-``(op, dtype, wire, backend)``
  async collectives into ONE contiguous flat buffer and flushes them as a
  *single* allreduce / reduce-scatter when the pending per-rank payload
  reaches ``fusion_buffer_bytes``, or on ``wait()`` / ``sync_all()``.
- A flush is ONE XLA dispatch: ``eager.run_fused`` compiles
  pack-concat + collective into a single plan per (layout, dtype,
  routing) and replays it — not k dispatches, not even pack + collective
  = 2. (The eager ``GradientBuckets`` path keeps its own persistent
  *donated* flat buffers — the ``BlockSequential.lua:29-89``
  flatten-once idiom — because its per-bucket handles are part of the
  public API.)
- Caller tensors are only ever *read* (copied into the fused buffer);
  donation never touches a live gradient.

``fusion_min_tensors`` guards the degenerate case: a flush holding fewer
tensors than that dispatches them unfused (packing one tensor buys
nothing). ``fusion_buffer_bytes = 0`` disables coalescing entirely —
every submit dispatches immediately, the pre-fusion behavior.

Telemetry (when enabled): tensors coalesced, flushes by reason
(``bytes`` / ``wait`` / ``explicit``), and fused-vs-unfused dispatch
latency histograms — the evidence stream ``bench.py --microbench`` reads.
"""

from __future__ import annotations

import time
from typing import Dict, List, Optional, Tuple

import jax
import jax.numpy as jnp

from .. import constants, telemetry as _telemetry
from ..runtime.communicator import Communicator
from ..runtime.handles import SyncHandle, handles
from ..telemetry import flightrecorder as _flight
from . import eager

# ops the fusion layer understands; everything else passes through
_FUSABLE = ("allreduce", "reducescatter")

_MET = None


def _metric_handles():
    global _MET
    if _MET is None:
        m = _telemetry.metrics
        _MET = (
            m.counter(
                "tm_fusion_tensors_total",
                "tensors entering the fusion layer by op/wire/path "
                "(path=fused: coalesced into a flat buffer; "
                "path=unfused: dispatched individually)",
            ),
            m.counter(
                "tm_fusion_flushes_total",
                "fusion-buffer flushes by op/reason "
                "(bytes=capacity, wait=handle drain, explicit=flush_all)",
            ),
            m.histogram(
                "tm_fusion_dispatch_seconds",
                "host-side dispatch wall time per flush by op/path — the "
                "fused-vs-unfused comparison bench.py --microbench reads",
            ),
        )
    return _MET


def count_coalesced(op: str, wire, n: int, path: str = "fused") -> None:
    """Feed the coalescing counters from packing done OUTSIDE the
    FusionBuffer (e.g. ``GradientBuckets``' persistent flat buffers)."""
    if _telemetry.enabled() and n:
        tensors, _, _ = _metric_handles()
        tensors.inc(n, op=op, wire=wire or "auto", path=path)


class FusionHandle(SyncHandle):
    """Handle for one tensor submitted to a :class:`FusionBuffer`.

    ``wait()`` forces the owning group's flush (reason ``wait``) if it has
    not flushed yet, then slices this tensor's segment out of the fused
    result. Registered in the global handle table under kind ``"fusion"``
    — NOT ``"collective"``: ``sync_all()`` (and thus ``stop()``) drains
    every kind, but ``run_async``'s in-flight backpressure only drains
    ``"collective"`` handles, so a below-threshold flush that dispatches
    unfused through ``run_async`` can never be handed one of its own
    group's handles mid-flush (re-entrant double dispatch). A pending
    fused submission is not an in-flight collective anyway."""

    __slots__ = ("_group", "_idx")

    def __init__(self, group: "_PendingGroup", idx: int):
        # the arrays slot is a placeholder: wait() is fully overridden
        super().__init__(arrays=())
        self._group = group
        self._idx = idx

    def wait(self):
        if self._done:
            return self._result
        out = self._group.result_for(self._idx)
        self._result = jax.block_until_ready(out)
        self._done = True
        if self._table_index is not None:
            handles._discard(self._table_index)
            self._table_index = None
        return self._result

    @property
    def done(self) -> bool:
        return self._done


class _PendingGroup:
    """Tensors awaiting one fused dispatch: same (op, dtype, wire,
    backend), each flattened to a [p, n] slab at a recorded offset."""

    def __init__(self, buffer: "FusionBuffer", key: Tuple, op: str, dtype,
                 wire, backend):
        self.buffer = buffer
        self.key = key
        self.op = op
        self.dtype = dtype
        self.itemsize = jnp.dtype(dtype).itemsize
        self.wire = wire
        self.backend = backend
        self.segments: List[Tuple[int, Tuple[int, ...]]] = []  # (n, shape)
        self.flats: List = []
        self.total = 0
        self._results: Optional[List] = None
        self._fused_buf = None

    def add(self, flat, shape) -> int:
        idx = len(self.segments)
        self.segments.append((int(flat.shape[1]), tuple(shape)))
        self.flats.append(flat)
        self.total += int(flat.shape[1])
        return idx

    @property
    def pending_bytes(self) -> int:
        return self.total * self.itemsize

    def flushed(self) -> bool:
        return self._results is not None or self._fused_buf is not None

    def result_for(self, idx: int):
        if not self.flushed():
            self.buffer._flush_group(self, reason="wait")
        if self._results is not None:
            r = self._results[idx]
            if isinstance(r, SyncHandle):
                r = self._results[idx] = r.wait()
            return r
        n, shape = self.segments[idx]
        off = sum(s[0] for s in self.segments[:idx])
        if self.op == "reducescatter":
            # interleaved packing (see _flush_group): rank r's fused block
            # holds each tensor's r-th scatter chunk contiguously, so the
            # segment comes back out by offset/p and the scattered shape
            # keeps every dim but the last, which shrank by p
            p = self.buffer.comm.size
            seg = self._fused_buf[:, off // p : (off + n) // p]
            return seg.reshape(shape[:-1] + (shape[-1] // p,))
        return self._fused_buf[:, off : off + n].reshape(shape)


class FusionBuffer:
    """Per-communicator coalescing dispatcher for eager async collectives.

    Obtain via :func:`get_fusion_buffer` (cached on the communicator, torn
    down by ``free_collective_resources``). ``submit()`` is the drop-in
    replacement for ``eager.run_async``: it returns a handle immediately;
    the collective itself launches when the buffer fills or the handle is
    waited."""

    def __init__(self, comm: Communicator):
        self.comm = comm
        self._groups: Dict[Tuple, _PendingGroup] = {}

    # ------------------------------------------------------------------
    def submit(
        self,
        op: str,
        x,
        wire_dtype: Optional[str] = None,
        backend: Optional[str] = None,
    ) -> SyncHandle:
        """Queue one rank-stacked tensor for a fused ``op``; returns a
        handle. Falls through to an immediate unfused async dispatch when
        coalescing cannot engage (disabled, unfusable op, or a
        reducescatter whose last dim does not divide by the world size)."""
        if not isinstance(x, jax.Array):
            x = jnp.asarray(x)
        cap = constants.get("fusion_buffer_bytes")
        fusable = (
            cap > 0
            and op in _FUSABLE
            and x.ndim >= 2
            and x.shape[0] == self.comm.size
            and not (
                op == "reducescatter"
                and (x.ndim != 2 or x.shape[-1] % self.comm.size)
            )
        )
        if not fusable:
            self._count_tensor(op, wire_dtype, "unfused")
            return self._dispatch_unfused(op, x, wire_dtype, backend)
        dtype = x.dtype
        key = (op, dtype, wire_dtype, backend)
        group = self._groups.get(key)
        if group is None:
            group = self._groups[key] = _PendingGroup(
                self, key, op, dtype, wire_dtype, backend
            )
        # reshape only when needed: a [p, n] tensor (the gradient-bucket
        # shape) skips the per-submit dispatch entirely
        flat = x if x.ndim == 2 else jnp.reshape(x, (self.comm.size, -1))
        group.add(flat, x.shape)
        h = FusionHandle(group, len(group.segments) - 1)
        handles.register(h, kind="fusion")
        if group.pending_bytes >= cap:
            self._flush_group(group, reason="bytes")
        return h

    def flush_all(self, reason: str = "explicit") -> None:
        """Dispatch every pending group now (handles stay waitable).

        Under ``overlap_schedule='reverse'`` groups flush in REVERSE
        insertion order: gradient producers submit forward-layer-first,
        so the reverse order puts the last layers — the first gradients
        ready during backward — on the wire first (the same flush order
        the bucket scheduler dispatches, ``schedule/overlap.py``)."""
        groups = list(self._groups.values())
        if constants.get("overlap_schedule") == "reverse":
            groups.reverse()
        for group in groups:
            if not group.flushed():
                self._flush_group(group, reason=reason)

    def flush_for(self, submitted, reason: str = "wait") -> None:
        """Dispatch only the pending groups the given handles belong to —
        a caller synchronizing ITS tensors must not cut short the
        capacity window of unrelated submitters sharing the buffer."""
        seen = set()
        for h in submitted:
            group = getattr(h, "_group", None)
            if group is not None and id(group) not in seen:
                seen.add(id(group))
                if not group.flushed():
                    self._flush_group(group, reason=reason)

    @property
    def pending_tensors(self) -> int:
        return sum(len(g.segments) for g in self._groups.values())

    # ------------------------------------------------------------------
    def _count_tensor(self, op, wire, path, n: int = 1) -> None:
        if _telemetry.enabled():
            tensors, _, _ = _metric_handles()
            tensors.inc(n, op=op, wire=wire or "auto", path=path)

    def _dispatch_unfused(self, op, x, wire_dtype, backend):
        # route like the public namespace (selector-decided backend when
        # none was pinned); local import breaks the package cycle
        from . import _dispatch as _ns_dispatch

        t0 = time.perf_counter()
        kw = {"wire_dtype": wire_dtype} if op in eager._WIRE_OPS else {}
        h = _ns_dispatch(op, x, self.comm, "async", backend, **kw)
        if _telemetry.enabled():
            _, _, lat = _metric_handles()
            lat.observe(time.perf_counter() - t0, op=op, path="unfused")
        return h

    def _flush_group(self, group: _PendingGroup, reason: str) -> None:
        self._groups.pop(group.key, None)
        telemetry_on = _telemetry.enabled()
        if telemetry_on:
            _, flushes, lat = _metric_handles()
            flushes.inc(op=group.op, reason=reason)
        flight_entry = None
        if _flight.enabled():
            # the flush event itself joins the comm's flight stream (the
            # dispatch it triggers records separately via eager): a
            # cross-rank layout mismatch here IS a desync even when the
            # per-tensor dispatches happen to agree
            flight_entry = _flight.recorder.record(
                _flight.comm_key(self.comm), f"fusion.{group.op}",
                payload=(tuple(n for n, _ in group.segments), group.dtype),
                wire=group.wire or "auto", backend=group.backend or "auto",
                routing=reason,
            )
        if len(group.segments) < max(1, constants.get("fusion_min_tensors")):
            # packing below the threshold costs more than it saves:
            # dispatch each tensor individually (handles index into the
            # per-segment results list)
            self._count_tensor(
                group.op, group.wire, "unfused", len(group.segments)
            )
            try:
                group._results = [
                    self._dispatch_unfused(
                        group.op, flat.reshape(shape), group.wire,
                        group.backend
                    )
                    for flat, (_, shape) in zip(group.flats, group.segments)
                ]
            except BaseException:
                if flight_entry is not None:
                    _flight.FlightRecorder.fail(flight_entry)
                raise
            group.flats = []
            if flight_entry is not None:
                _flight.FlightRecorder.complete(flight_entry)
            return
        self._count_tensor(
            group.op, group.wire, "fused", len(group.segments)
        )
        t0 = time.perf_counter()
        ns = tuple(n for n, _ in group.segments)
        from . import _dispatch as _ns_dispatch

        try:
            out = self._dispatch_fused(group, ns, _ns_dispatch)
        except BaseException:
            if flight_entry is not None:
                _flight.FlightRecorder.fail(flight_entry)
            raise
        if flight_entry is not None:
            _flight.FlightRecorder.complete(flight_entry)
        if telemetry_on:
            lat.observe(time.perf_counter() - t0, op=group.op, path="fused")
        group._fused_buf = (
            out.reshape(self.comm.size, -1) if out.ndim != 2 else out
        )

    def _dispatch_fused(self, group: _PendingGroup, ns, _ns_dispatch):
        if group.op == "reducescatter":
            # interleave so rank r's scattered block holds every tensor's
            # r-th chunk: [p, n_i] -> [p, p, n_i/p], concat chunk axes,
            # flatten back to [p, total] (each n_i divides by p — gated
            # at submit)
            p = self.comm.size
            parts = [
                f.reshape(p, p, n // p) for f, n in zip(group.flats, ns)
            ]
            buf = jnp.concatenate(parts, axis=2).reshape(p, -1)
            group.flats = []
            out = _ns_dispatch(
                group.op, buf, self.comm, "sync", group.backend,
                wire_dtype=group.wire,
            )
        else:
            # allreduce: pack + reduce as ONE compiled plan (run_fused) —
            # a flush of k tensors is a single XLA dispatch
            flats, group.flats = group.flats, []
            out = _ns_dispatch(
                group.op, flats, self.comm, "fused", group.backend,
                wire_dtype=group.wire,
            )
        return out


def get_fusion_buffer(comm: Optional[Communicator] = None) -> FusionBuffer:
    """The communicator's coalescing dispatcher (lazily attached, like the
    executable cache; dropped by ``free_collective_resources``)."""
    if comm is None:
        from .. import runtime_state

        comm = runtime_state.current_communicator()
    fb = getattr(comm, "_fusion_buffer", None)
    if fb is None:
        fb = FusionBuffer(comm)
        comm._fusion_buffer = fb  # type: ignore[attr-defined]
    return fb
