"""Collective backend selector.

Analog of ``mpi.collectiveSelector`` (``torchmpi/init.lua:463-555``): a
preference table keyed on ``(platform, single/multi node, sync/async,
collective)`` listing backend implementations in preference order; the first
*available* one wins. The reference's axes were
``[cpu|gpu][singlenode|multinode][sync|async]`` with backends
``{p2p, nccl, gloo, mpi}``; here the platforms are ``cpu|tpu`` and the
backends are:

- ``xla``  — fused XLA collective (the vendor path; NCCL/MPI analog)
- ``ring`` — custom chunked ``ppermute`` ring (the custom-p2p analog)
- ``pallas`` — Pallas ICI-RDMA ring kernels (TPU only; the cudaIPC analog)

``collective_availability()`` renders the availability matrix string like the
reference's introspection dump (``init.lua:557-660``).

The selector answers *which backend executor is available/preferred*;
*which schedule* a request actually runs (flat / hierarchical / staged
/ tree, cost-modeled and cached) is the schedule compiler's decision —
``python -m torchmpi_tpu.schedule --explain`` is the introspection
surface for that, superseding this module's static preference dump for
routing questions.
"""

from __future__ import annotations

from typing import Dict, List

import jax

_COLLECTIVES = (
    "broadcast",
    "reduce",
    "allreduce",
    "sendreceive",
    "allgather",
    "reducescatter",
    "alltoall",
)


def _pallas_available() -> bool:
    try:
        from ..ops import ring_kernels

        # the interpret test hook makes pallas runnable anywhere: let the
        # selector/autotuner see it too, so interpret-mode coverage is
        # end-to-end (dispatch included), not just direct kernel calls
        if ring_kernels._FORCE_INTERPRET:
            return True
        return (
            jax.devices()[0].platform == "tpu" and ring_kernels.available()
        )
    except Exception:
        return False


def backend_availability() -> Dict[str, bool]:
    return {
        "xla": True,
        "ring": True,
        "pallas": _pallas_available(),
    }


# single-home re-exports (primitives owns the encodings, eager owns the
# op set — re-deriving them here would let the dump drift from dispatch)
from .eager import _WIRE_OPS as WIRE_COLLECTIVES  # noqa: E402
from .primitives import WIRE_DTYPES as WIRE_FORMATS  # noqa: E402


def wire_format_availability() -> Dict[str, bool]:
    """Which wire encodings the custom-ring backends can put on the wire
    (every encoding is implemented on both the ppermute and pallas rings,
    so availability tracks the backends, not the formats)."""
    avail = backend_availability()
    custom = avail["ring"] or avail["pallas"]
    return {"full": True, "bf16": custom, "int8": custom}


# Preference order per (platform, nodes, mode, collective).
# Mirrors the reference's choices in spirit: single-node sync allreduce
# prefers the custom ring (its cudaIPC ring beat NCCL, README.md:104-106);
# small sizes are rerouted to 'xla' by eager.op_route either way.
_DEFAULT: Dict[str, Dict[str, Dict[str, Dict[str, List[str]]]]] = {
    "cpu": {
        "singlenode": {
            "sync": {c: ["xla", "ring"] for c in _COLLECTIVES},
            "async": {c: ["xla", "ring"] for c in _COLLECTIVES},
        },
        "multinode": {
            "sync": {c: ["xla", "ring"] for c in _COLLECTIVES},
            "async": {c: ["xla", "ring"] for c in _COLLECTIVES},
        },
    },
    "tpu": {
        "singlenode": {
            "sync": {
                "broadcast": ["pallas", "ring", "xla"],
                "reduce": ["ring", "xla"],
                "allreduce": ["pallas", "ring", "xla"],
                "sendreceive": ["xla", "ring"],
                "allgather": ["xla", "ring"],
                "reducescatter": ["xla", "ring"],
                "alltoall": ["xla", "ring"],
            },
            "async": {c: ["xla", "ring"] for c in _COLLECTIVES},
        },
        "multinode": {
            # Cross-host (DCN) traffic: trust XLA's hierarchical lowering
            # first, custom ring second (the staged/direct choice is a
            # constant, like kUseStagedCollectives).
            "sync": {c: ["xla", "ring"] for c in _COLLECTIVES},
            "async": {c: ["xla", "ring"] for c in _COLLECTIVES},
        },
    },
}


class CollectiveSelector:
    def __init__(self):
        self.table = _DEFAULT

    def select(
        self,
        collective: str,
        platform: str = None,
        multinode: bool = False,
        mode: str = "sync",
    ) -> str:
        platform = platform or jax.devices()[0].platform
        if platform not in ("cpu", "tpu"):
            platform = "tpu"  # any accelerator takes the tpu table
        nodes = "multinode" if multinode else "singlenode"
        prefs = self.table[platform][nodes][mode][collective]
        avail = backend_availability()
        for b in prefs:
            if avail.get(b):
                return b
        return "xla"

    def select_wire(self, collective: str, nelem: int = None,
                    dtype=None) -> str:
        """The wire format an eager call of ``collective`` would ship:
        the ``wire_dtype`` constant (the autotuner's persisted pick)
        gated by the engagement rules. ``nelem``/``dtype`` None = assume
        a large f32 payload (the routing question, not a specific call).
        """
        import jax.numpy as jnp

        from .. import constants
        from .eager import resolve_wire_dtype

        if nelem is None:
            nelem = constants.get("wire_quant_min_elements")
        return resolve_wire_dtype(
            collective, nelem, dtype if dtype is not None else jnp.float32
        )

    def describe(self) -> str:
        from .. import constants

        avail = backend_availability()
        lines = ["Backend availability: " + ", ".join(
            f"{k}={'yes' if v else 'no'}" for k, v in avail.items()
        )]
        wf = wire_format_availability()
        lines.append(
            "Wire formats (fp32 "
            + "/".join(WIRE_COLLECTIVES)
            + " >= wire_quant_min_elements): "
            + ", ".join(f"{k}={'yes' if v else 'no'}" for k, v in wf.items())
            + f" -> default {constants.get('wire_dtype')}"
        )
        for coll in WIRE_COLLECTIVES:
            # what a large f32 payload of this collective would ship
            lines.append(f"wire.{coll}: -> {self.select_wire(coll)}")
        for platform, nodes_tbl in self.table.items():
            for nodes, mode_tbl in nodes_tbl.items():
                for mode, coll_tbl in mode_tbl.items():
                    for coll, prefs in coll_tbl.items():
                        chosen = self.select(coll, platform, nodes == "multinode", mode)
                        lines.append(
                            f"{platform}.{nodes}.{mode}.{coll}: "
                            f"{' > '.join(prefs)} -> {chosen}"
                        )
        return "\n".join(lines)


selector = CollectiveSelector()


def collective_availability() -> str:
    return selector.describe()
