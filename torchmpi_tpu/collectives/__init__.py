"""Public collectives surface.

Namespace layout mirrors the reference Lua API
(``torchmpi/init.lua:145-365``): default (selector-routed) sync collectives at
the top level, per-backend namespaces (``xla`` ≙ stock MPI/NCCL, ``ring`` ≙
custom p2p), and ``async_`` variants returning :class:`SyncHandle`s. Scalar
collectives cross *processes* (multi-controller JAX) and are identity in
single-controller mode, where every rank lives in one process.
"""

from __future__ import annotations

from functools import partial
from typing import Optional

import jax

from ..runtime.communicator import Communicator
from ..runtime.handles import SyncHandle
from . import eager, primitives
from .eager import free_collective_resources, precompile
from .fusion import FusionBuffer, get_fusion_buffer
from .selector import collective_availability, selector


def _current_comm(comm: Optional[Communicator]) -> Communicator:
    if comm is not None:
        return comm
    from .. import runtime_state

    return runtime_state.current_communicator()


def _dispatch(op, x, comm, mode, backend=None, **kw):
    comm = _current_comm(comm)
    if backend is None:
        # Selector decisions are invariant per (comm, op, mode): memoize on
        # the communicator to keep eager launch overhead minimal (the
        # reference's <50us async-launch budget).
        cache = getattr(comm, "_selector_cache", None)
        if cache is None:
            cache = comm._selector_cache = {}
        backend = cache.get((op, mode))
        if backend is None:
            platform = comm._devices[0].platform
            backend = selector.select(
                op, platform, multinode=comm.num_nodes() > 1,
                # the fused plan dispatches synchronously; the selector
                # table only distinguishes sync/async
                mode="sync" if mode == "fused" else mode,
            )
            cache[(op, mode)] = backend
        if backend in ("ring", "pallas"):
            # The selector decides xla-vs-custom-ring; which custom ring
            # implements it is the ring_implementation constant (read per
            # call — it is mutable until freeze):
            from .. import constants
            from .selector import backend_availability

            impl = constants.get("ring_implementation")
            if impl in ("pallas", "pallas_bidir") and backend_availability().get(
                "pallas"
            ):
                backend = "pallas"
            elif impl == "ppermute":
                backend = "ring"
    if mode == "sync":
        return eager.run(op, x, comm, backend=backend, **kw)
    if mode == "fused":
        # x is a LIST of same-dtype [p, n_i] slabs; one compiled plan
        # packs and reduces them (see eager.run_fused)
        return eager.run_fused(op, x, comm, backend=backend, **kw)
    return eager.run_async(op, x, comm, backend=backend, **kw)


# --- selector-routed (default) namespace -----------------------------------
def broadcast_tensor(x, root=0, comm=None):
    return _dispatch("broadcast", x, comm, "sync", root=root)


def reduce_tensor(x, root=0, comm=None):
    return _dispatch("reduce", x, comm, "sync", root=root)


def allreduce_tensor(x, comm=None, wire_dtype=None):
    """Sum-allreduce. ``wire_dtype`` ('full' | 'bf16' | 'int8') overrides
    the wire format for the bandwidth path (None = constants default;
    engages only for f32 payloads above wire_quant_min_elements)."""
    return _dispatch("allreduce", x, comm, "sync", wire_dtype=wire_dtype)


def allgather_tensor(x, comm=None):
    return _dispatch("allgather", x, comm, "sync")


def sendreceive_tensor(x, src, dst, comm=None):
    return _dispatch("sendreceive", x, comm, "sync", src=src, dst=dst)


def reducescatter_tensor(x, comm=None, wire_dtype=None):
    """Reduce-scatter over the LAST dim (dual of ``allgather_tensor``'s
    concat-last-dim contract): rank r's output block is slice r of the
    elementwise sum. Beyond the reference's surface (it has no
    reduce-scatter collective; its ring used one internally,
    ``lib/detail/collectives.cpp:128-326``) — exposed because ZeRO-style
    sharded optimizers consume it directly. ``wire_dtype`` as in
    :func:`allreduce_tensor`."""
    return _dispatch("reducescatter", x, comm, "sync", wire_dtype=wire_dtype)


def alltoall_tensor(x, comm=None):
    """All-to-all: input [p, p, ...] where block [r, s] is rank r's payload
    for rank s; output block [r, j] is what rank j sent rank r. Beyond the
    reference's surface (its alltoall-shaped traffic was the PS shard
    fan-out, ``lib/parameterserver.cpp:309-353``) — exposed because expert
    parallelism dispatches through it (``parallel/ep.py``)."""
    return _dispatch("alltoall", x, comm, "sync")


def allgatherv_tensor(blocks, comm=None, backend: str = "xla"):
    """Variable-size allgather over ragged last-dim per-rank blocks
    (reference ``Allgatherv``, ``lib/collectives.cpp:245-290``)."""
    return eager.run_allgatherv(blocks, _current_comm(comm), backend=backend)


class _BackendNS:
    """``mpi.p2p.*`` / ``mpi.nccl.*`` style per-backend namespaces."""

    def __init__(self, backend: str, mode: str):
        self._backend = backend
        self._mode = mode

    def broadcast_tensor(self, x, root=0, comm=None):
        return _dispatch("broadcast", x, comm, self._mode, self._backend, root=root)

    def reduce_tensor(self, x, root=0, comm=None):
        return _dispatch("reduce", x, comm, self._mode, self._backend, root=root)

    def allreduce_tensor(self, x, comm=None, wire_dtype=None):
        return _dispatch(
            "allreduce", x, comm, self._mode, self._backend,
            wire_dtype=wire_dtype,
        )

    def allgather_tensor(self, x, comm=None):
        return _dispatch("allgather", x, comm, self._mode, self._backend)

    def sendreceive_tensor(self, x, src, dst, comm=None):
        return _dispatch(
            "sendreceive", x, comm, self._mode, self._backend, src=src, dst=dst
        )

    def reducescatter_tensor(self, x, comm=None, wire_dtype=None):
        return _dispatch(
            "reducescatter", x, comm, self._mode, self._backend,
            wire_dtype=wire_dtype,
        )

    def alltoall_tensor(self, x, comm=None):
        return _dispatch("alltoall", x, comm, self._mode, self._backend)


class _AsyncNS(_BackendNS):
    def __init__(self, backend=None):
        super().__init__(backend, "async")
        self.xla = _BackendNS("xla", "async")
        self.ring = _BackendNS("ring", "async")
        self.pallas = _BackendNS("pallas", "async")


xla = _BackendNS("xla", "sync")
ring = _BackendNS("ring", "sync")
pallas = _BackendNS("pallas", "sync")
async_ = _AsyncNS()


# --- scalar collectives (init.lua:125-134) ---------------------------------
def broadcast_scalar(value, root: int = 0):
    """Broadcast a host scalar across *processes* (multi-controller)."""
    if jax.process_count() == 1:
        return value
    from jax.experimental import multihost_utils

    import numpy as np

    arr = multihost_utils.broadcast_one_to_all(
        np.asarray(value), is_source=jax.process_index() == root
    )
    return type(value)(arr)


def allreduce_scalar(value):
    if jax.process_count() == 1:
        return value
    from jax.experimental import multihost_utils

    import numpy as np

    # process_allgather then sum: every process contributes its scalar.
    gathered = multihost_utils.process_allgather(np.asarray(value))
    return type(value)(gathered.sum())


def reduce_scalar(value, root: int = 0):
    """Reduce (sum) a host scalar to process ``root``; every other process
    returns its input unchanged — the per-C-type ``C.torchmpi_reduce_*``
    surface of the reference (``torchmpi/init.lua:125-134``)."""
    if jax.process_count() == 1:
        return value
    from jax.experimental import multihost_utils

    import numpy as np

    gathered = multihost_utils.process_allgather(np.asarray(value))
    if jax.process_index() == root:
        return type(value)(gathered.sum())
    return value


def sendreceive_scalar(value, src: int, dst: int):
    """Point-to-point host scalar: process ``dst`` returns ``src``'s value,
    every other process (including ``src``) returns its input unchanged —
    ``C.torchmpi_sendreceive_*`` (``torchmpi/init.lua:125-134``). Collective
    over processes: all must call it (the transport is a broadcast-from-src
    with only ``dst`` adopting the result)."""
    if jax.process_count() == 1 or src == dst:
        return value
    from jax.experimental import multihost_utils

    import numpy as np

    arr = multihost_utils.broadcast_one_to_all(
        np.asarray(value), is_source=jax.process_index() == src
    )
    if jax.process_index() == dst:
        return type(value)(arr)
    return value


def barrier(comm=None):
    eager.barrier(_current_comm(comm))


def wait(handle):
    from ..runtime.handles import wait as _wait

    return _wait(handle)


__all__ = [
    "broadcast_tensor",
    "reduce_tensor",
    "allreduce_tensor",
    "allgather_tensor",
    "allgatherv_tensor",
    "sendreceive_tensor",
    "reducescatter_tensor",
    "alltoall_tensor",
    "broadcast_scalar",
    "allreduce_scalar",
    "reduce_scalar",
    "sendreceive_scalar",
    "barrier",
    "wait",
    "free_collective_resources",
    "precompile",
    "FusionBuffer",
    "get_fusion_buffer",
    "xla",
    "ring",
    "pallas",
    "async_",
    "selector",
    "collective_availability",
    "eager",
    "primitives",
]
