"""Eager collectives over a communicator's devices.

The reference exposes *eager* collectives: ``mpi.allreduceTensor(t)`` acts on
a rank-local tensor, across processes, right now. The TPU-native equivalent
operates on a **rank-stacked array**: an array whose leading axis indexes the
communicator's ranks (size ``comm.size``), sharded so rank *i*'s block lives
on device *i*. Each call shards the input over the communicator's flat mesh
(one block per device = one "rank-local tensor"), runs the collective kernel
under ``shard_map``, and returns the rank-stacked result.

Key reference mechanics preserved:

- **Resource memoization**: the reference memoizes NCCL comms / IPC handles /
  Gloo contexts per ``(data pointer, communicator)`` with
  collective-at-first-use semantics (``lib/resources.cpp:102-163``,
  ``lib/resources.h:95-100``). Here the expensive lazily-created resource is
  the *compiled XLA executable*; it is memoized per
  ``(op, backend, shape, dtype, static args)`` on the communicator object,
  so first use pays compilation and subsequent calls are dispatch-only.
- **Async = dispatch + handle**: XLA dispatch is asynchronous, so the async
  variants return immediately with a :class:`SyncHandle` wrapping the
  in-flight arrays (the stream-handle variant of ``resources.h:230-253``);
  launch overhead is the Python dispatch cost, mirroring the <50µs assertion
  in ``test/collectives_all.lua:192-199``.
- **Small/large routing**: ``op_route`` consults the frozen constants to pick
  the latency path (fused XLA collective) below the element cutoffs and the
  bandwidth path (chunked ring) above, the analog of falling back to stock
  MPI below ``kSmallAllreduceSize`` (``lib/collectives.cpp:296-301``,
  ``lib/collectives_cuda.cpp:419-425``).
"""

from __future__ import annotations

import time
from collections import OrderedDict
from functools import partial
from typing import Any, Callable, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from .. import constants, telemetry as _telemetry
from ..runtime.communicator import Communicator
from ..runtime.handles import SyncHandle
from ..telemetry import flightrecorder as _flight
from . import primitives as prim

_AXIS = "mpi"

# telemetry handles, created on first instrumented dispatch (the metric
# objects are process-lived; the disabled path never touches them)
_MET = None


def _metric_handles():
    global _MET
    if _MET is None:
        m = _telemetry.metrics
        _MET = (
            m.counter(
                "tm_collective_calls_total",
                "eager collective dispatches by op/backend/wire",
            ),
            m.histogram(
                "tm_collective_dispatch_seconds",
                "host-side dispatch wall time per eager collective "
                "(XLA dispatch is async: submit cost, not completion)",
            ),
            m.counter(
                "tm_collective_compiles_total",
                "executable-cache misses (compilations) by op/backend",
            ),
            m.counter(
                "tm_collective_cache_hits_total",
                "executable-cache hits by op/backend",
            ),
        )
    return _MET


def _dispatch(fn, x, op: str, backend: str, wire: str, nelem: int,
              cache_hit: Optional[bool], comm: Optional[Communicator] = None,
              payload=None, routing: str = ""):
    """Run ``fn(x)`` (a compiled eager executable, or a composition like
    the staged allreduce), recording the dispatch (span + metrics) when
    telemetry is enabled, plus a flight-recorder entry (per-comm seq, op,
    payload, issue/complete stamps) when the recorder is on; one branch
    each when disabled. ``cache_hit=None`` means no single executable
    cache applies (multi-phase compositions). ``payload`` is the raw
    (shape, dtype) pair — stringified only at snapshot time."""
    entry = None
    if _flight.enabled() and comm is not None:
        entry = _flight.recorder.record(
            _flight.comm_key(comm), op, payload=payload, wire=wire,
            backend=backend, routing=routing,
        )
    if not _telemetry.enabled():
        if entry is None:
            return fn(x)
        try:
            out = fn(x)
        except BaseException:
            _flight.FlightRecorder.fail(entry)
            raise
        _flight.FlightRecorder.complete(entry)
        return out
    calls, lat, compiles, hits = _metric_handles()
    attrs = {"backend": backend, "wire_dtype": wire, "nelem": nelem}
    if cache_hit is not None:
        attrs["cache"] = "hit" if cache_hit else "miss"
    t0 = time.perf_counter()
    try:
        with _telemetry.span(f"collective.{op}", **attrs):
            out = fn(x)
    except BaseException:
        if entry is not None:
            _flight.FlightRecorder.fail(entry)
        raise
    if entry is not None:
        _flight.FlightRecorder.complete(entry)
    calls.inc(op=op, backend=backend, wire=wire)
    lat.observe(time.perf_counter() - t0, op=op, backend=backend)
    if cache_hit is not None:
        (hits if cache_hit else compiles).inc(op=op, backend=backend)
    return out


class CollectiveArgumentError(ValueError):
    pass


def _rank_spec(ndim: int) -> P:
    return P(_AXIS, *([None] * (ndim - 1)))


def _check_rank_stacked(x, comm: Communicator) -> None:
    if x.ndim < 1 or x.shape[0] != comm.size:
        raise CollectiveArgumentError(
            f"eager collectives expect a rank-stacked array with leading axis "
            f"== comm.size ({comm.size}); got shape {tuple(x.shape)}. Inside "
            f"jit/shard_map code use torchmpi_tpu.collectives.primitives "
            f"directly instead."
        )


class _LRUCache(OrderedDict):
    """Bounded executable cache: get() refreshes recency, inserts evict the
    least-recently-used entry past ``collective_cache_max_entries``. A
    2^8..2^23 x backends x dtypes tester sweep would otherwise accumulate
    hundreds of compiled executables with no way back — the reference frees
    its per-size IPC descriptors for the same reason
    (``torchmpi/cache.lua:19-61``).

    Entries may be **pinned** (:meth:`pin` — the AOT ``precompile`` path):
    pinned entries are never LRU-evicted, so a tester sweep cannot silently
    evict the executables a training loop declared up front. They still go
    away with the whole cache (``free_collective_resources`` / ``stop()``,
    whose contract is a wholesale teardown)."""

    def __init__(self, *args, **kwargs):
        super().__init__(*args, **kwargs)
        self._pinned = set()
        self._access_log = None  # set: records gets/inserts when armed

    def log_accesses(self, log: set) -> None:
        """Arm (or, with None, disarm) access logging: every hit and
        insert lands in ``log``. Used by ``precompile`` to pin exactly
        the entries its dispatches touched — including executables that
        already existed (a plain before/after key diff misses those)."""
        self._access_log = log

    def get(self, key, default=None):
        try:
            value = super().__getitem__(key)
        except KeyError:
            return default
        self.move_to_end(key)
        if self._access_log is not None:
            self._access_log.add(key)
        return value

    def pin(self, key) -> bool:
        """Exempt ``key`` from LRU eviction; True if it was present."""
        if key in self:
            self._pinned.add(key)
            return True
        return False

    def pinned_count(self) -> int:
        return len(self._pinned)

    def __setitem__(self, key, value):
        super().__setitem__(key, value)
        self.move_to_end(key)
        if self._access_log is not None:
            self._access_log.add(key)
        limit = constants.get("collective_cache_max_entries")
        while len(self) > limit:
            victim = next((k for k in self if k not in self._pinned), None)
            if victim is None:
                break  # everything pinned: the pins outrank the bound
            del self[victim]


def _resource_cache(comm: Communicator) -> dict:
    # Lazily attached, like acquireCollectiveResources keying off the comm.
    cache = getattr(comm, "_collective_resources", None)
    if cache is None:
        cache = _LRUCache()
        comm._collective_resources = cache  # type: ignore[attr-defined]
    return cache


def _dispatch_memo(comm: Communicator) -> dict:
    """The warm-dispatch fast-path memo: (call signature) -> terminal
    plan. A SEPARATE LRU from the executable cache so memo entries never
    perturb the executable-count accounting (tests and the reference's
    per-resource model count executables, not lookups) — but the same
    bound and the same wholesale teardown."""
    memo = getattr(comm, "_dispatch_memo", None)
    if memo is None:
        memo = _LRUCache()
        comm._dispatch_memo = memo  # type: ignore[attr-defined]
    return memo


def free_collective_resources(comm: Communicator) -> None:
    """Drop every cached compiled executable / sharding / selector decision
    / fusion buffer attached to ``comm`` — the analog of the reference's
    ``freeCollectiveResources`` (``torchmpi/cache.lua:19-61``, invoked by
    the tester between sizes, ``torchmpi/tester.lua:131-133``). Safe at any
    time: the next collective simply recompiles, and pending fused
    submissions are flushed first so no handle is orphaned. Pinned AOT
    entries go too — this is the wholesale teardown, not LRU pressure.
    Called by ``stop()`` for every live stack level."""
    fb = getattr(comm, "_fusion_buffer", None)
    if fb is not None:
        try:
            fb.flush_all(reason="explicit")
        except Exception:
            pass
    for attr in (
        "_collective_resources",
        "_dispatch_memo",
        "_selector_cache",
        "_fusion_buffer",
    ):
        if getattr(comm, attr, None) is not None:
            try:
                delattr(comm, attr)
            except AttributeError:
                pass


def _flat_mesh(comm: Communicator) -> Mesh:
    # The Communicator's device list is immutable: build the mesh once.
    mesh = getattr(comm, "_eager_flat_mesh", None)
    if mesh is None:
        mesh = comm.flat_mesh(_AXIS)
        comm._eager_flat_mesh = mesh  # type: ignore[attr-defined]
    return mesh


def _rank_sharding(comm: Communicator, ndim: int) -> NamedSharding:
    cache = _resource_cache(comm)
    key = ("_sharding", ndim)
    s = cache.get(key)
    if s is None:
        s = NamedSharding(_flat_mesh(comm), _rank_spec(ndim))
        cache[key] = s
    return s


def _compile(
    comm: Communicator,
    op: str,
    backend: str,
    aval: Tuple[Tuple[int, ...], Any],
    static: Tuple,
    build_kernel: Callable[[], Callable],
):
    """Fetch-or-build the jitted executable for this (op, comm, aval).
    Returns ``(fn, cache_hit)`` so dispatch telemetry can label the call."""
    cache = _resource_cache(comm)
    donate = constants.get("donate_eager_buffers")
    # donate participates in the key: toggling the constant after first use
    # must not silently keep the old executable's aliasing behavior.
    key = (op, backend, aval, static, donate)
    fn = cache.get(key)
    if fn is not None:
        return fn, True
    mesh = _flat_mesh(comm)
    ndim = len(aval[0])
    spec = _rank_spec(ndim)
    kernel = build_kernel()
    shmapped = jax.shard_map(
        kernel, mesh=mesh, in_specs=spec, out_specs=spec, check_vma=False
    )
    fn = jax.jit(shmapped, donate_argnums=(0,) if donate else ())
    cache[key] = fn
    return fn, False


def _per_rank_shape(x_shape: Tuple[int, ...]) -> Tuple[int, ...]:
    return (1,) + tuple(x_shape[1:])


def _nelem_per_rank(x) -> int:
    return int(np.prod(_per_rank_shape(x.shape)))


# ---------------------------------------------------------------------------
# backend kernel builders: operate on a [1, ...] per-rank block
# ---------------------------------------------------------------------------


def ring_tuning(platform: str) -> Tuple[int, int, int]:
    """(min_bytes, max_bytes, num_buffers) for the platform's custom rings —
    the reference's kMin/kMaxBufferSize + kNumBuffersPerCollective knobs
    (``lib/constants.cpp:142-150``), capped by
    ``max_num_buffers_per_collective`` (``lib/constants.h:77-78``)."""
    suffix = constants.platform_suffix(platform)
    nb = min(
        constants.get(f"num_buffers_per_collective_{suffix}"),
        constants.get("max_num_buffers_per_collective"),
    )
    return (
        constants.get(f"min_buffer_size_{suffix}"),
        constants.get(f"max_buffer_size_{suffix}"),
        nb,
    )


def broadcast_plan(nelem: int, dtype, platform: str) -> Tuple[bool, int]:
    """(use_tree, pipeline_chunks) for a broadcast of ``nelem`` elements:
    tree below broadcast_size_tree_based (collectives.cpp:58-64's 4MB
    switch); above it, the pipelined chunk count from the buffer-size
    bounds — every chunk <= max_buffer_size and no smaller than
    min_buffer_size (constants.cpp:142-150). One source of truth for the
    flat AND hierarchical routes."""
    suffix = constants.platform_suffix(platform)
    block_bytes = nelem * jnp.dtype(dtype).itemsize
    if block_bytes <= constants.get(f"broadcast_size_tree_based_{suffix}"):
        return True, 1
    minb, maxb, _ = ring_tuning(platform)
    k = max(1, -(-block_bytes // max(1, maxb)))
    k = min(k, max(1, block_bytes // max(1, minb)))
    return False, int(k)


def _pallas_reduce_scatter_lastdim(b, axis: str, wire_dtype=None):
    """Scatter-along-last-dim reduce-scatter (dual of the allgather
    contract) on a [1, ..., d] per-rank block via the pallas RS ring, which
    scatters dim 0 with psum_scatter tiled semantics."""
    from ..ops.ring_kernels import ring_reduce_scatter_pallas

    moved = jnp.moveaxis(b[0], -1, 0)  # [d, ...]
    mine = ring_reduce_scatter_pallas(
        moved, axis, wire_dtype=wire_dtype
    )  # [d/p, ...]
    return jnp.moveaxis(mine, 0, -1)[None]


def _pallas_allgather_lastdim(b, axis: str):
    """Concat-along-last-dim allgather (the eager contract) on a [1, ..., d]
    per-rank block via the (p-1)-step pallas forwarding ring. Shared by the
    flat backend table and the hierarchical intra phase."""
    from ..ops.ring_kernels import ring_allgather_pallas

    stacked = ring_allgather_pallas(b[0], axis)  # [p, ..., d]
    moved = jnp.moveaxis(stacked, 0, -2)  # [..., p, d]
    # b.shape[:-1] keeps the leading per-rank 1: output is [1, ..., p*d]
    return moved.reshape(b.shape[:-1] + (moved.shape[-2] * moved.shape[-1],))


def _kernels(op: str, backend: str, root: int, extra: Tuple,
             tuning: Tuple = (), wire: str = "full"):
    """Return a kernel fn(block) for the given op/backend.

    For ``backend='ring'`` broadcasts, ``extra`` carries the tree-vs-pipeline
    decision (made in :func:`run` from the platform-appropriate constant, so
    it participates in the executable cache key — ``collectives.cpp:58-64``'s
    4MB switch) plus the pipelined chunk count; ``tuning`` carries
    (min_bytes, max_bytes, num_buffers) for byte-bounded ring chunking;
    ``wire`` the resolved wire format for the bandwidth-path reductions."""
    minb, maxb, nbuf = tuning if tuning else (None, None, 1)
    wire_arg = wire if wire != "full" else None

    def _ring_allreduce(b):
        return prim.ring_allreduce(
            b, _AXIS,
            max_bytes_per_step=maxb, min_bytes_per_step=minb,
            num_buffers=nbuf, wire_dtype=wire_arg,
        )

    def _ring_reduce(b):
        return prim.ring_reduce(
            b, root, _AXIS,
            max_bytes_per_step=maxb, min_bytes_per_step=minb,
            num_buffers=nbuf,
        )

    def _bcast_builder(pipeline_fn):
        # shared tree-vs-pipeline routing for the custom-ring broadcasts;
        # extra carries the decision + the ('chunks', k) pipelining depth
        def bcast(b):
            if "tree" in extra:
                return prim.tree_broadcast(b, root, _AXIS)
            k = next(
                (e[1] for e in extra if isinstance(e, tuple) and e[0] == "chunks"),
                None,
            )
            return pipeline_fn(b, k)
        return bcast

    _ring_bcast = _bcast_builder(
        lambda b, k: prim.ring_broadcast(b, root, _AXIS, num_chunks=k)
    )

    if backend == "xla":
        table = {
            "allreduce": lambda b: prim.allreduce(b, _AXIS),
            "broadcast": lambda b: prim.broadcast(b, root, _AXIS),
            "reduce": lambda b: prim.reduce(b, root, _AXIS),
            "allgather": lambda b: prim.allgather(b, _AXIS, dim=-1),
            "sendreceive": lambda b: prim.sendreceive(b, extra[0], extra[1], _AXIS),
            "reducescatter": lambda b: prim.reduce_scatter(
                b, _AXIS, dim=b.ndim - 1
            ),
            # b: [1, p, ...] — scatter/stack the rank dimension
            "alltoall": lambda b: prim.alltoall(
                b, _AXIS, split_dim=1, concat_dim=1
            ),
        }
    elif backend == "ring":
        table = {
            "allreduce": _ring_allreduce,
            "broadcast": _ring_bcast,
            "reduce": _ring_reduce,
            "allgather": lambda b: prim.ring_allgather(b, _AXIS, dim=-1),
            "sendreceive": lambda b: prim.sendreceive(b, extra[0], extra[1], _AXIS),
            "reducescatter": lambda b: prim.ring_reduce_scatter(
                b, _AXIS, dim=-1, wire_dtype=wire_arg
            ),
            "alltoall": lambda b: prim.ring_alltoall(b[0], _AXIS)[None],
        }
    elif backend == "pallas":
        # Pallas ICI-RDMA rings for allreduce / reduce / allgather +
        # pipelined broadcast; only sendreceive takes the ppermute path
        # (a single point-to-point hop IS one XLA collective-permute — a
        # ring kernel would add nothing).
        from ..ops.ring_kernels import (
            ring_allreduce_bidir_pallas,
            ring_allreduce_pallas,
            ring_broadcast_pallas,
            ring_reduce_pallas,
        )

        _pallas_bcast = _bcast_builder(
            lambda b, k: ring_broadcast_pallas(b, root, _AXIS, num_chunks=k)
        )
        # a compressed wire pins the unidirectional kernel (the bidir
        # ring has no quant path; run() drops the marker accordingly)
        if wire_arg is not None:
            def _pallas_allreduce(b, axis):
                return ring_allreduce_pallas(b, axis, wire_dtype=wire_arg)
        else:
            _pallas_allreduce = (
                ring_allreduce_bidir_pallas
                if "bidir" in extra
                else ring_allreduce_pallas
            )

        table = {
            "allreduce": lambda b: _pallas_allreduce(b, _AXIS),
            "broadcast": _pallas_bcast,
            "reduce": lambda b: ring_reduce_pallas(b, root, _AXIS),
            "allgather": lambda b: _pallas_allgather_lastdim(b, _AXIS),
            "sendreceive": lambda b: prim.sendreceive(b, extra[0], extra[1], _AXIS),
            "reducescatter": lambda b: _pallas_reduce_scatter_lastdim(
                b, _AXIS, wire_arg
            ),
            # a single fused all_to_all IS one XLA collective already —
            # same rationale as sendreceive's ppermute path
            "alltoall": lambda b: prim.alltoall(
                b, _AXIS, split_dim=1, concat_dim=1
            ),
        }
    else:
        raise CollectiveArgumentError(f"unknown backend {backend!r}")
    if op not in table:
        raise CollectiveArgumentError(f"unknown collective {op!r}")
    return table[op]


# collectives the compressed wire formats apply to (the bandwidth-path
# reductions; data movers are lossless by contract and stay verbatim)
_WIRE_OPS = ("allreduce", "reducescatter")


def resolve_wire_dtype(op: str, nelem: int, dtype,
                       requested: Optional[str] = None) -> str:
    """The wire-format routing decision for one eager call: the explicit
    ``wire_dtype=`` argument wins, else the ``wire_dtype`` constant (the
    autotuner's persisted pick); 'full' whenever the encoding cannot
    engage — wrong op, non-f32 payload (ints pass through uncompressed,
    exactness is their contract), or below the min-elements cutoff."""
    wire = requested if requested is not None else constants.get("wire_dtype")
    if wire in (None, "", "full"):
        return "full"
    if wire not in ("int8", "bf16"):
        raise CollectiveArgumentError(
            f"unknown wire_dtype {wire!r}; expected 'full', 'bf16' or 'int8'"
        )
    if op not in _WIRE_OPS:
        return "full"
    if jnp.dtype(dtype) != jnp.dtype(jnp.float32):
        return "full"
    if nelem < constants.get("wire_quant_min_elements"):
        return "full"
    return wire


def _record_wire(op: str, nelem: int, dtype, wire: str) -> None:
    """Feed the tracing counters: per-rank logical payload bytes vs the
    bytes the chosen encoding puts on the wire per hop."""
    from ..utils import tracing

    itemsize = jnp.dtype(dtype).itemsize
    block = constants.get("wire_quant_block_size")
    wire_bytes = prim.wire_encoded_bytes(nelem, itemsize, wire, block)
    tracing.wire_stats.record(op, wire, nelem * itemsize, wire_bytes)


def op_route(op: str, nelem: int, platform: str, requested: str = "ring") -> str:
    """Size-based latency/bandwidth routing (reference
    ``collectives.cpp:296-301``): below the cutoff use the fused XLA path,
    above it the requested bandwidth backend (ring or pallas)."""
    suffix = constants.platform_suffix(platform)
    if op == "allreduce":
        cutoff = constants.get(f"small_allreduce_size_{suffix}")
    elif op == "broadcast":
        cutoff = constants.get(f"small_broadcast_size_{suffix}")
    else:
        return requested
    return "xla" if nelem <= cutoff else requested


def run(
    op: str,
    x,
    comm: Communicator,
    backend: str = "xla",
    root: int = 0,
    src: int = 0,
    dst: int = 0,
    route_small: bool = True,
    wire_dtype: Optional[str] = None,
):
    """Synchronous eager collective on a rank-stacked array.

    ``wire_dtype``: per-call wire-format override for the bandwidth-path
    reductions ('full' | 'bf16' | 'int8'; None = the ``wire_dtype``
    constant). See :func:`resolve_wire_dtype` for the engagement gates.
    """
    x = jnp.asarray(x)
    _check_rank_stacked(x, comm)
    if wire_dtype not in (None, "full", "bf16", "int8"):
        # validated unconditionally: a typo must not pass silently just
        # because this call happened to route to the fused XLA path
        raise CollectiveArgumentError(
            f"unknown wire_dtype {wire_dtype!r}; expected 'full', 'bf16' "
            "or 'int8'"
        )
    if op in ("broadcast", "reduce") and not 0 <= root < comm.size:
        raise CollectiveArgumentError(f"root {root} out of range")
    if op == "allgather" and x.ndim == 1:
        # One scalar per rank: lift to [p, 1] so the output stays rank-stacked
        # ([p, p]: every rank's block is the gathered vector).
        x = x[:, None]
    if op == "reducescatter":
        if x.ndim < 2 or x.shape[-1] % comm.size != 0:
            raise CollectiveArgumentError(
                f"reducescatter scatters the last dim, which must exist and "
                f"be divisible by the communicator size {comm.size}; got "
                f"shape {tuple(x.shape)}"
            )
    if op == "alltoall":
        if x.ndim < 2 or x.shape[1] != comm.size:
            raise CollectiveArgumentError(
                f"alltoall needs rank-stacked [p, p, ...] input (block "
                f"[r, s] = rank r's payload for rank s); got shape "
                f"{tuple(x.shape)} for p={comm.size}"
            )
    # warm-dispatch fast path: a (signature -> terminal plan) memo that
    # skips re-abstractification — routing, wire resolution, plan
    # building, and the executable-cache key construction — for call
    # signatures seen before. Entries embed the constants generation, so
    # ANY constants change (cutoffs, wire knob, donation) invalidates
    # them in O(1); only the flat terminal path is memoized (hierarchical
    # compositions re-route per call).
    memo = _dispatch_memo(comm)
    fkey = (
        "_fast", op, backend, root, src, dst, route_small, wire_dtype,
        tuple(x.shape), str(jnp.result_type(x)),
    )
    ent = memo.get(fkey)
    if ent is not None and ent[0] == constants.generation():
        _, fn, effective, wire, nelem = ent
        if effective in ("ring", "pallas") and op in _WIRE_OPS:
            _record_wire(op, nelem, jnp.result_type(x), wire)
        sharding = _rank_sharding(comm, x.ndim)
        if getattr(x, "sharding", None) != sharding:
            x = jax.device_put(x, sharding)
        return _dispatch(fn, x, op, effective, wire, nelem, True,
                         comm=comm, payload=(x.shape, x.dtype),
                         routing="flat")
    platform = comm._devices[0].platform
    effective = backend
    if backend in ("ring", "pallas") and route_small:
        effective = op_route(op, _nelem_per_rank(x), platform, backend)
    if effective == "pallas":
        from ..ops import ring_kernels

        dt = jnp.result_type(x)
        # dtype gates: REDUCTIONS must preserve the dtype exactly (round-1
        # silently corrupted int32 >= 2^24 via an f32 cast) — unsupported
        # dtypes take the ppermute ring. Data-movement ops carry any real
        # dtype losslessly as a byte view; only complex must fall back.
        if op in ("allreduce", "reduce", "reducescatter"):
            if not ring_kernels.supports_dtype(dt):
                effective = "ring"
        elif jnp.dtype(dt).kind == "c":
            effective = "ring"
    # wire-format decision (made once, BEFORE the hierarchical split, so
    # flat and hierarchical routes ship the same bytes). Byte accounting
    # happens at the TERMINAL dispatch — the flat path below, or inside
    # the hierarchical composition this call may delegate to (which also
    # covers direct run_hierarchical_* callers).
    wire = "full"
    if effective in ("ring", "pallas") and op in _WIRE_OPS:
        wire = resolve_wire_dtype(
            op, _nelem_per_rank(x), jnp.result_type(x), wire_dtype
        )
    hier = (
        effective in ("ring", "pallas")
        # route_small=False pins the EXACT backend (tester/autotuner
        # contract: each path measured on its own) — no hier rerouting
        and route_small
        and constants.get("use_hierarchical_collectives")
        and comm.has_inter_collective
        and comm.has_intra_collective
    )
    if hier and comm.cartesian:
        # two-level composition on hierarchical cartesian comms
        # (collectives_cuda.cpp:501-581,1057-1141); staged-vs-direct inter
        # transport selected by use_staged_collectives
        # (kUseStagedCollectives, detail/collectives_cuda.cpp:877-899)
        if op == "allreduce":
            # the intra (ICI) level is where the custom transport pays:
            # when the selector routed to pallas, the composition's intra
            # phase runs the RDMA ring (collectives_cuda.cpp:501-581 — the
            # reference's intra-IPC transport was the custom one there too)
            if constants.get("use_staged_collectives"):
                # the staged variant keeps the routed INTRA transport
                # (the reference's staged path still ran its custom IPC
                # rings inside the node, collectives_cuda.cpp:390-683)
                return run_hierarchical_allreduce(
                    x, comm, impl="staged", staged_intra=effective,
                    wire=wire,
                )
            return run_hierarchical_allreduce(
                x, comm, impl=effective, wire=wire
            )
        if op in ("broadcast", "reduce", "allgather"):
            return run_hierarchical_collective(
                op, x, comm, root=root, ring_impl=effective
            )
    elif hier and op == "allreduce":
        # non-cartesian (ragged/tree) comms: grouped reduce + roots
        # exchange + the trailing intra broadcast
        # (collectives_cuda.cpp:569-579)
        return run_tree_hierarchical_allreduce(x, comm, wire=wire)
    # flat terminal path: the byte accounting for this dispatch
    if effective in ("ring", "pallas") and op in _WIRE_OPS:
        _record_wire(op, _nelem_per_rank(x), jnp.result_type(x), wire)
    extra: Tuple = (src, dst) if op == "sendreceive" else ()
    if (
        effective == "pallas"
        and op == "allreduce"
        and constants.get("ring_implementation") == "pallas_bidir"
        and wire == "full"
    ):
        # bidirectional-ring variant; participates in the executable cache
        # key via ``extra`` so toggling the constant recompiles. The
        # quantized wire runs the unidirectional kernel (the bidir ring
        # has no quant path); dropping the marker here keeps the cache
        # key honest about which kernel actually compiled.
        extra = extra + ("bidir",)
    tuning: Tuple = ()
    if effective in ("ring", "pallas"):
        tuning = ring_tuning(platform)
    if effective in ("ring", "pallas") and op == "broadcast":
        tree, k = broadcast_plan(_nelem_per_rank(x), jnp.result_type(x), platform)
        extra = extra + (("tree",) if tree else ("pipeline", ("chunks", k)))
    # block size participates in the key only when an encoding engages
    # (toggling it must recompile the quantized executable, not the full
    # one)
    wire_key = (
        (wire, constants.get("wire_quant_block_size"))
        if wire != "full"
        else ("full",)
    )
    aval = (tuple(x.shape), jnp.result_type(x))
    static = (root,) + extra + (tuning, wire_key)
    fn, hit = _compile(
        comm,
        op,
        effective,
        aval,
        static,
        lambda: _kernels(op, effective, root, extra, tuning, wire),
    )
    # memoize the terminal plan for this signature (see the fast path
    # above); generation-stamped so constants changes invalidate it
    memo[fkey] = (
        constants.generation(), fn, effective, wire, _nelem_per_rank(x)
    )
    # Place the input on the communicator's devices (no-op if already there).
    sharding = _rank_sharding(comm, x.ndim)
    if getattr(x, "sharding", None) != sharding:
        x = jax.device_put(x, sharding)
    return _dispatch(fn, x, op, effective, wire, _nelem_per_rank(x), hit,
                     comm=comm, payload=(x.shape, x.dtype), routing="flat")


def run_fused(
    op: str,
    flats,
    comm: Communicator,
    backend: str = "xla",
    route_small: bool = True,
    wire_dtype: Optional[str] = None,
):
    """Coalesced multi-input dispatch: ``flats`` (same-dtype rank-stacked
    ``[p, n_i]`` slabs) are packed AND reduced by ONE compiled executable
    — concat + collective fused into a single plan, so a flush of k
    pending tensors costs one XLA dispatch, not k (and not even
    pack + collective = 2). The GC3 move (arXiv:2201.11840): the plan is
    compiled once per (op, layout, dtype, routing) and replayed.

    Routing (latency/bandwidth cutoff, wire format) is decided on the
    TOTAL payload — coalescing is exactly what pushes small tensors past
    the bandwidth-path and quantization cutoffs. Hierarchical
    communicators delegate to the (cached) hierarchical composition after
    a single-dispatch concat — 2 dispatches, still O(1) in k. Inputs are
    caller arrays and are never donated. Returns the fused ``[p, total]``
    result; callers slice their segments back out."""
    if op != "allreduce":
        raise CollectiveArgumentError(
            f"run_fused supports allreduce, got {op!r}"
        )
    flats = [
        f if isinstance(f, jax.Array) else jnp.asarray(f) for f in flats
    ]
    if not flats:
        raise CollectiveArgumentError("run_fused needs at least one tensor")
    for f in flats:
        _check_rank_stacked(f, comm)
    dtype = flats[0].dtype
    if any(f.dtype != dtype for f in flats):
        dtype = jnp.result_type(*flats)
        flats = [f.astype(dtype) for f in flats]
    ns = tuple(int(f.shape[1]) for f in flats)
    total = int(sum(ns))
    cache = _resource_cache(comm)
    memo = _dispatch_memo(comm)
    # warm-dispatch memo (see run()): skips routing/wire/plan-key work
    # for layouts seen before; generation-stamped against constants drift
    fkey = ("_fastfused", op, backend, route_small, wire_dtype, ns, dtype)
    ent = memo.get(fkey)
    if ent is not None and ent[0] == constants.generation():
        _, fn, effective, wire = ent
        if effective in ("ring", "pallas"):
            _record_wire(op, total, dtype, wire)
        return _dispatch(
            lambda args: fn(*args), flats, op, effective, wire, total, True,
            comm=comm, payload=(ns, dtype), routing="fused",
        )
    platform = comm._devices[0].platform
    effective = backend
    if backend in ("ring", "pallas") and route_small:
        effective = op_route(op, total, platform, backend)
    if effective == "pallas":
        from ..ops import ring_kernels

        if not ring_kernels.supports_dtype(dtype):
            effective = "ring"
    wire = "full"
    if effective in ("ring", "pallas"):
        wire = resolve_wire_dtype(op, total, dtype, wire_dtype)
    hier = (
        effective in ("ring", "pallas")
        and route_small
        and constants.get("use_hierarchical_collectives")
        and comm.has_inter_collective
        and comm.has_intra_collective
    )
    if hier:
        # concat in one dispatch, then the hierarchical composition (its
        # own cached executable): 2 dispatches for k tensors
        ckey = ("_fusecat", ns, str(jnp.dtype(dtype)))
        cat = cache.get(ckey)
        if cat is None:
            cat = jax.jit(lambda *bs: jnp.concatenate(bs, axis=1))
            cache[ckey] = cat
        return run(
            op, cat(*[f.astype(dtype) for f in flats]), comm,
            backend=backend, route_small=route_small, wire_dtype=wire_dtype,
        )
    if effective in ("ring", "pallas"):
        _record_wire(op, total, dtype, wire)
    extra: Tuple = ()
    if (
        effective == "pallas"
        and constants.get("ring_implementation") == "pallas_bidir"
        and wire == "full"
    ):
        extra = ("bidir",)
    tuning: Tuple = ()
    if effective in ("ring", "pallas"):
        tuning = ring_tuning(platform)
    wire_key = (
        (wire, constants.get("wire_quant_block_size"))
        if wire != "full"
        else ("full",)
    )
    key = (
        "_fused", op, effective, ns, str(jnp.dtype(dtype)), extra, tuning,
        wire_key,
    )
    fn = cache.get(key)
    hit = fn is not None
    if fn is None:
        inner = _kernels(op, effective, 0, extra, tuning, wire)

        def kernel(*blocks):  # each [1, n_i] per-rank slab
            return inner(jnp.concatenate(blocks, axis=-1))

        mesh = _flat_mesh(comm)
        spec = _rank_spec(2)
        shmapped = jax.shard_map(
            kernel, mesh=mesh, in_specs=(spec,) * len(ns), out_specs=spec,
            check_vma=False,
        )
        # in_shardings fold the device placement of every slab into this
        # one dispatch (the flat path's explicit per-array device_put,
        # amortized k-fold)
        sharding = _rank_sharding(comm, 2)
        fn = jax.jit(shmapped, in_shardings=(sharding,) * len(ns))
        cache[key] = fn
    memo[fkey] = (constants.generation(), fn, effective, wire)
    return _dispatch(
        lambda args: fn(*args), flats, op, effective, wire, total, hit,
        comm=comm, payload=(ns, dtype), routing="fused",
    )


def run_allgatherv(blocks, comm: Communicator, backend: str = "xla"):
    """Variable-size allgather: per-rank blocks with RAGGED last dims are
    concatenated along the last dimension on every rank — the reference's
    size-exchange + ``MPI_Allgatherv`` + output realloc
    (``lib/collectives.cpp:245-290``).

    ``blocks`` is a sequence of ``comm.size`` arrays that agree on every
    dimension except the last. XLA needs static shapes, so the reference's
    runtime size exchange happens at trace time (the sizes ARE the trace
    constants); on the wire the blocks travel padded to the max size and
    the valid prefixes are re-assembled in-graph.

    Returns a rank-stacked ``[p, ..., sum(sizes)]`` array (every rank's
    block holds the full concatenation, like the uniform allgather).
    """
    if len(blocks) != comm.size:
        raise CollectiveArgumentError(
            f"allgatherv expects {comm.size} blocks (one per rank), got "
            f"{len(blocks)}"
        )
    blocks = [jnp.asarray(b) for b in blocks]
    base = blocks[0].shape[:-1]
    dtype = jnp.result_type(blocks[0])
    for i, b in enumerate(blocks):
        if b.ndim == 0 or b.shape[:-1] != base:
            raise CollectiveArgumentError(
                f"block {i} shape {tuple(b.shape)} does not match leading "
                f"dims {base} (only the LAST dim may vary, like the "
                "reference's last-dim realloc)"
            )
        if jnp.result_type(b) != dtype:
            raise CollectiveArgumentError(
                f"block {i} dtype {b.dtype} != {dtype}"
            )
    sizes = tuple(int(b.shape[-1]) for b in blocks)
    nmax = max(sizes) if sizes else 0
    p = comm.size

    if backend == "ring":
        gather = lambda b: prim.ring_allgather(b, _AXIS, dim=0)  # noqa: E731
    elif backend == "xla":
        gather = lambda b: prim.allgather(b, _AXIS, dim=0)  # noqa: E731
    else:
        raise CollectiveArgumentError(
            f"allgatherv backend must be 'xla' or 'ring', got {backend!r}"
        )

    def build_kernel():
        def kernel(b):
            # b: [1, ..., nmax] per-rank padded block
            g = gather(b)  # [p, ..., nmax]
            parts = [
                jax.lax.slice_in_dim(
                    jax.lax.index_in_dim(g, r, 0, keepdims=False),
                    0, sizes[r], axis=len(base),  # the last dim
                )
                for r in range(p)
            ]
            return jnp.concatenate(parts, axis=-1)[None]

        return kernel

    stacked_shape = (p,) + base + (nmax,)
    fn, hit = _compile(
        comm, "allgatherv", backend, (stacked_shape, dtype), (sizes,),
        build_kernel,
    )

    padded = jnp.stack(
        [
            jnp.concatenate(
                [b, jnp.zeros(base + (nmax - s,), dtype)], axis=-1
            )
            if s < nmax
            else b
            for b, s in zip(blocks, sizes)
        ]
    )
    sharding = _rank_sharding(comm, padded.ndim)
    if getattr(padded, "sharding", None) != sharding:
        padded = jax.device_put(padded, sharding)
    return _dispatch(
        fn, padded, "allgatherv", backend, "full", int(sum(sizes)), hit,
        comm=comm, payload=(sizes, dtype), routing="flat",
    )


def run_async(op: str, x, comm: Communicator, **kw) -> SyncHandle:
    """Asynchronous variant: returns a handle immediately; the arrays are
    in flight on device (XLA async dispatch replaces the reference's
    offload-thread + future machinery for device collectives). The handle is
    registered in the global table so ``sync_all()`` (and thus ``stop()``)
    drains it, matching ``resources.cpp:463-481``."""
    from ..runtime.handles import handles

    # Backpressure: bound the number of unwaited async collectives
    # (kNumAsyncCollectivesInFlight, lib/constants.cpp:152-155) — when the
    # table is full, the oldest outstanding handle is drained first, the
    # analog of the reference's bounded future queues blocking enqueue.
    limit = constants.get("num_async_collectives_in_flight")
    while handles.outstanding_kind("collective") >= limit:
        if not handles.wait_oldest("collective"):
            break
    out = run(op, x, comm, **kw)
    h = SyncHandle(arrays=out)
    handles.register(h, kind="collective")
    return h


def precompile(specs, comm: Optional[Communicator] = None,
               pin: bool = True) -> int:
    """AOT warm-up: populate (and **pin**) the executable cache from
    declared collective specs so the first training step never compiles a
    collective — the GC3 move (arXiv:2201.11840) of compiling collective
    *plans* ahead of time and replaying them.

    ``specs`` is an iterable of tuples ``(op, shape, dtype)`` optionally
    extended with ``backend`` and ``wire_dtype`` (or dicts with those
    keys plus ``root``). ``shape`` is the rank-stacked shape; a shape
    whose leading axis differs from ``comm.size`` is treated as the
    per-rank block shape and the rank axis is prepended. A dict spec may
    instead carry ``layout``: a tuple of per-rank widths declaring a
    coalesced multi-tensor group — warmed through :func:`run_fused`, the
    executable a ``FusionBuffer`` flush of that layout replays.

    Each spec is dispatched once on a zeros payload through the exact
    production route (selector, wire resolution, hierarchical
    composition), so both the jitted executable AND the per-signature
    fast-path memo are warm afterwards; every cache entry the warm-up
    touches — newly compiled OR already present — is pinned against LRU
    eviction (``free_collective_resources`` still frees them — wholesale
    teardown outranks pins). Returns the number of specs warmed.
    Typically invoked via ``start(precompile_collectives=...)`` or
    ``AllReduceSGDEngine.precompile()``."""
    if comm is None:
        from .. import runtime_state

        comm = runtime_state.current_communicator()
    cache = _resource_cache(comm)
    touched: set = set()
    if pin:
        # log every cache hit AND insert the warm-up dispatches make, so
        # pinning covers executables that already existed (a key diff
        # against a 'before' snapshot would silently skip those)
        cache.log_accesses(touched)
    pending = []
    try:
        warmed = _precompile_dispatch(specs, comm, pending)
    finally:
        if pin:
            cache.log_accesses(None)
    # drain so compile time is paid HERE, not inside step 1's first wait
    jax.block_until_ready(pending)
    if pin:
        for key in touched:
            cache.pin(key)
    return warmed


def _precompile_dispatch(specs, comm, pending) -> int:
    """The spec-by-spec warm-up loop of :func:`precompile` (split out so
    the caller's try/finally owns logging disarm + pinning)."""
    from . import _dispatch as _ns_dispatch

    warmed = 0
    for spec in specs:
        if isinstance(spec, dict) and "layout" in spec:
            flats = [
                jnp.zeros((comm.size, int(n)), spec["dtype"])
                for n in spec["layout"]
            ]
            kw = {}
            if spec.get("wire_dtype") is not None:
                kw["wire_dtype"] = spec["wire_dtype"]
            pending.append(
                _ns_dispatch(
                    spec.get("op", "allreduce"), flats, comm, "fused",
                    spec.get("backend"), **kw,
                )
            )
            warmed += 1
            continue
        if isinstance(spec, dict):
            op = spec["op"]
            shape = tuple(spec["shape"])
            dtype = spec["dtype"]
            backend = spec.get("backend")
            wire = spec.get("wire_dtype")
            root = spec.get("root", 0)
        else:
            op, shape, dtype = spec[0], tuple(spec[1]), spec[2]
            backend = spec[3] if len(spec) > 3 else None
            wire = spec[4] if len(spec) > 4 else None
            root = 0
        if shape and shape[0] != comm.size:
            shape = (comm.size,) + shape
        kw = {}
        if wire is not None and op in _WIRE_OPS:
            kw["wire_dtype"] = wire
        if op in ("broadcast", "reduce"):
            kw["root"] = root
        out = _ns_dispatch(
            op, jnp.zeros(shape, dtype), comm, "sync", backend, **kw
        )
        pending.append(out)
        warmed += 1
    return warmed


def run_hierarchical_allreduce(
    x, comm: Communicator, impl: str = "ring", staged_intra: str = "ring",
    wire: str = "full",
):
    """Explicit two-level allreduce over a cartesian communicator: ring
    reduce within each intra group, ring across the inter dimension, then
    the intra all-gather — the reference's hierarchical dispatch
    (``allreducep2pHierarchicalImpl``, ``collectives_cuda.cpp:501-581``).
    The *cartesian shortcut* is structural here: every device sits in an
    inter ring of same-intra-rank peers, so no trailing intra broadcast is
    needed (``docs/communicators.md:24-31``).

    Requires a cartesian comm with both levels populated; the flat path is
    the right tool otherwise (callers fall back).
    """
    x = jnp.asarray(x)
    _check_rank_stacked(x, comm)
    if not (comm.cartesian and comm.has_inter_collective and comm.has_intra_collective):
        raise CollectiveArgumentError(
            "hierarchical allreduce needs a cartesian communicator with "
            "multiple intra groups of size > 1"
        )
    # byte accounting for the composition (once per dispatch, like the
    # flat path — run() no longer records for calls it delegates here, so
    # direct callers and routed calls count identically)
    if impl in ("ring", "pallas", "staged"):
        _record_wire(
            "allreduce", _nelem_per_rank(x), jnp.result_type(x), wire
        )
    if impl == "staged":
        return _dispatch(
            lambda a: _run_staged_hierarchical_allreduce(
                a, comm, staged_intra, wire
            ),
            x, "staged_allreduce", staged_intra, wire,
            _nelem_per_rank(x), None,
            comm=comm, payload=(x.shape, x.dtype), routing="staged",
        )
    donate = constants.get("donate_eager_buffers")
    tuning = (
        ring_tuning(comm._devices[0].platform)
        if impl in ("ring", "pallas")
        else ()
    )
    # the uni-vs-bidirectional pallas variant participates in the cache
    # key: the autotuner toggles ring_implementation between measurements
    bidir = (
        impl == "pallas"
        and constants.get("ring_implementation") == "pallas_bidir"
        and wire == "full"
    )
    wire_arg = wire if wire != "full" else None
    key = (
        "hier_allreduce", impl, tuple(x.shape), jnp.result_type(x), donate,
        tuning, bidir,
        (wire, constants.get("wire_quant_block_size"))
        if wire != "full" else ("full",),
    )

    if impl == "pallas":
        # intra = ICI: the Pallas RDMA ring (uni- or bidirectional per
        # ring_implementation); inter = cross-ICI/DCN: the ppermute ring
        # (XLA schedules it over the slower fabric) — the reference's
        # intra-IPC-ring x inter-MPI split. The wire format applies to
        # BOTH levels: the inter hop is the slowest fabric, exactly where
        # compression pays most.
        intra_ring, _ = _pallas_intra_ring(wire_arg)
        minb, maxb, nbuf = tuning

        def kernel(b):
            b = intra_ring(b, "intra")
            return prim.ring_allreduce(
                b, "inter",
                max_bytes_per_step=maxb, min_bytes_per_step=minb,
                num_buffers=nbuf, wire_dtype=wire_arg,
            )
    elif impl == "ring":
        minb, maxb, nbuf = tuning

        def kernel(b):
            b = prim.ring_allreduce(
                b, "intra",
                max_bytes_per_step=maxb, min_bytes_per_step=minb,
                num_buffers=nbuf, wire_dtype=wire_arg,
            )
            return prim.ring_allreduce(
                b, "inter",
                max_bytes_per_step=maxb, min_bytes_per_step=minb,
                num_buffers=nbuf, wire_dtype=wire_arg,
            )
    else:
        def kernel(b):
            return jax.lax.psum(jax.lax.psum(b, "intra"), "inter")

    fn, hit = _hier_compile(comm, key, x.ndim, donate, kernel)
    return _dispatch(
        fn, x, "hier_allreduce", impl, wire, _nelem_per_rank(x), hit,
        comm=comm, payload=(x.shape, x.dtype), routing="hier",
    )


def _pallas_intra_ring(wire_arg: Optional[str] = None):
    """(ring_fn, bidir) for the intra (ICI) allreduce phase when the
    selector routed 'pallas' — uni- or bidirectional per
    ``ring_implementation``. The ONE selection site shared by the direct
    and staged hierarchical paths, so their intra transports can never
    diverge. A compressed ``wire_arg`` pins the unidirectional quantized
    kernel (the bidir ring has no quant path)."""
    from ..ops.ring_kernels import (
        ring_allreduce_bidir_pallas,
        ring_allreduce_pallas,
    )

    if wire_arg is not None:
        def quant_ring(b, axis):
            return ring_allreduce_pallas(b, axis, wire_dtype=wire_arg)

        return quant_ring, False
    bidir = constants.get("ring_implementation") == "pallas_bidir"
    return (
        ring_allreduce_bidir_pallas if bidir else ring_allreduce_pallas,
        bidir,
    )


def _run_staged_hierarchical_allreduce(
    x, comm: Communicator, intra_impl: str = "ring", wire: str = "full"
):
    """Host-staged cross-group allreduce — the TPU analog of
    ``allreducep2pCrossNodesViaCPU`` (staged-via-pinned-CPU,
    ``detail/collectives_cuda.cpp:390-683``), selected by
    ``use_staged_collectives``:

    1. device: ring-allreduce within each intra group (ICI-local) — the
       ppermute ring, or the Pallas RDMA ring when the selector routed
       ``intra_impl='pallas'`` (the reference's staged path likewise kept
       its custom IPC transport inside the node);
    2. host: fetch one representative group-sum per group, reduce across
       groups in host memory (the DCN-staged hop);
    3. device: push the global total back to every rank.

    The staged hop trades device-collective bandwidth for not needing any
    inter-group device link — exactly the reference's rationale when GDR
    was unavailable.
    """
    cache = _resource_cache(comm)
    tuning = ring_tuning(comm._devices[0].platform)
    wire_arg = wire if wire != "full" else None
    bidir = (
        intra_impl == "pallas"
        and constants.get("ring_implementation") == "pallas_bidir"
        and wire_arg is None
    )
    key = (
        "staged_allreduce", intra_impl, bidir, tuple(x.shape),
        jnp.result_type(x), tuning,
        (wire, constants.get("wire_quant_block_size"))
        if wire_arg else ("full",),
    )
    entry = cache.get(key)
    if entry is None:
        perm = np.concatenate(comm._groups).astype(np.int32)
        inv = np.argsort(perm).astype(np.int32)
        mesh = comm.mesh
        spec = P(("inter", "intra"), *([None] * (x.ndim - 1)))
        minb, maxb, nbuf = tuning

        if intra_impl == "pallas":
            intra_ring, _ = _pallas_intra_ring(wire_arg)

            def intra_kernel(b):
                return intra_ring(b, "intra")
        else:
            def intra_kernel(b):
                return prim.ring_allreduce(
                    b, "intra",
                    max_bytes_per_step=maxb, min_bytes_per_step=minb,
                    num_buffers=nbuf, wire_dtype=wire_arg,
                )

        shmapped = jax.shard_map(
            intra_kernel, mesh=mesh, in_specs=spec, out_specs=spec,
            check_vma=False,
        )
        perm_j = jnp.asarray(perm)
        # the output stays in GROUP-MAJOR order, pinned to the SAME
        # (inter, intra) mesh the shard_map runs on (a rank-order out
        # sharding would use a different device order and jit rejects
        # mixed orders). Row k is rank perm[k]'s group sum, one row per
        # device — so the rep extraction below is partition-exact and
        # position k maps to a rank through perm.
        intra_fn = jax.jit(
            lambda a: shmapped(jnp.take(a, perm_j, axis=0)),
            out_shardings=NamedSharding(mesh, spec),
        )
        # reps (group firsts) sit at the head of each group-major block
        isz = len(comm._groups[0])
        rep_pos = np.arange(len(comm._groups), dtype=np.int32) * isz
        entry = (intra_fn, rep_pos)
        cache[key] = entry
    intra_fn, rep_pos = entry
    reduced = intra_fn(x)  # group-major; every row = its group's sum
    # host-staged inter reduction (the DCN hop)
    procs = sorted({d.process_index for d in comm._devices})
    if len(procs) > 1:
        # Multi-controller: jax.device_get of the full representative set
        # would raise — most rep rows are non-addressable here. Instead
        # each process sums the rep rows it OWNS (partition-exact: one
        # group-major row per device) and the partials meet over the PS
        # socket transport: host wires, no inter-group device link — the
        # point of the staged path (collectives_cuda.cpp:390-683).
        rep_set = {int(k) for k in rep_pos}
        rows = {}
        for shard in reduced.addressable_shards:
            k = shard.index[0].start or 0
            if k in rep_set and k not in rows:
                rows[k] = np.asarray(shard.data)[0]
        dt = np.dtype(reduced.dtype)
        per_row = tuple(x.shape[1:])
        partial = np.zeros(per_row, dt)
        for row in rows.values():
            partial = partial + row
        partial = np.ascontiguousarray(partial, dt)
        from ..parameterserver import transport as ps_transport

        if ps_transport._transport is None and len(procs) < jax.process_count():
            # Bootstrapping the transport does a JOB-global address
            # exchange; entering it from a collective only a subset of
            # processes runs would hang the subset forever. Bootstrap is
            # a job-global act — demand it happen at one.
            raise RuntimeError(
                "staged hierarchical allreduce on a communicator spanning "
                f"processes {procs} of {jax.process_count()}: the PS socket "
                "transport is not bootstrapped, and bootstrapping is "
                "job-global. Call torchmpi_tpu.parameterserver.transport."
                "ensure_transport() once on EVERY process (e.g. right "
                "after start()) before staged collectives on subset "
                "communicators."
            )
        # distinct gather tag per exchange, scoped to the PARTICIPATING
        # process set: SPMD program order is only guaranteed among the
        # processes that actually run this collective, so a process-global
        # counter would desync when subset communicators overlap
        pkey = tuple(procs)
        epoch = _staged_exchange_epochs.get(pkey, 0) + 1
        _staged_exchange_epochs[pkey] = epoch
        tag = f"staged-allreduce:{','.join(map(str, pkey))}:{epoch}"
        blobs = ps_transport.ensure_transport().allgather_blob(
            procs, tag, partial.tobytes(),
            timeout=constants.get("deadlock_timeout_seconds") or None,
        )
        total = np.zeros(per_row, dt)
        for blob in blobs.values():
            total = total + np.frombuffer(blob, dt).reshape(per_row)
        total = total.astype(dt, copy=False)
    else:
        host = np.asarray(jax.device_get(reduced[np.asarray(rep_pos)]))
        total = host.sum(axis=0).astype(host.dtype)
    stacked = np.broadcast_to(total, (comm.size,) + total.shape)
    # make_array_from_callback works on single- AND multi-controller
    # meshes (device_put with a global sharding does not on the latter)
    return jax.make_array_from_callback(
        stacked.shape, _rank_sharding(comm, x.ndim), lambda idx: stacked[idx]
    )


# monotone counters giving every staged exchange a distinct gather tag,
# one per participating process set (SPMD program order holds within a
# set, not across overlapping subset communicators)
_staged_exchange_epochs: dict = {}


def _hier_compile(comm: Communicator, key, ndim: int, donate: bool, kernel,
                  post=None):
    """Shared scaffolding for 2-level (cartesian) compositions: permute the
    rank-stacked rows into group-major mesh order, shard_map ``kernel`` over
    the (inter, intra) mesh, permute back (+ optional ``post(out, inv)``),
    jit with donation, memoize under ``key``. Returns ``(fn, cache_hit)``."""
    cache = _resource_cache(comm)
    fn = cache.get(key)
    if fn is not None:
        return fn, True
    perm = np.concatenate(comm._groups).astype(np.int32)
    inv = np.argsort(perm).astype(np.int32)
    mesh = comm.mesh  # 2D (inter, intra)
    spec = P(("inter", "intra"), *([None] * (ndim - 1)))
    shmapped = jax.shard_map(
        kernel, mesh=mesh, in_specs=spec, out_specs=spec, check_vma=False
    )
    perm_j, inv_j = jnp.asarray(perm), jnp.asarray(inv)

    def run_fn(a):
        out = jnp.take(shmapped(jnp.take(a, perm_j, axis=0)), inv_j, axis=0)
        return out if post is None else post(out, inv_j)

    fn = jax.jit(run_fn, donate_argnums=(0,) if donate else ())
    cache[key] = fn
    return fn, False


def run_hierarchical_collective(
    op: str, x, comm: Communicator, root: int = 0, ring_impl: str = "ring"
):
    """Two-level composition of broadcast/reduce/allgather on a cartesian
    communicator, routed like the hierarchical allreduce — the reference's
    per-collective hierarchical dispatch (``collectives_cuda.cpp:501-581,
    1057-1141``):

    - broadcast: inter-level ring/tree broadcast from the root's group
      within every intra row, then intra broadcast from the root's intra
      rank (every rank ends with the root's block).
    - reduce: intra ring-reduce to the root's intra rank, inter ring-reduce
      to the root's group; non-root ranks keep their input (this API's
      defined MPI_Reduce behavior).
    - allgather: intra all-gather then inter all-gather along the last dim,
      with the concatenation re-ordered from mesh (group-major) order to
      global rank order.

    ``ring_impl`` selects the INTRA-phase transport: ``'ring'`` (ppermute)
    or ``'pallas'`` (ICI RDMA kernels) — the level where the custom
    transport pays, like the reference's intra-IPC rings
    (``collectives_cuda.cpp:1057-1141``). The inter phase always runs the
    ppermute ring (it rides the slower cross-group fabric).
    """
    x = jnp.asarray(x)
    _check_rank_stacked(x, comm)
    if not (comm.cartesian and comm.has_inter_collective and comm.has_intra_collective):
        raise CollectiveArgumentError(
            "hierarchical collectives need a cartesian communicator with "
            "multiple intra groups of size > 1"
        )
    if op in ("broadcast", "reduce") and not 0 <= root < comm.size:
        raise CollectiveArgumentError(f"root {root} out of range")
    donate = constants.get("donate_eager_buffers")
    platform = comm._devices[0].platform
    tuning = ring_tuning(platform)
    minb, maxb, nbuf = tuning
    tree, chunks = True, 1
    if op == "broadcast":
        tree, chunks = broadcast_plan(
            _nelem_per_rank(x), jnp.result_type(x), platform
        )
    key = (
        "hier", op, root, tuple(x.shape), jnp.result_type(x), donate, tuning,
        (tree, chunks), ring_impl,
    )
    g0 = next(gi for gi, g in enumerate(comm._groups) if root in g)
    i0 = comm.member(root).intra_rank
    pallas_intra = ring_impl == "pallas"

    def bcast_axis(b, r, axis):
        if tree:
            return prim.tree_broadcast(b, r, axis)
        return prim.ring_broadcast(b, r, axis, num_chunks=chunks)

    def intra_bcast(b):
        if pallas_intra:
            from ..ops.ring_kernels import ring_broadcast_pallas

            return ring_broadcast_pallas(b, i0, "intra", num_chunks=chunks)
        return bcast_axis(b, i0, "intra")

    def intra_reduce(b):
        if pallas_intra:
            from ..ops.ring_kernels import ring_reduce_pallas

            return ring_reduce_pallas(b, i0, "intra")
        return prim.ring_reduce(
            b, i0, "intra",
            max_bytes_per_step=maxb, min_bytes_per_step=minb,
            num_buffers=nbuf,
        )

    def intra_allgather(b):
        if pallas_intra:
            return _pallas_allgather_lastdim(b, "intra")
        return prim.ring_allgather(b, "intra", dim=-1)

    if op == "broadcast":
        def kernel(b):
            # inter phase within every intra row, then intra phase
            b = bcast_axis(b, g0, "inter")
            return intra_bcast(b)
        post = None
    elif op == "reduce":
        def kernel(b):
            y = intra_reduce(b)
            z = prim.ring_reduce(
                y, g0, "inter",
                max_bytes_per_step=maxb, min_bytes_per_step=minb,
                num_buffers=nbuf,
            )
            is_root = (lax.axis_index("inter") == g0) & (
                lax.axis_index("intra") == i0
            )
            return jnp.where(is_root, z, b)
        post = None
    else:  # allgather
        def kernel(b):
            b = intra_allgather(b)
            return prim.ring_allgather(b, "inter", dim=-1)

        p, d = comm.size, int(x.shape[-1])

        def post(out, inv_j):
            # concat blocks arrive in mesh (group-major) order: put them
            # in global rank order along the gathered dim
            blocks = out.reshape(out.shape[:-1] + (p, d))
            return jnp.take(blocks, inv_j, axis=-2).reshape(out.shape)

    fn, hit = _hier_compile(comm, key, x.ndim, donate, kernel, post)
    return _dispatch(
        fn, x, f"hier_{op}", ring_impl, "full", _nelem_per_rank(x), hit,
        comm=comm, payload=(x.shape, x.dtype), routing="hier",
    )


def _binomial_reduce_steps(groups, p: int):
    """Static (perm, recv_mask) schedule per step of a binomial reduction to
    each group's first member: member j at span s receives from j+span when
    j % 2span == 0. ``log2(max group)`` steps; every value accumulated
    exactly once."""
    steps = []
    span = 1
    while True:
        perm = []
        mask = np.zeros((p,), bool)
        for g in groups:
            for j in range(0, len(g), 2 * span):
                if j + span < len(g):
                    perm.append((g[j + span], g[j]))
                    mask[g[j]] = True
        if not perm:
            break
        steps.append((perm, mask))
        span *= 2
    return steps


def run_tree_hierarchical_allreduce(x, comm: Communicator,
                                    wire: str = "full"):
    """Hierarchical allreduce on a NON-cartesian (ragged/tree) communicator
    — the reference's non-cartesian path (intra reduce to group root, inter
    exchange among roots, final intra broadcast,
    ``collectives_cuda.cpp:546-581``).

    TPU-native expression: statically-scheduled binomial ``ppermute``
    reductions (ragged groups forbid XLA's ``axis_index_groups``, which
    requires equal-size groups on TPU): reduce within each group to its
    root, reduce across the roots to the global root, then a static
    cross-device gather broadcasts the total — the trailing broadcast of
    the reference, collapsed to one hop.

    A compressed ``wire`` encodes every binomial exchange hop (partials
    quantized on send, f32 accumulate — non-target ranks receive zeros,
    which decode to exact zeros); only the final one-hop gather broadcast
    ships full precision.
    """
    x = jnp.asarray(x)
    _check_rank_stacked(x, comm)
    if not (comm.has_inter_collective and comm.has_intra_collective):
        raise CollectiveArgumentError(
            "hierarchical allreduce needs a communicator with both levels"
        )
    # byte accounting (once per dispatch; run() delegates before recording)
    _record_wire("allreduce", _nelem_per_rank(x), jnp.result_type(x), wire)
    cache = _resource_cache(comm)
    donate = constants.get("donate_eager_buffers")
    wire_arg = wire if wire != "full" else None
    block = constants.get("wire_quant_block_size")
    key = (
        "tree_hier_allreduce", tuple(x.shape), jnp.result_type(x), donate,
        (wire, block) if wire_arg else ("full",),
    )
    fn = cache.get(key)
    hit = fn is not None
    if fn is None:
        p = comm.size
        groups = [list(map(int, g)) for g in comm._groups]
        roots = [g[0] for g in groups]
        schedule = _binomial_reduce_steps(groups, p) + _binomial_reduce_steps(
            [roots], p
        )
        mesh = _flat_mesh(comm)
        spec = _rank_spec(x.ndim)

        def kernel(b):
            for perm, mask in schedule:
                if wire_arg:
                    # non-targets receive zero q/scales -> decode to 0
                    recv = prim._wire_send_recv(
                        b, _AXIS, perm, wire_arg, block
                    )
                else:
                    recv = lax.ppermute(b, _AXIS, perm)  # non-targets: 0
                receives = jnp.take(
                    jnp.asarray(mask), lax.axis_index(_AXIS)
                )
                b = jnp.where(receives, b + recv, b)
            return b

        shmapped = jax.shard_map(
            kernel, mesh=mesh, in_specs=spec, out_specs=spec, check_vma=False
        )
        sharding = _rank_sharding(comm, x.ndim)
        # trailing broadcast: everyone reads the global root's total
        idx = jnp.full((p,), roots[0], jnp.int32)

        def run_fn(a):
            y = shmapped(a)
            return jax.lax.with_sharding_constraint(
                jnp.take(y, idx, axis=0), sharding
            )

        fn = jax.jit(run_fn, donate_argnums=(0,) if donate else ())
        cache[key] = fn
    return _dispatch(
        fn, x, "tree_hier_allreduce", "ring", wire, _nelem_per_rank(x), hit,
        comm=comm, payload=(x.shape, x.dtype), routing="tree",
    )


def run_group_broadcast(x, comm: Communicator, root: int = 0):
    """Broadcast within each *intra group* of ``comm`` from the member with
    intra rank ``root`` — the hierarchical building block of mixed
    PS × data-parallel updates (``update.lua:104-112``) and of the
    reference's non-cartesian hierarchical allreduce's final intra
    broadcast (``collectives_cuda.cpp:569-579``).

    Works for cartesian and ragged (tree) communicators alike: the source
    map rank -> group-root is a static permutation, so the op lowers to a
    cross-device gather.
    """
    x = jnp.asarray(x)
    _check_rank_stacked(x, comm)
    cache = _resource_cache(comm)
    key = ("_group_bcast", root, tuple(x.shape), jnp.result_type(x))
    fn = cache.get(key)
    if fn is None:
        groups: dict = {}
        for r in range(comm.size):
            m = comm.member(r)
            groups.setdefault(m.intra_group, {})[m.intra_rank] = r
        src = np.zeros((comm.size,), np.int32)
        for r in range(comm.size):
            g = groups[comm.member(r).intra_group]
            if root not in g:
                raise CollectiveArgumentError(
                    f"intra root {root} out of range for group of size {len(g)}"
                )
            src[r] = g[root]
        sharding = _rank_sharding(comm, x.ndim)
        idx = jnp.asarray(src)
        fn = jax.jit(
            lambda a: jax.lax.with_sharding_constraint(
                jnp.take(a, idx, axis=0), sharding
            )
        )
        cache[key] = fn
    sharding = _rank_sharding(comm, x.ndim)
    if getattr(x, "sharding", None) != sharding:
        x = jax.device_put(x, sharding)
    return fn(x)


def barrier(comm: Communicator) -> None:
    """Device barrier over the communicator (``torch_mpi.cpp:270-280``)."""
    cache = _resource_cache(comm)
    fn = cache.get("_barrier")
    if fn is None:
        mesh = comm.flat_mesh(_AXIS)
        fn = jax.jit(
            jax.shard_map(
                lambda x: prim.barrier_value(_AXIS) + x * 0,
                mesh=mesh,
                in_specs=P(_AXIS),
                out_specs=P(_AXIS),
            )
        )
        cache["_barrier"] = fn
    jax.block_until_ready(fn(jnp.zeros((comm.size,), jnp.int32)))
