"""Eager collectives over a communicator's devices.

The reference exposes *eager* collectives: ``mpi.allreduceTensor(t)`` acts on
a rank-local tensor, across processes, right now. The TPU-native equivalent
operates on a **rank-stacked array**: an array whose leading axis indexes the
communicator's ranks (size ``comm.size``), sharded so rank *i*'s block lives
on device *i*. Each call shards the input over the communicator's flat mesh
(one block per device = one "rank-local tensor"), runs the collective kernel
under ``shard_map``, and returns the rank-stacked result.

Key reference mechanics preserved:

- **Resource memoization**: the reference memoizes NCCL comms / IPC handles /
  Gloo contexts per ``(data pointer, communicator)`` with
  collective-at-first-use semantics (``lib/resources.cpp:102-163``,
  ``lib/resources.h:95-100``). Here the expensive lazily-created resource is
  the *compiled XLA executable*; it is memoized per
  ``(op, backend, shape, dtype, static args)`` on the communicator object,
  so first use pays compilation and subsequent calls are dispatch-only.
- **Async = dispatch + handle**: XLA dispatch is asynchronous, so the async
  variants return immediately with a :class:`SyncHandle` wrapping the
  in-flight arrays (the stream-handle variant of ``resources.h:230-253``);
  launch overhead is the Python dispatch cost, mirroring the <50µs assertion
  in ``test/collectives_all.lua:192-199``.
- **Routing is compiled, not branched**: every dispatch flows through the
  schedule compiler (:mod:`torchmpi_tpu.schedule`) — the request is resolved
  to a cost-modeled :class:`~torchmpi_tpu.schedule.ir.Plan` against the
  declared topology and bound to an executable; the small/large latency
  routing (the analog of falling back to stock MPI below
  ``kSmallAllreduceSize``, ``lib/collectives.cpp:296-301``), hierarchical /
  staged / tree composition, and wire-format choice are all plan-compiler
  decisions now. The ``run_hierarchical_*`` entry points remain as thin
  wrappers that pin a plan generator.

This module keeps the executor-side machinery the compiler lowers onto:
the per-communicator executable caches (with AOT pin semantics), the flat
kernel table over the xla / ppermute-ring / pallas backends, and the
telemetry dispatch wrapper that stamps every call with its ``plan_id``.
"""

from __future__ import annotations

import time
from collections import OrderedDict
from typing import Any, Callable, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from .. import constants, telemetry as _telemetry
from ..runtime.communicator import Communicator
from ..runtime.handles import SyncHandle
from ..telemetry import flightrecorder as _flight
from . import primitives as prim

_AXIS = "mpi"

# telemetry handles, created on first instrumented dispatch (the metric
# objects are process-lived; the disabled path never touches them)
_MET = None


def _metric_handles():
    global _MET
    if _MET is None:
        m = _telemetry.metrics
        _MET = (
            m.counter(
                "tm_collective_calls_total",
                "eager collective dispatches by op/backend/wire",
            ),
            m.histogram(
                "tm_collective_dispatch_seconds",
                "host-side dispatch wall time per eager collective "
                "(XLA dispatch is async: submit cost, not completion)",
            ),
            m.counter(
                "tm_collective_compiles_total",
                "executable-cache misses (compilations) by op/backend",
            ),
            m.counter(
                "tm_collective_cache_hits_total",
                "executable-cache hits by op/backend",
            ),
        )
    return _MET


def _dispatch(fn, x, op: str, backend: str, wire: str, nelem: int,
              cache_hit: Optional[bool], comm: Optional[Communicator] = None,
              payload=None, routing: str = "", plan: str = ""):
    """Run ``fn(x)`` (a compiled eager executable, or a composition like
    the staged allreduce), recording the dispatch (span + metrics) when
    telemetry is enabled, plus a flight-recorder entry (per-comm seq, op,
    payload, issue/complete stamps) when the recorder is on; one branch
    each when disabled. ``cache_hit=None`` means no single executable
    cache applies (multi-phase compositions). ``payload`` is the raw
    (shape, dtype) pair — stringified only at snapshot time. ``plan`` is
    the schedule compiler's stable plan_id: the cross-rank identity that
    lets the desync analyzer name the diverging *plan*, not just the op
    (hierarchical sub-structure included — the old entries said
    ``routing="hier"`` and nothing else)."""
    entry = None
    if _flight.enabled() and comm is not None:
        entry = _flight.recorder.record(
            _flight.comm_key(comm), op, payload=payload, wire=wire,
            backend=backend, routing=routing, plan=plan,
        )
    if not _telemetry.enabled():
        if entry is None:
            return fn(x)
        try:
            out = fn(x)
        except BaseException:
            _flight.FlightRecorder.fail(entry)
            raise
        _flight.FlightRecorder.complete(entry)
        return out
    calls, lat, compiles, hits = _metric_handles()
    attrs = {"backend": backend, "wire_dtype": wire, "nelem": nelem}
    if plan:
        attrs["plan"] = plan
    if cache_hit is not None:
        attrs["cache"] = "hit" if cache_hit else "miss"
    t0 = time.perf_counter()
    try:
        with _telemetry.span(f"collective.{op}", **attrs):
            out = fn(x)
    except BaseException:
        if entry is not None:
            _flight.FlightRecorder.fail(entry)
        raise
    if entry is not None:
        _flight.FlightRecorder.complete(entry)
    calls.inc(op=op, backend=backend, wire=wire)
    lat.observe(time.perf_counter() - t0, op=op, backend=backend)
    if cache_hit is not None:
        (hits if cache_hit else compiles).inc(op=op, backend=backend)
    return out


class CollectiveArgumentError(ValueError):
    pass


def _rank_spec(ndim: int) -> P:
    return P(_AXIS, *([None] * (ndim - 1)))


def _check_rank_stacked(x, comm: Communicator) -> None:
    if x.ndim < 1 or x.shape[0] != comm.size:
        raise CollectiveArgumentError(
            f"eager collectives expect a rank-stacked array with leading axis "
            f"== comm.size ({comm.size}); got shape {tuple(x.shape)}. Inside "
            f"jit/shard_map code use torchmpi_tpu.collectives.primitives "
            f"directly instead."
        )


class _LRUCache(OrderedDict):
    """Bounded executable cache: get() refreshes recency, inserts evict the
    least-recently-used entry past ``collective_cache_max_entries``. A
    2^8..2^23 x backends x dtypes tester sweep would otherwise accumulate
    hundreds of compiled executables with no way back — the reference frees
    its per-size IPC descriptors for the same reason
    (``torchmpi/cache.lua:19-61``).

    Entries may be **pinned** (:meth:`pin` — the AOT ``precompile`` path):
    pinned entries are never LRU-evicted, so a tester sweep cannot silently
    evict the executables a training loop declared up front. They still go
    away with the whole cache (``free_collective_resources`` / ``stop()``,
    whose contract is a wholesale teardown). The schedule compiler's plan
    cache and dispatch memo reuse this class — same bound, same pin
    semantics, same teardown."""

    def __init__(self, *args, **kwargs):
        super().__init__(*args, **kwargs)
        self._pinned = set()
        self._access_log = None  # set: records gets/inserts when armed

    def log_accesses(self, log: set) -> None:
        """Arm (or, with None, disarm) access logging: every hit and
        insert lands in ``log``. Used by ``precompile`` to pin exactly
        the entries its dispatches touched — including executables that
        already existed (a plain before/after key diff misses those)."""
        self._access_log = log

    def get(self, key, default=None):
        try:
            value = super().__getitem__(key)
        except KeyError:
            return default
        self.move_to_end(key)
        if self._access_log is not None:
            self._access_log.add(key)
        return value

    def pin(self, key) -> bool:
        """Exempt ``key`` from LRU eviction; True if it was present."""
        if key in self:
            self._pinned.add(key)
            return True
        return False

    def pinned_count(self) -> int:
        return len(self._pinned)

    def __setitem__(self, key, value):
        super().__setitem__(key, value)
        self.move_to_end(key)
        if self._access_log is not None:
            self._access_log.add(key)
        limit = constants.get("collective_cache_max_entries")
        while len(self) > limit:
            victim = next((k for k in self if k not in self._pinned), None)
            if victim is None:
                break  # everything pinned: the pins outrank the bound
            del self[victim]


def _resource_cache(comm: Communicator) -> dict:
    # Lazily attached, like acquireCollectiveResources keying off the comm.
    cache = getattr(comm, "_collective_resources", None)
    if cache is None:
        cache = _LRUCache()
        comm._collective_resources = cache  # type: ignore[attr-defined]
    return cache


def _dispatch_memo(comm: Communicator) -> dict:
    """The warm-dispatch fast-path memo: (call signature) -> bound
    :class:`~torchmpi_tpu.schedule.compiler.ExecutablePlan`. A SEPARATE
    LRU from the executable cache so memo entries never perturb the
    executable-count accounting (tests and the reference's per-resource
    model count executables, not lookups) — but the same bound and the
    same wholesale teardown."""
    memo = getattr(comm, "_dispatch_memo", None)
    if memo is None:
        memo = _LRUCache()
        comm._dispatch_memo = memo  # type: ignore[attr-defined]
    return memo


def free_collective_resources(comm: Communicator) -> None:
    """Drop every cached compiled executable / sharding / selector decision
    / plan-cache entry / fusion buffer attached to ``comm`` — the analog of
    the reference's ``freeCollectiveResources`` (``torchmpi/cache.lua:19-61``,
    invoked by the tester between sizes, ``torchmpi/tester.lua:131-133``).
    Safe at any time: the next collective simply recompiles, and pending
    fused submissions are flushed first so no handle is orphaned. Pinned
    AOT entries go too — this is the wholesale teardown, not LRU pressure.
    Called by ``stop()`` for every live stack level."""
    fb = getattr(comm, "_fusion_buffer", None)
    if fb is not None:
        try:
            fb.flush_all(reason="explicit")
        except Exception:
            pass
    for attr in (
        "_collective_resources",
        "_dispatch_memo",
        "_plan_cache",
        "_selector_cache",
        "_fusion_buffer",
    ):
        if getattr(comm, attr, None) is not None:
            try:
                delattr(comm, attr)
            except AttributeError:
                pass


def _flat_mesh(comm: Communicator) -> Mesh:
    # The Communicator's device list is immutable: build the mesh once.
    mesh = getattr(comm, "_eager_flat_mesh", None)
    if mesh is None:
        mesh = comm.flat_mesh(_AXIS)
        comm._eager_flat_mesh = mesh  # type: ignore[attr-defined]
    return mesh


def _rank_sharding(comm: Communicator, ndim: int) -> NamedSharding:
    cache = _resource_cache(comm)
    key = ("_sharding", ndim)
    s = cache.get(key)
    if s is None:
        s = NamedSharding(_flat_mesh(comm), _rank_spec(ndim))
        cache[key] = s
    return s


def _compile(
    comm: Communicator,
    op: str,
    backend: str,
    aval: Tuple[Tuple[int, ...], Any],
    static: Tuple,
    build_kernel: Callable[[], Callable],
):
    """Fetch-or-build the jitted executable for this (op, comm, aval).
    Returns ``(fn, cache_hit)`` so dispatch telemetry can label the call."""
    cache = _resource_cache(comm)
    donate = constants.get("donate_eager_buffers")
    # donate participates in the key: toggling the constant after first use
    # must not silently keep the old executable's aliasing behavior.
    key = (op, backend, aval, static, donate)
    fn = cache.get(key)
    if fn is not None:
        return fn, True
    mesh = _flat_mesh(comm)
    ndim = len(aval[0])
    spec = _rank_spec(ndim)
    kernel = build_kernel()
    shmapped = jax.shard_map(
        kernel, mesh=mesh, in_specs=spec, out_specs=spec, check_vma=False
    )
    fn = jax.jit(shmapped, donate_argnums=(0,) if donate else ())
    cache[key] = fn
    return fn, False


def _per_rank_shape(x_shape: Tuple[int, ...]) -> Tuple[int, ...]:
    return (1,) + tuple(x_shape[1:])


def _nelem_per_rank(x) -> int:
    return int(np.prod(_per_rank_shape(x.shape)))


# ---------------------------------------------------------------------------
# backend kernel builders: operate on a [1, ...] per-rank block
# ---------------------------------------------------------------------------


def ring_tuning(platform: str) -> Tuple[int, int, int]:
    """(min_bytes, max_bytes, num_buffers) for the platform's custom rings —
    the reference's kMin/kMaxBufferSize + kNumBuffersPerCollective knobs
    (``lib/constants.cpp:142-150``), capped by
    ``max_num_buffers_per_collective`` (``lib/constants.h:77-78``)."""
    suffix = constants.platform_suffix(platform)
    nb = min(
        constants.get(f"num_buffers_per_collective_{suffix}"),
        constants.get("max_num_buffers_per_collective"),
    )
    return (
        constants.get(f"min_buffer_size_{suffix}"),
        constants.get(f"max_buffer_size_{suffix}"),
        nb,
    )


def broadcast_plan(nelem: int, dtype, platform: str) -> Tuple[bool, int]:
    """(use_tree, pipeline_chunks) for a broadcast of ``nelem`` elements:
    tree below broadcast_size_tree_based (collectives.cpp:58-64's 4MB
    switch); above it, the pipelined chunk count from the buffer-size
    bounds — every chunk <= max_buffer_size and no smaller than
    min_buffer_size (constants.cpp:142-150). One source of truth for the
    flat AND hierarchical lowerings (schedule/lower.py consumes it)."""
    suffix = constants.platform_suffix(platform)
    block_bytes = nelem * jnp.dtype(dtype).itemsize
    if block_bytes <= constants.get(f"broadcast_size_tree_based_{suffix}"):
        return True, 1
    minb, maxb, _ = ring_tuning(platform)
    k = max(1, -(-block_bytes // max(1, maxb)))
    k = min(k, max(1, block_bytes // max(1, minb)))
    return False, int(k)


def _pallas_reduce_scatter_lastdim(b, axis: str, wire_dtype=None):
    """Scatter-along-last-dim reduce-scatter (dual of the allgather
    contract) on a [1, ..., d] per-rank block via the pallas RS ring, which
    scatters dim 0 with psum_scatter tiled semantics."""
    from ..ops.ring_kernels import ring_reduce_scatter_pallas

    moved = jnp.moveaxis(b[0], -1, 0)  # [d, ...]
    mine = ring_reduce_scatter_pallas(
        moved, axis, wire_dtype=wire_dtype
    )  # [d/p, ...]
    return jnp.moveaxis(mine, 0, -1)[None]


def _pallas_allgather_lastdim(b, axis: str):
    """Concat-along-last-dim allgather (the eager contract) on a [1, ..., d]
    per-rank block via the (p-1)-step pallas forwarding ring. Shared by the
    flat backend table and the hierarchical intra phase."""
    from ..ops.ring_kernels import ring_allgather_pallas

    stacked = ring_allgather_pallas(b[0], axis)  # [p, ..., d]
    moved = jnp.moveaxis(stacked, 0, -2)  # [..., p, d]
    # b.shape[:-1] keeps the leading per-rank 1: output is [1, ..., p*d]
    return moved.reshape(b.shape[:-1] + (moved.shape[-2] * moved.shape[-1],))


def _kernels(op: str, backend: str, root: int, extra: Tuple,
             tuning: Tuple = (), wire: str = "full"):
    """Return a kernel fn(block) for the given op/backend.

    For ``backend='ring'`` broadcasts, ``extra`` carries the tree-vs-pipeline
    decision (made by the flat lowering from the platform-appropriate
    constant, so it participates in the executable cache key —
    ``collectives.cpp:58-64``'s 4MB switch) plus the pipelined chunk count;
    ``tuning`` carries (min_bytes, max_bytes, num_buffers) for byte-bounded
    ring chunking; ``wire`` the resolved wire format for the bandwidth-path
    reductions. A ``('pipeline', d)`` marker in ``extra`` (the schedule
    compiler's plan depth, already part of the executable cache key via
    ``static``) threads the chunk-pipeline depth into the ppermute ring."""
    minb, maxb, nbuf = tuning if tuning else (None, None, 1)
    wire_arg = wire if wire != "full" else None
    pipe = next(
        (e[1] for e in extra if isinstance(e, tuple) and e[0] == "pipeline"),
        1,
    )

    def _ring_allreduce(b):
        return prim.ring_allreduce(
            b, _AXIS,
            max_bytes_per_step=maxb, min_bytes_per_step=minb,
            num_buffers=nbuf, wire_dtype=wire_arg, pipeline_depth=pipe,
        )

    def _ring_reduce(b):
        return prim.ring_reduce(
            b, root, _AXIS,
            max_bytes_per_step=maxb, min_bytes_per_step=minb,
            num_buffers=nbuf,
        )

    def _bcast_builder(pipeline_fn):
        # shared tree-vs-pipeline routing for the custom-ring broadcasts;
        # extra carries the decision + the ('chunks', k) pipelining depth
        def bcast(b):
            if "tree" in extra:
                return prim.tree_broadcast(b, root, _AXIS)
            k = next(
                (e[1] for e in extra if isinstance(e, tuple) and e[0] == "chunks"),
                None,
            )
            return pipeline_fn(b, k)
        return bcast

    _ring_bcast = _bcast_builder(
        lambda b, k: prim.ring_broadcast(b, root, _AXIS, num_chunks=k)
    )

    if backend == "xla":
        table = {
            "allreduce": lambda b: prim.allreduce(b, _AXIS),
            "broadcast": lambda b: prim.broadcast(b, root, _AXIS),
            "reduce": lambda b: prim.reduce(b, root, _AXIS),
            "allgather": lambda b: prim.allgather(b, _AXIS, dim=-1),
            "sendreceive": lambda b: prim.sendreceive(b, extra[0], extra[1], _AXIS),
            "reducescatter": lambda b: prim.reduce_scatter(
                b, _AXIS, dim=b.ndim - 1
            ),
            # b: [1, p, ...] — scatter/stack the rank dimension
            "alltoall": lambda b: prim.alltoall(
                b, _AXIS, split_dim=1, concat_dim=1
            ),
        }
    elif backend == "ring":
        table = {
            "allreduce": _ring_allreduce,
            "broadcast": _ring_bcast,
            "reduce": _ring_reduce,
            "allgather": lambda b: prim.ring_allgather(b, _AXIS, dim=-1),
            "sendreceive": lambda b: prim.sendreceive(b, extra[0], extra[1], _AXIS),
            "reducescatter": lambda b: prim.ring_reduce_scatter(
                b, _AXIS, dim=-1, wire_dtype=wire_arg
            ),
            "alltoall": lambda b: prim.ring_alltoall(b[0], _AXIS)[None],
        }
    elif backend == "pallas":
        # Pallas ICI-RDMA rings for allreduce / reduce / allgather +
        # pipelined broadcast; only sendreceive takes the ppermute path
        # (a single point-to-point hop IS one XLA collective-permute — a
        # ring kernel would add nothing).
        from ..ops.ring_kernels import (
            ring_allreduce_bidir_pallas,
            ring_allreduce_pallas,
            ring_broadcast_pallas,
            ring_reduce_pallas,
        )

        _pallas_bcast = _bcast_builder(
            lambda b, k: ring_broadcast_pallas(b, root, _AXIS, num_chunks=k)
        )
        # a compressed wire pins the unidirectional kernel (the bidir
        # ring has no quant path; the flat lowering drops the marker
        # accordingly)
        if wire_arg is not None:
            def _pallas_allreduce(b, axis):
                return ring_allreduce_pallas(b, axis, wire_dtype=wire_arg)
        else:
            _pallas_allreduce = (
                ring_allreduce_bidir_pallas
                if "bidir" in extra
                else ring_allreduce_pallas
            )

        table = {
            "allreduce": lambda b: _pallas_allreduce(b, _AXIS),
            "broadcast": _pallas_bcast,
            "reduce": lambda b: ring_reduce_pallas(b, root, _AXIS),
            "allgather": lambda b: _pallas_allgather_lastdim(b, _AXIS),
            "sendreceive": lambda b: prim.sendreceive(b, extra[0], extra[1], _AXIS),
            "reducescatter": lambda b: _pallas_reduce_scatter_lastdim(
                b, _AXIS, wire_arg
            ),
            # a single fused all_to_all IS one XLA collective already —
            # same rationale as sendreceive's ppermute path
            "alltoall": lambda b: prim.alltoall(
                b, _AXIS, split_dim=1, concat_dim=1
            ),
        }
    else:
        raise CollectiveArgumentError(f"unknown backend {backend!r}")
    if op not in table:
        raise CollectiveArgumentError(f"unknown collective {op!r}")
    return table[op]


# collectives the compressed wire formats apply to (the bandwidth-path
# reductions; data movers are lossless by contract and stay verbatim)
_WIRE_OPS = ("allreduce", "reducescatter")


def resolve_wire_dtype(op: str, nelem: int, dtype,
                       requested: Optional[str] = None) -> str:
    """The wire-format routing decision for one eager call: the explicit
    ``wire_dtype=`` argument wins, else the ``wire_dtype`` constant (the
    autotuner's persisted pick); 'full' whenever the encoding cannot
    engage — wrong op, non-f32 payload (ints pass through uncompressed,
    exactness is their contract), or below the min-elements cutoff."""
    wire = requested if requested is not None else constants.get("wire_dtype")
    if wire in (None, "", "full"):
        return "full"
    if wire not in ("int8", "bf16"):
        raise CollectiveArgumentError(
            f"unknown wire_dtype {wire!r}; expected 'full', 'bf16' or 'int8'"
        )
    if op not in _WIRE_OPS:
        return "full"
    if jnp.dtype(dtype) != jnp.dtype(jnp.float32):
        return "full"
    if nelem < constants.get("wire_quant_min_elements"):
        return "full"
    return wire


def _record_wire(op: str, nelem: int, dtype, wire: str) -> None:
    """Feed the tracing counters: per-rank logical payload bytes vs the
    bytes the chosen encoding puts on the wire per hop."""
    from ..utils import tracing

    itemsize = jnp.dtype(dtype).itemsize
    block = constants.get("wire_quant_block_size")
    wire_bytes = prim.wire_encoded_bytes(nelem, itemsize, wire, block)
    tracing.wire_stats.record(op, wire, nelem * itemsize, wire_bytes)


def op_route(op: str, nelem: int, platform: str, requested: str = "ring") -> str:
    """Size-based latency/bandwidth routing (reference
    ``collectives.cpp:296-301``): below the cutoff use the fused XLA path,
    above it the requested bandwidth backend (ring or pallas). Consumed by
    the schedule compiler's backend resolution — the cutoff constants are
    the MEASURED crossover the cost model defers to."""
    suffix = constants.platform_suffix(platform)
    if op == "allreduce":
        cutoff = constants.get(f"small_allreduce_size_{suffix}")
    elif op == "broadcast":
        cutoff = constants.get(f"small_broadcast_size_{suffix}")
    else:
        return requested
    return "xla" if nelem <= cutoff else requested


def _validate(op: str, x, comm: Communicator, root: int,
              wire_dtype: Optional[str]):
    """Shared argument validation for the compiled dispatch path; returns
    the (possibly lifted) input."""
    _check_rank_stacked(x, comm)
    if wire_dtype not in (None, "full", "bf16", "int8"):
        # validated unconditionally: a typo must not pass silently just
        # because this call happened to route to the fused XLA path
        raise CollectiveArgumentError(
            f"unknown wire_dtype {wire_dtype!r}; expected 'full', 'bf16' "
            "or 'int8'"
        )
    if op in ("broadcast", "reduce") and not 0 <= root < comm.size:
        raise CollectiveArgumentError(f"root {root} out of range")
    if op == "allgather" and x.ndim == 1:
        # One scalar per rank: lift to [p, 1] so the output stays rank-stacked
        # ([p, p]: every rank's block is the gathered vector).
        x = x[:, None]
    if op == "reducescatter":
        if x.ndim < 2 or x.shape[-1] % comm.size != 0:
            raise CollectiveArgumentError(
                f"reducescatter scatters the last dim, which must exist and "
                f"be divisible by the communicator size {comm.size}; got "
                f"shape {tuple(x.shape)}"
            )
    if op == "alltoall":
        if x.ndim < 2 or x.shape[1] != comm.size:
            raise CollectiveArgumentError(
                f"alltoall needs rank-stacked [p, p, ...] input (block "
                f"[r, s] = rank r's payload for rank s); got shape "
                f"{tuple(x.shape)} for p={comm.size}"
            )
    return x


def run(
    op: str,
    x,
    comm: Communicator,
    backend: str = "xla",
    root: int = 0,
    src: int = 0,
    dst: int = 0,
    route_small: bool = True,
    wire_dtype: Optional[str] = None,
):
    """Synchronous eager collective on a rank-stacked array.

    The request is compiled by the schedule compiler
    (:func:`torchmpi_tpu.schedule.compile_collective`): effective backend,
    wire format, and schedule family (flat / hierarchical / staged /
    tree) are one cached plan decision, and the bound executable replays
    through the telemetry dispatch with its ``plan_id``. Warm calls are
    a single memo hit — no routing work at all.

    ``wire_dtype``: per-call wire-format override for the bandwidth-path
    reductions ('full' | 'bf16' | 'int8'; None = the ``wire_dtype``
    constant). See :func:`resolve_wire_dtype` for the engagement gates.
    """
    x = jnp.asarray(x)
    x = _validate(op, x, comm, root, wire_dtype)
    from ..schedule import compiler as _sched

    ep = _sched.compile_collective(
        op, tuple(x.shape), jnp.result_type(x), comm,
        backend=backend, route_small=route_small, wire_dtype=wire_dtype,
        root=root, src=src, dst=dst,
    )
    return ep.execute(x)


def run_fused(
    op: str,
    flats,
    comm: Communicator,
    backend: str = "xla",
    route_small: bool = True,
    wire_dtype: Optional[str] = None,
):
    """Coalesced multi-input dispatch: ``flats`` (same-dtype rank-stacked
    ``[p, n_i]`` slabs) are packed AND reduced by ONE compiled executable
    — concat + collective fused into a single plan, so a flush of k
    pending tensors costs one XLA dispatch, not k (and not even
    pack + collective = 2). The GC3 move (arXiv:2201.11840): the plan is
    compiled once per (op, layout, dtype, routing) and replayed.

    Routing (latency/bandwidth cutoff, wire format) is decided by the
    schedule compiler on the TOTAL payload — coalescing is exactly what
    pushes small tensors past the bandwidth-path and quantization
    cutoffs. Hierarchical communicators delegate to the (cached)
    hierarchical composition after a single-dispatch concat — 2
    dispatches, still O(1) in k. Inputs are caller arrays and are never
    donated. Returns the fused ``[p, total]`` result; callers slice
    their segments back out."""
    if op != "allreduce":
        raise CollectiveArgumentError(
            f"run_fused supports allreduce, got {op!r}"
        )
    flats = [
        f if isinstance(f, jax.Array) else jnp.asarray(f) for f in flats
    ]
    if not flats:
        raise CollectiveArgumentError("run_fused needs at least one tensor")
    for f in flats:
        _check_rank_stacked(f, comm)
    dtype = flats[0].dtype
    if any(f.dtype != dtype for f in flats):
        dtype = jnp.result_type(*flats)
        flats = [f.astype(dtype) for f in flats]
    ns = tuple(int(f.shape[1]) for f in flats)
    from ..schedule import compiler as _sched

    ep = _sched.compile_fused(
        op, ns, dtype, comm,
        backend=backend, route_small=route_small, wire_dtype=wire_dtype,
    )
    return ep.execute(flats)


def run_allgatherv(blocks, comm: Communicator, backend: str = "xla"):
    """Variable-size allgather: per-rank blocks with RAGGED last dims are
    concatenated along the last dimension on every rank — the reference's
    size-exchange + ``MPI_Allgatherv`` + output realloc
    (``lib/collectives.cpp:245-290``).

    ``blocks`` is a sequence of ``comm.size`` arrays that agree on every
    dimension except the last. XLA needs static shapes, so the reference's
    runtime size exchange happens at trace time (the sizes ARE the trace
    constants); on the wire the blocks travel padded to the max size and
    the valid prefixes are re-assembled in-graph.

    Returns a rank-stacked ``[p, ..., sum(sizes)]`` array (every rank's
    block holds the full concatenation, like the uniform allgather).
    """
    if len(blocks) != comm.size:
        raise CollectiveArgumentError(
            f"allgatherv expects {comm.size} blocks (one per rank), got "
            f"{len(blocks)}"
        )
    blocks = [jnp.asarray(b) for b in blocks]
    base = blocks[0].shape[:-1]
    dtype = jnp.result_type(blocks[0])
    for i, b in enumerate(blocks):
        if b.ndim == 0 or b.shape[:-1] != base:
            raise CollectiveArgumentError(
                f"block {i} shape {tuple(b.shape)} does not match leading "
                f"dims {base} (only the LAST dim may vary, like the "
                "reference's last-dim realloc)"
            )
        if jnp.result_type(b) != dtype:
            raise CollectiveArgumentError(
                f"block {i} dtype {b.dtype} != {dtype}"
            )
    sizes = tuple(int(b.shape[-1]) for b in blocks)
    nmax = max(sizes) if sizes else 0
    p = comm.size

    if backend == "ring":
        gather = lambda b: prim.ring_allgather(b, _AXIS, dim=0)  # noqa: E731
    elif backend == "xla":
        gather = lambda b: prim.allgather(b, _AXIS, dim=0)  # noqa: E731
    else:
        raise CollectiveArgumentError(
            f"allgatherv backend must be 'xla' or 'ring', got {backend!r}"
        )

    def build_kernel():
        def kernel(b):
            # b: [1, ..., nmax] per-rank padded block
            g = gather(b)  # [p, ..., nmax]
            parts = [
                jax.lax.slice_in_dim(
                    jax.lax.index_in_dim(g, r, 0, keepdims=False),
                    0, sizes[r], axis=len(base),  # the last dim
                )
                for r in range(p)
            ]
            return jnp.concatenate(parts, axis=-1)[None]

        return kernel

    stacked_shape = (p,) + base + (nmax,)
    fn, hit = _compile(
        comm, "allgatherv", backend, (stacked_shape, dtype), (sizes,),
        build_kernel,
    )

    padded = jnp.stack(
        [
            jnp.concatenate(
                [b, jnp.zeros(base + (nmax - s,), dtype)], axis=-1
            )
            if s < nmax
            else b
            for b, s in zip(blocks, sizes)
        ]
    )
    sharding = _rank_sharding(comm, padded.ndim)
    if getattr(padded, "sharding", None) != sharding:
        padded = jax.device_put(padded, sharding)
    return _dispatch(
        fn, padded, "allgatherv", backend, "full", int(sum(sizes)), hit,
        comm=comm, payload=(sizes, dtype), routing="flat",
    )


def run_async(op: str, x, comm: Communicator, **kw) -> SyncHandle:
    """Asynchronous variant: returns a handle immediately; the arrays are
    in flight on device (XLA async dispatch replaces the reference's
    offload-thread + future machinery for device collectives). The handle is
    registered in the global table so ``sync_all()`` (and thus ``stop()``)
    drains it, matching ``resources.cpp:463-481``."""
    from ..runtime.handles import handles

    # Backpressure: bound the number of unwaited async collectives
    # (kNumAsyncCollectivesInFlight, lib/constants.cpp:152-155) — when the
    # table is full, the oldest outstanding handle is drained first, the
    # analog of the reference's bounded future queues blocking enqueue.
    limit = constants.get("num_async_collectives_in_flight")
    while handles.outstanding_kind("collective") >= limit:
        if not handles.wait_oldest("collective"):
            break
    out = run(op, x, comm, **kw)
    h = SyncHandle(arrays=out)
    handles.register(h, kind="collective")
    return h


def precompile(specs, comm: Optional[Communicator] = None,
               pin: bool = True) -> int:
    """AOT warm-up: populate (and **pin**) the executable cache from
    declared collective specs so the first training step never compiles a
    collective — the GC3 move (arXiv:2201.11840) of compiling collective
    *plans* ahead of time and replaying them.

    ``specs`` is an iterable of tuples ``(op, shape, dtype)`` optionally
    extended with ``backend`` and ``wire_dtype`` (or dicts with those
    keys plus ``root``). ``shape`` is the rank-stacked shape; a shape
    whose leading axis differs from ``comm.size`` is treated as the
    per-rank block shape and the rank axis is prepended. A dict spec may
    instead carry ``layout``: a tuple of per-rank widths declaring a
    coalesced multi-tensor group — warmed through :func:`run_fused`, the
    executable a ``FusionBuffer`` flush of that layout replays.

    Each spec is dispatched once on a zeros payload through the exact
    production route (schedule compiler, wire resolution, hierarchical
    composition), so the jitted executable AND the plan cache AND the
    per-signature dispatch memo are all warm afterwards; every entry the
    warm-up touches in any of the three — newly compiled OR already
    present — is pinned against LRU eviction
    (``free_collective_resources`` still frees them — wholesale teardown
    outranks pins). After precompile, a training loop's dispatches hit
    zero executable compiles AND zero plan-cache misses (the
    ``bench.py --microbench --check`` gates). Returns the number of
    specs warmed. Typically invoked via
    ``start(precompile_collectives=...)`` or
    ``AllReduceSGDEngine.precompile()``."""
    if comm is None:
        from .. import runtime_state

        comm = runtime_state.current_communicator()
    from ..schedule import compiler as _sched

    caches = [_resource_cache(comm), _dispatch_memo(comm),
              _sched._plan_cache(comm)]
    touched = [set(), set(), set()]
    if pin:
        # log every cache hit AND insert the warm-up dispatches make, so
        # pinning covers executables that already existed (a key diff
        # against a 'before' snapshot would silently skip those)
        for cache, log in zip(caches, touched):
            cache.log_accesses(log)
    pending = []
    try:
        warmed = _precompile_dispatch(specs, comm, pending)
    finally:
        if pin:
            for cache in caches:
                cache.log_accesses(None)
    # drain so compile time is paid HERE, not inside step 1's first wait
    jax.block_until_ready(pending)
    if pin:
        for cache, log in zip(caches, touched):
            for key in log:
                cache.pin(key)
    return warmed


def _precompile_dispatch(specs, comm, pending) -> int:
    """The spec-by-spec warm-up loop of :func:`precompile` (split out so
    the caller's try/finally owns logging disarm + pinning)."""
    from . import _dispatch as _ns_dispatch

    warmed = 0
    for spec in specs:
        if isinstance(spec, dict) and "layout" in spec:
            flats = [
                jnp.zeros((comm.size, int(n)), spec["dtype"])
                for n in spec["layout"]
            ]
            kw = {}
            if spec.get("wire_dtype") is not None:
                kw["wire_dtype"] = spec["wire_dtype"]
            pending.append(
                _ns_dispatch(
                    spec.get("op", "allreduce"), flats, comm, "fused",
                    spec.get("backend"), **kw,
                )
            )
            warmed += 1
            continue
        if isinstance(spec, dict):
            op = spec["op"]
            shape = tuple(spec["shape"])
            dtype = spec["dtype"]
            backend = spec.get("backend")
            wire = spec.get("wire_dtype")
            root = spec.get("root", 0)
        else:
            op, shape, dtype = spec[0], tuple(spec[1]), spec[2]
            backend = spec[3] if len(spec) > 3 else None
            wire = spec[4] if len(spec) > 4 else None
            root = 0
        if shape and shape[0] != comm.size:
            shape = (comm.size,) + shape
        kw = {}
        if wire is not None and op in _WIRE_OPS:
            kw["wire_dtype"] = wire
        if op in ("broadcast", "reduce"):
            kw["root"] = root
        out = _ns_dispatch(
            op, jnp.zeros(shape, dtype), comm, "sync", backend, **kw
        )
        pending.append(out)
        warmed += 1
    return warmed


# ---------------------------------------------------------------------------
# generator-pinning wrappers (the legacy hierarchical entry points)
# ---------------------------------------------------------------------------


def run_hierarchical_allreduce(
    x, comm: Communicator, impl: str = "ring", staged_intra: str = "ring",
    wire: str = "full",
):
    """Explicit two-level allreduce over a cartesian communicator — the
    reference's hierarchical dispatch (``allreducep2pHierarchicalImpl``,
    ``collectives_cuda.cpp:501-581``). Now a thin wrapper that PINS the
    'hier' (or 'staged') plan generator on the schedule compiler; the
    composition itself lives in ``schedule/lower.py``. ``wire`` is the
    resolved wire format, passed through verbatim (no re-resolution —
    direct callers pin the encoding like the legacy entry point did).

    Requires a cartesian comm with both levels populated; the flat path is
    the right tool otherwise (callers fall back)."""
    x = jnp.asarray(x)
    _check_rank_stacked(x, comm)
    if not (comm.cartesian and comm.has_inter_collective and comm.has_intra_collective):
        raise CollectiveArgumentError(
            "hierarchical allreduce needs a cartesian communicator with "
            "multiple intra groups of size > 1"
        )
    from ..schedule import compiler as _sched

    if impl == "staged":
        generator, eff = "staged", staged_intra
    else:
        generator, eff = "hier", impl
    ep = _sched.compile_collective(
        "allreduce", tuple(x.shape), jnp.result_type(x), comm,
        generator=generator, impl=eff, wire_override=wire,
    )
    return ep.execute(x)


def run_hierarchical_collective(
    op: str, x, comm: Communicator, root: int = 0, ring_impl: str = "ring"
):
    """Two-level composition of broadcast/reduce/allgather on a cartesian
    communicator (``collectives_cuda.cpp:501-581,1057-1141``) — a thin
    wrapper pinning the 'hier' plan generator; ``ring_impl`` selects the
    INTRA-phase transport ('ring' = ppermute, 'pallas' = ICI RDMA), the
    plan's ``impl`` attribute now."""
    x = jnp.asarray(x)
    _check_rank_stacked(x, comm)
    if not (comm.cartesian and comm.has_inter_collective and comm.has_intra_collective):
        raise CollectiveArgumentError(
            "hierarchical collectives need a cartesian communicator with "
            "multiple intra groups of size > 1"
        )
    if op not in ("broadcast", "reduce", "allgather"):
        raise CollectiveArgumentError(
            f"hierarchical collective supports broadcast/reduce/allgather, "
            f"got {op!r}"
        )
    if op in ("broadcast", "reduce") and not 0 <= root < comm.size:
        raise CollectiveArgumentError(f"root {root} out of range")
    from ..schedule import compiler as _sched

    ep = _sched.compile_collective(
        op, tuple(x.shape), jnp.result_type(x), comm,
        root=root, generator="hier", impl=ring_impl, wire_override="full",
    )
    return ep.execute(x)


def run_tree_hierarchical_allreduce(x, comm: Communicator,
                                    wire: str = "full"):
    """Hierarchical allreduce on a NON-cartesian (ragged/tree) communicator
    — the reference's non-cartesian path (``collectives_cuda.cpp:546-581``),
    now a thin wrapper pinning the 'tree' plan generator (binomial
    ppermute schedule + one-hop gather broadcast, ``schedule/lower.py``).
    A compressed ``wire`` encodes every binomial exchange hop."""
    x = jnp.asarray(x)
    _check_rank_stacked(x, comm)
    if not (comm.has_inter_collective and comm.has_intra_collective):
        raise CollectiveArgumentError(
            "hierarchical allreduce needs a communicator with both levels"
        )
    from ..schedule import compiler as _sched

    ep = _sched.compile_collective(
        "allreduce", tuple(x.shape), jnp.result_type(x), comm,
        generator="tree", impl="ring", wire_override=wire,
    )
    return ep.execute(x)


def run_group_broadcast(x, comm: Communicator, root: int = 0):
    """Broadcast within each *intra group* of ``comm`` from the member with
    intra rank ``root`` — the hierarchical building block of mixed
    PS × data-parallel updates (``update.lua:104-112``) and of the
    reference's non-cartesian hierarchical allreduce's final intra
    broadcast (``collectives_cuda.cpp:569-579``).

    Works for cartesian and ragged (tree) communicators alike: the source
    map rank -> group-root is a static permutation, so the op lowers to a
    cross-device gather.
    """
    x = jnp.asarray(x)
    _check_rank_stacked(x, comm)
    cache = _resource_cache(comm)
    key = ("_group_bcast", root, tuple(x.shape), jnp.result_type(x))
    fn = cache.get(key)
    if fn is None:
        groups: dict = {}
        for r in range(comm.size):
            m = comm.member(r)
            groups.setdefault(m.intra_group, {})[m.intra_rank] = r
        src = np.zeros((comm.size,), np.int32)
        for r in range(comm.size):
            g = groups[comm.member(r).intra_group]
            if root not in g:
                raise CollectiveArgumentError(
                    f"intra root {root} out of range for group of size {len(g)}"
                )
            src[r] = g[root]
        sharding = _rank_sharding(comm, x.ndim)
        idx = jnp.asarray(src)
        fn = jax.jit(
            lambda a: jax.lax.with_sharding_constraint(
                jnp.take(a, idx, axis=0), sharding
            )
        )
        cache[key] = fn
    sharding = _rank_sharding(comm, x.ndim)
    if getattr(x, "sharding", None) != sharding:
        x = jax.device_put(x, sharding)
    return fn(x)


def barrier(comm: Communicator) -> None:
    """Device barrier over the communicator (``torch_mpi.cpp:270-280``)."""
    cache = _resource_cache(comm)
    fn = cache.get("_barrier")
    if fn is None:
        mesh = comm.flat_mesh(_AXIS)
        fn = jax.jit(
            jax.shard_map(
                lambda x: prim.barrier_value(_AXIS) + x * 0,
                mesh=mesh,
                in_specs=P(_AXIS),
                out_specs=P(_AXIS),
            )
        )
        cache["_barrier"] = fn
    jax.block_until_ready(fn(jnp.zeros((comm.size,), jnp.int32)))
