"""In-graph collective primitives over a named mesh axis.

These are the building blocks usable directly inside ``jit`` / ``shard_map``
code (the idiomatic TPU path), and the kernels the eager API compiles.

Two families, mirroring the reference's backend split:

- **xla**: single fused XLA collectives (``psum`` / ``all_gather`` /
  ``ppermute``) — the analog of the stock MPI / NCCL paths
  (``lib/collectives.cpp:126-290``, ``lib/collectives_cuda.cpp:871-1161``):
  trust the vendor collective.
- **ring**: explicit chunked ring algorithms written with ``lax.ppermute``
  neighbor exchanges — the TPU-native re-design of the reference's custom
  p2p rings (``lib/detail/collectives.cpp:128-326``,
  ``lib/detail/collectives_cuda.cpp:202-388``): ring reduce-scatter followed
  by ring all-gather, and tree-vs-pipelined broadcast with the 4MB switch
  (``lib/detail/collectives.cpp:27-113``). On TPU, ``ppermute`` lowers to
  ICI neighbor DMA, which is exactly the transport the reference built by
  hand with cudaIPC; a Pallas RDMA variant lives in ``ops/ring_kernels.py``.

All functions take ``axis`` (a mesh axis name) and are shape-polymorphic but
trace-time static, per XLA semantics.
"""

from __future__ import annotations

import math
from functools import partial
from typing import Optional, Sequence, Tuple

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax

# ---------------------------------------------------------------------------
# XLA-backed (stock) collectives
# ---------------------------------------------------------------------------


def allreduce(x, axis: str = "mpi", average: bool = False):
    """Sum-allreduce (reference semantics: sum only, division left to the
    caller — ``lib/detail/collectives.cpp:163-165``, ``torchmpi/nn.lua:40``)."""
    out = lax.psum(x, axis)
    if average:
        out = out / lax.psum(1, axis)
    return out


def broadcast(x, root: int = 0, axis: str = "mpi"):
    """Everyone receives the root's value."""
    idx = lax.axis_index(axis)
    return lax.psum(jnp.where(idx == root, x, jnp.zeros_like(x)), axis)


def reduce(x, root: int = 0, axis: str = "mpi"):
    """Root receives the sum; non-root ranks keep their input (MPI_Reduce
    leaves non-root output undefined; the reference leaves the input tensor
    untouched, which we make the defined behavior)."""
    idx = lax.axis_index(axis)
    total = lax.psum(x, axis)
    return jnp.where(idx == root, total, x)


def allgather(x, axis: str = "mpi", dim: int = -1, tiled: bool = True):
    """Concatenate every rank's tensor along ``dim`` (reference allgather
    concatenates along the last dimension after a size exchange,
    ``lib/collectives.cpp:245-290``; sizes here are static so no exchange)."""
    if dim < 0:
        dim = x.ndim + dim
    return lax.all_gather(x, axis, axis=dim, tiled=tiled)


def sendreceive(x, src: int, dst: int, axis: str = "mpi"):
    """Point-to-point: ``dst`` receives ``src``'s tensor, everyone else keeps
    their own (reference ``sendreceive_TH*Tensor``,
    ``lib/collectives.cpp:204-242``)."""
    recv = lax.ppermute(x, axis, [(src, dst)])
    idx = lax.axis_index(axis)
    return jnp.where(idx == dst, recv, x)


def shift(x, offset: int = 1, axis: str = "mpi", axis_size: Optional[int] = None):
    """Cyclic rotation by ``offset`` positions (building block for rings and
    for sequence-parallel ring attention)."""
    n = axis_size or lax.axis_size(axis)
    perm = [(i, (i + offset) % n) for i in range(n)]
    return lax.ppermute(x, axis, perm)


def reduce_scatter(x, axis: str = "mpi", dim: int = 0, tiled: bool = True):
    return lax.psum_scatter(x, axis, scatter_dimension=dim, tiled=tiled)


def barrier_value(axis: str = "mpi"):
    """A tiny psum whose completion orders all ranks (device barrier)."""
    return lax.psum(jnp.ones((), jnp.int32), axis)


# ---------------------------------------------------------------------------
# Block-quantized wire format (EQuARX-style, arXiv:2506.17615): the
# bandwidth-path rings optionally ship each per-step message as int8 with
# one fp32 scale per block (or as a bf16 cast), summing in an fp32
# accumulator and dequantizing once at the end. Compression lives in the
# collective composition layer (HiCCL's argument, arXiv:2408.05962), not
# in the model: callers opt in via wire_dtype= or the constants default.
# ---------------------------------------------------------------------------

#: wire encodings the rings understand ('full' = ship the dtype verbatim)
WIRE_DTYPES = ("full", "bf16", "int8")

# smallest positive scale: a zero block must not divide by zero, and the
# dequantized zeros stay exactly zero
_SCALE_FLOOR = 1e-30


def quantize_blocks(x, block: int):
    """Quantize a float32 tensor to ``(q_int8, scales_f32, n)``: flattened,
    zero-padded to whole blocks of ``block`` elements, one symmetric scale
    ``amax/127`` per block. Exact for blocks whose values are all equal
    (the tester's closed-form inputs) and for zeros."""
    flat = x.reshape(-1)
    n = flat.shape[0]
    pad = -n % block
    if pad:
        flat = jnp.concatenate([flat, jnp.zeros((pad,), flat.dtype)])
    b = flat.reshape(-1, block)
    scale = jnp.maximum(jnp.max(jnp.abs(b), axis=1, keepdims=True),
                        _SCALE_FLOOR) / 127.0
    q = jnp.round(b / scale).astype(jnp.int8)
    return q, scale.astype(jnp.float32), n


def dequantize_blocks(q, scale, n: int, shape=None):
    """Inverse of :func:`quantize_blocks`; returns f32 of ``shape`` (flat
    length ``n`` when shape is None)."""
    out = (q.astype(jnp.float32) * scale).reshape(-1)[:n]
    return out if shape is None else out.reshape(shape)


def _wire_send_recv(buf, axis, perm, wire: str, block: int):
    """Encode ``buf`` for the wire, one-hop ppermute it, decode. The
    single quantize/transport/dequantize building block every quantized
    ring step uses — RS steps add the result into their f32 partial
    (higher-precision accumulate), AG steps install it verbatim."""
    if wire == "int8":
        q, scale, n = quantize_blocks(buf, block)
        q = lax.ppermute(q, axis, perm)
        scale = lax.ppermute(scale, axis, perm)
        return dequantize_blocks(q, scale, n, buf.shape)
    if wire == "bf16":
        recv = lax.ppermute(buf.astype(jnp.bfloat16), axis, perm)
        return recv.astype(jnp.float32)
    return lax.ppermute(buf, axis, perm)


def wire_encoded_bytes(nelem: int, itemsize: int, wire: str,
                       block: int) -> int:
    """On-wire bytes for ``nelem`` elements under a wire encoding (the
    tracing counters' accounting model: int8 payload padded to whole
    blocks + one f32 scale per block)."""
    if wire == "int8":
        nblocks = -(-max(1, nelem) // block)
        return nblocks * block + nblocks * 4
    if wire == "bf16":
        return nelem * 2
    return nelem * itemsize


def wire_engages(wire: Optional[str], dtype, nelem: int) -> bool:
    """Whether a compressed wire format actually applies: only f32
    payloads (ints/bools pass through uncompressed — exactness is their
    contract) at or above the min-elements cutoff."""
    from .. import constants

    return (
        wire in ("int8", "bf16")
        and jnp.dtype(dtype) == jnp.dtype(jnp.float32)
        and nelem >= constants.get("wire_quant_min_elements")
    )


# ---------------------------------------------------------------------------
# Custom ring algorithms (the reference's p2p path, TPU-native)
# ---------------------------------------------------------------------------


def _flatten_pad(x, p: int):
    """Flatten to 1-D and pad to a multiple of ``p`` chunks."""
    flat = x.reshape(-1)
    n = flat.shape[0]
    chunk = -(-n // p)  # ceil
    pad = chunk * p - n
    if pad:
        flat = jnp.concatenate([flat, jnp.zeros((pad,), flat.dtype)])
    return flat, n, chunk


def _ring_phases(chunks, axis: str, p: int, r, perm, nb: int):
    """Run the reduce-scatter + all-gather ring phases in lockstep over
    ``nb`` independent segments ``chunks[nb, p, chunk]``. Each ring step
    issues ``nb`` independent ppermutes (one per in-flight segment), which
    XLA's scheduler may overlap — the in-flight-buffers semantics of the
    reference's ``kNumBuffersPerCollectiveCPU/GPU`` pipelining
    (``lib/detail/collectives.cpp:128-326``)."""

    def rs_step(s, ch):
        # Send chunk (r - s) mod p rightward; add incoming (r - s - 1) mod p.
        send_idx = (r - s) % p
        recv_idx = (r - s - 1) % p
        outs = []
        for j in range(nb):
            buf = lax.dynamic_index_in_dim(ch[j], send_idx, keepdims=False)
            recv = lax.ppermute(buf, axis, perm)
            upd = lax.dynamic_index_in_dim(ch[j], recv_idx, keepdims=False) + recv
            outs.append(lax.dynamic_update_index_in_dim(ch[j], upd, recv_idx, 0))
        return jnp.stack(outs)

    chunks = lax.fori_loop(0, p - 1, rs_step, chunks)

    def ag_step(s, ch):
        # After reduce-scatter, rank r owns fully-reduced chunk (r + 1) mod p.
        send_idx = (r + 1 - s) % p
        recv_idx = (r - s) % p
        outs = []
        for j in range(nb):
            buf = lax.dynamic_index_in_dim(ch[j], send_idx, keepdims=False)
            recv = lax.ppermute(buf, axis, perm)
            outs.append(lax.dynamic_update_index_in_dim(ch[j], recv, recv_idx, 0))
        return jnp.stack(outs)

    return lax.fori_loop(0, p - 1, ag_step, chunks)


def _ring_phases_wire(chunks, axis: str, p: int, r, perm, wire: str,
                      block: int, nb: int = 1):
    """Reduce-scatter + all-gather ring phases with a compressed wire
    format: every hop encodes its outgoing chunk (int8 + per-block f32
    scales, or a bf16 cast), the RS phase accumulates the DECODED values
    into the f32 partials, and the AG phase forwards reduced chunks the
    same way — re-encoding a just-decoded chunk reproduces the same code
    points, so AG forwarding is lossless up to fp rounding. ``chunks``:
    [nb, p, chunk] f32 — ``nb`` independent pipeline segments whose
    encode / ppermute / decode chains are issued per step like
    :func:`_ring_phases`'s buffers, so XLA's scheduler can overlap
    quantize(k+1) with the DMA of chunk k; same fori_loop step structure
    as :func:`_ring_phases` so the two schedules can be compared line
    for line."""

    def rs_step(s, ch):
        send_idx = (r - s) % p
        recv_idx = (r - s - 1) % p
        outs = []
        for j in range(nb):
            buf = lax.dynamic_index_in_dim(ch[j], send_idx, keepdims=False)
            recv = _wire_send_recv(buf, axis, perm, wire, block)
            upd = lax.dynamic_index_in_dim(ch[j], recv_idx,
                                           keepdims=False) + recv
            outs.append(
                lax.dynamic_update_index_in_dim(ch[j], upd, recv_idx, 0)
            )
        return jnp.stack(outs)

    chunks = lax.fori_loop(0, p - 1, rs_step, chunks)

    def ag_step(s, ch):
        send_idx = (r + 1 - s) % p
        recv_idx = (r - s) % p
        outs = []
        for j in range(nb):
            buf = lax.dynamic_index_in_dim(ch[j], send_idx, keepdims=False)
            recv = _wire_send_recv(buf, axis, perm, wire, block)
            outs.append(
                lax.dynamic_update_index_in_dim(ch[j], recv, recv_idx, 0)
            )
        return jnp.stack(outs)

    return lax.fori_loop(0, p - 1, ag_step, chunks)


def _pipeline_segments(flat, p: int, chunk: int, depth: int,
                       align: int = 1):
    """Reshape a ring-padded flat buffer ``[p * chunk]`` into ``depth``
    interleaved pipeline segments ``[d, p, sub]`` — segment j holds
    sub-span j of EVERY ring chunk, so an element keeps its ring-chunk
    index (= its reduction start rank) and the per-element accumulation
    order is bit-identical to the unpipelined ring. ``align`` (the int8
    quantization block) keeps every sub-span boundary on the block grid,
    so chunked quantization reproduces the unchunked scales exactly.
    Returns ``(segments, d, sub)`` with ``d`` clamped to the spans that
    actually exist."""
    sub = -(-chunk // max(1, depth))
    if align > 1:
        sub = -(-sub // align) * align
    sub = max(1, sub)
    d = max(1, -(-chunk // sub))
    a = flat.reshape(p, chunk)
    pad = d * sub - chunk
    if pad:
        a = jnp.concatenate([a, jnp.zeros((p, pad), a.dtype)], axis=1)
    return jnp.transpose(a.reshape(p, d, sub), (1, 0, 2)), d, sub


def _pipeline_unsegment(segs, p: int, chunk: int):
    """Inverse of :func:`_pipeline_segments`: ``[d, p, sub]`` back to the
    flat ``[p * chunk]`` ring layout (intra-chunk padding dropped)."""
    d, _, sub = segs.shape
    a = jnp.transpose(segs, (1, 0, 2)).reshape(p, d * sub)
    return a[:, :chunk].reshape(-1)


def ring_allreduce(
    x,
    axis: str = "mpi",
    axis_size: Optional[int] = None,
    max_bytes_per_step: Optional[int] = None,
    min_bytes_per_step: Optional[int] = None,
    num_buffers: int = 1,
    wire_dtype: Optional[str] = None,
    wire_block: Optional[int] = None,
    pipeline_depth: int = 1,
):
    """Chunked ring allreduce: (p-1) reduce-scatter steps then (p-1)
    all-gather steps, the schedule memoized by the reference as a "plan"
    (``lib/resources.cpp:582-672``, algorithm doc ``lib/detail/README.md``).

    Receive-centric pull model like the reference: at every step each rank
    combines the chunk arriving from its left neighbor. On TPU each
    ``ppermute`` is a one-hop ICI transfer, so total bytes moved per rank is
    ``2 n (p-1)/p`` — the bus-bandwidth-optimal volume the baseline's
    analytic model assumes.

    Byte-bounded chunking (``lib/constants.cpp:142-150``,
    ``lib/detail/collectives.cpp:139-176``): when the per-step message
    (``n/p`` elements) would exceed ``max_bytes_per_step``, the buffer is cut
    into segments so every ppermute moves at most that many bytes (and at
    least ``min_bytes_per_step`` where possible); ``num_buffers`` segments
    travel the ring concurrently (pipelining depth ≙
    ``kNumBuffersPerCollective``), waves of segments are scanned
    sequentially.

    ``wire_dtype`` ('int8' | 'bf16') selects the compressed wire format
    for f32 payloads above the ``wire_quant_min_elements`` cutoff
    (``wire_block`` elements per scale block; constants default). The
    quantized path keeps f32 accumulation and takes the unsegmented
    route (one chunk per ring step — the encode/decode already bounds
    the per-step wire bytes).

    ``pipeline_depth`` > 1 is the schedule IR's chunk pipeline: the
    payload is split into that many INTERLEAVED segments (sub-span j of
    every ring chunk — block-aligned under a compressed wire), and every
    ring step issues the segments' independent encode / ppermute /
    decode-accumulate chains so quantize(k+1) can overlap send(k) and
    dequantize/reduce(k-1) under recv(k). The interleaving keeps each
    element's ring-chunk index — and therefore its floating-point
    accumulation order and its quantization block grid — identical to
    depth 1: the pipelined result is BITWISE equal to its unpipelined
    twin (tests/test_pipeline.py pins the matrix). On the byte-bounded
    segmented path (``max_bytes_per_step`` exceeded) the depth is
    ignored — ``num_buffers`` already pipelines the waves there.
    """
    p = axis_size or lax.axis_size(axis)
    if p == 1:
        return x
    r = lax.axis_index(axis)
    perm = [(i, (i + 1) % p) for i in range(p)]
    itemsize = jnp.dtype(x.dtype).itemsize
    n = int(np.prod(x.shape)) if x.shape else 1
    chunk = -(-n // p)

    if wire_engages(wire_dtype, x.dtype, n):
        from .. import constants

        block = wire_block or constants.get("wire_quant_block_size")
        flat, n, chunk = _flatten_pad(x, p)
        if pipeline_depth > 1:
            segs, d, _sub = _pipeline_segments(
                flat, p, chunk, pipeline_depth, align=block
            )
            if d > 1:
                out = _ring_phases_wire(
                    segs, axis, p, r, perm, wire_dtype, block, nb=d
                )
                return _pipeline_unsegment(out, p, chunk)[:n].reshape(
                    x.shape
                )
        out = _ring_phases_wire(
            flat.reshape(1, p, chunk), axis, p, r, perm, wire_dtype, block
        )
        return _pipeline_unsegment(out, p, chunk)[:n].reshape(x.shape)

    if max_bytes_per_step is None or chunk * itemsize <= max_bytes_per_step:
        flat, n, chunk = _flatten_pad(x, p)
        if pipeline_depth > 1:
            segs, d, _sub = _pipeline_segments(flat, p, chunk,
                                               pipeline_depth)
            if d > 1:
                out = _ring_phases(segs, axis, p, r, perm, d)
                return _pipeline_unsegment(out, p, chunk)[:n].reshape(
                    x.shape
                )
        chunks = _ring_phases(flat.reshape(1, p, chunk), axis, p, r, perm, 1)
        return chunks.reshape(-1)[:n].reshape(x.shape)

    # Segmented path: per-step message size in [min, max] bytes.
    seg_chunk = max(1, int(max_bytes_per_step) // itemsize)
    if min_bytes_per_step:
        floor = -(-int(min_bytes_per_step) // itemsize)
        seg_chunk = max(seg_chunk, min(chunk, floor))
    seg = seg_chunk * p
    nseg = -(-n // seg)
    nb = max(1, min(int(num_buffers), nseg))
    nwave = -(-nseg // nb)
    total = nwave * nb * seg
    flat = x.reshape(-1)
    if total > n:
        flat = jnp.concatenate([flat, jnp.zeros((total - n,), flat.dtype)])
    waves = flat.reshape(nwave, nb, p, seg_chunk)

    def run_wave(carry, wave):
        return carry, _ring_phases(wave, axis, p, r, perm, nb)

    _, out = lax.scan(run_wave, 0, waves)
    return out.reshape(-1)[:n].reshape(x.shape)


def ring_broadcast(
    x,
    root: int = 0,
    axis: str = "mpi",
    axis_size: Optional[int] = None,
    num_chunks: Optional[int] = None,
):
    """Pipelined chunked ring broadcast (the reference's large-message path,
    ``lib/detail/collectives.cpp:58-113``): the buffer is cut into chunks
    that flow around the ring, so steady-state bandwidth is one full buffer
    regardless of p. ``num_chunks`` defaults to p (plan-style)."""
    p = axis_size or lax.axis_size(axis)
    if p == 1:
        return x
    k = num_chunks or p
    flat, n, chunk = _flatten_pad(x, k)
    chunks = flat.reshape(k, chunk)
    r = lax.axis_index(axis)
    d = (r - root) % p  # distance downstream from root
    perm = [(i, (i + 1) % p) for i in range(p)]

    def step(t, ch):
        # At step t a rank at distance d forwards chunk (t - d), which it
        # received at step t-1; its left neighbor (distance d-1) is sending
        # chunk (t - d + 1), so that is what arrives this step. Chunk c thus
        # reaches distance d at step c + d - 1, giving k + p - 2 total steps.
        send_idx = jnp.clip(t - d, 0, k - 1)
        buf = lax.dynamic_index_in_dim(ch, send_idx, keepdims=False)
        recv = lax.ppermute(buf, axis, perm)
        recv_idx = t - d + 1
        valid = (d > 0) & (recv_idx >= 0) & (recv_idx < k)
        rclip = jnp.clip(recv_idx, 0, k - 1)
        cur = lax.dynamic_index_in_dim(ch, rclip, keepdims=False)
        return lax.dynamic_update_index_in_dim(
            ch, jnp.where(valid, recv, cur), rclip, 0
        )

    chunks = lax.fori_loop(0, k + p - 2, step, chunks)
    return chunks.reshape(-1)[:n].reshape(x.shape)


def tree_broadcast(x, root: int = 0, axis: str = "mpi", axis_size: Optional[int] = None):
    """Binomial-tree (recursive doubling) broadcast — the reference's
    small/medium-message path (``lib/detail/collectives.cpp:27-56``):
    log2(p) steps, each doubling the set of ranks that hold the data."""
    p = axis_size or lax.axis_size(axis)
    if p == 1:
        return x
    r = lax.axis_index(axis)
    d = (r - root) % p  # tree is rooted at distance 0
    steps = max(1, math.ceil(math.log2(p)))
    for k in range(steps):
        span = 1 << k
        perm = []
        for i in range(p):
            di = (i - root) % p
            if di < span and di + span < p:
                perm.append((i, (i + span) % p))
        if not perm:
            break
        recv = lax.ppermute(x, axis, perm)
        receives = (d >= span) & (d < 2 * span)
        x = jnp.where(receives, recv, x)
    return x


def ring_reduce(
    x,
    root: int = 0,
    axis: str = "mpi",
    axis_size: Optional[int] = None,
    max_bytes_per_step: Optional[int] = None,
    min_bytes_per_step: Optional[int] = None,
    num_buffers: int = 1,
    wire_dtype: Optional[str] = None,
):
    """Reduce-to-root as ring reduce-scatter + gather-to-root; implemented as
    ring_allreduce masked to root (the reference reduces via the same plan)."""
    total = ring_allreduce(
        x,
        axis=axis,
        axis_size=axis_size,
        max_bytes_per_step=max_bytes_per_step,
        min_bytes_per_step=min_bytes_per_step,
        num_buffers=num_buffers,
        wire_dtype=wire_dtype,
    )
    idx = lax.axis_index(axis)
    return jnp.where(idx == root, total, x)


def ring_reduce_scatter(
    x, axis: str = "mpi", dim: int = -1, axis_size: Optional[int] = None,
    wire_dtype: Optional[str] = None, wire_block: Optional[int] = None,
):
    """Reduce-scatter over ``dim`` as the (p-1)-step reduce-scatter phase of
    the ring (``lib/detail/collectives.cpp:128-326``'s first half, standalone):
    rank r returns slice r of the summed tensor (``lax.psum_scatter`` tiled
    semantics). ``x.shape[dim]`` must be divisible by the axis size.
    ``wire_dtype`` selects the compressed wire format for f32 payloads
    (same contract as :func:`ring_allreduce`): each hop's partial slice is
    encoded on send and the f32 partial accumulates the decoded values."""
    p = axis_size or lax.axis_size(axis)
    if dim < 0:
        dim = x.ndim + dim
    if p == 1:
        return x
    if x.shape[dim] % p != 0:
        raise ValueError(
            f"reduce_scatter dim {dim} ({x.shape[dim]}) must be divisible "
            f"by the axis size ({p})"
        )
    r = lax.axis_index(axis)
    perm = [(i, (i + 1) % p) for i in range(p)]
    moved = jnp.moveaxis(x, dim, 0)  # [d, ...]
    ch = moved.reshape((p, moved.shape[0] // p) + moved.shape[1:])
    nelem = int(np.prod(x.shape)) if x.shape else 1
    wire = None
    if wire_engages(wire_dtype, x.dtype, nelem):
        from .. import constants

        wire = wire_dtype
        block = wire_block or constants.get("wire_quant_block_size")
        ch = ch.astype(jnp.float32)

    def rs_step(s, ch):
        # schedule shifted one slot vs the allreduce RS phase so rank r
        # finishes owning slice r (not (r+1) mod p): at step s it sends
        # partial slice (r-s-1) and folds the incoming (r-s-2)
        send_idx = (r - s - 1) % p
        recv_idx = (r - s - 2) % p
        buf = lax.dynamic_index_in_dim(ch, send_idx, keepdims=False)
        if wire:
            recv = _wire_send_recv(buf, axis, perm, wire, block)
        else:
            recv = lax.ppermute(buf, axis, perm)
        upd = lax.dynamic_index_in_dim(ch, recv_idx, keepdims=False) + recv
        return lax.dynamic_update_index_in_dim(ch, upd, recv_idx, 0)

    ch = lax.fori_loop(0, p - 1, rs_step, ch)
    mine = lax.dynamic_index_in_dim(ch, r, keepdims=False)  # [d/p, ...]
    return jnp.moveaxis(mine, 0, dim).astype(x.dtype)


def alltoall(x, axis: str = "mpi", split_dim: int = 0, concat_dim: int = 0):
    """Fused XLA all-to-all: ``x``'s ``split_dim`` (length p) is scattered,
    one block per rank, and the received blocks are stacked along
    ``concat_dim`` — block j of the output came from rank j."""
    return lax.all_to_all(
        x, axis, split_axis=split_dim, concat_axis=concat_dim, tiled=True
    )


def ring_alltoall(x, axis: str = "mpi", axis_size: Optional[int] = None):
    """All-to-all as p-1 pairwise exchanges (one ``ppermute`` per relative
    offset — the custom-p2p decomposition; the reference's alltoall-shaped
    traffic is its PS shard fan-out, ``lib/parameterserver.cpp:309-353``).
    ``x``: [p, ...] where block s is this rank's payload for rank s; returns
    [p, ...] where block j came from rank j."""
    p = axis_size or lax.axis_size(axis)
    if p == 1:
        return x
    r = lax.axis_index(axis)
    own = lax.dynamic_index_in_dim(x, r, keepdims=False)
    out = jnp.zeros_like(x)
    out = lax.dynamic_update_index_in_dim(out, own, r, 0)
    for k in range(1, p):
        # every rank i sends its block for rank (i+k) directly; what
        # arrives came from rank (r-k)
        perm = [(i, (i + k) % p) for i in range(p)]
        buf = lax.dynamic_index_in_dim(x, (r + k) % p, keepdims=False)
        recv = lax.ppermute(buf, axis, perm)
        out = lax.dynamic_update_index_in_dim(out, recv, (r - k) % p, 0)
    return out


def ring_allgather(x, axis: str = "mpi", dim: int = -1, axis_size: Optional[int] = None):
    """All-gather as p-1 ring forwarding steps (same plan as the allgather
    phase of the ring allreduce)."""
    p = axis_size or lax.axis_size(axis)
    if dim < 0:
        dim = x.ndim + dim
    if p == 1:
        return x
    r = lax.axis_index(axis)
    perm = [(i, (i + 1) % p) for i in range(p)]
    # Accumulate into a leading rank dimension, then reassemble along dim.
    out = jnp.zeros((p,) + x.shape, x.dtype)
    out = lax.dynamic_update_index_in_dim(out, x, r, 0)

    def step(s, carry):
        buf, out = carry
        recv = lax.ppermute(buf, axis, perm)
        src = (r - s - 1) % p
        out = lax.dynamic_update_index_in_dim(out, recv, src, 0)
        return recv, out

    _, out = lax.fori_loop(0, p - 1, step, (x, out))
    # [p, ...] -> concatenate blocks along `dim`.
    moved = jnp.moveaxis(out, 0, dim)  # [..., p, dim_size, ...]
    new_shape = x.shape[:dim] + (p * x.shape[dim],) + x.shape[dim + 1 :]
    return moved.reshape(new_shape)
