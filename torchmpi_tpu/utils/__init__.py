from .data import (
    DistributedIterator,
    load_mnist_idx,
    synthetic_imagenet,
    synthetic_mnist,
    synthetic_tokens,
)
from .tracing import ProfilerWindow, Timer, set_debug_level, vlog

__all__ = [
    "DistributedIterator",
    "synthetic_mnist",
    "synthetic_imagenet",
    "synthetic_tokens",
    "load_mnist_idx",
    "ProfilerWindow",
    "Timer",
    "vlog",
    "set_debug_level",
]
