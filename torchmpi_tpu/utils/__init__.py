from .data import DistributedIterator, load_mnist_idx, synthetic_mnist

__all__ = ["DistributedIterator", "synthetic_mnist", "load_mnist_idx"]
