"""Tracing / profiling / logging utilities.

Reference analogs (SURVEY.md §5):

- nvprof window between fixed steps (``sgdengine.lua:38-63``, ``wrap.sh``
  NVPROF=1) → :class:`ProfilerWindow` around ``jax.profiler`` traces (the
  engine wires this via ``profile_dir``/``profile_window``).
- ``VLOG_1/VLOG_2`` compile-time debug macros with thread ids
  (``resources.h:43-53``) → :func:`vlog` gated by the
  ``TORCHMPI_TPU_DEBUG`` env var (0/1/2).
- per-rank log redirection ``LOG_TO_FILE=1`` → ``/tmp/mpi_<rank>``
  (``wrap.sh:70-77``) → :func:`redirect_logs_per_process`.
- ``torch.Timer`` benchmark timing (``tester.lua``) → :class:`Timer`.
- logical-vs-on-wire byte accounting for the compressed wire formats
  (``wire_dtype``) → :data:`wire_stats` (no reference analog: the 2017
  reference shipped full-precision bytes only).
"""

from __future__ import annotations

import contextlib
import os
import sys
import threading
import time
from ..analysis import lockmon as _lockmon
from pathlib import Path
from typing import Dict, Optional, Tuple

_DEBUG_LEVEL = int(os.environ.get("TORCHMPI_TPU_DEBUG", "0") or 0)


def debug_level() -> int:
    return _DEBUG_LEVEL


def set_debug_level(level: int) -> None:
    global _DEBUG_LEVEL
    _DEBUG_LEVEL = int(level)


def vlog(level: int, msg: str) -> None:
    """VLOG-style leveled debug logging with thread id (resources.h:43-53)."""
    if _DEBUG_LEVEL >= level:
        tid = threading.get_ident() & 0xFFFF
        print(f"[tm:{level}][t{tid:04x}] {msg}", file=sys.stderr, flush=True)


class Timer:
    """torch.Timer-alike: lap timing for benchmark loops."""

    def __init__(self):
        self.reset()

    def reset(self) -> None:
        self._t0 = time.perf_counter()

    def time(self) -> float:
        return time.perf_counter() - self._t0


class ProfilerWindow:
    """Open a jax.profiler trace for steps [begin, end) — the engine's
    nvprof-window analog, usable standalone:

        win = ProfilerWindow('/tmp/trace', 3, 8)
        try:
            for step in ...:
                win.step(step)   # starts/stops the trace at the boundaries
        finally:
            win.close()          # loops shorter than the window, and
                                 # exception exits, must still stop it
    """

    def __init__(self, log_dir: str, begin: int = 3, end: int = 8):
        begin, end = int(begin), int(end)
        if begin < 0 or end <= begin:
            # a [begin, end) window with end <= begin would start a trace
            # it stops one step late (or never, if the loop ends first)
            raise ValueError(
                f"profiler window must satisfy 0 <= begin < end, got "
                f"[{begin}, {end})"
            )
        self.log_dir = log_dir
        self.begin = begin
        self.end = end
        self._active = False

    @property
    def active(self) -> bool:
        """Whether a trace is currently open (callers that can name the
        in-flight arrays should block on them before the stopping
        ``step``/``close`` so async dispatch tails land in the trace)."""
        return self._active

    def step(self, step: int) -> None:
        import jax

        if step == self.begin and not self._active:
            jax.profiler.start_trace(self.log_dir)
            self._active = True
        elif step >= self.end and self._active:
            jax.profiler.stop_trace()
            self._active = False

    def close(self) -> None:
        if self._active:
            import jax

            jax.profiler.stop_trace()
            self._active = False


def redirect_logs_per_process(directory: str = "/tmp", prefix: str = "tm_") -> Path:
    """Redirect this process's stdout/stderr to ``<dir>/<prefix><rank>``
    (wrap.sh LOG_TO_FILE analog). Returns the log path."""
    import jax

    rank = jax.process_index()
    path = Path(directory) / f"{prefix}{rank}"
    f = open(path, "a", buffering=1)
    os.dup2(f.fileno(), sys.stdout.fileno())
    os.dup2(f.fileno(), sys.stderr.fileno())
    return path


@contextlib.contextmanager
def annotate(name: str):
    """Named trace annotation (shows up in the profiler timeline)."""
    import jax

    with jax.profiler.TraceAnnotation(name):
        yield


class WireByteCounters:
    """Logical-vs-on-wire byte accounting for the bandwidth-path
    collectives: every eager dispatch through a ring backend records its
    per-rank payload bytes (``logical``) and the bytes its wire encoding
    actually puts on each hop (``wire`` — int8 values padded to whole
    blocks plus one f32 scale per block; bf16 = half; full = identity).
    ``compression_ratio()`` is the observable the wire-format autotuner
    and the acceptance tests read. Thread-safe; counts accumulate until
    :meth:`reset`.

    Accounting model, not a packet capture: bytes are computed from the
    static encoding at dispatch time (compiled executables are cached, so
    in-graph instrumentation would count once per compile, not per call).
    """

    def __init__(self):
        self._lock = _lockmon.make_lock(
            "tracing.py:WireByteCounters._lock"
        )
        self.reset()

    def reset(self) -> None:
        with self._lock:
            self.calls = 0
            self.logical_bytes = 0
            self.wire_bytes = 0
            # (op, wire_format) -> [calls, logical, wire]
            self.by_format: Dict[Tuple[str, str], list] = {}

    def record(self, op: str, wire_format: str, logical: int,
               wire: int) -> None:
        with self._lock:
            self.calls += 1
            self.logical_bytes += int(logical)
            self.wire_bytes += int(wire)
            ent = self.by_format.setdefault((op, wire_format), [0, 0, 0])
            ent[0] += 1
            ent[1] += int(logical)
            ent[2] += int(wire)

    def compression_ratio(self) -> float:
        """logical/wire over everything recorded (1.0 when nothing is)."""
        with self._lock:
            if not self.wire_bytes:
                return 1.0
            return self.logical_bytes / self.wire_bytes

    def snapshot(self) -> dict:
        with self._lock:
            return {
                "calls": self.calls,
                "logical_bytes": self.logical_bytes,
                "wire_bytes": self.wire_bytes,
                "compression_ratio": (
                    self.logical_bytes / self.wire_bytes
                    if self.wire_bytes
                    else 1.0
                ),
                "by_format": {
                    f"{op}:{fmt}": tuple(v)
                    for (op, fmt), v in self.by_format.items()
                },
            }


#: process-global wire-format byte counters (see :class:`WireByteCounters`)
wire_stats = WireByteCounters()
