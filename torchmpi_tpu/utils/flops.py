"""Analytic FLOP models for the benchmark workloads.

The reference grounds every reported number in an analytic model (its
collectives tester converts measured time to bus GB/s with an algorithm
bandwidth formula, ``test/collectives_all.lua:313-318``). This module does
the same for compute: walk the model architectures layer by layer, count
multiply-accumulate FLOPs, and convert a measured samples/sec into achieved
FLOP/s and model-FLOPs-utilization (MFU) against the chip's peak.

Conventions (stated so the numbers are auditable):
- 1 MAC = 2 FLOPs (multiply + add), the standard accounting.
- Training step = 3x forward FLOPs (backward ~= 2x forward: one pass for
  input grads, one for weight grads). Elementwise ops (relu, batchnorm,
  pooling, softmax) are ignored — they are <1% of conv/dense FLOPs and are
  VPU work, not MXU work, so including them would overstate MFU.
- Peaks are per-chip dense bf16 from Google's published specs. MFU is
  reported as ``None`` when the device kind is unknown (e.g. CPU) rather
  than guessed.
"""

from __future__ import annotations

import math
from typing import Optional


def conv2d_flops(h: int, w: int, cin: int, cout: int, kh: int, kw: int,
                 stride: int = 1) -> tuple[int, int, int]:
    """FLOPs of a SAME-padded conv; returns (flops, h_out, w_out)."""
    ho, wo = math.ceil(h / stride), math.ceil(w / stride)
    return 2 * kh * kw * cin * cout * ho * wo, ho, wo


def dense_flops(cin: int, cout: int) -> int:
    return 2 * cin * cout


def lenet_forward_flops(image: int = 28) -> int:
    """Per-sample forward FLOPs of ``models.mnist.LeNet`` (28x28x1 input)."""
    f1, h, w = conv2d_flops(image, image, 1, 32, 5, 5)
    h, w = h // 2, w // 2  # max_pool 2x2 s2
    f2, h, w = conv2d_flops(h, w, 32, 64, 5, 5)
    h, w = h // 2, w // 2
    f3 = dense_flops(h * w * 64, 256)
    f4 = dense_flops(256, 10)
    return f1 + f2 + f3 + f4


def resnet_forward_flops(image: int = 224, stage_sizes=(3, 4, 6, 3),
                         bottleneck: bool = True, num_classes: int = 1000,
                         num_filters: int = 64) -> int:
    """Per-sample forward FLOPs of ``models.resnet.ResNet`` (NHWC input).

    Mirrors the module walk in ``models/resnet.py`` exactly: 7x7/2 stem,
    3x3/2 max-pool, then bottleneck (1x1 -> 3x3 -> 1x1, x4 expansion) or
    basic (3x3 -> 3x3) stages with stride-2 at each stage entry (v1.5:
    stride on the 3x3) and a 1x1 projection whenever shapes change.
    For 224px ResNet-50 this yields ~8.2 GFLOP forward (= the commonly
    cited ~4.1 GMACs at 2 FLOPs/MAC).
    """
    total, h, w = 0, image, image
    f, h, w = conv2d_flops(h, w, 3, num_filters, 7, 7, stride=2)
    total += f
    h, w = math.ceil(h / 2), math.ceil(w / 2)  # max_pool 3x3 s2 SAME
    cin = num_filters
    for i, count in enumerate(stage_sizes):
        feats = num_filters * 2 ** i
        cout = feats * 4 if bottleneck else feats
        for j in range(count):
            stride = 2 if (i > 0 and j == 0) else 1
            if bottleneck:
                f1, _, _ = conv2d_flops(h, w, cin, feats, 1, 1)
                f2, h2, w2 = conv2d_flops(h, w, feats, feats, 3, 3, stride)
                f3, _, _ = conv2d_flops(h2, w2, feats, cout, 1, 1)
                total += f1 + f2 + f3
            else:
                f2, h2, w2 = conv2d_flops(h, w, cin, feats, 3, 3, stride)
                f3, _, _ = conv2d_flops(h2, w2, feats, feats, 3, 3)
                total += f2 + f3
            if cin != cout or stride != 1:
                fp, _, _ = conv2d_flops(h, w, cin, cout, 1, 1, stride)
                total += fp
            h, w, cin = h2, w2, cout
    total += dense_flops(cin, num_classes)
    return total


def transformer_forward_flops(seq: int, d_model: int, num_layers: int,
                              num_heads: int, head_dim: int, vocab: int,
                              mlp_ratio: int = 4) -> int:
    """Per-SEQUENCE forward FLOPs of ``models.LongContextTransformer``.

    Counts the matmuls as executed: the attention kernels compute the full
    T x T score/value products and mask afterwards (streaming-softmax ring
    blocks do the same per block pair), so causal masking does NOT halve
    the counted FLOPs — masked MACs still run on the MXU. Embedding lookup
    (a gather) is free; the vocabulary head is not. Divide by ``seq`` for
    per-token FLOPs (the LM bench reports tokens/sec)."""
    attn_dim = num_heads * head_dim
    per_layer = (
        dense_flops(d_model, 3 * attn_dim) * seq          # qkv projection
        + 2 * seq * seq * attn_dim                        # q @ k^T
        + 2 * seq * seq * attn_dim                        # softmax @ v
        + dense_flops(attn_dim, d_model) * seq            # output proj
        + dense_flops(d_model, mlp_ratio * d_model) * seq # mlp up
        + dense_flops(mlp_ratio * d_model, d_model) * seq # mlp down
    )
    head = dense_flops(d_model, vocab) * seq
    return num_layers * per_layer + head


def train_flops(forward_flops: int) -> int:
    """Forward + backward (~2x forward) per-sample training FLOPs."""
    return 3 * forward_flops


# Per-chip dense peak FLOP/s (bf16 unless noted), from published TPU specs.
# Keys are matched as substrings of jax's ``device.device_kind``.
_TPU_PEAK_BF16 = (
    ("v6", 918e12),     # Trillium / v6e
    ("v5p", 459e12),
    ("v5", 197e12),     # v5e / "TPU v5 lite" (checked after v5p)
    ("v4", 275e12),
    ("v3", 123e12),
    ("v2", 46e12),
)


def device_peak_flops(device) -> Optional[float]:
    """Per-chip bf16 peak for a jax device, or None if unknown."""
    kind = getattr(device, "device_kind", "") or ""
    kind = kind.lower()
    if "tpu" not in kind and getattr(device, "platform", "") != "tpu":
        return None
    for tag, peak in _TPU_PEAK_BF16:
        if tag in kind:
            return peak
    return None


def mfu(samples_per_sec_per_chip: float, flops_per_sample: int,
        device) -> tuple[float, Optional[float]]:
    """(achieved FLOP/s per chip, fraction-of-peak or None)."""
    achieved = samples_per_sec_per_chip * flops_per_sample
    peak = device_peak_flops(device)
    return achieved, (achieved / peak if peak else None)
