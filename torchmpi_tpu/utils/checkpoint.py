"""Distributed checkpoint / resume.

The reference has **no** checkpointing (SURVEY.md §5: users relied on
``torch.save``; nothing distributed-aware exists) — this is a deliberate
capability addition for the TPU rebuild: engine state (params, optimizer
state, mutable model state, step counters) and parameter-server centers are
saved via Orbax, which handles sharded arrays and multi-host coordination
natively.
"""

from __future__ import annotations

import json
from pathlib import Path
from typing import Any, Dict, Optional

import jax
import numpy as np


def _ckptr():
    import orbax.checkpoint as ocp

    return ocp.PyTreeCheckpointer()


def _engine_state(engine) -> Dict[str, Any]:
    state = {"params": engine.params, "opt_state": engine.opt_state}
    if engine.model_state is not None:
        state["model_state"] = engine.model_state
    return state


def save_engine(path, engine, step: int = 0, extra: Optional[Dict] = None) -> None:
    """Save an AllReduceSGDEngine's full training state.

    Multi-process (multi-controller) runs hand the LIVE jax arrays to
    Orbax — sharded/non-addressable arrays (fsdp over processes) are
    written cooperatively by all hosts; ``jax.device_get`` would raise on
    them. Single-process saves go through host numpy (robust for typed
    optax nodes and independent of live placement).
    """
    path = Path(path).resolve()
    path.mkdir(parents=True, exist_ok=True)
    if jax.process_count() > 1:
        state = _engine_state(engine)
    else:
        state = jax.tree_util.tree_map(
            lambda a: jax.device_get(a), _engine_state(engine)
        )
    _ckptr().save(path / "state", state, force=True)
    if jax.process_index() == 0:
        meta = {"step": int(step), "mode": engine.mode, **(extra or {})}
        (path / "meta.json").write_text(json.dumps(meta))


def restore_engine(path, engine) -> Dict[str, Any]:
    """Restore state saved by :func:`save_engine` into the engine. Device
    placement follows each live leaf's CURRENT sharding — replicated
    engines restore replicated, fsdp engines restore sharded (densifying
    to replicated would silently drop ZeRO-3 and force a recompile).
    Returns the meta dict (incl. ``step``).

    The engine's current state is passed as the restore template so typed
    pytree nodes (optax namedtuple states like ScaleByAdamState) come back
    with their original structure instead of plain lists/dicts."""
    path = Path(path).resolve()
    live = _engine_state(engine)
    if jax.process_count() > 1:
        # cooperative multi-host restore straight into the live shardings
        import orbax.checkpoint as ocp

        restore_args = ocp.checkpoint_utils.construct_restore_args(live)
        state = _ckptr().restore(
            path / "state", item=live, restore_args=restore_args
        )
    else:
        template = jax.tree_util.tree_map(lambda a: jax.device_get(a), live)
        restored = _ckptr().restore(path / "state", item=template)
        state = jax.tree_util.tree_map(
            lambda cur, new: jax.device_put(new, cur.sharding), live, restored
        )

    engine.params = state["params"]
    engine.opt_state = state["opt_state"]
    if "model_state" in state and engine.model_state is not None:
        engine.model_state = state["model_state"]
    return json.loads((path / "meta.json").read_text())


def save_parameter_servers(path, ps_group) -> None:
    """Save a PSGroup's center values (assembled from shards)."""
    path = Path(path).resolve()
    path.mkdir(parents=True, exist_ok=True)
    centers = [srv.receive().wait() for srv in ps_group.servers]
    _ckptr().save(path / "ps_centers", {"centers": centers}, force=True)


def restore_parameter_servers(path, ps_group) -> None:
    """Restore PS centers: each server's shards are overwritten via the
    'copy' rule (a collective in the reference; here applied per shard)."""
    path = Path(path).resolve()
    state = _ckptr().restore(path / "ps_centers")
    for srv, center in zip(ps_group.servers, state["centers"]):
        srv.send(np.asarray(center), rule="copy").wait()
