"""Distributed checkpoint / resume.

The reference has **no** checkpointing (SURVEY.md §5: users relied on
``torch.save``; nothing distributed-aware exists) — this is a deliberate
capability addition for the TPU rebuild: engine state (params, optimizer
state, mutable model state, step counters) and parameter-server centers are
saved via Orbax, which handles sharded arrays and multi-host coordination
natively.
"""

from __future__ import annotations

import json
from pathlib import Path
from typing import Any, Dict, Optional

import jax
import numpy as np


def _ckptr():
    import orbax.checkpoint as ocp

    return ocp.PyTreeCheckpointer()


def save_engine(path, engine, step: int = 0, extra: Optional[Dict] = None) -> None:
    """Save an AllReduceSGDEngine's full training state."""
    path = Path(path).resolve()
    path.mkdir(parents=True, exist_ok=True)
    state = {
        "params": jax.device_get(engine.params),
        "opt_state": jax.device_get(engine.opt_state),
    }
    if engine.model_state is not None:
        state["model_state"] = jax.device_get(engine.model_state)
    _ckptr().save(path / "state", state, force=True)
    meta = {"step": int(step), "mode": engine.mode, **(extra or {})}
    (path / "meta.json").write_text(json.dumps(meta))


def restore_engine(path, engine) -> Dict[str, Any]:
    """Restore state saved by :func:`save_engine` into the engine (device
    placement follows the engine's replicated sharding). Returns the meta
    dict (incl. ``step``).

    The engine's current state is passed as the restore template so typed
    pytree nodes (optax namedtuple states like ScaleByAdamState) come back
    with their original structure instead of plain lists/dicts."""
    path = Path(path).resolve()
    template = {
        "params": jax.device_get(engine.params),
        "opt_state": jax.device_get(engine.opt_state),
    }
    if engine.model_state is not None:
        template["model_state"] = jax.device_get(engine.model_state)
    state = _ckptr().restore(path / "state", item=template)
    engine.params = jax.device_put(state["params"], engine.replicated)
    engine.opt_state = jax.device_put(state["opt_state"], engine.replicated)
    if "model_state" in state and engine.model_state is not None:
        engine.model_state = jax.device_put(
            state["model_state"], engine.replicated
        )
    return json.loads((path / "meta.json").read_text())


def save_parameter_servers(path, ps_group) -> None:
    """Save a PSGroup's center values (assembled from shards)."""
    path = Path(path).resolve()
    path.mkdir(parents=True, exist_ok=True)
    centers = [srv.receive().wait() for srv in ps_group.servers]
    _ckptr().save(path / "ps_centers", {"centers": centers}, force=True)


def restore_parameter_servers(path, ps_group) -> None:
    """Restore PS centers: each server's shards are overwritten via the
    'copy' rule (a collective in the reference; here applied per shard)."""
    path = Path(path).resolve()
    state = _ckptr().restore(path / "ps_centers")
    for srv, center in zip(ps_group.servers, state["centers"]):
        srv.send(np.asarray(center), rule="copy").wait()
