"""Distributed checkpoint / resume.

The reference has **no** checkpointing (SURVEY.md §5: users relied on
``torch.save``; nothing distributed-aware exists) — this is a deliberate
capability addition for the TPU rebuild: engine state (params, optimizer
state, mutable model state, step counters) and parameter-server centers are
saved via Orbax, which handles sharded arrays and multi-host coordination
natively.

Two formats live here:

- the **orbax** format (:func:`save_engine`/:func:`restore_engine`):
  cooperative multi-host saves of live (possibly non-addressable) arrays.
  Layout metadata (world size, sharding, step, structure fingerprint) is
  stamped in an atomically-written ``meta.json`` header, and restore
  validates it up front — a mismatched world/sharding fails loudly with
  the mismatch *named* instead of shape-erroring mid-load.
- the **portable sharded** format (:func:`save_engine_sharded` /
  :func:`restore_engine_sharded` / :func:`reshape_sharded`): one plain
  ``.npy`` file per (leaf, shard rank) under a contiguous
  :class:`~..reshard.Layout`, published via an atomic ``CURRENT``
  pointer (write temp dir + fsync + rename — a save killed at ANY point
  leaves the previous checkpoint intact). Because shards are files, an
  N-way checkpoint reshapes onto an M-way world **offline** with bounded
  memory (mmap'd reads through the reshard executor's chunked scratch;
  ``python -m torchmpi_tpu.reshard``) or transparently at restore time.
"""

from __future__ import annotations

import hashlib
import json
import os
import secrets
from pathlib import Path
from typing import Any, Dict, List, Optional

import jax
import numpy as np

SHARDED_FORMAT = "tmsc1"


def _ckptr():
    import orbax.checkpoint as ocp

    return ocp.PyTreeCheckpointer()


def _engine_state(engine) -> Dict[str, Any]:
    state = {"params": engine.params, "opt_state": engine.opt_state}
    if engine.model_state is not None:
        state["model_state"] = engine.model_state
    return state


class CheckpointMismatchError(RuntimeError):
    """A checkpoint's layout header disagrees with the restore target.

    Raised BEFORE any state is touched, naming the mismatched field —
    the alternative is a shape error halfway through an orbax load with
    half the engine already overwritten."""


def _fsync_file(path: Path) -> None:
    fd = os.open(path, os.O_RDONLY)
    try:
        os.fsync(fd)
    finally:
        os.close(fd)


def _atomic_write_text(path: Path, text: str) -> None:
    """temp + fsync + rename: readers see the old bytes or the new bytes,
    never a torn file — and a crash mid-write leaves the old file."""
    tmp = path.with_name(f"{path.name}.tmp-{os.getpid()}")
    tmp.write_text(text)
    _fsync_file(tmp)
    os.replace(tmp, path)
    try:  # land the rename itself before callers rely on it
        dirfd = os.open(path.parent, os.O_RDONLY)
        os.fsync(dirfd)
        os.close(dirfd)
    except OSError:
        pass


def _tree_fingerprint(state: Dict[str, Any]) -> str:
    """Structure fingerprint: tree shape + per-leaf (path, shape, dtype).
    Two engines with the same fingerprint can exchange checkpoints; a
    mismatch names exactly what diverged (model width, optimizer kind)."""
    leaves, _ = jax.tree_util.tree_flatten_with_path(state)
    desc = [
        (jax.tree_util.keystr(p), tuple(np.shape(a)),
         np.dtype(getattr(a, "dtype", None) or np.asarray(a).dtype).str)
        for p, a in leaves
    ]
    return hashlib.sha1(repr(desc).encode()).hexdigest()[:12]


def _layout_meta(engine, step: int, extra: Optional[Dict],
                 state: Optional[Dict[str, Any]] = None) -> Dict[str, Any]:
    return {
        "step": int(step),
        "mode": engine.mode,
        "world": int(engine.comm.size),
        "sharding": engine.param_sharding,
        "fingerprint": _tree_fingerprint(
            _engine_state(engine) if state is None else state
        ),
        **(extra or {}),
    }


def _check_layout(meta: Dict[str, Any], engine, path,
                  allow_world_mismatch: bool = False) -> None:
    """Validate a checkpoint header against the restore target, naming
    the first mismatch (the satellite contract: fail loudly up front)."""
    want_fp = _tree_fingerprint(_engine_state(engine))
    if meta.get("fingerprint") and meta["fingerprint"] != want_fp:
        raise CheckpointMismatchError(
            f"checkpoint {path} was saved from a different model/optimizer "
            f"structure (fingerprint {meta['fingerprint']} != engine "
            f"{want_fp}): same architecture + optimizer required"
        )
    if meta.get("sharding") and meta["sharding"] != engine.param_sharding:
        raise CheckpointMismatchError(
            f"checkpoint {path} holds param_sharding="
            f"{meta['sharding']!r} state but the engine runs "
            f"{engine.param_sharding!r}; rebuild the engine with "
            f"param_sharding={meta['sharding']!r} (the portable sharded "
            "format reshapes world sizes, not sharding strategies)"
        )
    world = meta.get("world")
    if (
        not allow_world_mismatch
        and world is not None
        and int(world) != engine.comm.size
        and engine.param_sharding != "replicated"  # replicated state is
        # world-independent: the same full arrays land on any mesh
    ):
        raise CheckpointMismatchError(
            f"checkpoint {path} was saved from a {world}-way world but "
            f"this engine spans {engine.comm.size} ranks; reshape it "
            f"(`python -m torchmpi_tpu.reshard --from {world} "
            f"--to {engine.comm.size} <ckpt> <out>`) or use "
            "restore_engine_sharded, which reshards transparently"
        )


def save_engine(path, engine, step: int = 0, extra: Optional[Dict] = None) -> None:
    """Save an AllReduceSGDEngine's full training state.

    Multi-process (multi-controller) runs hand the LIVE jax arrays to
    Orbax — sharded/non-addressable arrays (fsdp over processes) are
    written cooperatively by all hosts; ``jax.device_get`` would raise on
    them. Single-process saves go through host numpy (robust for typed
    optax nodes and independent of live placement).

    ``meta.json`` is the layout header (world size, sharding, step,
    structure fingerprint), written atomically (temp + fsync + rename)
    and LAST — so a save killed mid-write never publishes a header whose
    state payload is torn, and restore can validate before loading.
    """
    path = Path(path).resolve()
    path.mkdir(parents=True, exist_ok=True)
    if jax.process_count() > 1:
        state = _engine_state(engine)
    else:
        state = jax.tree_util.tree_map(
            lambda a: jax.device_get(a), _engine_state(engine)
        )
    _ckptr().save(path / "state", state, force=True)
    if jax.process_index() == 0:
        _atomic_write_text(
            path / "meta.json", json.dumps(_layout_meta(engine, step, extra))
        )


def restore_engine(path, engine) -> Dict[str, Any]:
    """Restore state saved by :func:`save_engine` into the engine. Device
    placement follows each live leaf's CURRENT sharding — replicated
    engines restore replicated, fsdp engines restore sharded (densifying
    to replicated would silently drop ZeRO-3 and force a recompile).
    Returns the meta dict (incl. ``step``).

    The layout header is validated FIRST: a checkpoint from a different
    world size, sharding mode, or model structure raises
    :class:`CheckpointMismatchError` naming the mismatch, before any of
    the engine's state is touched.

    The engine's current state is passed as the restore template so typed
    pytree nodes (optax namedtuple states like ScaleByAdamState) come back
    with their original structure instead of plain lists/dicts."""
    path = Path(path).resolve()
    meta = json.loads((path / "meta.json").read_text())
    _check_layout(meta, engine, path)
    live = _engine_state(engine)
    if jax.process_count() > 1:
        # cooperative multi-host restore straight into the live shardings
        import orbax.checkpoint as ocp

        restore_args = ocp.checkpoint_utils.construct_restore_args(live)
        state = _ckptr().restore(
            path / "state", item=live, restore_args=restore_args
        )
    else:
        template = jax.tree_util.tree_map(lambda a: jax.device_get(a), live)
        restored = _ckptr().restore(path / "state", item=template)
        state = jax.tree_util.tree_map(
            lambda cur, new: jax.device_put(new, cur.sharding), live, restored
        )

    engine.params = state["params"]
    engine.opt_state = state["opt_state"]
    if "model_state" in state and engine.model_state is not None:
        engine.model_state = state["model_state"]
    return json.loads((path / "meta.json").read_text())


# ---------------------------------------------------------------------------
# portable sharded format: per-(leaf, rank) .npy shards + atomic CURRENT
# pointer. The on-disk twin of the live fsdp/zero1 layouts — and the unit
# the offline reshaper (`python -m torchmpi_tpu.reshard`) operates on.
# ---------------------------------------------------------------------------


def _sharded_trees(engine) -> Dict[str, str]:
    """tree name -> 'sharded' | 'replicated' under the engine's mode.
    The PORTABLE layout is defined here (flat contiguous shards), not by
    live device placement: fsdp shards params+opt, zero1 shards only the
    optimizer state, replicated engines shard nothing."""
    kind = {
        "fsdp": {"params": "sharded", "opt_state": "sharded"},
        "zero1": {"params": "replicated", "opt_state": "sharded"},
        "replicated": {"params": "replicated", "opt_state": "replicated"},
    }[engine.param_sharding]
    out = dict(kind)
    if engine.model_state is not None:
        out["model_state"] = kind["params"]
    return out


def _leaf_records(state: Dict[str, Any], kinds: Dict[str, str]) -> List[dict]:
    records = []
    for tree_name in sorted(state):
        leaves, _ = jax.tree_util.tree_flatten_with_path(state[tree_name])
        for p, a in leaves:
            arr_dtype = np.dtype(getattr(a, "dtype", np.asarray(a).dtype))
            records.append({
                "tree": tree_name,
                "path": jax.tree_util.keystr(p),
                "shape": list(np.shape(a)),
                "dtype": arr_dtype.str,
                "n": int(np.prod(np.shape(a), dtype=np.int64)),
                "kind": kinds[tree_name],
            })
    return records


def _shard_file(data_dir: Path, leaf_idx: int, rank: Optional[int]) -> Path:
    name = (
        f"leaf{leaf_idx}.full.npy" if rank is None
        else f"leaf{leaf_idx}.rank{rank}.npy"
    )
    return data_dir / name


def current_data_dir(path) -> Path:
    """The live data directory a sharded checkpoint's CURRENT points at."""
    path = Path(path).resolve()
    cur = (path / "CURRENT").read_text().strip()
    return path / cur


def read_sharded_meta(path) -> Dict[str, Any]:
    meta = json.loads((current_data_dir(path) / "meta.json").read_text())
    if meta.get("format") != SHARDED_FORMAT:
        raise CheckpointMismatchError(
            f"{path} is not a {SHARDED_FORMAT} sharded checkpoint "
            f"(format={meta.get('format')!r})"
        )
    return meta


def save_engine_sharded(
    path, engine, step: int = 0, extra: Optional[Dict] = None,
    world: Optional[int] = None, state: Optional[Dict[str, Any]] = None,
) -> Path:
    """Save the engine's state as a portable sharded checkpoint.

    Every leaf is flattened and cut into ``world`` contiguous shards
    (:class:`~..reshard.Layout` — byte-identical to what a fresh
    ``world``-way scatter would place on each rank); replicated trees
    (zero1 params) store ONE full copy. All files land in a fresh
    ``data-<token>/`` directory, fsync'd, and only then does the atomic
    ``CURRENT`` pointer swing to it — a save killed at any point (power
    loss included) leaves the previous checkpoint fully intact, and the
    superseded data dir is garbage-collected on the NEXT successful save.

    Single-controller only (every leaf must be addressable); multi-host
    jobs use the orbax format and reshape offline.

    ``state`` overrides the engine's live trees: an async caller (the
    engine's ``checkpoint_every`` hook) passes the reference snapshot
    it took on the step thread, so a save never serializes a tree the
    next step() already half-replaced. Every published checkpoint is
    registered as the newest rollback artifact
    (:func:`~..supervise.checkpoints.register_checkpoint`).
    """
    from ..reshard import Layout

    if jax.process_count() > 1:
        raise RuntimeError(
            "save_engine_sharded is single-controller only (leaves must "
            "be host-addressable); multi-host jobs save via save_engine "
            "and reshape offline with `python -m torchmpi_tpu.reshard`"
        )
    path = Path(path).resolve()
    path.mkdir(parents=True, exist_ok=True)
    world = int(world or engine.comm.size)
    live_state = _engine_state(engine) if state is None else state
    state = jax.tree_util.tree_map(
        lambda a: np.asarray(jax.device_get(a)), live_state
    )
    kinds = _sharded_trees(engine)
    records = _leaf_records(state, kinds)
    meta = {
        "format": SHARDED_FORMAT,
        **_layout_meta(engine, step, extra, state=live_state),
        "world": world,
        "leaves": records,
    }
    token = secrets.token_hex(4)
    data_dir = path / f"data-{token}"
    tmp_dir = path / f".tmp-{token}"
    tmp_dir.mkdir()
    leaves = [
        a for tree_name in sorted(state)
        for a in jax.tree_util.tree_leaves(state[tree_name])
    ]
    layout = Layout(world)
    for i, (rec, arr) in enumerate(zip(records, leaves)):
        flat = np.asarray(arr).reshape(-1)
        if rec["kind"] == "replicated":
            files = [(_shard_file(tmp_dir, i, None), flat)]
        else:
            files = [
                (_shard_file(tmp_dir, i, r), flat[s:e])
                for r, (s, e) in enumerate(layout.intervals(rec["n"]))
            ]
        for f, data in files:
            np.save(f, data)
            _fsync_file(f)
    (tmp_dir / "meta.json").write_text(json.dumps(meta))
    _fsync_file(tmp_dir / "meta.json")
    os.replace(tmp_dir, data_dir)  # the complete payload becomes visible
    prev = None
    try:
        prev = current_data_dir(path)
    except (OSError, ValueError):
        pass
    _atomic_write_text(path / "CURRENT", data_dir.name)
    # the artifact is published: register it as the newest rollback
    # target (what DataLoss messages and the supervisor's rollback name)
    from ..supervise import checkpoints as _registry

    _registry.register_checkpoint(path, step)
    # GC the superseded payload (and any orphaned temp dirs from saves
    # that died before publishing) only AFTER the pointer swung
    import shutil

    for stale in list(path.glob(".tmp-*")) + (
        [prev] if prev is not None and prev != data_dir else []
    ):
        if stale.name == data_dir.name:
            continue
        shutil.rmtree(stale, ignore_errors=True)
    return data_dir


def _assemble_leaf(data_dir: Path, leaf_idx: int, rec: dict,
                   world: int) -> np.ndarray:
    """Reassemble one leaf's full flat array from its shard files."""
    if rec["kind"] == "replicated":
        return np.load(_shard_file(data_dir, leaf_idx, None))
    parts = [
        np.load(_shard_file(data_dir, leaf_idx, r)) for r in range(world)
    ]
    return np.concatenate(parts) if parts else np.empty(0, rec["dtype"])


def restore_engine_sharded(path, engine) -> Dict[str, Any]:
    """Restore a portable sharded checkpoint into the engine — from ANY
    source world size: when the checkpoint's world differs from the
    engine's, the shard files are redistributed through the reshard
    planner on the way in (each live leaf receives exactly the bytes a
    fresh ``engine.comm.size``-way scatter of the assembled state would
    give it). Structure/sharding mismatches still fail loudly."""
    path = Path(path).resolve()
    meta = read_sharded_meta(path)
    _check_layout(meta, engine, path, allow_world_mismatch=True)
    data_dir = current_data_dir(path)
    world = int(meta["world"])
    live = _engine_state(engine)
    leaves, treedef = jax.tree_util.tree_flatten(live)
    records = meta["leaves"]
    if len(records) != len(leaves):
        raise CheckpointMismatchError(
            f"checkpoint {path} holds {len(records)} leaves but the "
            f"engine has {len(leaves)}"
        )
    restored = []
    for i, (rec, cur) in enumerate(zip(records, leaves)):
        full = _assemble_leaf(data_dir, i, rec, world)
        arr = full.reshape(tuple(rec["shape"]))
        restored.append(jax.device_put(arr, cur.sharding))
    state = jax.tree_util.tree_unflatten(treedef, restored)
    engine.params = state["params"]
    engine.opt_state = state["opt_state"]
    if "model_state" in state and engine.model_state is not None:
        engine.model_state = state["model_state"]
    return meta


def reshape_sharded(
    src_path, dst_path, to_world: int,
    chunk_bytes: Optional[int] = None,
) -> Dict[str, Any]:
    """Offline N-way -> M-way reshape of a sharded checkpoint with
    bounded memory: source shards are mmap'd read-only, target shards are
    preallocated memmaps, and every byte moves through the reshard
    executor's single chunked scratch buffer — the full array is never
    materialized, regardless of checkpoint size. Returns a stats dict
    incl. the asserted ``peak_scratch_bytes`` bound.
    """
    from ..reshard import Layout, Redistributor

    src_path, dst_path = Path(src_path).resolve(), Path(dst_path).resolve()
    if int(to_world) < 1:
        raise ValueError(f"--to world must be >= 1, got {to_world}")
    meta = read_sharded_meta(src_path)
    src_dir = current_data_dir(src_path)
    from_world = int(meta["world"])
    dst_path.mkdir(parents=True, exist_ok=True)
    token = secrets.token_hex(4)
    tmp_dir = dst_path / f".tmp-{token}"
    tmp_dir.mkdir()
    src_layout, dst_layout = Layout(from_world), Layout(int(to_world))
    stats = {
        "from": from_world, "to": int(to_world), "leaves": len(meta["leaves"]),
        "peak_scratch_bytes": 0, "largest_shard_bytes": 0,
        "moved_bytes": 0, "plans": [],
    }
    for i, rec in enumerate(meta["leaves"]):
        dt = np.dtype(rec["dtype"])
        n = int(rec["n"])
        if rec["kind"] == "replicated":
            # one full copy in, one full copy out — streamed in chunks
            src = np.load(_shard_file(src_dir, i, None), mmap_mode="r")
            out = np.lib.format.open_memmap(
                _shard_file(tmp_dir, i, None), mode="w+", dtype=dt,
                shape=(n,),
            )
            from ..reshard.core import chunk_elems_for, chunk_spans

            for s, e in chunk_spans(n, chunk_elems_for(dt.itemsize,
                                                       chunk_bytes)):
                out[s:e] = src[s:e]
            out.flush()
            continue
        rd = Redistributor(n, dt, src_layout, dst_layout, chunk_bytes)
        srcs = [
            np.load(_shard_file(src_dir, i, r), mmap_mode="r")
            for r in range(from_world)
        ]
        outs = [
            np.lib.format.open_memmap(
                _shard_file(tmp_dir, i, r), mode="w+", dtype=dt,
                shape=(max(0, e - s),),
            )
            for r, (s, e) in enumerate(dst_layout.intervals(n))
        ]

        def read(rank, off, view):
            view[:] = srcs[rank][off:off + view.shape[0]]

        def write(rank, off, values):
            outs[rank][off:off + values.shape[0]] = values

        rd.run(read, write)
        for o in outs:
            o.flush()
        stats["peak_scratch_bytes"] = max(
            stats["peak_scratch_bytes"], rd.peak_scratch_bytes
        )
        stats["largest_shard_bytes"] = max(
            stats["largest_shard_bytes"],
            max((a.nbytes for a in srcs), default=0),
            max((a.nbytes for a in outs), default=0),
        )
        stats["moved_bytes"] += sum(t.n for t in rd.transfers) * dt.itemsize
        stats["plans"].append(rd.plan.plan_id)
    new_meta = dict(meta, world=int(to_world))
    (tmp_dir / "meta.json").write_text(json.dumps(new_meta))
    _fsync_file(tmp_dir / "meta.json")
    for f in tmp_dir.iterdir():
        _fsync_file(f)
    data_dir = dst_path / f"data-{token}"
    os.replace(tmp_dir, data_dir)
    _atomic_write_text(dst_path / "CURRENT", data_dir.name)
    return stats


def save_parameter_servers(path, ps_group) -> None:
    """Save a PSGroup's center values (assembled from shards)."""
    path = Path(path).resolve()
    path.mkdir(parents=True, exist_ok=True)
    centers = [srv.receive().wait() for srv in ps_group.servers]
    _ckptr().save(path / "ps_centers", {"centers": centers}, force=True)


def restore_parameter_servers(path, ps_group) -> None:
    """Restore PS centers: each server's shards are overwritten via the
    'copy' rule (a collective in the reference; here applied per shard)."""
    path = Path(path).resolve()
    state = _ckptr().restore(path / "ps_centers")
    for srv, center in zip(ps_group.servers, state["centers"]):
        srv.send(np.asarray(center), rule="copy").wait()
