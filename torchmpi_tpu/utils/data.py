"""Dataset + distributed input pipeline.

Analog of ``examples/mnist/makeiterator.lua``: the global batch is divided by
world size (``batch 336/size``, makeiterator.lua:31) and each rank sees its
own partition of the dataset; iterators support prefetching the next batch
while the current step computes (``sgdengine.lua:118-124``'s
``iterator:prefetch()``).

This environment has no network egress and no local MNIST archive, so
``synthetic_mnist`` generates a deterministic MNIST-shaped classification
dataset (class-prototype + noise images, 784 features, 10 classes). The
convergence *test strategy* is unchanged from the reference: distributed
training must match the sequential baseline's loss on the same data
(``examples/mnist/mnist_allreduce.lua:87-113``). ``load_mnist_idx`` reads
real MNIST IDX files when a directory is provided.
"""

from __future__ import annotations

import queue
import struct
import threading
from typing import Iterator, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np


def synthetic_mnist(
    num_train: int = 8192,
    num_test: int = 2048,
    num_classes: int = 10,
    seed: int = 1234,
    image_shape: Tuple[int, int] = (28, 28),
):
    """Deterministic MNIST-shaped dataset: each class is a smoothed random
    prototype image; samples are prototype + gaussian noise, clipped to
    [0, 1]. Linearly separable enough for logistic regression to reach low
    error in a few epochs, like real MNIST."""
    rng = np.random.RandomState(seed)
    h, w = image_shape
    protos = rng.randn(num_classes, h * w).astype(np.float32)
    # Smooth prototypes to make pixels locally correlated (image-like).
    protos = protos.reshape(num_classes, h, w)
    for _ in range(2):
        protos = (
            protos
            + np.roll(protos, 1, axis=1)
            + np.roll(protos, -1, axis=1)
            + np.roll(protos, 1, axis=2)
            + np.roll(protos, -1, axis=2)
        ) / 5.0
    protos = protos.reshape(num_classes, h * w)
    protos /= np.abs(protos).max(axis=1, keepdims=True)

    def make(n, rs):
        labels = rs.randint(0, num_classes, size=n).astype(np.int32)
        x = protos[labels] + 0.9 * rs.randn(n, h * w).astype(np.float32)
        x = np.clip(0.5 + 0.5 * x, 0.0, 1.0).astype(np.float32)
        return x.reshape(n, h, w), labels

    train = make(num_train, np.random.RandomState(seed + 1))
    test = make(num_test, np.random.RandomState(seed + 2))
    return train, test


def synthetic_imagenet(
    num_train: int = 1024,
    num_test: int = 256,
    num_classes: int = 1000,
    image_size: int = 224,
    seed: int = 4321,
):
    """Deterministic ImageNet-shaped dataset (NHWC float32 in [0, 1]):
    class prototypes are smooth low-frequency color fields; samples add
    gaussian noise. Same role as ``synthetic_mnist`` for the ResNet
    data-parallel config (BASELINE.json config #4) in a zero-egress
    environment."""
    rng = np.random.RandomState(seed)
    h = w = image_size
    # low-res prototypes upsampled: cheap and image-like
    lo = 8
    protos_lo = rng.randn(num_classes, lo, lo, 3).astype(np.float32)
    reps = -(-h // lo)

    def upsample(p):
        big = np.repeat(np.repeat(p, reps, axis=0), reps, axis=1)
        return big[:h, :w]

    def make(n, rs):
        labels = rs.randint(0, num_classes, size=n).astype(np.int32)
        x = np.empty((n, h, w, 3), np.float32)
        for i in range(n):
            base = upsample(protos_lo[labels[i]])
            x[i] = base + 0.5 * rs.randn(h, w, 3).astype(np.float32)
        x = np.clip(0.5 + 0.25 * x, 0.0, 1.0)
        return x, labels

    train = make(num_train, np.random.RandomState(seed + 1))
    test = make(num_test, np.random.RandomState(seed + 2))
    return train, test


def synthetic_tokens(
    num_seqs: int = 512,
    seq_len: int = 1024,
    vocab: int = 8192,
    seed: int = 97,
):
    """Deterministic LM dataset: ``(tokens_in, tokens_target)`` int32 pairs
    of shape ``[num_seqs, seq_len]`` where target[t] = in[t+1]. The stream
    is an order-1 structured process (each token is a fixed affine map of
    its predecessor plus occasional jumps), so a model genuinely reduces
    loss by attending backwards — same zero-egress role as
    ``synthetic_mnist``."""
    rs = np.random.RandomState(seed)
    raw = np.empty((num_seqs, seq_len + 1), np.int64)
    raw[:, 0] = rs.randint(0, vocab, size=num_seqs)
    jumps = rs.rand(num_seqs, seq_len) < 0.05
    noise = rs.randint(0, vocab, size=(num_seqs, seq_len))
    for t in range(seq_len):
        step = (raw[:, t] * 31 + 17) % vocab
        raw[:, t + 1] = np.where(jumps[:, t], noise[:, t], step)
    tokens = raw.astype(np.int32)
    return tokens[:, :-1], tokens[:, 1:]


def load_mnist_idx(directory: str):
    """Load real MNIST from IDX files if present (no download)."""
    import gzip
    import os

    def read_images(path):
        opener = gzip.open if path.endswith(".gz") else open
        with opener(path, "rb") as f:
            magic, n, h, w = struct.unpack(">IIII", f.read(16))
            assert magic == 2051
            return (
                np.frombuffer(f.read(), np.uint8)
                .reshape(n, h, w)
                .astype(np.float32)
                / 255.0
            )

    def read_labels(path):
        opener = gzip.open if path.endswith(".gz") else open
        with opener(path, "rb") as f:
            magic, n = struct.unpack(">II", f.read(8))
            assert magic == 2049
            return np.frombuffer(f.read(), np.uint8).astype(np.int32)

    def find(stem):
        import glob

        hits = glob.glob(f"{directory}/{stem}*")
        if not hits:
            raise FileNotFoundError(f"{stem} under {directory}")
        return hits[0]

    return (
        (read_images(find("train-images")), read_labels(find("train-labels"))),
        (read_images(find("t10k-images")), read_labels(find("t10k-labels"))),
    )


class DistributedIterator:
    """Rank-partitioned minibatch iterator with background prefetch.

    Yields rank-stacked device batches ``(x[p, B/p, ...], y[p, B/p])``: the
    global batch of ``batch_size`` is split evenly over the communicator's
    ``p`` ranks (makeiterator.lua:31's ``batch/size``), each rank drawing
    from its own contiguous shard of the dataset (partitioned sampling).
    ``prefetch`` batches are staged onto devices ahead of consumption by a
    background thread.
    """

    def __init__(
        self,
        x: np.ndarray,
        y: np.ndarray,
        batch_size: int,
        num_ranks: int,
        shuffle: bool = True,
        seed: int = 0,
        prefetch: int = 2,
        sharding=None,
    ):
        # Note: partial tail batches are always dropped (static shapes keep
        # the jitted step from recompiling), like the reference's fixed
        # batch/size partitioning.
        if batch_size < num_ranks or batch_size % num_ranks != 0:
            raise ValueError(
                f"global batch {batch_size} must be a positive multiple of "
                f"the {num_ranks} ranks (>= one sample per rank)"
            )
        self.x, self.y = x, y
        self.batch_size = batch_size
        self.p = num_ranks
        self.per_rank = batch_size // num_ranks
        self.shuffle = shuffle
        self.seed = seed
        self.prefetch = max(1, prefetch)
        self.sharding = sharding
        n = len(x)
        self.shard_len = n // num_ranks
        self.batches_per_epoch = self.shard_len // self.per_rank
        if self.batches_per_epoch == 0:
            raise ValueError(
                f"dataset of {n} samples is too small for {num_ranks} ranks x "
                f"{self.per_rank} per-rank batch"
            )
        self._epoch = 0

    def __len__(self) -> int:
        return self.batches_per_epoch

    def _epoch_order(self) -> np.ndarray:
        if not self.shuffle:
            return np.arange(self.shard_len * self.p).reshape(
                self.p, self.shard_len
            )
        rs = np.random.RandomState(self.seed + self._epoch)
        # Each rank permutes within its own contiguous shard.
        return np.stack(
            [
                r * self.shard_len + rs.permutation(self.shard_len)
                for r in range(self.p)
            ]
        )

    def _host_batches(self) -> Iterator[Tuple[np.ndarray, np.ndarray]]:
        order = self._epoch_order()
        for b in range(self.batches_per_epoch):
            idx = order[:, b * self.per_rank : (b + 1) * self.per_rank]
            yield self.x[idx], self.y[idx]

    def _device_transfer_in_producer(self) -> bool:
        """Stage batches onto devices from the prefetch thread only on real
        accelerators. The XLA CPU backend executes collectives as blocking
        rendezvous on the host thread pool; on low-core machines a
        background-thread jax dispatch can starve one rendezvous participant
        and deadlock the whole program (observed: 8 virtual devices, 1 core,
        conv workload). On CPU the producer therefore stays pure-numpy and
        transfer happens in the consumer thread."""
        if self.sharding is None:
            return False
        devices = getattr(self.sharding, "device_set", None)
        if not devices:
            return False
        return next(iter(devices)).platform != "cpu"

    def __iter__(self):
        q: "queue.Queue" = queue.Queue(maxsize=self.prefetch)
        stop = threading.Event()
        stage_in_producer = self._device_transfer_in_producer()

        def put_on_device(xb, yb):
            xb_d, yb_d = jnp.asarray(xb), jnp.asarray(yb)
            if self.sharding is not None:
                xb_d = jax.device_put(xb_d, self.sharding)
                yb_d = jax.device_put(yb_d, self.sharding)
            return xb_d, yb_d

        def producer():
            try:
                for xb, yb in self._host_batches():
                    if stop.is_set():
                        return
                    q.put(put_on_device(xb, yb) if stage_in_producer else (xb, yb))
            finally:
                # Deliver the end-of-epoch sentinel without risking a
                # permanent block: if the consumer broke early (stop set) the
                # queue may stay full forever and a blocking put would leak
                # this thread and pin its staged batches.
                while not stop.is_set():
                    try:
                        q.put(None, timeout=0.1)
                        break
                    except queue.Full:
                        continue

        t = threading.Thread(target=producer, daemon=True)
        t.start()
        try:
            while True:
                item = q.get()
                if item is None:
                    break
                yield item if stage_in_producer else put_on_device(*item)
        finally:
            stop.set()
            # drain so the producer can exit
            while not q.empty():
                try:
                    q.get_nowait()
                except queue.Empty:
                    break
            # advance the shuffle epoch even when the consumer stops early,
            # so a max-steps loop never replays the same permutation
            self._epoch += 1
