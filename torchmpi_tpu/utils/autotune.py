"""Cutoff autotuner.

The reference ships hand-tuned small-message cutoffs and leaves autotuning
as a TODO ("implement an autotuner; YMMV", ``lib/c_api.h:93-95``). This
implements it: measure the latency (fused XLA) and bandwidth (ring) paths
across the size sweep on the *actual* communicator and set the crossover
as the platform's cutoff constant.
"""

from __future__ import annotations

from typing import List, Optional, Tuple

from .. import constants
from ..runtime.communicator import Communicator
from .tester import run_one_config, sweep_sizes


def tune_allreduce_cutoff(
    comm: Optional[Communicator] = None,
    min_pow: int = 8,
    max_pow: int = 20,
    warmup: int = 3,
    timed: int = 5,
    apply: bool = True,
) -> Tuple[int, List]:
    """Find the element count where the ring path starts beating the fused
    XLA path for allreduce; optionally set it as the platform cutoff.
    Returns ``(cutoff_elements, measurements)``."""
    if comm is None:
        from .. import runtime_state

        comm = runtime_state.current_communicator()
    if apply and constants.constants_frozen():
        # fail fast: the expensive sweep would end in FrozenConstantsError
        raise constants.FrozenConstantsError(
            "constants are frozen; call with apply=False to only measure"
        )
    suffix = constants.platform_suffix(comm.devices[0].platform)

    results = []
    crossover = None
    for n in sweep_sizes(min_pow, max_pow, jitter_seed=None):
        xla = run_one_config(
            "allreduce", n, comm, backend="xla", benchmark=True,
            warmup=warmup, timed=timed, route_override=False,
        )
        ring = run_one_config(
            "allreduce", n, comm, backend="ring", benchmark=True,
            warmup=warmup, timed=timed, route_override=False,
        )
        results.append((n, xla.mean_us, ring.mean_us))
        if crossover is None and ring.mean_us < xla.mean_us:
            # op_route keeps nelem <= cutoff on the fused path, so the
            # cutoff must sit strictly BELOW the first ring win
            crossover = n - 1
    # Never-crosses -> keep everything on the fused path (huge cutoff).
    cutoff = crossover if crossover is not None else 1 << (max_pow + 4)
    if apply:
        constants.set(f"small_allreduce_size_{suffix}", int(cutoff))
    return int(cutoff), results
