"""Routing-constant autotuner with persistence.

The reference ships hand-tuned small-message cutoffs and leaves autotuning
as a TODO ("implement an autotuner; YMMV", ``lib/c_api.h:93-95``). This
implements it across the board: every routing constant is set from
measurement on the *actual* communicator —

- :func:`tune_allreduce_cutoff` / :func:`tune_broadcast_cutoff`: the
  element count where the custom ring starts beating the fused XLA path
  (``kSmallAllreduceSize`` / ``kSmallBcastSize``,
  ``lib/constants.cpp:136-141``).
- :func:`tune_tree_pipeline_switch`: the byte size where the pipelined
  ring broadcast overtakes the binomial tree
  (``kBcastSizeTreeBased``, ``lib/constants.cpp:146-147``).
- :func:`tune_chunk_size`: the best max ring-message size
  (``kMin/kMaxBufferSize``, ``lib/constants.cpp:142-145``).
- :func:`tune_ring_implementation`: ppermute vs pallas for the custom
  ring, measured — the preference table stops asserting and starts
  citing numbers (the round-1 verdict's demand).
- :func:`tune_wire_dtype`: full vs bf16 vs int8 on-wire encoding for the
  bandwidth-path reductions (EQuARX-style block quantization) — measures
  whether compression wins on THIS fabric and persists the answer.
- :func:`tune_plan`: measured candidate-plan search for the schedule
  compiler — every structurally possible schedule family is run on the
  live topology and the winner persists as a plan override per
  plan-cache key, overriding the analytic cost model's pick.
- :func:`tune_pipeline_depth`: measured chunk-pipeline depth for the
  ring plan families (the schedule IR's pipeline dimension) — the
  winner pins ``plan_pipeline_depth``, overriding the stage-overlap
  cost model's depth choice.

:func:`tune_all` runs everything; results persist per
``(platform, world size)`` in a JSON cache
(``~/.cache/torchmpi_tpu/autotune.json`` or ``$TORCHMPI_TPU_TUNING_CACHE``)
and :func:`load_tuning` re-applies them — called automatically by
``start()``.
"""

from __future__ import annotations

import json
import os
from pathlib import Path
from typing import Dict, List, Optional, Tuple

from .. import constants, telemetry
from ..runtime.communicator import Communicator
from .tester import run_one_config, sweep_sizes


def _audit_decision(knob: str, chosen, applied: bool, candidates) -> None:
    """Every tuned knob lands in the telemetry audit journal with the
    measurements that justified it — the decision log the reference's
    'YMMV' comment never had. Always on: tuning is a cold path and the
    journal is bounded."""
    telemetry.audit(
        "autotune",
        knob=knob,
        chosen=chosen,
        applied=bool(applied),
        candidates=[list(c) for c in candidates],
    )

# constants a tuning run may set; only these are persisted/applied
_TUNABLE = (
    "small_allreduce_size_{s}",
    "small_broadcast_size_{s}",
    "broadcast_size_tree_based_{s}",
    "min_buffer_size_{s}",
    "max_buffer_size_{s}",
    "ring_implementation",
    "wire_dtype",
    "fusion_buffer_bytes",
    "ps_chunk_bytes",
    "plan_pipeline_depth",
)

#: canonical LeNet gradient leaf element counts (conv1 w/b, conv2 w/b,
#: fc1-3 w/b) — the latency-bound north-star's actual small-tensor set,
#: shared by :func:`tune_fusion_threshold` and ``bench.py --microbench``
LENET_LEAF_SIZES = (150, 6, 2400, 16, 48000, 120, 10080, 84, 840, 10)


def _comm(comm: Optional[Communicator]) -> Communicator:
    if comm is not None:
        return comm
    from .. import runtime_state

    return runtime_state.current_communicator()


def _check_unfrozen(apply: bool, measure_mutates: bool = False) -> None:
    if constants.constants_frozen() and (apply or measure_mutates):
        # fail fast: the expensive sweep would end in FrozenConstantsError
        if measure_mutates:
            raise constants.FrozenConstantsError(
                "constants are frozen; this tuner must temporarily set "
                "constants to pin each measured configuration, so it cannot "
                "run at all after freeze_constants()"
            )
        raise constants.FrozenConstantsError(
            "constants are frozen; call with apply=False to only measure"
        )


def _suffix(comm: Communicator) -> str:
    return constants.platform_suffix(comm.devices[0].platform)


def _tune_small_cutoff(
    op: str,
    comm: Optional[Communicator],
    min_pow: int,
    max_pow: int,
    warmup: int,
    timed: int,
    apply: bool,
) -> Tuple[int, List]:
    comm = _comm(comm)
    _check_unfrozen(apply)
    suffix = _suffix(comm)
    results = []
    crossover = None
    for n in sweep_sizes(min_pow, max_pow, jitter_seed=None):
        xla = run_one_config(
            op, n, comm, backend="xla", benchmark=True,
            warmup=warmup, timed=timed, route_override=False,
        )
        ring = run_one_config(
            op, n, comm, backend="ring", benchmark=True,
            warmup=warmup, timed=timed, route_override=False,
        )
        results.append((n, xla.mean_us, ring.mean_us))
        if crossover is None and ring.mean_us < xla.mean_us:
            # op_route keeps nelem <= cutoff on the fused path, so the
            # cutoff must sit strictly BELOW the first ring win
            crossover = n - 1
    # Never-crosses -> keep everything on the fused path (huge cutoff).
    cutoff = crossover if crossover is not None else 1 << (max_pow + 4)
    if apply:
        constants.set(f"small_{op}_size_{suffix}", int(cutoff))
    _audit_decision(f"small_{op}_size_{suffix}", int(cutoff), apply, results)
    return int(cutoff), results


def tune_allreduce_cutoff(
    comm: Optional[Communicator] = None,
    min_pow: int = 8,
    max_pow: int = 20,
    warmup: int = 3,
    timed: int = 5,
    apply: bool = True,
) -> Tuple[int, List]:
    """Find the element count where the ring path starts beating the fused
    XLA path for allreduce; optionally set it as the platform cutoff.
    Returns ``(cutoff_elements, measurements)``."""
    return _tune_small_cutoff(
        "allreduce", comm, min_pow, max_pow, warmup, timed, apply
    )


def tune_broadcast_cutoff(
    comm: Optional[Communicator] = None,
    min_pow: int = 8,
    max_pow: int = 20,
    warmup: int = 3,
    timed: int = 5,
    apply: bool = True,
) -> Tuple[int, List]:
    """Same crossover search for broadcast (``kSmallBcastSize``)."""
    return _tune_small_cutoff(
        "broadcast", comm, min_pow, max_pow, warmup, timed, apply
    )


def _pinned_ring_broadcast_us(
    comm: Communicator, n: int, force_tree: bool, warmup: int, timed: int
) -> float:
    """Measure the ring broadcast with the tree/pipeline decision pinned by
    temporarily moving the switch constant."""
    suffix = _suffix(comm)
    name = f"broadcast_size_tree_based_{suffix}"
    prev = constants.get(name)
    constants.set(name, (1 << 62) if force_tree else 0)
    try:
        res = run_one_config(
            "broadcast", n, comm, backend="ring", benchmark=True,
            warmup=warmup, timed=timed, route_override=False,
        )
    finally:
        constants.set(name, prev)
    return res.mean_us


def tune_tree_pipeline_switch(
    comm: Optional[Communicator] = None,
    min_pow: int = 10,
    max_pow: int = 22,
    warmup: int = 3,
    timed: int = 5,
    apply: bool = True,
) -> Tuple[int, List]:
    """Find the message size (BYTES) where the pipelined ring broadcast
    overtakes the binomial tree; set ``broadcast_size_tree_based``.
    Returns ``(switch_bytes, measurements)``.

    Requires unfrozen constants even with ``apply=False``: the measurement
    itself pins each variant by temporarily moving the switch constant."""
    comm = _comm(comm)
    _check_unfrozen(apply, measure_mutates=True)
    suffix = _suffix(comm)
    results = []
    crossover_bytes = None
    for n in sweep_sizes(min_pow, max_pow, jitter_seed=None):
        tree_us = _pinned_ring_broadcast_us(comm, n, True, warmup, timed)
        pipe_us = _pinned_ring_broadcast_us(comm, n, False, warmup, timed)
        results.append((n, tree_us, pipe_us))
        if crossover_bytes is None and pipe_us < tree_us:
            crossover_bytes = n * 4 - 1  # f32 sweep; switch sits below
    switch = crossover_bytes if crossover_bytes is not None else 1 << 62
    if apply:
        constants.set(f"broadcast_size_tree_based_{suffix}", int(switch))
    _audit_decision(
        f"broadcast_size_tree_based_{suffix}", int(switch), apply, results
    )
    return int(switch), results


def tune_chunk_size(
    comm: Optional[Communicator] = None,
    nelem: int = 1 << 20,
    candidates: Tuple[int, ...] = (1 << 17, 1 << 18, 1 << 19, 1 << 20, 1 << 22),
    warmup: int = 2,
    timed: int = 4,
    apply: bool = True,
) -> Tuple[int, List]:
    """Pick the max ring-message size (BYTES) minimizing large-allreduce
    latency; sets ``max_buffer_size`` (and ``min_buffer_size`` = max/8).
    Returns ``(best_max_bytes, measurements)``.

    Requires unfrozen constants even with ``apply=False``: each candidate
    is measured by temporarily setting the buffer-size constants."""
    comm = _comm(comm)
    _check_unfrozen(apply, measure_mutates=True)
    suffix = _suffix(comm)
    max_name = f"max_buffer_size_{suffix}"
    min_name = f"min_buffer_size_{suffix}"
    prev_max, prev_min = constants.get(max_name), constants.get(min_name)
    results = []
    best = (float("inf"), prev_max)
    try:
        for cand in candidates:
            constants.set(max_name, int(cand))
            constants.set(min_name, int(max(1, cand // 8)))
            res = run_one_config(
                "allreduce", nelem, comm, backend="ring", benchmark=True,
                warmup=warmup, timed=timed, route_override=False,
            )
            results.append((cand, res.mean_us))
            if res.mean_us < best[0]:
                best = (res.mean_us, cand)
    finally:
        constants.set(max_name, prev_max)
        constants.set(min_name, prev_min)
    if apply:
        constants.set(max_name, int(best[1]))
        constants.set(min_name, int(max(1, best[1] // 8)))
    _audit_decision(max_name, int(best[1]), apply, results)
    return int(best[1]), results


def tune_ring_implementation(
    comm: Optional[Communicator] = None,
    nelem: int = 1 << 20,
    warmup: int = 2,
    timed: int = 4,
    apply: bool = True,
) -> Tuple[str, List]:
    """Measure ppermute vs pallas vs pallas_bidir for the custom ring
    allreduce and set ``ring_implementation`` to the winner. Falls back to
    'ppermute' where pallas is unavailable (CPU, single chip). The
    preference table's pallas entry thereby becomes a measurement, not an
    assertion — and the bidirectional ring (both ICI directions per step)
    must EARN its slot on the wire, like the reference's "our ring beats
    NCCL" claim."""
    comm = _comm(comm)
    # measure_mutates: the sweep itself flips ring_implementation to time
    # each kernel, so frozen constants must fail fast even with apply=False
    _check_unfrozen(apply, measure_mutates=True)
    from ..collectives.selector import backend_availability

    results = []
    winner = "ppermute"
    if backend_availability().get("pallas"):
        ring = run_one_config(
            "allreduce", nelem, comm, backend="ring", benchmark=True,
            warmup=warmup, timed=timed, route_override=False,
        )
        results = [("ppermute", ring.mean_us)]
        best_us = ring.mean_us
        prev = constants.get("ring_implementation")
        try:
            for impl in ("pallas", "pallas_bidir"):
                constants.set("ring_implementation", impl)
                res = run_one_config(
                    "allreduce", nelem, comm, backend="pallas",
                    benchmark=True, warmup=warmup, timed=timed,
                    route_override=False,
                )
                results.append((impl, res.mean_us))
                if res.correct and res.mean_us < best_us:
                    winner, best_us = impl, res.mean_us
        finally:
            constants.set("ring_implementation", prev)
    if apply:
        constants.set("ring_implementation", winner)
    _audit_decision("ring_implementation", winner, apply, results)
    return winner, results


def tune_wire_dtype(
    comm: Optional[Communicator] = None,
    nelem: int = 1 << 20,
    warmup: int = 2,
    timed: int = 4,
    apply: bool = True,
) -> Tuple[str, List]:
    """Measure the wire encodings ('full', 'bf16', 'int8') for the large
    custom-ring allreduce and set ``wire_dtype`` to the fastest CORRECT
    one. Quantization must EARN its place on the wire: on fabrics where
    the encode/decode cost exceeds the bandwidth saving (fast ICI, small
    worlds) the tuner keeps 'full', and the persisted entry per
    (platform, world size) means ``start()`` re-applies the measured
    answer, never a guess.

    Measures the ring that would actually serve the traffic: the pallas
    RDMA ring when available (via the already-tuned
    ``ring_implementation``), else the ppermute ring.

    Requires unfrozen constants even with ``apply=False``: the sweep pins
    each encoding by temporarily setting the ``wire_dtype`` constant."""
    comm = _comm(comm)
    _check_unfrozen(apply, measure_mutates=True)
    from ..collectives.selector import backend_availability

    backend = (
        "pallas"
        if (
            backend_availability().get("pallas")
            and constants.get("ring_implementation")
            in ("pallas", "pallas_bidir")
        )
        else "ring"
    )
    prev = constants.get("wire_dtype")
    results: List = []
    best = (float("inf"), "full")
    try:
        for wire in ("full", "bf16", "int8"):
            constants.set("wire_dtype", wire)
            res = run_one_config(
                "allreduce", nelem, comm, backend=backend, benchmark=True,
                warmup=warmup, timed=timed, route_override=False,
            )
            results.append((wire, res.mean_us))
            if res.correct and res.mean_us < best[0]:
                best = (res.mean_us, wire)
    finally:
        constants.set("wire_dtype", prev)
    if apply:
        constants.set("wire_dtype", best[1])
    _audit_decision("wire_dtype", best[1], apply, results)
    return best[1], results


def tune_plan(
    comm: Optional[Communicator] = None,
    op: str = "allreduce",
    nelem: int = 1 << 20,
    warmup: int = 2,
    timed: int = 4,
    apply: bool = True,
) -> Tuple[str, List]:
    """Measured candidate-plan search: run every *structurally possible*
    schedule family (flat / hier / staged / tree) the compiler generates
    for a large ``op`` on THIS communicator's declared topology, and
    persist the winner as a plan override for its plan-cache key.

    This is the autotuner's schedule-compiler face: where the other
    tuners twiddle threshold constants, this one overrides the analytic
    cost model's *choice* with a measurement — ``set_plan_override``
    keyed exactly like the plan cache (op, topology fingerprint,
    payload bucket, wire), persisted in the tuning cache and re-applied
    by ``start()`` like ``tune_wire_dtype``'s answer. The analytic
    model still orders candidates everywhere a measurement has not
    spoken."""
    import time as _time

    import jax
    import jax.numpy as jnp

    comm = _comm(comm)
    from ..collectives import eager
    from ..collectives.selector import backend_availability
    from ..schedule import compiler as _sched
    from ..schedule import generators as _gen
    from ..schedule.topology import Topology

    backend = (
        "pallas"
        if (
            backend_availability().get("pallas")
            and constants.get("ring_implementation")
            in ("pallas", "pallas_bidir")
        )
        else "ring"
    )
    topo = Topology.from_communicator(comm)
    wire = eager.resolve_wire_dtype(op, nelem, jnp.float32, None)
    okey = _sched.override_key(
        op, topo.fingerprint(), _sched.payload_bucket(nelem * 4), wire
    )
    cands = _gen.candidate_plans(
        op, nelem, 4, topo, backend, wire=wire, route_small=True
    )
    p = comm.size
    x = jnp.ones((p, nelem), jnp.float32)
    jax.block_until_ready(x)
    results: List = []
    best = (float("inf"), None)
    measured = set()
    for cand in cands:
        if not cand.structural:
            continue
        gen = cand.plan.generator
        if gen in measured:
            continue  # xla + custom flat candidates share one generator
        measured.add(gen)
        try:
            ep = _sched.compile_collective(
                op, (p, nelem), jnp.float32, comm,
                generator=gen, impl=backend, wire_override=wire,
            )
            laps = []
            for it in range(warmup + timed):
                t0 = _time.perf_counter()
                out = jax.block_until_ready(ep.execute(x))
                if it >= warmup:
                    laps.append(_time.perf_counter() - t0)
            import numpy as _np

            if not _np.allclose(_np.asarray(out), float(p), rtol=1e-4):
                results.append((gen, None, "incorrect"))
                continue
            mean_us = 1e6 * sum(laps) / max(1, len(laps))
            results.append((gen, mean_us))
            if mean_us < best[0]:
                best = (mean_us, gen)
        except Exception as exc:  # family unrunnable here: skip, keep going
            results.append((gen, None, f"{type(exc).__name__}"))
    winner = best[1] or "flat"
    if apply:
        _sched.set_plan_override(okey, winner)
    _audit_decision(f"plan:{okey}", winner, apply, results)
    return winner, results


def tune_pipeline_depth(
    comm: Optional[Communicator] = None,
    nelem: int = 1 << 20,
    warmup: int = 2,
    timed: int = 4,
    apply: bool = True,
) -> Tuple[int, List]:
    """Measure the chunk-pipeline depths (1, 2, 4, ... per the
    ``plan_pipeline_*`` knobs) for the large flat ring allreduce on THIS
    communicator and pin the fastest CORRECT one as
    ``plan_pipeline_depth`` — persisted per (platform, world size) and
    re-applied by ``start()`` like every tuned knob. The pipeline must
    EARN its depth: on fabrics where per-hop launch overhead beats the
    stage overlap (tiny chunks, alpha-dominated rings) the tuner keeps
    depth 1, which PINS pipelining off; the analytic stage-overlap model
    only decides where no measurement has spoken (the default 0).

    Requires unfrozen constants even with ``apply=False``: the sweep
    pins each depth by temporarily setting ``plan_pipeline_depth``."""
    import time as _time

    import jax
    import jax.numpy as jnp
    import numpy as _np

    comm = _comm(comm)
    _check_unfrozen(apply, measure_mutates=True)
    from ..collectives import eager
    from ..schedule import compiler as _sched
    from ..schedule import pipeline as _pipe

    wire = eager.resolve_wire_dtype("allreduce", nelem, jnp.float32, None)
    depths = [1] + _pipe.depth_candidates(nelem * 4)
    p = comm.size
    x = jnp.ones((p, nelem), jnp.float32)
    jax.block_until_ready(x)
    prev = constants.get("plan_pipeline_depth")
    results: List = []
    best = (float("inf"), 1)
    try:
        for d in depths:
            constants.set("plan_pipeline_depth", int(d))
            ep = _sched.compile_collective(
                "allreduce", (p, nelem), jnp.float32, comm,
                generator="flat", impl="ring", wire_override=wire,
            )
            laps = []
            out = None
            for it in range(warmup + timed):
                t0 = _time.perf_counter()
                out = jax.block_until_ready(ep.execute(x))
                if it >= warmup:
                    laps.append(_time.perf_counter() - t0)
            if not _np.allclose(_np.asarray(out), float(p), rtol=1e-4):
                results.append((d, None, "incorrect"))
                continue
            mean_us = 1e6 * sum(laps) / max(1, len(laps))
            results.append((d, mean_us))
            if mean_us < best[0]:
                best = (mean_us, d)
    finally:
        constants.set("plan_pipeline_depth", prev)
    if apply:
        constants.set("plan_pipeline_depth", int(best[1]))
    _audit_decision("plan_pipeline_depth", int(best[1]), apply, results)
    return int(best[1]), results


def tune_fusion_threshold(
    comm: Optional[Communicator] = None,
    leaf_sizes: Optional[Tuple[int, ...]] = None,
    candidates: Tuple[int, ...] = (0, 1 << 18, 1 << 20, 4 << 20, 16 << 20),
    warmup: int = 2,
    timed: int = 5,
    apply: bool = True,
) -> Tuple[int, List]:
    """Measure the coalescing dispatch (``FusionBuffer``) end-to-end on a
    canonical small-tensor set — default: the LeNet gradient leaves, the
    latency-bound north-star's workload — under candidate
    ``fusion_buffer_bytes`` values, including 0 (coalescing disabled),
    and set the constant to the fastest. Coalescing must EARN its flush
    boundary: a tiny capacity flushes mid-set (several fused dispatches),
    a huge one defers everything to the drain — the measurement, not a
    guess, picks where the knob sits on this host.

    Requires unfrozen constants even with ``apply=False``: each candidate
    is measured by temporarily setting ``fusion_buffer_bytes``."""
    import time as _time

    import jax
    import jax.numpy as jnp

    comm = _comm(comm)
    _check_unfrozen(apply, measure_mutates=True)
    from ..collectives.fusion import get_fusion_buffer

    sizes = tuple(leaf_sizes or LENET_LEAF_SIZES)
    p = comm.size
    xs = [jnp.ones((p, n), jnp.float32) for n in sizes]
    jax.block_until_ready(xs)
    prev = constants.get("fusion_buffer_bytes")
    results: List = []
    best = (float("inf"), prev)
    try:
        for cand in candidates:
            constants.set("fusion_buffer_bytes", int(cand))
            fb = get_fusion_buffer(comm)
            laps = []
            for it in range(warmup + timed):
                t0 = _time.perf_counter()
                handles = [fb.submit("allreduce", x) for x in xs]
                fb.flush_all(reason="explicit")
                outs = [h.wait() for h in handles]
                jax.block_until_ready(outs)
                if it >= warmup:
                    laps.append(_time.perf_counter() - t0)
            mean_us = 1e6 * sum(laps) / max(1, len(laps))
            results.append((int(cand), mean_us))
            if mean_us < best[0]:
                best = (mean_us, int(cand))
    finally:
        constants.set("fusion_buffer_bytes", prev)
    if apply:
        constants.set("fusion_buffer_bytes", int(best[1]))
    _audit_decision("fusion_buffer_bytes", int(best[1]), apply, results)
    return int(best[1]), results


def tune_ps_chunk_bytes(
    comm: Optional[Communicator] = None,
    nelem: int = 1 << 18,
    candidates: Tuple[int, ...] = (0, 1 << 16, 1 << 18, 1 << 20),
    warmup: int = 2,
    timed: int = 5,
    apply: bool = True,
) -> Tuple[int, List]:
    """Measure the PS transport's shard round trip (UPDATE + TRIGGER of an
    ``nelem``-element f32 payload over a real loopback listener/channel —
    the full frame/mailbox/apply path) under candidate ``ps_chunk_bytes``
    values, including 0 (monolithic frames), and set the constant to the
    fastest. The chunk pipeline must EARN its framing overhead: on a
    loopback-fast fabric the monolithic frame can win, on a real DCN the
    encode/wire/decode overlap does — measured here, persisted per
    (platform, world size) like every other knob, re-applied by
    ``start()``.

    Requires unfrozen constants even with ``apply=False``: each candidate
    is measured by temporarily setting ``ps_chunk_bytes``."""
    import time as _time

    comm = _comm(comm)
    _check_unfrozen(apply, measure_mutates=True)
    import numpy as np

    from ..parameterserver import transport as T
    from ..parameterserver.server import _server

    inst = _server.register(np.zeros(nelem, np.float32), 1)
    lst = T._Listener(lambda i: inst if i == inst.id else None)
    ch = T._PeerChannel({0: ("localhost", lst.port)}, 0)
    prev = constants.get("ps_chunk_bytes")
    x = np.random.default_rng(0).standard_normal(nelem).astype(np.float32)
    results: List = []
    best = (float("inf"), prev)
    try:
        for cand in candidates:
            constants.set("ps_chunk_bytes", int(cand))
            laps = []
            for it in range(warmup + timed):
                t0 = _time.perf_counter()
                ch.request(
                    T._KIND_UPDATE, inst.id, 0, 0, rule="copy",
                    payload_arr=x,
                )
                ch.request(T._KIND_TRIGGER, inst.id, 0, 0)
                if it >= warmup:
                    laps.append(_time.perf_counter() - t0)
            mean_us = 1e6 * sum(laps) / max(1, len(laps))
            results.append((int(cand), mean_us))
            if mean_us < best[0]:
                best = (mean_us, int(cand))
    finally:
        constants.set("ps_chunk_bytes", prev)
        ch.close()
        lst.close()
        _server.unregister(inst)
    if apply:
        constants.set("ps_chunk_bytes", int(best[1]))
    _audit_decision("ps_chunk_bytes", int(best[1]), apply, results)
    return int(best[1]), results


def tune_all(
    comm: Optional[Communicator] = None,
    quick: bool = True,
    apply: bool = True,
    persist: bool = True,
) -> Dict[str, object]:
    """Run every tuner and (optionally) persist the resulting constants for
    this (platform, world size). ``quick`` shrinks the sweeps for CI-scale
    runs."""
    comm = _comm(comm)
    _check_unfrozen(apply)
    max_pow = 16 if quick else 20
    big = 1 << (16 if quick else 20)
    out: Dict[str, object] = {}
    out["small_allreduce"] = tune_allreduce_cutoff(
        comm, max_pow=max_pow, apply=apply
    )[0]
    out["small_broadcast"] = tune_broadcast_cutoff(
        comm, max_pow=max_pow, apply=apply
    )[0]
    out["tree_pipeline_switch"] = tune_tree_pipeline_switch(
        comm, max_pow=max_pow + 2, apply=apply
    )[0]
    out["chunk_size"] = tune_chunk_size(comm, nelem=big, apply=apply)[0]
    out["ring_implementation"] = tune_ring_implementation(
        comm, nelem=big, apply=apply
    )[0]
    out["wire_dtype"] = tune_wire_dtype(comm, nelem=big, apply=apply)[0]
    out["plan"] = tune_plan(
        comm, nelem=big, timed=3 if quick else 5, apply=apply
    )[0]
    out["plan_pipeline_depth"] = tune_pipeline_depth(
        comm, nelem=big, timed=3 if quick else 5, apply=apply
    )[0]
    out["fusion_buffer_bytes"] = tune_fusion_threshold(
        comm, timed=3 if quick else 5, apply=apply
    )[0]
    out["ps_chunk_bytes"] = tune_ps_chunk_bytes(
        comm, nelem=big, timed=3 if quick else 5, apply=apply
    )[0]
    if apply and persist:
        save_tuning(comm)
    return out


# ---------------------------------------------------------------------------
# persistence per (platform, world size)
# ---------------------------------------------------------------------------


def _cache_path() -> Path:
    env = os.environ.get("TORCHMPI_TPU_TUNING_CACHE")
    if env:
        return Path(env)
    return Path.home() / ".cache" / "torchmpi_tpu" / "autotune.json"


def _cache_key(comm: Communicator) -> str:
    return f"{comm.devices[0].platform}:{comm.size}"


def save_tuning(comm: Optional[Communicator] = None) -> Path:
    """Persist the current values of every tunable routing constant under
    this (platform, world size).

    Multi-process safe: the write is atomic (temp file + ``os.replace``)
    so a reader or a crash never sees a torn file, and every process
    writes — the cache path is HOST-local (~/.cache), so gating on a
    global rank would leave other hosts' caches empty and their processes
    loading default routing constants on restart (divergent SPMD backend
    choices across controllers). Same-host concurrent writers all persist
    the SAME (platform, size) entry with the same measured values, so
    last-writer-wins is content-identical."""
    comm = _comm(comm)
    path = _cache_path()
    suffix = _suffix(comm)
    names = [t.format(s=suffix) for t in _TUNABLE]
    entry = {n: constants.get(n) for n in names}
    from ..schedule import compiler as _sched

    overrides = _sched.plan_overrides()
    if overrides:
        # measured plan winners (tune_plan) persist alongside the tuned
        # constants and ride the same load path back in at start()
        entry["plan_overrides"] = overrides
    path.parent.mkdir(parents=True, exist_ok=True)
    data = {}
    if path.exists():
        try:
            data = json.loads(path.read_text())
        except Exception:
            data = {}
    data[_cache_key(comm)] = entry
    tmp = path.with_name(f"{path.name}.tmp.{os.getpid()}")
    tmp.write_text(json.dumps(data, indent=2, sort_keys=True))
    os.replace(tmp, path)
    return path


def load_tuning(
    comm: Optional[Communicator] = None, apply: bool = True
) -> Optional[Dict[str, object]]:
    """Load persisted tuning for this (platform, world size); apply it to
    the constants table when ``apply``. Returns the entry or None."""
    comm = _comm(comm)
    path = _cache_path()
    if not path.exists():
        return None
    try:
        data = json.loads(path.read_text())
    except Exception:
        return None
    entry = data.get(_cache_key(comm))
    if not entry:
        return None
    if apply:
        suffix = _suffix(comm)
        valid = {t.format(s=suffix) for t in _TUNABLE}
        applied = {}
        for name, value in entry.items():
            if name in valid:
                try:
                    constants.set(name, value)
                    applied[name] = value
                except Exception:
                    pass  # type drift in an old cache: keep the default
        overrides = entry.get("plan_overrides")
        if isinstance(overrides, dict):
            from ..schedule import compiler as _sched

            applied_plans = _sched.apply_plan_overrides(overrides)
            if applied_plans:
                applied["plan_overrides"] = applied_plans
        telemetry.audit(
            "autotune_load", key=_cache_key(comm), applied=applied
        )
    return entry
