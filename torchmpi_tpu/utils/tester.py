"""Collective correctness/benchmark harness.

Analog of ``torchmpi/tester.lua`` + the measurement protocol of
``test/collectives_all.lua``: size sweep 2^8..2^23 elements with random
jitter (``tester.lua:43-47``), correctness on the first run from closed-form
values (rank r contributes r), benchmark mode = 10 warmup + 10 timed runs
reporting µs and effective bus GB/s from the analytic communication-volume
models (``tester.lua:103-126``, ``collectives_all.lua:313-318``):

- allreduce: ``2 n (p-1)/p`` bytes moved per rank (ring model)
- broadcast / reduce: ``n`` bytes (pipelined model)
- allgather: ``n (p-1)`` bytes
"""

from __future__ import annotations

import time
from dataclasses import dataclass
from typing import Callable, Iterable, List, Optional

import jax
import jax.numpy as jnp
import numpy as np

from .. import collectives
from ..runtime.communicator import Communicator


def sweep_sizes(
    min_pow: int = 8, max_pow: int = 23, jitter_seed: Optional[int] = 0
) -> List[int]:
    """2^min..2^max with the reference's random jitter on each size."""
    rng = np.random.RandomState(jitter_seed)
    sizes = []
    for k in range(min_pow, max_pow + 1):
        base = 1 << k
        jitter = int(rng.randint(0, max(1, base // 8))) if jitter_seed is not None else 0
        sizes.append(base + jitter)
    return sizes


def bus_bytes(op: str, nbytes: int, p: int) -> float:
    """Analytic communication volume per rank (BASELINE.md models)."""
    if op == "allreduce":
        return 2 * nbytes * (p - 1) / p
    if op in ("broadcast", "reduce"):
        return float(nbytes)
    if op == "allgather":
        return float(nbytes * (p - 1))
    if op == "sendreceive":
        return float(nbytes)
    if op in ("reducescatter", "alltoall"):
        # ring RS: each rank forwards (p-1) partial slices of n/p bytes;
        # alltoall: each rank ships (p-1) of its p blocks
        return nbytes * (p - 1) / p
    raise ValueError(op)


@dataclass
class BenchResult:
    op: str
    backend: str
    nelem: int
    mean_us: float
    bus_gbps: float
    correct: bool


_EXPECTED = {
    "allreduce": lambda p, root: p * (p - 1) / 2,
    "broadcast": lambda p, root: float(root),
    "reduce": lambda p, root: p * (p - 1) / 2,  # on root only
}


def run_one_config(
    op: str,
    nelem: int,
    comm: Communicator,
    backend: Optional[str] = None,
    mode: str = "sync",
    benchmark: bool = False,
    warmup: int = 10,
    timed: int = 10,
    root: int = 0,
    route_override: bool = True,
) -> BenchResult:
    """One (op, size, backend, mode) cell of the config matrix
    (``tester.runOneConfig``). Correctness is always checked on the first
    run; benchmark mode adds the timed loop. ``route_override=False`` pins
    the exact backend (disabling the small-size latency rerouting) — needed
    by the autotuner, which measures each path on its own."""
    from ..collectives import eager

    p = comm.size
    if op == "alltoall":
        # [p, p, chunk] rank-addressed blocks, ~nelem elements per rank
        chunk = max(1, nelem // p)
        r_idx = jnp.arange(p, dtype=jnp.float32)
        x = jnp.broadcast_to(
            (100.0 * r_idx[:, None] + r_idx[None, :])[:, :, None],
            (p, p, chunk),
        )
    elif op == "reducescatter":
        n = max(p, -(-max(1, nelem) // p) * p)  # last dim divisible by p
        x = jnp.tile(jnp.arange(p, dtype=jnp.float32)[:, None], (1, n))
    else:
        x = jnp.tile(
            jnp.arange(p, dtype=jnp.float32)[:, None], (1, max(1, nelem))
        )
    pinned = not route_override and backend in ("xla", "ring", "pallas")
    ns = collectives.async_ if mode == "async" else collectives
    if backend and not pinned:
        ns = getattr(ns, backend) if backend != "selector" else ns

    def call():
        if pinned:
            kw = dict(backend=backend, route_small=False)
            if op in ("broadcast", "reduce"):
                kw["root"] = root
            if op == "sendreceive":
                kw.update(src=0, dst=p - 1)
            if mode == "async":
                return eager.run_async(op, x, comm, **kw).wait()
            return eager.run(op, x, comm, **kw)
        if op == "allreduce":
            r = ns.allreduce_tensor(x, comm=comm)
        elif op == "broadcast":
            r = ns.broadcast_tensor(x, root=root, comm=comm)
        elif op == "reduce":
            r = ns.reduce_tensor(x, root=root, comm=comm)
        elif op == "allgather":
            r = ns.allgather_tensor(x, comm=comm)
        elif op == "sendreceive":
            r = ns.sendreceive_tensor(x, src=0, dst=p - 1, comm=comm)
        elif op == "reducescatter":
            r = ns.reducescatter_tensor(x, comm=comm)
        elif op == "alltoall":
            r = ns.alltoall_tensor(x, comm=comm)
        else:
            raise ValueError(op)
        if mode == "async":
            r = r.wait()
        return r

    out = np.asarray(jax.block_until_ready(call()))
    correct = True
    if op in ("allreduce", "broadcast"):
        correct = bool(np.allclose(out, _EXPECTED[op](p, root)))
    elif op == "reduce":
        correct = bool(np.allclose(out[root], p * (p - 1) / 2))
    elif op == "allgather":
        expect = np.repeat(np.arange(p, dtype=np.float32), out.shape[1] // p)
        correct = bool(np.allclose(out[0], expect))
    elif op == "reducescatter":
        correct = bool(np.allclose(out, p * (p - 1) / 2))
    elif op == "alltoall":
        r_idx = np.arange(p, dtype=np.float32)
        expect = 100.0 * r_idx[None, :, None] + r_idx[:, None, None]
        correct = bool(np.allclose(out, expect))

    mean_us = float("nan")
    gbps = float("nan")
    if benchmark:
        for _ in range(warmup):
            call()
        jax.block_until_ready(call())
        t0 = time.perf_counter()
        for _ in range(timed):
            r = call()
        jax.block_until_ready(r)
        dt = (time.perf_counter() - t0) / timed
        mean_us = dt * 1e6
        nbytes = nelem * 4
        gbps = bus_bytes(op, nbytes, p) / dt / 1e9
    return BenchResult(op, backend or "selector", nelem, mean_us, gbps, correct)


def run_matrix(
    comm: Communicator,
    ops: Iterable[str] = ("broadcast", "reduce", "allreduce", "allgather"),
    backends: Iterable[str] = ("xla", "ring"),
    modes: Iterable[str] = ("sync", "async"),
    sizes: Optional[List[int]] = None,
    benchmark: bool = False,
    report: Optional[Callable[[BenchResult], None]] = None,
) -> List[BenchResult]:
    """The full config-matrix sweep (``collectives_all.lua:554-598``).

    Like the reference tester, per-size resources are freed as the sweep
    walks the matrix (``tester.lua:131-133`` frees IPC descriptors between
    sizes): here the per-size resource is the compiled executable, so the
    per-communicator cache is dropped after each op's sweep — the LRU bound
    caps growth within one, the explicit free keeps a long matrix flat."""
    from ..collectives.eager import free_collective_resources

    sizes = sizes or sweep_sizes()
    results = []
    for op in ops:
        for backend in backends:
            for mode in modes:
                for n in sizes:
                    res = run_one_config(
                        op, n, comm, backend, mode, benchmark=benchmark
                    )
                    results.append(res)
                    if report:
                        report(res)
        free_collective_resources(comm)
    return results


def run_ps_throughput(
    comm: Communicator,
    nelem: int = 1 << 20,
    warmup: int = 3,
    timed: int = 10,
):
    """Parameter-server center-traffic throughput: timed client
    ``send('add')`` fan-out (handle completes on APPLIED, the Ssend
    happens-before) and full ``receive`` assembly, reported in MB/s — the
    PS analog of the collectives bus-bandwidth lines, matching the
    reference's chunked clientSend/clientReceive hot path
    (``lib/parameterserver.cpp:309-400``).

    Single-controller runs measure the in-process shard pipeline; under
    multi-controller JAX the same call exercises the cross-process socket
    transport (run the bench example once per process). Returns a dict
    with ``send_mbps``, ``recv_mbps``, ``nbytes``.
    """
    from ..parameterserver.server import ParameterServer

    x = np.ones(nelem, np.float32)
    nbytes = x.nbytes
    ps = ParameterServer(np.zeros(nelem, np.float32), comm=comm)
    try:
        for _ in range(warmup):
            ps.send(x, rule="add").wait()
        t0 = time.perf_counter()
        for _ in range(timed):
            ps.send(x, rule="add").wait()
        send_dt = time.perf_counter() - t0

        for _ in range(warmup):
            ps.receive().wait()
        t0 = time.perf_counter()
        for _ in range(timed):
            ps.receive().wait()
        recv_dt = time.perf_counter() - t0
    finally:
        ps.free()
    return {
        "send_mbps": nbytes * timed / send_dt / 1e6,
        "recv_mbps": nbytes * timed / recv_dt / 1e6,
        "nbytes": nbytes,
    }
