"""Offline checkpoint reshaper.

    python -m torchmpi_tpu.reshard --from N --to M <src-ckpt> <dst-ckpt>

Reshapes a portable sharded checkpoint
(``utils.checkpoint.save_engine_sharded``) from an N-way world onto an
M-way world with bounded memory: source shards are mmap'd, target shards
are preallocated memmaps, and bytes move through ONE
``reshard_chunk_bytes``-sized scratch buffer — the full array is never
materialized, so a terabyte checkpoint reshapes on a laptop. ``--from``
is optional (the checkpoint header knows its world); when given it is
validated against the header, failing loudly on a mismatch.

``--explain`` prints the compiled redistribution plan (the PR 9 schedule
IR) for each leaf instead of writing anything.

Exit codes: 0 success, 2 usage/header error.
"""

from __future__ import annotations

import argparse
import json
import sys


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(
        prog="python -m torchmpi_tpu.reshard",
        description="reshape a sharded checkpoint between world sizes "
        "with bounded memory",
    )
    ap.add_argument("src", help="source sharded checkpoint directory")
    ap.add_argument("dst", nargs="?", default=None,
                    help="destination directory (required unless --explain)")
    ap.add_argument("--from", dest="from_world", type=int, default=None,
                    help="expected source world size (validated against "
                    "the checkpoint header; optional — the header knows)")
    ap.add_argument("--to", dest="to_world", type=int, required=True,
                    help="target world size")
    ap.add_argument("--chunk-bytes", type=int, default=None,
                    help="scratch chunk size (default: the "
                    "reshard_chunk_bytes knob)")
    ap.add_argument("--explain", action="store_true",
                    help="print each leaf's compiled redistribution plan "
                    "+ cost estimate; write nothing")
    ap.add_argument("--json", action="store_true", dest="as_json",
                    help="machine-readable stats output")
    args = ap.parse_args(argv)

    from ..utils import checkpoint as ckpt
    from .core import Layout, build_plan, estimate_us

    try:
        meta = ckpt.read_sharded_meta(args.src)
    except (OSError, ValueError, ckpt.CheckpointMismatchError) as e:
        print(f"reshard: cannot read {args.src}: {e}", file=sys.stderr)
        return 2
    from_world = int(meta["world"])
    if args.from_world is not None and args.from_world != from_world:
        print(
            f"reshard: --from {args.from_world} but {args.src} was saved "
            f"from a {from_world}-way world (header `world`)",
            file=sys.stderr,
        )
        return 2
    if args.to_world < 1:
        print(f"reshard: --to must be >= 1, got {args.to_world}",
              file=sys.stderr)
        return 2

    if args.explain:
        src_l, dst_l = Layout(from_world), Layout(args.to_world)
        for i, rec in enumerate(meta["leaves"]):
            if rec["kind"] == "replicated":
                print(f"leaf {i} {rec['tree']}{rec['path']}: replicated "
                      f"({rec['n']} elements, copied verbatim)")
                continue
            import numpy as np

            plan = build_plan(
                int(rec["n"]), np.dtype(rec["dtype"]).itemsize,
                src_l, dst_l, args.chunk_bytes,
            )
            print(f"leaf {i} {rec['tree']}{rec['path']}: "
                  f"est {estimate_us(plan):.1f}us")
            print("  " + plan.describe().replace("\n", "\n  "))
        return 0

    if args.dst is None:
        print("reshard: a destination directory is required "
              "(or pass --explain)", file=sys.stderr)
        return 2
    stats = ckpt.reshape_sharded(
        args.src, args.dst, args.to_world, chunk_bytes=args.chunk_bytes
    )
    if args.as_json:
        print(json.dumps(stats, indent=2))
    else:
        print(
            f"reshaped {args.src} {stats['from']}-way -> "
            f"{stats['to']}-way at {args.dst}: {stats['leaves']} leaves, "
            f"{stats['moved_bytes']} bytes moved, peak scratch "
            f"{stats['peak_scratch_bytes']}B (largest shard "
            f"{stats['largest_shard_bytes']}B)"
        )
    return 0


if __name__ == "__main__":
    sys.exit(main())
