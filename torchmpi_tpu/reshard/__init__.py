"""Live elastic resharding: move sharded state between any two
``(world size, sharding)`` layouts with bounded memory.

One primitive, three consumers:

- **live world resize** — :meth:`~..engine.AllReduceSGDEngine.resize`
  (in-place fsdp/zero1 shard redistribution over a resized device
  world) and :mod:`.elastic` (cross-process membership: survive rank
  death / operator grow-shrink without relaunching training);
- **checkpoint reshaping** — restore an N-way checkpoint onto an M-way
  world (:mod:`..utils.checkpoint`), also offline via
  ``python -m torchmpi_tpu.reshard --from N --to M``;
- **PS chain re-formation** — re-replicate a surviving shard onto a
  fresh process after a PR 8 failover
  (:meth:`~..parameterserver.ParameterServer.reform`).
"""

from .core import (
    Layout,
    Redistributor,
    Transfer,
    build_plan,
    chunk_spans,
    chunk_transfers,
    compile_reshard,
    estimate_us,
    plan_transfers,
    redistribute_arrays,
    wire_elements,
)

__all__ = [
    "Layout",
    "Redistributor",
    "Transfer",
    "build_plan",
    "chunk_spans",
    "chunk_transfers",
    "compile_reshard",
    "estimate_us",
    "plan_transfers",
    "redistribute_arrays",
    "wire_elements",
]
