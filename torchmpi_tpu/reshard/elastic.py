"""Elastic membership + live cross-process resharding.

The cross-process half of the reshard subsystem: a job whose world can
GROW and SHRINK — rank death included — without relaunching anyone.

Three pieces:

- :class:`ElasticCoordinator` — the membership service (runs inside
  ``launch --elastic``): members join, heartbeat, and barrier through
  it; a member silent for 5 heartbeats is declared dead, an operator
  ``grow``/``shrink`` request adds or evicts a member — every
  membership change publishes a new **epoch** (monotone int) with the
  member list.
- :class:`ElasticMember` — one training process's handle: a control
  connection to the coordinator plus a peer **data plane** (chunked
  binary frames, one listener per member). :meth:`ElasticMember.sync`
  is the *resize barrier*: on an epoch change, survivors agree on the
  new world through the coordinator, then redistribute every registered
  array from the old layout to the new one using the
  :func:`~.core.plan_transfers` schedule — chunked to
  ``reshard_chunk_bytes``, so the transfer memory is one chunk, never a
  full array. Sharded arrays keep a **ring replica** (rank ``r``'s
  shard is mirrored on rank ``r+1``, refreshed every step), which is
  what makes a shard survive its owner's death: the plan's transfer
  sources fall back to the replica holder when the primary is gone.
- :class:`ElasticZero1` — a host-level ZeRO-1 data-parallel SGD
  trainer over the data plane: params replicated, momentum sharded;
  per step a gradient reduce-scatter, a sharded optimizer update, a
  parameter allgather, and the replica refresh. A mid-step membership
  change raises :class:`EpochChanged`; the step is retried against the
  new world after the resize barrier (at most one partially-applied
  step is superseded by the post-resize state agreement — the
  parameters re-sync from the most-advanced survivor, so the loss
  curve continues instead of cold-restoring).

Everything here is numpy + stdlib sockets/threads — no jax, so the
elastic layer works identically on a TPU VM host and in CI.
"""

from __future__ import annotations

import json
import os
import socket
import struct
import threading
import time
from typing import Any, Callable, Dict, List, Optional, Tuple

import numpy as np

from .. import constants
from ..analysis import lockmon as _lockmon
from ..supervise import checkpoints as _checkpoints
from ..telemetry import flightrecorder as _flight
from .core import Layout, chunk_spans, chunk_elems_for, plan_transfers

# data-plane frame kinds
K_SHARD = 1   # resize: a chunk of a target rank's new primary shard
K_FULL = 2    # resize: a chunk of a replicated array (anchor -> all)
K_REPL = 3    # replica: a chunk of a predecessor's primary shard
K_RS = 4      # step: a reduce-scatter contribution chunk
K_AG = 5      # step: an allgather slice chunk

# kind(u8) epoch(u32) src_mid(u32) aid(u16) tag(u32) off(u64) nbytes(u64)
_HDR = struct.Struct("!BIIHIQQ")

_DEAD_BEATS = 5  # heartbeats of silence before a member is declared dead
# epochs of member-list history the coordinator keeps for resolving the
# source layout of survivors whose last committed resize predates the
# current epoch (resize storms). A commit older than the window fails
# LOUDLY at the barrier release (src_members=None -> DataLoss) instead
# of silently redistributing from the wrong layout.
_HISTORY_EPOCHS = 16


class EpochChanged(Exception):
    """The world changed under a collective: retry after the resize
    barrier. Carries the newest epoch this member has heard of."""

    def __init__(self, epoch: int):
        super().__init__(f"membership epoch advanced to {epoch}")
        self.epoch = epoch


class Evicted(Exception):
    """This member is no longer part of the world (operator shrink):
    exit the training loop gracefully."""


class DataLoss(RuntimeError):
    """A shard's primary AND its ring replica died in one epoch — the
    single-fault contract is exhausted. The message names the last
    registered rollback artifact (:mod:`~..supervise.checkpoints`):
    the supervisor's rollback rung and the operator both need the
    checkpoint path and step, not a bare "restore from checkpoint"."""


def _recv_exact(sock: socket.socket, n: int) -> bytes:
    buf = bytearray(n)
    view = memoryview(buf)
    while view:
        got = sock.recv_into(view)
        if got == 0:
            raise ConnectionError("elastic peer closed")
        view = view[got:]
    return bytes(buf)


def _json_roundtrip(addr: Tuple[str, int], req: dict,
                    timeout: float = 60.0) -> dict:
    """One JSON request/reply on a short-lived control connection."""
    with socket.create_connection(addr, timeout=timeout) as s:
        s.settimeout(timeout)
        payload = json.dumps(req).encode()
        s.sendall(struct.pack("!I", len(payload)) + payload)
        n = struct.unpack("!I", _recv_exact(s, 4))[0]
        return json.loads(_recv_exact(s, n))


def operator_request(addr, op: str, timeout: float = 60.0,
                     **extra) -> dict:
    """Operator surface: ``grow`` (spawn + admit one member),
    ``shrink`` (evict the highest-id member), or ``evict`` (evict a
    SPECIFIC member, ``mid=``  — the supervisor's targeted-removal
    primitive). ``addr`` is ``(host, port)`` or ``"host:port"`` (what
    ``launch --elastic`` prints / writes to ``--elastic-addr-file``)."""
    if isinstance(addr, str):
        h, _, p = addr.rpartition(":")
        addr = (h, int(p))
    return _json_roundtrip(addr, {"op": op, **extra}, timeout=timeout)


# ---------------------------------------------------------------------------
# coordinator
# ---------------------------------------------------------------------------


class ElasticCoordinator:
    """Membership + epoch service (one per job; lives in the launcher).

    Thread-per-control-connection (connections are short-lived and the
    member count is small); all state under one lock + condition. Every
    membership change — join, heartbeat death, operator shrink — bumps
    ``epoch`` and re-publishes the sorted member list; the previous
    epoch's list rides along so joiners can compute the redistribution
    plan they are the target of."""

    def __init__(self, host: str = "127.0.0.1", port: int = 0,
                 on_grow: Optional[Callable[[], None]] = None,
                 serve: bool = True,
                 clock: Optional[Callable[[], float]] = None,
                 on_telemetry: Optional[Callable[[dict], None]] = None):
        self._on_grow = on_grow
        # live telemetry piggyback: member heartbeats may carry one
        # bounded exporter frame (launch --elastic --telemetry-live);
        # the hook hands it to the fleet aggregator — zero extra
        # sockets per member
        self._on_telemetry = on_telemetry
        self._now = clock or time.monotonic
        self._lock = _lockmon.make_lock("elastic.py:Coordinator._lock")
        self._cv = threading.Condition(self._lock)
        self._members: Dict[int, dict] = {}
        self._next_mid = 0
        self.epoch = 0
        self._epoch_members: List[int] = []
        self._prev_members: List[int] = []
        self._history: Dict[int, List[int]] = {}
        # (epoch) -> {mid: value} barrier arrivals
        self._barriers: Dict[int, Dict[int, Any]] = {}
        # (epoch) -> the one release reply every arrival shares (the
        # summary is computed ONCE at release, not once per member)
        self._released: Dict[int, dict] = {}
        self._closed = False
        self._srv: Optional[socket.socket] = None
        self.address: Optional[Tuple[str, int]] = None
        if serve:
            self._srv = socket.socket()
            self._srv.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
            self._srv.bind((host, port))
            self._srv.listen(64)
            self.address = self._srv.getsockname()[:2]
            threading.Thread(
                target=self._accept_loop, name="tm-elastic-coord",
                daemon=True,
            ).start()
            threading.Thread(
                target=self._monitor_loop, name="tm-elastic-mon",
                daemon=True,
            ).start()

    # -- internals ---------------------------------------------------------
    def _bump_epoch_locked(self) -> None:
        self._prev_members = self._epoch_members
        self.epoch += 1
        self._epoch_members = sorted(self._members)
        self._barriers.pop(self.epoch - 1, None)
        self._released.pop(self.epoch - 1, None)
        # bounded epoch->members history: a resize aborted by a SECOND
        # membership change leaves survivors laid out per the epoch they
        # last COMMITTED ("was" in the barrier value) — which may be
        # older than epoch-1, so `prev` alone cannot name their layout.
        # The history stays coordinator-internal: the barrier release
        # resolves the source member list and ships it in the summary,
        # so views no longer carry (and re-serialize, per member, per
        # fetch) the whole table.
        self._history[self.epoch] = self._epoch_members
        while len(self._history) > _HISTORY_EPOCHS:
            del self._history[min(self._history)]
        self._cv.notify_all()

    def _view_locked(self) -> dict:
        return {
            "epoch": self.epoch,
            "members": [
                [m, self._members[m]["host"], self._members[m]["data_port"]]
                for m in self._epoch_members
            ],
            "prev": list(self._prev_members),
        }

    def _accept_loop(self) -> None:
        while not self._closed:
            try:
                conn, _ = self._srv.accept()
            except OSError:
                return
            threading.Thread(
                target=self._serve, args=(conn,), daemon=True
            ).start()

    def _serve(self, conn: socket.socket) -> None:
        try:
            with conn:
                conn.settimeout(600)
                n = struct.unpack("!I", _recv_exact(conn, 4))[0]
                req = json.loads(_recv_exact(conn, n))
                reply = self._handle(req)
                payload = json.dumps(reply).encode()
                conn.sendall(struct.pack("!I", len(payload)) + payload)
        except (OSError, ValueError, struct.error):
            pass

    def _handle(self, req: dict) -> dict:
        op = req.get("op")
        if op == "beat" and self._on_telemetry is not None:
            # forward the piggybacked telemetry frame OUTSIDE the
            # membership lock: the aggregator takes its own lock and
            # must never serialize against epoch bumps
            tel = req.get("telemetry")
            if isinstance(tel, dict):
                try:
                    self._on_telemetry(tel)
                except Exception:  # noqa: BLE001 - telemetry must never
                    pass           # break membership liveness
        with self._cv:
            if op == "join":
                mid = self._next_mid
                self._next_mid += 1
                self._members[mid] = {
                    "host": req["host"],
                    "data_port": int(req["data_port"]),
                    "beat": self._now(),
                }
                self._bump_epoch_locked()
                return {"mid": mid, **self._view_locked()}
            if op == "beat":
                m = self._members.get(req["mid"])
                if m is not None:
                    m["beat"] = self._now()
                return {"epoch": self.epoch,
                        "member": req["mid"] in self._members}
            if op == "view":
                return self._view_locked()
            if op == "leave":
                if self._members.pop(req["mid"], None) is not None:
                    self._bump_epoch_locked()
                return {"ok": True}
            if op == "shrink":
                if len(self._members) <= 1:
                    return {"ok": False, "error": "cannot shrink below 1"}
                victim = max(self._members)
                del self._members[victim]
                self._bump_epoch_locked()
                return {"ok": True, "evicted": victim,
                        "epoch": self.epoch}
            if op == "evict":
                # targeted eviction (the supervisor's remediation for
                # named members), ``mid`` or ``mids``: the whole wave is
                # ONE membership change — one epoch bump, one resize —
                # exactly like sweep_dead (per-corpse epochs would leave
                # barrier-less epoch gaps the analyzer reads as desync).
                # Idempotent: evicting an absent member is success, the
                # goal state ("not a member") already holds.
                want = req.get("mids")
                if want is None:
                    want = [req.get("mid")]
                victims = [m for m in want if m in self._members]
                if not victims:
                    return {"ok": True, "evicted": [],
                            "epoch": self.epoch}
                if len(victims) >= len(self._members):
                    return {"ok": False, "error": "cannot evict below 1"}
                for m in victims:
                    del self._members[m]
                self._bump_epoch_locked()
                return {"ok": True, "evicted": sorted(victims),
                        "epoch": self.epoch}
            if op == "barrier":
                return self._barrier_locked(req)
        if op == "grow":
            if self._on_grow is None:
                return {"ok": False, "error": "no grow hook"}
            self._on_grow()  # the new member's join bumps the epoch
            return {"ok": True}
        return {"ok": False, "error": f"unknown op {op!r}"}

    def _release_locked(self, epoch: int, arrived: Dict[int, Any]) -> dict:
        """Compute the ONE release reply every barrier member shares:
        the resize agreement (stateful set, committed source epoch and
        its member list, anchor, agreed resume step) aggregated HERE
        instead of shipping every member's raw value to every member —
        the per-member reply stays O(world), not O(world) dicts, and the
        anchor/agreed-step selection runs once instead of N times."""
        stateful = sorted(
            m for m, v in arrived.items() if (v or {}).get("stateful")
        )
        was = sorted({
            int((arrived[m] or {}).get("was", -1)) for m in stateful
        })
        summary: Dict[str, Any] = {
            "stateful": stateful, "was": was,
            "anchor": None, "step": 0, "src_members": [],
        }
        if len(was) == 1:
            src = self._history.get(was[0])
            if src is None:
                src = self._prev_members
                if 0 <= was[0] < self.epoch - 1:
                    # the survivors' committed layout predates the
                    # bounded history window (a resize storm outlasted
                    # it): naming ANY other list would silently
                    # redistribute from the wrong layout — fail loudly
                    summary["src_unresolved"] = True
            summary["src_members"] = list(src)
            members = set(self._epoch_members)
            survivors = [
                m for m in src if m in members and m in set(stateful)
            ]
            if survivors and not summary.get("src_unresolved"):
                anchor = max(
                    survivors,
                    key=lambda m: (
                        int((arrived[m] or {}).get("step", 0)), -m
                    ),
                )
                summary["anchor"] = anchor
                summary["step"] = int(
                    (arrived[anchor] or {}).get("step", 0)
                )
        return {"ok": True, "summary": summary}

    def _barrier_arrive_locked(self, mid: int, epoch: int,
                               value=None) -> Optional[dict]:
        if epoch in self._released:
            return self._released[epoch]
        if epoch != self.epoch or mid not in self._members:
            return {"stale": True, "epoch": self.epoch}
        arrived = self._barriers.setdefault(epoch, {})
        arrived[mid] = value
        # arrivals are gated on current membership above, so counting
        # suffices until the counts match — the O(world) set comparison
        # runs once at the release, not once per arrival (at 10k ranks
        # the per-arrival form is an O(world^2) barrier)
        if len(arrived) >= len(self._epoch_members) and (
            set(arrived) >= set(self._epoch_members)
        ):
            rel = self._release_locked(epoch, arrived)
            self._released[epoch] = rel
        self._cv.notify_all()
        return self._released.get(epoch)

    def barrier_arrive(self, mid: int, epoch: int, value=None
                       ) -> Optional[dict]:
        """Non-blocking barrier arrival (the sim's entry point; the
        threaded ``_barrier_locked`` wraps it). Returns the stale reply,
        the shared release reply (when this arrival completes the set),
        or None while the barrier is still filling."""
        with self._cv:
            return self._barrier_arrive_locked(mid, epoch, value)

    def _barrier_poll_locked(self, epoch: int) -> Optional[dict]:
        if epoch in self._released:
            return self._released[epoch]
        if self.epoch != epoch:
            return {"stale": True, "epoch": self.epoch}
        return None

    def barrier_poll(self, epoch: int) -> Optional[dict]:
        """The non-blocking side of a pending arrival: the release reply
        once every member arrived, a stale reply after an epoch bump,
        None while still filling."""
        with self._cv:
            return self._barrier_poll_locked(epoch)

    def _barrier_locked(self, req: dict) -> dict:
        """Blocking barrier (socket control plane; self._cv HELD)."""
        mid, epoch = int(req["mid"]), int(req["epoch"])
        deadline = self._now() + float(req.get(
            "timeout", constants.get("elastic_barrier_timeout_s")
        ))
        rep = self._barrier_arrive_locked(mid, epoch, req.get("value"))
        while rep is None:
            if not self._cv.wait(min(1.0, deadline - self._now())):
                if self._now() >= deadline:
                    return {"stale": True, "epoch": self.epoch,
                            "timeout": True}
            rep = self._barrier_poll_locked(epoch)
        return rep

    def sweep_dead(self, hb: Optional[float] = None) -> List[int]:
        """Evict members whose heartbeat is older than ``_DEAD_BEATS``
        periods; one epoch bump covers the whole sweep (a death WAVE is
        one membership change, not one resize per corpse). Returns the
        evicted mids. Called by the monitor thread; the sim calls it on
        its virtual clock."""
        if hb is None:
            hb = float(constants.get("elastic_heartbeat_seconds"))
        cutoff = self._now() - _DEAD_BEATS * hb
        with self._cv:
            dead = [m for m, info in self._members.items()
                    if info["beat"] < cutoff]
            for m in dead:
                del self._members[m]
            if dead:
                self._bump_epoch_locked()
        return dead

    def bulk_join(self, specs: List[Tuple[str, int]]) -> List[int]:
        """Admit a cohort in one membership change: N joins, ONE epoch
        bump (serial joins pay an O(N log N) member sort per join — a
        10k-rank formation is 10k epochs and ~N^2 log N work). Used by
        the fleet simulator's formation; returns the assigned mids."""
        with self._cv:
            mids = []
            for host, data_port in specs:
                mid = self._next_mid
                self._next_mid += 1
                self._members[mid] = {
                    "host": host,
                    "data_port": int(data_port),
                    "beat": self._now(),
                }
                mids.append(mid)
            if mids:
                self._bump_epoch_locked()
        return mids

    def _monitor_loop(self) -> None:
        while not self._closed:
            hb = float(constants.get("elastic_heartbeat_seconds"))
            time.sleep(hb)
            self.sweep_dead(hb)

    def members(self) -> List[int]:
        with self._cv:
            return list(self._epoch_members)

    def close(self) -> None:
        self._closed = True
        if self._srv is not None:
            try:
                self._srv.close()
            except OSError:
                pass


# ---------------------------------------------------------------------------
# elastic state: the arrays a member carries across resizes
# ---------------------------------------------------------------------------


class _Entry:
    __slots__ = ("name", "kind", "init", "n", "dtype",
                 "full", "shard", "replica")

    def __init__(self, name: str, kind: str, init: np.ndarray):
        self.name = name
        self.kind = kind
        self.init = np.ascontiguousarray(init).reshape(-1)
        self.n = int(self.init.shape[0])
        self.dtype = self.init.dtype
        self.full: Optional[np.ndarray] = None      # replicated arrays
        self.shard: Optional[np.ndarray] = None     # my primary shard
        self.replica: Optional[np.ndarray] = None   # predecessor's mirror


class ElasticState:
    """The named arrays that survive resizes. ``kind``:

    - ``'replicated'`` — every member holds the full array (params);
      on resize, re-synced from the agreed anchor member.
    - ``'sharded'`` — contiguous :class:`~.core.Layout` shard per
      member (optimizer state), plus the ring replica of the
      predecessor's shard (refreshed each step) that makes one death
      survivable.

    ``init`` arrays must be identical on every member (deterministic
    init) — the cold-attach path scatters them without any traffic."""

    def __init__(self):
        self.entries: Dict[str, _Entry] = {}
        self.initialized = False

    def add(self, name: str, init, kind: str = "sharded") -> None:
        if kind not in ("sharded", "replicated"):
            raise ValueError(f"kind must be sharded|replicated, got {kind!r}")
        self.entries[name] = _Entry(name, kind, np.asarray(init))

    def names(self) -> List[str]:
        return sorted(self.entries)

    def aid(self, name: str) -> int:
        return self.names().index(name)


# ---------------------------------------------------------------------------
# member
# ---------------------------------------------------------------------------


class _View:
    __slots__ = ("epoch", "members", "prev")

    def __init__(self, d: dict):
        self.epoch = int(d["epoch"])
        self.members = [(int(m), h, int(p)) for m, h, p in d["members"]]
        self.prev = [int(m) for m in d.get("prev", [])]

    def mids(self) -> List[int]:
        return [m for m, _, _ in self.members]

    def rank_of(self, mid: int) -> int:
        return self.mids().index(mid)

    def addr_of(self, mid: int) -> Tuple[str, int]:
        for m, h, p in self.members:
            if m == mid:
                return (h, p)
        raise KeyError(mid)


class ElasticMember:
    """One process's elastic handle: control plane + peer data plane.

    The data plane is a tiny framed protocol: each frame carries
    ``(kind, epoch, src_mid, array id, tag, offset, bytes)`` and lands
    in an inbox the reader threads always drain — so a peer's send can
    never deadlock against ours. Frames below the epoch being resized
    to are dropped on arrival (stale world); frames ahead of us are
    buffered (a peer may enter the next epoch first)."""

    def __init__(self, coordinator, state: ElasticState,
                 host: str = "127.0.0.1"):
        if isinstance(coordinator, ElasticCoordinator):
            coordinator = coordinator.address
        if isinstance(coordinator, str):
            h, _, p = coordinator.rpartition(":")
            coordinator = (h, int(p))
        self.coord = (coordinator[0], int(coordinator[1]))
        self.state = state
        self._cv = threading.Condition(
            _lockmon.make_lock("elastic.py:Member._cv")
        )
        self._inbox: List[tuple] = []
        self._accept_epoch = 0
        self._known_epoch = 0
        self._evicted = False
        self._closed = False
        self._view: Optional[_View] = None
        self._addrs: Dict[int, Tuple[str, int]] = {}
        self._send_locks: Dict[int, threading.Lock] = {}
        self._conns: Dict[int, socket.socket] = {}
        self._conn_guard = _lockmon.make_lock("elastic.py:Member._conns")
        self.last_resize_stats: Dict[str, Any] = {}
        # called with the agreed resume step AFTER the resize barrier
        # but BEFORE redistribution: a trainer uses it to reconcile a
        # torn step the anchor committed but this member did not (the
        # missed-apply counterpart of the staged-commit no-double-apply
        # rule — see ElasticZero1._apply_stash)
        self.on_agreed_step: Optional[Callable[[int], None]] = None
        self._srv = socket.socket()
        self._srv.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
        self._srv.bind((host, 0))
        self._srv.listen(64)
        self.data_port = self._srv.getsockname()[1]
        threading.Thread(
            target=self._accept_loop, name="tm-elastic-data", daemon=True
        ).start()
        rep = _json_roundtrip(
            self.coord,
            {"op": "join", "host": host, "data_port": self.data_port},
        )
        self.mid = int(rep["mid"])
        self._note_epoch(int(rep["epoch"]))
        threading.Thread(
            target=self._beat_loop, name="tm-elastic-beat", daemon=True
        ).start()

    # -- data plane --------------------------------------------------------
    def _accept_loop(self) -> None:
        while not self._closed:
            try:
                conn, _ = self._srv.accept()
            except OSError:
                return
            threading.Thread(
                target=self._read_loop, args=(conn,), daemon=True
            ).start()

    def _read_loop(self, conn: socket.socket) -> None:
        try:
            with conn:
                while not self._closed:
                    hdr = _HDR.unpack(_recv_exact(conn, _HDR.size))
                    payload = _recv_exact(conn, hdr[6]) if hdr[6] else b""
                    with self._cv:
                        if hdr[1] >= self._accept_epoch:
                            self._inbox.append((hdr, payload))
                            self._cv.notify_all()
        except (ConnectionError, OSError, struct.error):
            pass

    def _send(self, mid: int, kind: int, epoch: int, aid: int, tag: int,
              off: int, payload) -> None:
        """One frame to a peer; reconnects once on a broken pipe. A peer
        that stays unreachable raises — the caller's epoch poll turns
        that into an EpochChanged retry once the coordinator notices."""
        if self._closed:
            # a closed member is DEAD to the world: it must go silent,
            # not keep half-feeding peers frames that let them partially
            # complete a step the resize is about to supersede
            raise ConnectionError("elastic member is closed")
        data = bytes(payload)
        with self._conn_guard:
            lock = self._send_locks.setdefault(
                mid, _lockmon.make_lock("elastic.py:Member._send_locks[]")
            )
        for attempt in (0, 1):
            with lock:
                try:
                    with self._conn_guard:
                        sock = self._conns.get(mid)
                    if sock is None:
                        sock = socket.create_connection(
                            self._addrs[mid], timeout=30
                        )
                        with self._conn_guard:
                            self._conns[mid] = sock
                    sock.sendall(
                        _HDR.pack(kind, epoch, self.mid, aid, tag, off,
                                  len(data)) + data
                    )
                    return
                except (OSError, KeyError) as e:
                    with self._conn_guard:
                        dead = self._conns.pop(mid, None)
                    if dead is not None:
                        try:
                            dead.close()
                        except OSError:
                            pass
                    if attempt:
                        raise ConnectionError(
                            f"elastic peer {mid} unreachable: {e}"
                        ) from e

    def _send_chunked(self, mid: int, kind: int, epoch: int, aid: int,
                      tag: int, base_off: int, arr: np.ndarray) -> int:
        """Chunk ``arr`` by ``reshard_chunk_bytes`` — the one bounded-
        memory rule every elastic byte obeys. Returns the peak chunk
        size in bytes (the caller's scratch-bound evidence)."""
        celems = chunk_elems_for(arr.dtype.itemsize)
        peak = 0
        for s, e in chunk_spans(arr.shape[0], celems):
            chunk = np.ascontiguousarray(arr[s:e])
            peak = max(peak, chunk.nbytes)
            self._send(mid, kind, epoch, aid, tag, base_off + s,
                       chunk.tobytes())
        return peak

    def _take(self, epoch: int, pred, deadline: float) -> tuple:
        """Pop the first inbox frame matching ``pred``; while waiting,
        an epoch advance raises EpochChanged (the mid-collective escape
        that turns a peer death into a retry instead of a hang)."""
        with self._cv:
            while True:
                for i, (hdr, payload) in enumerate(self._inbox):
                    if hdr[1] == epoch and pred(hdr):
                        del self._inbox[i]
                        return hdr, payload
                if self._known_epoch > epoch:
                    raise EpochChanged(self._known_epoch)
                if self._evicted:
                    raise Evicted()
                if not self._cv.wait(timeout=0.25):
                    if time.monotonic() > deadline:
                        raise TimeoutError(
                            f"elastic collective starved at epoch {epoch}"
                        )

    # -- control plane -----------------------------------------------------
    def _note_epoch(self, epoch: int) -> None:
        with self._cv:
            if epoch > self._known_epoch:
                self._known_epoch = epoch
                self._cv.notify_all()

    def _beat_loop(self) -> None:
        while not self._closed:
            time.sleep(float(constants.get("elastic_heartbeat_seconds")))
            req: dict = {"op": "beat", "mid": self.mid}
            exp = None
            try:
                # live telemetry piggyback: when the exporter is armed
                # in carrier mode (launch --elastic --telemetry-live),
                # each beat carries one bounded frame to the
                # coordinator-resident aggregator
                from ..telemetry import live as _live

                tel = _live.heartbeat_frame()
                if tel is not None:
                    req["telemetry"] = tel
                    exp = _live.exporter()
            except Exception:  # noqa: BLE001 - beats outrank telemetry
                pass
            try:
                rep = _json_roundtrip(self.coord, req, timeout=10)
            except (OSError, ValueError):
                if exp is not None:
                    # the frame never arrived: break the delta chain so
                    # the next beat ships a full snapshot
                    exp.mark_dropped()
                continue
            self._note_epoch(int(rep["epoch"]))
            if not rep.get("member", True):
                with self._cv:
                    self._evicted = True
                    self._cv.notify_all()

    def _fetch_view(self) -> _View:
        view = _View(_json_roundtrip(self.coord, {"op": "view"}))
        self._note_epoch(view.epoch)
        return view

    @property
    def epoch(self) -> int:
        return self._view.epoch if self._view is not None else 0

    def epoch_changed(self) -> bool:
        return self._known_epoch > self.epoch or self._evicted

    def wait_world(self, n: int, timeout: float = 120.0) -> None:
        """Block until the membership holds >= n members (initial
        formation; call before the first :meth:`sync`)."""
        deadline = time.monotonic() + timeout
        while True:
            view = self._fetch_view()
            if len(view.members) >= n:
                return
            if time.monotonic() > deadline:
                raise TimeoutError(
                    f"elastic world never reached {n} members "
                    f"(have {len(view.members)})"
                )
            time.sleep(0.05)

    # -- the resize barrier ------------------------------------------------
    def sync(self, step: int = 0) -> dict:
        """The resize barrier: cheap no-op while the epoch is unchanged;
        on a membership change, agree on the new world via the
        coordinator barrier and redistribute every registered array.
        Returns ``{"epoch", "rank", "world", "step", "resized"}`` —
        ``step`` is the agreed resume step (the max completed step any
        stateful survivor reported) after a resize, else the caller's.

        Raises :class:`Evicted` when this member was shrunk away."""
        if self._evicted:
            raise Evicted()
        if (
            self.state.initialized
            and self._view is not None
            and self._known_epoch == self._view.epoch
        ):
            return {
                "epoch": self._view.epoch,
                "rank": self._view.rank_of(self.mid),
                "world": len(self._view.members),
                "step": step,
                "resized": False,
            }
        while True:
            try:
                return self._resize(step)
            except EpochChanged:
                continue
            except ConnectionError:
                # a peer died mid-resize: wait for the coordinator to
                # publish the post-death epoch, then redo the barrier
                target = self._known_epoch
                deadline = time.monotonic() + 60
                while self._known_epoch <= target:
                    if self._evicted:
                        raise Evicted() from None
                    if time.monotonic() > deadline:
                        raise
                    time.sleep(0.05)

    def _resize(self, step: int) -> dict:
        view = self._fetch_view()
        if self.mid not in view.mids():
            with self._cv:
                self._evicted = True
            raise Evicted()
        epoch = view.epoch
        with self._cv:
            # accept the new epoch's frames from now on; drop stale ones
            self._accept_epoch = epoch
            self._inbox = [f for f in self._inbox if f[0][1] >= epoch]
        # address book of the world being resized TO (joiners are not in
        # the old view); stale per-mid sockets reconnect lazily
        self._addrs.update({m: (h, p) for m, h, p in view.members})
        entry = None
        if _flight.enabled():
            entry = _flight.recorder.record(
                "resize", "resize.enter",
                payload=f"{len(view.prev)}->{len(view.members)}",
                backend="elastic", routing=f"mid={self.mid}", seq=epoch,
            )
        t0 = time.monotonic()
        barrier_s = float(constants.get("elastic_barrier_timeout_s"))
        rep = _json_roundtrip(self.coord, {
            "op": "barrier", "mid": self.mid, "epoch": epoch,
            "timeout": barrier_s,
            "value": {"step": int(step),
                      "stateful": bool(self.state.initialized),
                      "was": self._view.epoch if self._view else -1},
        }, timeout=barrier_s + 30)
        if rep.get("stale"):
            self._note_epoch(int(rep["epoch"]))
            if entry is not None:
                _flight.FlightRecorder.fail(entry)
            raise EpochChanged(int(rep["epoch"]))
        summary = rep["summary"]
        stateful = {int(m) for m in summary["stateful"]}
        stats: Dict[str, Any] = {
            "epoch": epoch, "old_world": len(view.prev),
            "new_world": len(view.members), "peak_chunk_bytes": 0,
            "largest_shard_bytes": 0, "wire_bytes": 0, "cold": False,
        }
        if not stateful:
            self._cold_attach(view)
            stats["cold"] = True
            agreed = 0
        else:
            agreed = self._redistribute(view, summary, stateful, stats)
        self._view = view
        self.state.initialized = True
        stats["seconds"] = time.monotonic() - t0
        self.last_resize_stats = stats
        try:
            if epoch > int(constants.get("resize_epoch")):
                # one set() = one generation() bump: every generation-
                # stamped cache in this process invalidates coherently
                constants.set("resize_epoch", epoch)
        except constants.FrozenConstantsError:
            pass
        if entry is not None:
            _flight.FlightRecorder.complete(entry)
        return {
            "epoch": epoch,
            "rank": view.rank_of(self.mid),
            "world": len(view.members),
            "step": agreed,
            "resized": True,
        }

    def _cold_attach(self, view: _View) -> None:
        """First stable epoch: scatter the deterministic init arrays —
        identical on every member, so zero bytes move."""
        k, r = len(view.members), view.rank_of(self.mid)
        for e in self.state.entries.values():
            if e.kind == "replicated":
                e.full = e.init.copy()
            else:
                lay = Layout(k)
                s, en = lay.interval(e.n, r)
                e.shard = e.init[s:en].copy()
                ps, pe = lay.interval(e.n, (r - 1) % k)
                e.replica = e.init[ps:pe].copy() if k > 1 else None

    def _redistribute(self, view: _View, summary: Dict[str, Any],
                      stateful: set, stats: Dict[str, Any]) -> int:
        """Move every array from the previous epoch's layout to the new
        one. Transfer sources resolve to the primary holder when it
        survived, else to its ring-replica holder (the single-death
        contract); the joiningest member is a pure receiver. Replicated
        arrays re-sync from the anchor — the stateful survivor with the
        highest completed step (resolved ONCE by the coordinator at the
        barrier release) — which also defines the agreed resume step,
        superseding any step the death tore mid-collective."""
        epoch = view.epoch
        mids = view.mids()
        # the SOURCE layout is the world the survivors last COMMITTED —
        # normally epoch-1 (== view.prev), but a resize aborted by a
        # second membership change leaves them on an older epoch, whose
        # member list only the coordinator's history knows (the barrier
        # summary carries it). Mixed commit epochs (some members
        # finished the aborted resize) cannot be redistributed
        # coherently: fail loudly.
        was = summary.get("was", [])
        if len(was) > 1:
            raise DataLoss(
                f"epoch {epoch}: survivors hold mixed resize layouts "
                f"(committed epochs {sorted(was)}) after an aborted "
                f"resize — {_checkpoints.describe_last()}"
            )
        if summary.get("src_unresolved"):
            raise DataLoss(
                f"epoch {epoch}: survivors' committed layout (epoch "
                f"{was[0]}) predates the coordinator's membership "
                f"history — {_checkpoints.describe_last()}"
            )
        prev = [int(m) for m in summary.get("src_members", [])] or view.prev
        k_old, k_new = len(prev), len(mids)
        r_new = view.rank_of(self.mid)
        deadline = time.monotonic() + float(
            constants.get("elastic_barrier_timeout_s")
        )
        anchor = summary.get("anchor")
        if anchor is None:
            raise DataLoss(
                f"epoch {epoch}: no stateful survivor from {prev} — "
                f"{_checkpoints.describe_last()}"
            )
        anchor = int(anchor)
        agreed = int(summary.get("step", 0))
        if self.on_agreed_step is not None:
            # reconcile BEFORE any transfer reads this member's shards:
            # if the anchor committed the step this member tore, the
            # staged update commits now, so every redistribution source
            # is on the agreed step
            self.on_agreed_step(agreed)

        def live_src(old_rank: int) -> Tuple[int, bool]:
            """(member, from_replica) serving old shard ``old_rank``."""
            m = prev[old_rank]
            if m in mids and m in stateful:
                return m, False
            holder = prev[(old_rank + 1) % k_old]
            if holder in mids and holder in stateful and k_old > 1:
                return holder, True
            raise DataLoss(
                f"shard {old_rank}: primary {m} and replica holder "
                f"{prev[(old_rank + 1) % k_old]} both gone in epoch "
                f"{epoch} — {_checkpoints.describe_last()}"
            )

        # STAGED commit: nothing overwrites a source buffer until every
        # array landed — a resize attempt aborted by a second membership
        # change (EpochChanged/ConnectionError mid-transfer) must leave
        # the old-layout shards intact for the retry's plan to read
        staged: Dict[str, tuple] = {}
        for aid, name in enumerate(self.state.names()):
            e = self.state.entries[name]
            itemsize = e.dtype.itemsize
            if e.kind == "replicated":
                if self.mid == anchor:
                    for m in mids:
                        if m != self.mid:
                            stats["peak_chunk_bytes"] = max(
                                stats["peak_chunk_bytes"],
                                self._send_chunked(
                                    m, K_FULL, epoch, aid, 0, 0, e.full
                                ),
                            )
                            stats["wire_bytes"] += e.full.nbytes
                else:
                    buf = np.empty(e.n, e.dtype)
                    got = 0
                    while got < buf.nbytes:
                        hdr, payload = self._take(
                            epoch,
                            lambda h, a=aid: h[0] == K_FULL and h[3] == a,
                            deadline,
                        )
                        off = hdr[5]
                        part = np.frombuffer(payload, e.dtype)
                        buf[off:off + part.shape[0]] = part
                        got += len(payload)
                        stats["peak_chunk_bytes"] = max(
                            stats["peak_chunk_bytes"], len(payload)
                        )
                    stats["wire_bytes"] += got
                    staged[name] = ("full", buf)
                continue

            lay_old, lay_new = Layout(k_old), Layout(k_new)
            transfers = plan_transfers(e.n, lay_old, lay_new)
            my_s, my_e = lay_new.interval(e.n, r_new)
            new_shard = np.empty(max(0, my_e - my_s), e.dtype)
            stats["largest_shard_bytes"] = max(
                stats["largest_shard_bytes"],
                max(
                    (en - s) * itemsize
                    for lay, kk in ((lay_old, k_old), (lay_new, k_new))
                    for s, en in lay.intervals(e.n)
                ),
            )
            expect = 0
            for t in transfers:
                src_m, from_replica = live_src(t.src)
                dst_m = mids[t.dst]
                if src_m == self.mid:
                    src_buf = e.replica if from_replica else e.shard
                    view_src = src_buf[t.src_off:t.src_off + t.n]
                    if dst_m == self.mid:
                        new_shard[t.dst_off:t.dst_off + t.n] = view_src
                    else:
                        stats["peak_chunk_bytes"] = max(
                            stats["peak_chunk_bytes"],
                            self._send_chunked(
                                dst_m, K_SHARD, epoch, aid, 0, t.dst_off,
                                view_src,
                            ),
                        )
                        stats["wire_bytes"] += t.n * itemsize
                elif dst_m == self.mid:
                    expect += t.n * itemsize
            got = 0
            while got < expect:
                hdr, payload = self._take(
                    epoch, lambda h, a=aid: h[0] == K_SHARD and h[3] == a,
                    deadline,
                )
                off = hdr[5]
                part = np.frombuffer(payload, e.dtype)
                new_shard[off:off + part.shape[0]] = part
                got += len(payload)
                stats["peak_chunk_bytes"] = max(
                    stats["peak_chunk_bytes"], len(payload)
                )
            stats["wire_bytes"] += got
            # ring-replica re-formation on the NEW world: my fresh shard
            # mirrors to my successor; my predecessor's mirrors here
            rep_buf = None
            if k_new > 1:
                succ = mids[(r_new + 1) % k_new]
                self._send_chunked(
                    succ, K_REPL, epoch, aid, 0, 0, new_shard
                )
                ps, pe = lay_new.interval(e.n, (r_new - 1) % k_new)
                rep_buf = np.empty(max(0, pe - ps), e.dtype)
                got = 0
                while got < rep_buf.nbytes:
                    hdr, payload = self._take(
                        epoch,
                        lambda h, a=aid: h[0] == K_REPL and h[3] == a
                        and h[4] == 0,
                        deadline,
                    )
                    off = hdr[5]
                    part = np.frombuffer(payload, e.dtype)
                    rep_buf[off:off + part.shape[0]] = part
                    got += len(payload)
            staged[name] = ("shard", new_shard, rep_buf)
        for name, ent in staged.items():
            e = self.state.entries[name]
            if ent[0] == "full":
                e.full = ent[1]
            else:
                e.shard, e.replica = ent[1], ent[2]
        return agreed

    # -- step collectives (the host-zero1 data plane) ----------------------
    def reduce_scatter_sum(self, vec: np.ndarray, step: int,
                           timeout: float = 120.0) -> np.ndarray:
        """Sum ``vec`` across members, returning MY Layout slice of the
        sum. Chunked sends to every peer's slice; contributions
        accumulate as they arrive."""
        view = self._view
        epoch, k = view.epoch, len(view.members)
        r = view.rank_of(self.mid)
        lay = Layout(k)
        vec = np.ascontiguousarray(vec)
        s, e = lay.interval(vec.shape[0], r)
        acc = vec[s:e].astype(vec.dtype, copy=True)
        deadline = time.monotonic() + timeout
        for dst, (ds, de) in enumerate(lay.intervals(vec.shape[0])):
            if dst == r or de <= ds:
                continue
            self._send_chunked(
                view.members[dst][0], K_RS, epoch, 0, step, 0,
                vec[ds:de],
            )
        expect = (k - 1) * acc.nbytes
        got = 0
        while got < expect:
            hdr, payload = self._take(
                epoch, lambda h: h[0] == K_RS and h[4] == step, deadline
            )
            part = np.frombuffer(payload, vec.dtype)
            off = hdr[5]
            acc[off:off + part.shape[0]] += part
            got += len(payload)
        return acc

    def allgather(self, out: np.ndarray, my_slice: np.ndarray, step: int,
                  timeout: float = 120.0) -> None:
        """Fill ``out`` with every member's Layout slice; ``my_slice``
        is this rank's contribution (offsets are GLOBAL)."""
        view = self._view
        epoch, k = view.epoch, len(view.members)
        r = view.rank_of(self.mid)
        lay = Layout(k)
        s, e = lay.interval(out.shape[0], r)
        out[s:e] = my_slice
        deadline = time.monotonic() + timeout
        for dst, (m, _, _) in enumerate(view.members):
            if dst != r:
                self._send_chunked(m, K_AG, epoch, 0, step, s, my_slice)
        expect = out.nbytes - my_slice.nbytes
        got = 0
        while got < expect:
            hdr, payload = self._take(
                epoch, lambda h: h[0] == K_AG and h[4] == step, deadline
            )
            part = np.frombuffer(payload, out.dtype)
            off = hdr[5]
            out[off:off + part.shape[0]] = part
            got += len(payload)

    def exchange_replica(self, name: str, shard: np.ndarray, step: int,
                         timeout: float = 120.0) -> Optional[np.ndarray]:
        """Per-step ring-replica exchange, STAGED: send ``shard`` (the
        value my shard of ``name`` is about to become) to my successor
        and return my predecessor's counterpart — without committing
        either side here. The caller commits shard and replica together
        once every exchange of the step completed, so a death mid-step
        can never leave shard and replica on different steps (the
        replica is the death-recovery source). Returns ``None`` at
        world size 1."""
        view = self._view
        k = len(view.members)
        if k <= 1:
            return None
        epoch, r = view.epoch, view.rank_of(self.mid)
        aid = self.state.aid(name)
        e = self.state.entries[name]
        self._send_chunked(
            view.members[(r + 1) % k][0], K_REPL, epoch, aid, step + 1, 0,
            np.ascontiguousarray(shard),
        )
        deadline = time.monotonic() + timeout
        fresh = np.empty_like(e.replica)
        got = 0
        while got < fresh.nbytes:
            hdr, payload = self._take(
                epoch,
                lambda h: h[0] == K_REPL and h[3] == aid
                and h[4] == step + 1,
                deadline,
            )
            part = np.frombuffer(payload, e.dtype)
            off = hdr[5]
            fresh[off:off + part.shape[0]] = part
            got += len(payload)
        return fresh

    def leave(self) -> None:
        try:
            _json_roundtrip(
                self.coord, {"op": "leave", "mid": self.mid}, timeout=10
            )
        except (OSError, ValueError):
            pass
        self.close()

    def close(self) -> None:
        self._closed = True
        try:
            self._srv.close()
        except OSError:
            pass
        with self._conn_guard:
            conns, self._conns = dict(self._conns), {}
        for c in conns.values():
            try:
                c.close()
            except OSError:
                pass
        with self._cv:
            self._cv.notify_all()


def from_env(state: ElasticState) -> ElasticMember:
    """Member bootstrap inside ``launch --elastic`` workers: the
    coordinator address rides the TORCHMPI_TPU_ELASTIC env var.
    ``launch --set-constant`` knob overrides apply here too (elastic
    workers need not call ``start()`` — the host data plane has no jax
    runtime dependency)."""
    addr = os.environ.get("TORCHMPI_TPU_ELASTIC")
    if not addr:
        raise RuntimeError(
            "TORCHMPI_TPU_ELASTIC is not set — run under "
            "`python -m torchmpi_tpu.launch --elastic ...` or pass a "
            "coordinator address to ElasticMember explicitly"
        )
    from ..runtime_state import _apply_env_constants

    _apply_env_constants()
    return ElasticMember(addr, state)


# ---------------------------------------------------------------------------
# host-zero1 checkpointing: the rollback artifact checkpoint_every keeps
# fresh (atomic single-file .npz; registered with supervise.checkpoints)
# ---------------------------------------------------------------------------


def save_zero1_checkpoint(path, params: np.ndarray, step: int) -> None:
    """Atomically persist ``{params, step}`` to ``path`` (a ``.npz``
    file: temp + rename, so a death mid-save leaves the previous
    artifact intact) and register it as the newest rollback artifact
    (:func:`~..supervise.checkpoints.register_checkpoint`) — which is
    what DataLoss messages and the supervisor's rollback rung name."""
    import pathlib

    p = pathlib.Path(path)
    p.parent.mkdir(parents=True, exist_ok=True)
    tmp = p.with_name(p.name + f".tmp.{os.getpid()}")
    with open(tmp, "wb") as f:
        np.savez(f, params=np.asarray(params), step=np.int64(step))
        f.flush()
        os.fsync(f.fileno())
    os.replace(tmp, p)
    _checkpoints.register_checkpoint(p, int(step))


def load_zero1_checkpoint(path) -> Optional[Dict[str, Any]]:
    """``{"params", "step"}`` from :func:`save_zero1_checkpoint`, or
    None when no artifact exists yet (cold start)."""
    import pathlib

    p = pathlib.Path(path)
    if not p.exists():
        return None
    with np.load(p) as z:
        return {"params": np.array(z["params"]), "step": int(z["step"])}


# ---------------------------------------------------------------------------
# host-zero1 elastic trainer
# ---------------------------------------------------------------------------


class ElasticZero1:
    """Host-level ZeRO-1 data-parallel SGD over the elastic data plane.

    Params replicated on every member; momentum SHARDED (the zero1
    memory shape) with the per-step ring replica that makes a death
    recoverable. One step:

    1. ``grad_fn(params, rank, world) -> (loss, grad)`` — the caller's
       local gradient on its data assignment;
    2. gradient reduce-scatter (each member receives the summed slice
       of its momentum shard);
    3. sharded update: ``m = mu*m + g/world``; ``p_slice -= lr*m``;
    4. parameter allgather (everyone gets the new full params);
    5. momentum-replica refresh to the ring successor.

    A membership change anywhere in 1-5 raises through the collectives
    and the step retries after :meth:`ElasticMember.sync` redistributed
    the state — the loss curve continues on the new world.
    """

    def __init__(self, member: ElasticMember, params: np.ndarray,
                 lr: float = 0.1, momentum: float = 0.9):
        self.member = member
        p = np.asarray(params, np.float32).reshape(-1)
        member.state.add("params", p, kind="replicated")
        member.state.add("momentum", np.zeros_like(p), kind="sharded")
        self.lr, self.mu = float(lr), float(momentum)
        self.step_idx = 0
        self._stash: Optional[dict] = None
        self._ckpt_every = 0
        self._ckpt_path = None
        self._ckpt_thread: Optional[threading.Thread] = None
        self._ckpt_warned = False
        self._ckpt_saved_step = -1
        member.on_agreed_step = self._apply_stash

    def checkpoint_every(self, steps: int, path) -> None:
        """Arm the async rollback-artifact hook: every ``steps``
        committed steps, the member currently at rank 0 saves
        ``{params, step}`` to ``path`` on a background thread
        (:func:`save_zero1_checkpoint`: atomic replace + registry).
        One save in flight at a time — when a save is still running at
        the next due step, that step is skipped, not queued (the
        artifact is a recency floor, not a history;
        :meth:`flush_checkpoint` makes the FINAL state durable).
        ``steps=0`` disarms (the engine hook's convention)."""
        if int(steps) < 0:
            raise ValueError(f"checkpoint_every expects steps >= 0, "
                             f"got {steps}")
        self._ckpt_every = int(steps)
        self._ckpt_path = path

    def _maybe_checkpoint(self, rank: int) -> None:
        if (
            not self._ckpt_every
            or rank != 0
            or self.step_idx % self._ckpt_every != 0
        ):
            return
        t = self._ckpt_thread
        if t is not None and t.is_alive():
            return  # previous save still in flight: skip this boundary
        # snapshot on the step thread — the training loop may mutate
        # params while the writer thread serializes
        params = self.params.copy()
        step = self.step_idx
        self._ckpt_thread = threading.Thread(
            target=self._save_checkpoint, args=(params, step),
            name="tm-zero1-ckpt", daemon=True,
        )
        self._ckpt_thread.start()

    def _save_checkpoint(self, params: np.ndarray, step: int) -> None:
        try:
            save_zero1_checkpoint(self._ckpt_path, params, step)
            self._ckpt_saved_step = step
        except Exception as e:  # noqa: BLE001 - a failed save must never
            # kill training (nor die as a silent daemon-thread
            # traceback) — but a save that ALWAYS fails means no
            # rollback artifact: say so once
            if not self._ckpt_warned:
                self._ckpt_warned = True
                import sys

                print(
                    f"[elastic] checkpoint_every save to "
                    f"{self._ckpt_path} failed: {e!r} (further "
                    "failures suppressed)",
                    file=sys.stderr,
                )

    def flush_checkpoint(self, timeout: float = 30.0) -> None:
        """Make the CURRENT state durable before a deliberate exit:
        join any in-flight async save, then — when this member is rank
        0 and the last boundary was skipped (a save was in flight) or
        hasn't been reached — save synchronously, so the artifact never
        trails a clean shutdown."""
        t = self._ckpt_thread
        if t is not None:
            t.join(timeout=timeout)
        view = self.member._view
        if (
            self._ckpt_every
            and self._ckpt_path is not None
            and view is not None
            and view.rank_of(self.member.mid) == 0
            and self._ckpt_saved_step != self.step_idx
        ):
            self._save_checkpoint(self.params.copy(), self.step_idx)

    def _apply_stash(self, agreed: int) -> None:
        """Resize-barrier reconciliation: a step is torn when SOME
        member aborts it mid-exchange while the anchor committed it
        (agreed step = mine + 1). The anchor can only have committed if
        every member reached its replica-exchange send — which happens
        after ``new_mom`` was staged — so the stash always exists here,
        and committing it puts this member's momentum shard on the
        agreed step before redistribution reads it. Without this, the
        shard would permanently miss one update (the missed-apply dual
        of the double-apply the staged commit prevents)."""
        st, self._stash = self._stash, None
        view = self.member._view
        if (
            st is not None
            and view is not None
            and st["epoch"] == view.epoch
            and st["step"] == self.step_idx
            and agreed == st["step"] + 1
        ):
            self.member.state.entries["momentum"].shard[:] = st["mom"]

    @property
    def params(self) -> np.ndarray:
        return self.member.state.entries["params"].full

    def step(self, grad_fn) -> float:
        m = self.member
        while True:
            role = m.sync(self.step_idx)
            if role["resized"]:
                self.step_idx = role["step"]
            rank, world = role["rank"], role["world"]
            st = self.member.state.entries
            try:
                loss, grad = grad_fn(st["params"].full, rank, world)
                grad = np.asarray(grad, np.float32).reshape(-1)
                gsum = m.reduce_scatter_sum(grad, self.step_idx)
                # STAGED update: nothing commits until every exchange of
                # the step — allgather AND replica refresh — completed.
                # Committing earlier lets a death between the commit and
                # the refresh retry the step against already-updated
                # state (a double-applied update) with the dead rank's
                # half rebuilt from a one-step-stale replica.
                new_mom = self.mu * st["momentum"].shard + gsum / world
                # stash the staged update for _apply_stash: from here on
                # peers may complete the step using our sends even if WE
                # abort, and the resize agreement will tell us whether
                # the step counts (agreed step == ours + 1)
                self._stash = {"epoch": role["epoch"],
                               "step": self.step_idx, "mom": new_mom}
                lay = Layout(world)
                s, e = lay.interval(grad.shape[0], rank)
                new_slice = st["params"].full[s:e] - self.lr * new_mom
                new_params = np.empty_like(st["params"].full)
                m.allgather(new_params, new_slice, self.step_idx)
                new_replica = m.exchange_replica(
                    "momentum", new_mom, self.step_idx
                )
                st["params"].full[:] = new_params
                st["momentum"].shard[:] = new_mom
                if new_replica is not None:
                    st["momentum"].replica[:] = new_replica
                self._stash = None
                self.step_idx += 1
                self._maybe_checkpoint(rank)
                return float(loss)
            except EpochChanged:
                continue
            except ConnectionError:
                # a peer died under a send before the coordinator
                # noticed: wait out the heartbeat detection, then retry
                # the step against the post-death world
                if m._closed:
                    raise
                deadline = time.monotonic() + 60
                while not m.epoch_changed():
                    if m._closed or time.monotonic() > deadline:
                        raise
                    time.sleep(0.05)
                continue


def _main(argv=None) -> int:
    """Operator CLI: ``python -m torchmpi_tpu.reshard.elastic grow
    host:port`` / ``... shrink host:port``."""
    import argparse
    import sys

    ap = argparse.ArgumentParser(
        prog="python -m torchmpi_tpu.reshard.elastic",
        description="send an operator command to a live elastic job",
    )
    ap.add_argument("command", choices=["grow", "shrink", "evict", "view"])
    ap.add_argument("address", help="coordinator host:port "
                    "(see launch --elastic-addr-file)")
    ap.add_argument("--mid", type=int, default=None,
                    help="member id to remove (required with evict)")
    args = ap.parse_args(argv)
    extra = {}
    if args.command == "evict":
        if args.mid is None:
            ap.error("evict requires --mid")
        extra["mid"] = args.mid
    rep = operator_request(args.address, args.command, **extra)
    print(json.dumps(rep))
    return 0 if rep.get("ok", True) else 1


if __name__ == "__main__":
    import sys

    sys.exit(_main())
