"""Portable array redistribution: the minimal-transfer reshard planner.

The missing elasticity primitive (ROADMAP item 3): move sharded state
between ANY two ``(world size, sharding)`` layouts with bounded memory —
the "Memory-efficient array redistribution through portable collective
communication" framing (PAPERS.md). A redistribution is *compiled*, not
hand-routed:

1. :func:`plan_transfers` computes the **minimal** transfer schedule
   between a source and target :class:`Layout` of the same flat array:
   every target element is received exactly once, from the unique source
   rank that holds it, and elements whose owner does not change never
   touch a wire (they appear as ``src_rank == dst_rank`` local copies).
2. :func:`build_plan` expresses that schedule as a PR 9
   :class:`~..schedule.ir.Plan` DAG — aggregated send/recv steps on the
   ``host`` link class, chunk counts in ``meta`` — so redistribution is
   cost-modeled, cached, and introspectable (``--explain``) through the
   same machinery as every other collective. Ragged worlds (a 3-survivor
   shrink of a 4-rank world) are just layouts; nothing special-cases
   them.
3. :class:`Redistributor` executes the schedule with **bounded peak
   memory**: transfers are cut into ``reshard_chunk_bytes`` chunks and
   copied through one reusable scratch buffer — the full array is never
   materialized on any rank, and :attr:`Redistributor.peak_scratch_bytes`
   makes the bound assertable (< 2x the largest single shard, tested).

Everything here is numpy/stdlib only — plans are buildable offline (the
``python -m torchmpi_tpu.reshard`` CLI) and the same schedule drives the
in-process engine resize, the cross-process elastic exchange
(:mod:`.elastic`), the checkpoint reshaper (:mod:`..utils.checkpoint`)
and the PS chain re-formation's shard copy chunking.
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import Callable, Dict, Iterator, List, Optional, Tuple

import numpy as np

from .. import constants
from ..schedule import cost as _cost
from ..schedule import pipeline as _sched_pipeline
from ..schedule.ir import Plan, Step


@dataclass(frozen=True)
class Layout:
    """One ``(world size, sharding)`` placement of a flat n-element array.

    ``kind``:

    - ``'sharded'`` — contiguous uniform partition over ``world`` ranks
      (the engine's fsdp/zero1 leaf layout, the PS ``shard_range``
      layout, the elastic host-zero1 optimizer layout). ``rotation``
      places the ``n % world`` remainder on the cyclic rank interval
      ``[rotation, rotation + extra)`` (PS byte-aware placement).
    - ``'replicated'`` — every rank holds the full array (engine
      replicated params). A replicated *source* serves each target
      interval from the co-located rank when possible (zero wire
      bytes); a replicated *target* receives the full array on every
      rank.
    """

    world: int
    kind: str = "sharded"
    rotation: int = 0

    def __post_init__(self):
        if self.world < 1:
            raise ValueError(f"layout world must be >= 1, got {self.world}")
        if self.kind not in ("sharded", "replicated"):
            raise ValueError(
                f"layout kind must be 'sharded'|'replicated', got "
                f"{self.kind!r}"
            )

    def interval(self, n: int, rank: int) -> Tuple[int, int]:
        """[start, end) of ``rank``'s elements in the flat array."""
        if self.kind == "replicated":
            return 0, n
        from ..parameterserver.server import shard_range

        return shard_range(n, self.world, rank, self.rotation)

    def intervals(self, n: int) -> List[Tuple[int, int]]:
        return [self.interval(n, r) for r in range(self.world)]

    def token(self) -> str:
        tail = f"@rot{self.rotation}" if self.rotation else ""
        return f"{self.kind[:4]}{self.world}{tail}"


@dataclass(frozen=True)
class Transfer:
    """One contiguous span moving from a source rank to a target rank.

    Offsets are into the *local* shard buffers of each side (the flat
    global span is ``[global_start, global_start + n)``); a transfer
    with ``src == dst`` is a local copy and never touches a wire."""

    src: int
    dst: int
    src_off: int
    dst_off: int
    n: int
    global_start: int


def plan_transfers(n: int, src: Layout, dst: Layout) -> List[Transfer]:
    """The minimal transfer schedule from ``src`` to ``dst`` layout.

    Minimality: each target element appears in exactly ONE transfer
    (received once), sourced from a rank that holds it — and when the
    holding source rank IS the target rank the element moves locally
    (zero wire bytes). A replicated source always serves a target rank
    from itself when the target rank also exists in the source world,
    else from ``dst_rank % src.world`` (spreads the load of a grow from
    a replicated checkpoint over all sources)."""
    if n < 0:
        raise ValueError(f"n must be >= 0, got {n}")
    out: List[Transfer] = []
    if n == 0:
        return out
    if src.kind == "replicated":
        for d in range(dst.world):
            ds, de = dst.interval(n, d)
            if de <= ds:
                continue
            s = d if d < src.world else d % src.world
            out.append(Transfer(s, d, ds, 0, de - ds, ds))
        return out
    # Both interval lists are ordered contiguous partitions of [0, n)
    # (shard_range is monotone in rank), so a two-pointer sweep finds
    # every overlap in O(src.world + dst.world + transfers). The naive
    # all-pairs scan was O(src.world * dst.world) — ~100M interval
    # comparisons for one 10k -> 9.9k resize, which the fleet simulator
    # measured as ~90s of coordinator-side planning per epoch.
    src_ivs = src.intervals(n)
    s = 0
    for d in range(dst.world):
        ds, de = dst.interval(n, d)
        if de <= ds:
            continue
        while s < src.world and src_ivs[s][1] <= ds:
            s += 1
        i = s
        while i < src.world and src_ivs[i][0] < de:
            ss, se = src_ivs[i]
            lo, hi = max(ds, ss), min(de, se)
            if hi > lo:
                out.append(Transfer(i, d, lo - ss, lo - ds, hi - lo, lo))
            if se >= de:
                break
            i += 1
    return out


def wire_elements(transfers: List[Transfer]) -> int:
    """Elements that actually cross ranks (the minimality metric)."""
    return sum(t.n for t in transfers if t.src != t.dst)


def chunk_spans(n: int, chunk: int) -> Iterator[Tuple[int, int]]:
    """Cut ``[0, n)`` into ``(start, end)`` spans of at most ``chunk``
    elements. The one chunking rule everywhere reshard bytes move — the
    elastic exchange, the checkpoint reshaper and the PS re-formation
    copy all bound their peak memory with it. The span math is the
    schedule IR's shared chunk-pipeline rule
    (:func:`~..schedule.pipeline.split_spans`), so reshard, the PS wire
    codec and the pipelined plan families cut payloads identically."""
    for off, ln in _sched_pipeline.split_spans(n, max(1, int(chunk))):
        yield off, off + ln


def chunk_transfers(
    transfers: List[Transfer], chunk_elems: int
) -> Iterator[Transfer]:
    """Split every transfer into <= ``chunk_elems``-element pieces (the
    bounded-memory execution unit)."""
    for t in transfers:
        for lo, hi in chunk_spans(t.n, chunk_elems):
            yield Transfer(
                t.src, t.dst, t.src_off + lo, t.dst_off + lo, hi - lo,
                t.global_start + lo,
            )


def chunk_elems_for(itemsize: int, chunk_bytes: Optional[int] = None) -> int:
    """Elements per chunk from the ``reshard_chunk_bytes`` knob."""
    if chunk_bytes is None:
        chunk_bytes = int(constants.get("reshard_chunk_bytes"))
    if chunk_bytes <= 0:
        return 1 << 62  # chunking disabled: one piece per transfer
    return max(1, chunk_bytes // max(1, int(itemsize)))


# ---------------------------------------------------------------------------
# plan IR: a redistribution as a schedule-compiler plan DAG
# ---------------------------------------------------------------------------


def build_plan(
    n: int,
    itemsize: int,
    src: Layout,
    dst: Layout,
    chunk_bytes: Optional[int] = None,
    platform: str = "cpu",
) -> Plan:
    """Express the minimal schedule as a PR 9 plan: aggregated per-rank
    send/recv steps on the ``host`` link class (redistribution rides the
    host blob fabric — the staged-DCN hop of the topology model), local
    copies as ``local_reduce``-priced moves, chunk counts in ``meta``.
    The plan's ``plan_id`` is the stable identity flight-recorder resize
    entries and the reshard cache share."""
    transfers = plan_transfers(n, src, dst)
    celems = chunk_elems_for(itemsize, chunk_bytes)
    wire_by_src: Dict[int, int] = {}
    local_elems = 0
    nchunks = 0
    for t in transfers:
        if t.src == t.dst:
            local_elems += t.n
        else:
            wire_by_src[t.src] = wire_by_src.get(t.src, 0) + t.n
            nchunks += (t.n + celems - 1) // celems
    steps: List[Step] = []
    if wire_by_src:
        worst = max(wire_by_src.values())
        senders = len(wire_by_src)
        steps.append(Step(
            "send", "host", worst * itemsize, count=senders,
            note="per-rank worst-case wire bytes",
        ))
        steps.append(Step(
            "recv", "host", worst * itemsize, count=senders,
        ))
    if local_elems:
        steps.append(Step(
            "local_reduce", "local", local_elems * itemsize,
            note="owner-stable elements (never on a wire)",
        ))
    return Plan(
        op="reshard",
        generator="reshard",
        backend="host",
        wire="full",
        topology_fp=f"{platform}:reshard:{src.token()}->{dst.token()}",
        steps=tuple(steps),
        meta=(
            ("chunks", nchunks),
            ("chunk_elems", min(celems, n) if n else 0),
            ("n", n),
            ("wire_elems", sum(wire_by_src.values())),
        ),
    )


# compiled-reshard cache: (n, itemsize, src, dst, chunk, generation()) ->
# (plan, transfers). generation() in the key is the coherence contract —
# a resize bumps `resize_epoch`, every cached schedule (this one AND the
# collective dispatch memos) invalidates together.
_plan_cache: Dict[tuple, Tuple[Plan, List[Transfer]]] = {}
_PLAN_CACHE_CAP = 128


def compile_reshard(
    n: int,
    itemsize: int,
    src: Layout,
    dst: Layout,
    chunk_bytes: Optional[int] = None,
) -> Tuple[Plan, List[Transfer]]:
    """Cached plan + transfer list for one redistribution request."""
    key = (n, itemsize, src, dst, chunk_bytes, constants.generation())
    ent = _plan_cache.get(key)
    if ent is None:
        ent = (
            build_plan(n, itemsize, src, dst, chunk_bytes),
            plan_transfers(n, src, dst),
        )
        while len(_plan_cache) >= _PLAN_CACHE_CAP:
            _plan_cache.pop(next(iter(_plan_cache)))
        _plan_cache[key] = ent
    return ent


def estimate_us(plan: Plan) -> float:
    """Cost-model estimate (the ordering signal ``--explain`` prints)."""
    return _cost.estimate_us(plan)


# ---------------------------------------------------------------------------
# bounded-memory executor
# ---------------------------------------------------------------------------


class Redistributor:
    """Execute a reshard schedule chunk-by-chunk with bounded scratch.

    ``read(rank, off, out_view)`` must fill ``out_view`` with elements
    ``[off, off + len)`` of source rank ``rank``'s shard;
    ``write(rank, off, values)`` stores into target rank ``rank``'s
    shard. The executor never allocates more than one chunk of scratch
    at a time; ``peak_scratch_bytes`` is the asserted memory bound.

    This one class serves every consumer: in-process (reads/writes are
    numpy copies), cross-process (read fills from a received blob,
    write lands in the local target shard — see :mod:`.elastic`), and
    offline (reads are mmap'd checkpoint shard files)."""

    def __init__(
        self,
        n: int,
        dtype,
        src: Layout,
        dst: Layout,
        chunk_bytes: Optional[int] = None,
    ):
        self.n = int(n)
        self.dtype = np.dtype(dtype)
        self.src = src
        self.dst = dst
        self.plan, self.transfers = compile_reshard(
            self.n, self.dtype.itemsize, src, dst, chunk_bytes
        )
        self.chunk_elems = chunk_elems_for(self.dtype.itemsize, chunk_bytes)
        self.peak_scratch_bytes = 0
        self._scratch: Optional[np.ndarray] = None

    def _scratch_for(self, nelem: int) -> np.ndarray:
        if self._scratch is None or self._scratch.shape[0] < nelem:
            self._scratch = np.empty(nelem, self.dtype)
            self.peak_scratch_bytes = max(
                self.peak_scratch_bytes, self._scratch.nbytes
            )
        return self._scratch[:nelem]

    def run(
        self,
        read: Callable[[int, int, np.ndarray], None],
        write: Callable[[int, int, np.ndarray], None],
        ranks: Optional[set] = None,
    ) -> None:
        """Run every (chunked) transfer; ``ranks`` restricts execution to
        transfers whose source AND target live in the given rank set (the
        in-process case passes None = all). Execution flows through the
        shared :class:`~..schedule.pipeline.ChunkPipeline` driver — the
        read/write stages reuse one scratch buffer (the bounded-memory
        contract) and every chunk's flight sub-entry is stamped
        ``(plan_id, chunk_idx)`` on the rank-local ``chunks`` stream."""
        pieces = (
            t for t in chunk_transfers(self.transfers, self.chunk_elems)
            if ranks is None or (t.src in ranks and t.dst in ranks)
        )
        itemsize = self.dtype.itemsize

        def stage(idx: int, t: Transfer) -> None:
            buf = self._scratch_for(t.n)
            read(t.src, t.src_off, buf)
            write(t.dst, t.dst_off, buf)

        _sched_pipeline.ChunkPipeline(
            self.plan.plan_id, self.plan.op,
            nbytes_of=lambda t: t.n * itemsize,
        ).run(pieces, stage)


def redistribute_arrays(
    shards: Dict[int, np.ndarray],
    n: int,
    src: Layout,
    dst: Layout,
    chunk_bytes: Optional[int] = None,
) -> Tuple[Dict[int, np.ndarray], Redistributor]:
    """In-process reference executor: source shards in, freshly-allocated
    target shards out (bitwise-equal to a fresh ``dst`` scatter of the
    assembled array — the equivalence the tests pin). Returns the
    executor too so callers can assert its memory bound."""
    dt = None
    for a in shards.values():
        dt = np.asarray(a).dtype
        break
    if dt is None:
        raise ValueError("no source shards given")
    rd = Redistributor(n, dt, src, dst, chunk_bytes)
    out = {
        r: np.empty(max(0, e - s), dt)
        for r, (s, e) in enumerate(dst.intervals(n))
    }

    def read(rank: int, off: int, view: np.ndarray) -> None:
        view[:] = np.asarray(shards[rank]).reshape(-1)[off:off + view.shape[0]]

    def write(rank: int, off: int, values: np.ndarray) -> None:
        out[rank][off:off + values.shape[0]] = values

    rd.run(read, write)
    return out, rd
