"""Pipeline parallelism over a mesh axis (GPipe-style microbatching).

The reference has no layer pipelining across devices (SURVEY.md §2.3 marks
PP absent — only *communication* pipelining of chunks); this is a
capability extension in the modern taxonomy, built TPU-first:

- each device along the ``pp`` mesh axis owns ONE stage's parameters;
- the classic GPipe schedule runs as a ``lax.scan`` over
  ``num_microbatches + p - 1`` ticks: every tick each stage computes on
  the activation received from its left neighbor and hands its output
  rightward with a single ``lax.ppermute`` (one-hop ICI transfer);
- ``ppermute`` is differentiable, so ``jax.grad`` through
  :func:`pipeline_forward` yields the standard GPipe backward schedule
  automatically — no hand-written bubble bookkeeping;
- stage activations must share one shape/dtype (the usual uniform-width
  transformer-block restriction).

Bubble fraction is the textbook ``(p-1)/(m+p-1)``; pick
``num_microbatches >> p`` to amortize.
"""

from __future__ import annotations

from typing import Callable

import jax.numpy as jnp
from jax import lax


def pipeline_forward(
    stage_fn: Callable,
    stage_params,
    microbatches,
    axis: str = "pp",
    replicate_outputs: bool = True,
):
    """Run ``p`` pipeline stages over ``m`` microbatches inside shard_map.

    Parameters
    ----------
    stage_fn : ``stage_fn(params, x) -> y`` — one stage's computation;
        ``y`` must have ``x``'s shape/dtype.
    stage_params : THIS device's stage parameters (shard_map'd so device
        ``s`` holds stage ``s``'s pytree).
    microbatches : ``[m, mb, ...]`` — the full input, present on every
        stage (only stage 0 reads it; XLA DCEs the rest).
    axis : the pipeline mesh axis name.

    Returns ``[m, mb, ...]`` outputs of the LAST stage. With
    ``replicate_outputs`` (default) they are broadcast to every stage via
    a masked ``psum`` so callers can read them anywhere — but the psum's
    TRANSPOSE sums p identical cotangents, so do NOT differentiate a loss
    of the replicated outputs inside shard_map (p-scaled gradients); use
    :func:`pipeline_loss_fn`, which masks the LOSS instead, for in-graph
    training. ``replicate_outputs=False`` returns each stage's raw buffer
    (meaningful only on stage p-1).
    """
    p = lax.axis_size(axis)
    m = microbatches.shape[0]
    s = lax.axis_index(axis)
    right = [(i, (i + 1) % p) for i in range(p)]
    mb_shape = microbatches.shape[1:]
    dtype = microbatches.dtype

    def tick(carry, t):
        incoming, outputs = carry
        # stage 0 injects microbatch t (while t < m); others consume the
        # activation their left neighbor produced last tick
        inject = lax.dynamic_index_in_dim(
            microbatches, jnp.clip(t, 0, m - 1), 0, keepdims=False
        )
        x = jnp.where(s == 0, inject, incoming)
        y = stage_fn(stage_params, x)
        # the LAST stage's result for microbatch t-(p-1) is ready at tick
        # t; record it (only stage p-1's lane is meaningful, fixed below)
        out_idx = jnp.clip(t - (p - 1), 0, m - 1)
        outputs = lax.dynamic_update_index_in_dim(
            outputs,
            jnp.where(t - (p - 1) >= 0, y, outputs[out_idx]),
            out_idx,
            0,
        )
        # hand rightward: stage s's output becomes s+1's next input
        incoming = lax.ppermute(y, axis, right)
        return (incoming, outputs), None

    init = (
        jnp.zeros(mb_shape, dtype),
        jnp.zeros((m,) + mb_shape, dtype),
    )
    (incoming, outputs), _ = lax.scan(
        tick, init, jnp.arange(m + p - 1)
    )
    if not replicate_outputs:
        return outputs  # true outputs only on stage p-1
    # only stage p-1 holds the true outputs; broadcast them to every
    # stage with a masked psum (single collective)
    mine = jnp.where(s == p - 1, outputs, jnp.zeros_like(outputs))
    return lax.psum(mine, axis)


def pipeline_loss_fn(
    stage_fn: Callable,
    loss_of_outputs: Callable,
    axis: str = "pp",
):
    """Build ``fn(stage_params, microbatches, targets) -> scalar`` for use
    inside shard_map: GPipe forward + the caller's loss over the final
    outputs. ``jax.grad`` of this function — inside OR outside shard_map —
    gives each device its OWN stage's gradients at the correct scale (the
    PP backward schedule falls out of ppermute's transpose).

    Gradient-scale discipline: under SPMD differentiation the transpose of
    ``psum`` SUMS the per-device cotangents of its replicated result, so
    differentiating a psum'd loss inside shard_map p-scales every
    gradient. The returned scalar therefore separates value from gradient:
    the VALUE is the psum-replicated last-stage loss, but the GRADIENT
    flows only through the local masked lane
    (``masked + stop_gradient(replicated - masked)``).

    Supported differentiation pattern: take the grad INSIDE the shard_map
    region — ``shard_map(jax.value_and_grad(fn), ...)`` — which yields
    exact sequential-parity stage gradients (tested). Differentiating the
    already-shard_mapped function from OUTSIDE uses the opposite
    replicated-output cotangent convention (1/p per lane) and is not
    supported."""

    def fn(stage_params, microbatches, targets):
        outs = pipeline_forward(
            stage_fn, stage_params, microbatches, axis,
            replicate_outputs=False,
        )
        p = lax.axis_size(axis)
        s = lax.axis_index(axis)
        loss_local = loss_of_outputs(outs, targets)
        masked = jnp.where(
            s == p - 1, loss_local, jnp.zeros_like(loss_local)
        )
        replicated = lax.psum(masked, axis)
        return masked + lax.stop_gradient(replicated - masked)

    return fn
