"""Pipeline parallelism over a mesh axis (GPipe-style microbatching).

The reference has no layer pipelining across devices (SURVEY.md §2.3 marks
PP absent — only *communication* pipelining of chunks); this is a
capability extension in the modern taxonomy, built TPU-first:

- each device along the ``pp`` mesh axis owns ONE stage's parameters;
- the classic GPipe schedule runs as a ``lax.scan`` over
  ``num_microbatches + p - 1`` ticks: every tick each stage computes on
  the activation received from its left neighbor and hands its output
  rightward with a single ``lax.ppermute`` (one-hop ICI transfer);
- ``ppermute`` is differentiable, so ``jax.grad`` through
  :func:`pipeline_forward` yields the standard GPipe backward schedule
  automatically — no hand-written bubble bookkeeping;
- stage activations must share one shape/dtype (the usual uniform-width
  transformer-block restriction).

Bubble fraction is the textbook ``(p-1)/(m+p-1)``; pick
``num_microbatches >> p`` to amortize.

Two schedules:

- GPipe via autodiff (:func:`pipeline_forward` / :func:`pipeline_loss_fn`):
  the backward falls out of ``ppermute``'s transpose; activation residuals
  grow O(m) with the scan length.
- 1F1B / PipeDream-flush (:func:`pipeline_1f1b_value_and_grad`): an
  explicit static schedule interleaving one forward with one backward per
  stage after warmup, with per-tick ``jax.vjp`` against an O(p) circular
  activation stash — same tick count, flat memory in m.
"""

from __future__ import annotations

from typing import Callable

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax


def pipeline_forward(
    stage_fn: Callable,
    stage_params,
    microbatches,
    axis: str = "pp",
    replicate_outputs: bool = True,
):
    """Run ``p`` pipeline stages over ``m`` microbatches inside shard_map.

    Parameters
    ----------
    stage_fn : ``stage_fn(params, x) -> y`` — one stage's computation;
        ``y`` must have ``x``'s shape/dtype.
    stage_params : THIS device's stage parameters (shard_map'd so device
        ``s`` holds stage ``s``'s pytree).
    microbatches : ``[m, mb, ...]`` — the full input, present on every
        stage (only stage 0 reads it; XLA DCEs the rest).
    axis : the pipeline mesh axis name.

    Returns ``[m, mb, ...]`` outputs of the LAST stage. With
    ``replicate_outputs`` (default) they are broadcast to every stage via
    a masked ``psum`` so callers can read them anywhere — but the psum's
    TRANSPOSE sums p identical cotangents, so do NOT differentiate a loss
    of the replicated outputs inside shard_map (p-scaled gradients); use
    :func:`pipeline_loss_fn`, which masks the LOSS instead, for in-graph
    training. ``replicate_outputs=False`` returns each stage's raw buffer
    (meaningful only on stage p-1).
    """
    p = lax.axis_size(axis)
    m = microbatches.shape[0]
    s = lax.axis_index(axis)
    right = [(i, (i + 1) % p) for i in range(p)]
    mb_shape = microbatches.shape[1:]
    dtype = microbatches.dtype

    def tick(carry, t):
        incoming, outputs = carry
        # stage 0 injects microbatch t (while t < m); others consume the
        # activation their left neighbor produced last tick
        inject = lax.dynamic_index_in_dim(
            microbatches, jnp.clip(t, 0, m - 1), 0, keepdims=False
        )
        x = jnp.where(s == 0, inject, incoming)
        y = stage_fn(stage_params, x)
        # the LAST stage's result for microbatch t-(p-1) is ready at tick
        # t; record it (only stage p-1's lane is meaningful, fixed below)
        out_idx = jnp.clip(t - (p - 1), 0, m - 1)
        outputs = lax.dynamic_update_index_in_dim(
            outputs,
            jnp.where(t - (p - 1) >= 0, y, outputs[out_idx]),
            out_idx,
            0,
        )
        # hand rightward: stage s's output becomes s+1's next input
        incoming = lax.ppermute(y, axis, right)
        return (incoming, outputs), None

    init = (
        jnp.zeros(mb_shape, dtype),
        jnp.zeros((m,) + mb_shape, dtype),
    )
    (incoming, outputs), _ = lax.scan(
        tick, init, jnp.arange(m + p - 1)
    )
    if not replicate_outputs:
        return outputs  # true outputs only on stage p-1
    # only stage p-1 holds the true outputs; broadcast them to every
    # stage with a masked psum (single collective)
    mine = jnp.where(s == p - 1, outputs, jnp.zeros_like(outputs))
    return lax.psum(mine, axis)


def pipeline_loss_fn(
    stage_fn: Callable,
    loss_of_outputs: Callable,
    axis: str = "pp",
    convention: str = "grad-inside",
):
    """Build ``fn(stage_params, microbatches, targets) -> scalar`` for use
    inside shard_map: GPipe forward + the caller's loss over the final
    outputs. ``jax.grad`` gives each device its OWN stage's gradients (the
    PP backward schedule falls out of ppermute's transpose).

    Gradient-scale discipline: under SPMD differentiation the transpose of
    ``psum`` SUMS the per-device cotangents of its replicated result, so
    differentiating a psum'd loss inside shard_map p-scales every
    gradient. The returned scalar therefore separates value from gradient:
    the VALUE is the psum-replicated last-stage loss, but the GRADIENT
    flows only through the local masked lane
    (``masked + stop_gradient(replicated - masked)``).

    The two differentiation patterns use OPPOSITE replicated-output
    cotangent conventions, so ``convention`` must name where the grad is
    taken (measured: the other placement yields gradients off by exactly
    p or 1/p):

    - ``'grad-inside'`` (default): ``shard_map(jax.value_and_grad(fn))`` —
      every device's loss lane receives cotangent 1.
    - ``'grad-outside'``: ``jax.grad(shard_map(fn, out_specs=P()))`` — the
      replicated output's transpose hands each lane cotangent 1/p; the
      differentiable lane is pre-scaled by p to compensate, so stage
      gradients come out at sequential parity (tested both ways).
    """
    if convention not in ("grad-inside", "grad-outside"):
        raise ValueError(
            "convention must be 'grad-inside' (shard_map(grad(fn))) or "
            f"'grad-outside' (grad(shard_map(fn))), got {convention!r}"
        )

    def fn(stage_params, microbatches, targets):
        outs = pipeline_forward(
            stage_fn, stage_params, microbatches, axis,
            replicate_outputs=False,
        )
        p = lax.axis_size(axis)
        s = lax.axis_index(axis)
        loss_local = loss_of_outputs(outs, targets)
        masked = jnp.where(
            s == p - 1, loss_local, jnp.zeros_like(loss_local)
        )
        replicated = lax.psum(masked, axis)
        # the differentiable lane: x1 when each lane's cotangent is 1
        # (grad-inside), xp when the outside transpose hands each lane 1/p
        diff_lane = masked * p if convention == "grad-outside" else masked
        return diff_lane + lax.stop_gradient(replicated - diff_lane)

    return fn


# ---------------------------------------------------------------------------
# 1F1B (PipeDream-flush) schedule
# ---------------------------------------------------------------------------


def _one_f_one_b_schedule(p: int, m: int):
    """Static greedy 1F1B schedule: per tick and stage, which microbatch to
    forward / backward (-1 = idle). One compute slot per tick per stage;
    activations/cotangents sent at the end of a tick are usable the next.

    Policy: each stage runs warmup forwards until ``min(m, p - s)``
    microbatches are in flight, then strictly prefers backward over forward
    (the 1F1B alternation) — bounding live activations at O(p) instead of
    GPipe's O(m). Dependencies (fwd needs left's fwd done, bwd needs
    right's bwd done and the local fwd) are enforced by construction."""
    fwd_next, bwd_next = [0] * p, [0] * p
    fwd_time: dict = {}
    bwd_time: dict = {}
    max_inflight = [min(m, p - s) for s in range(p)]
    rows_f, rows_b = [], []
    t = 0
    while any(b < m for b in bwd_next):
        row_f, row_b = [-1] * p, [-1] * p
        for s in range(p):
            jf, jb = fwd_next[s], bwd_next[s]
            # .get default t => "not yet happened" fails the < t check
            can_fwd = jf < m and (
                s == 0 or fwd_time.get((s - 1, jf), t) < t
            )
            can_bwd = (
                jb < m
                and jb < jf
                and (s == p - 1 or bwd_time.get((s + 1, jb), t) < t)
            )
            if can_bwd and (jf - jb >= max_inflight[s] or not can_fwd):
                row_b[s] = jb
                bwd_time[(s, jb)] = t
                bwd_next[s] += 1
            elif can_fwd and jf - jb < max_inflight[s]:
                # at capacity with no backward ready the stage IDLES (a
                # bubble): forwarding anyway would grow live activations
                # to O(m) and forfeit exactly the bound 1F1B exists for
                row_f[s] = jf
                fwd_time[(s, jf)] = t
                fwd_next[s] += 1
        rows_f.append(row_f)
        rows_b.append(row_b)
        t += 1
        if t > 4 * (m + p) + 8:
            raise AssertionError(
                f"1F1B schedule failed to converge for p={p}, m={m}"
            )
    return (
        np.asarray(rows_f, np.int32),
        np.asarray(rows_b, np.int32),
        fwd_time,
        bwd_time,
    )


def _min_safe_stash(m: int, lives) -> int:
    """Smallest circular-buffer size with no live-range collision: slots
    ``j % size`` may not alias while both live. ``lives`` is a list of
    (j, write_tick, read_tick) tuples; static schedule -> exact check."""
    for size in range(1, m + 1):
        ok = True
        for j, w, r in lives:
            for j2, w2, r2 in lives:
                if j2 <= j or (j2 - j) % size != 0:
                    continue
                if w2 <= r:  # j2 overwrites the slot before j is read
                    ok = False
                    break
            if not ok:
                break
        if ok:
            return size
    return m


def _one_f_one_b_plan(p: int, m: int):
    """Schedule arrays + exact minimal stash sizes (all static)."""
    rows_f, rows_b, fwd_time, bwd_time = _one_f_one_b_schedule(p, m)
    # x stash: written at the stage's own fwd tick, read at its bwd tick
    x_lives = [
        [
            (j, fwd_time[(s, j)], bwd_time[(s, j)])
            for j in range(m)
        ]
        for s in range(p)
    ]
    # incoming activations: written the tick after the LEFT stage's fwd,
    # read at this stage's fwd tick
    in_lives = [
        [
            (j, fwd_time[(s - 1, j)] + 1, fwd_time[(s, j)])
            for j in range(m)
        ]
        for s in range(1, p)
    ]
    # incoming cotangents: written the tick after the RIGHT stage's bwd,
    # read at this stage's bwd tick
    gy_lives = [
        [
            (j, bwd_time[(s + 1, j)] + 1, bwd_time[(s, j)])
            for j in range(m)
        ]
        for s in range(p - 1)
    ]
    x_buf = max(_min_safe_stash(m, lv) for lv in x_lives)
    in_buf = max(
        (_min_safe_stash(m, lv) for lv in in_lives), default=1
    )
    gy_buf = max(
        (_min_safe_stash(m, lv) for lv in gy_lives), default=1
    )
    return rows_f, rows_b, x_buf, in_buf, gy_buf


def pipeline_1f1b_value_and_grad(
    stage_fn: Callable,
    loss_of_microbatch: Callable,
    axis: str = "pp",
):
    """Build ``fn(stage_params, microbatches, targets) -> (loss, grads)``
    running the 1F1B (PipeDream-flush) schedule — backward of microbatch j
    starts as soon as its forward clears the pipe, so live activations are
    bounded by O(p) stash slots instead of GPipe-through-autodiff's O(m)
    scan residuals. Use inside ``shard_map``; each device returns its OWN
    stage's parameter gradients (exact sequential parity, tested) and the
    replicated total loss ``(1/m) * sum_j loss_of_microbatch(y_j, t_j)``.

    No differentiation-convention trap here: the function computes its
    gradients internally (per-tick ``jax.vjp`` against the stashed stage
    input — rematerializing the stage forward, the standard TPU
    memory/FLOPs trade) and is not meant to be differentiated again.

    ``stage_fn(params, x) -> y`` with ``y.shape == x.shape``;
    ``loss_of_microbatch(y, target) -> scalar``.
    """

    def fn(stage_params, microbatches, targets):
        p = lax.axis_size(axis)
        s = lax.axis_index(axis)
        m = microbatches.shape[0]
        mb_shape = microbatches.shape[1:]
        dtype = microbatches.dtype
        rows_f, rows_b, x_buf, in_buf, gy_buf = _one_f_one_b_plan(p, m)
        fwd_sched = jnp.asarray(rows_f)  # [T, p]
        bwd_sched = jnp.asarray(rows_b)
        right = [(i, (i + 1) % p) for i in range(p)]
        left = [(i, (i - 1) % p) for i in range(p)]
        s_left = lax.rem(s + p - 1, p)
        s_right = lax.rem(s + 1, p)

        def masked_write(buf, idx, value, cond):
            cur = lax.dynamic_index_in_dim(buf, idx, 0, keepdims=False)
            return lax.dynamic_update_index_in_dim(
                buf, jnp.where(cond, value, cur), idx, 0
            )

        def tick(carry, t):
            in_act, gy, x_saved, grads, loss_sum = carry
            jf = fwd_sched[t, s]
            jb = bwd_sched[t, s]
            do_fwd, do_bwd = jf >= 0, jb >= 0
            jf_c = jnp.clip(jf, 0, m - 1)
            jb_c = jnp.clip(jb, 0, m - 1)

            # ---- forward slot ----
            x_in = jnp.where(
                s == 0,
                lax.dynamic_index_in_dim(
                    microbatches, jf_c, 0, keepdims=False
                ),
                lax.dynamic_index_in_dim(
                    in_act, jf_c % in_buf, 0, keepdims=False
                ),
            )
            # idle slots skip the stage compute entirely (lax.cond is a
            # real branch inside shard_map+scan on TPU — masking with
            # jnp.where would burn both slots' FLOPs every tick)
            y = lax.cond(
                do_fwd,
                lambda: stage_fn(stage_params, x_in),
                lambda: jnp.zeros(mb_shape, dtype),
            )
            x_saved = masked_write(x_saved, jf_c % x_buf, x_in, do_fwd)

            # ---- backward slot (remat: vjp against the stashed input) ----
            x_b = lax.dynamic_index_in_dim(
                x_saved, jb_c % x_buf, 0, keepdims=False
            )
            tgt_b = lax.dynamic_index_in_dim(
                targets, jb_c, 0, keepdims=False
            )
            last = s == p - 1
            gy_in = lax.dynamic_index_in_dim(
                gy, jb_c % gy_buf, 0, keepdims=False
            )

            def run_bwd():
                def fwd_and_loss(w, xx):
                    yy = stage_fn(w, xx)
                    return yy, loss_of_microbatch(yy, tgt_b)

                (y_b, l_b), pull = jax.vjp(fwd_and_loss, stage_params, x_b)
                cot_y = jnp.where(last, jnp.zeros_like(y_b), gy_in)
                cot_l = jnp.where(last, jnp.asarray(1.0 / m, l_b.dtype),
                                  jnp.asarray(0.0, l_b.dtype))
                gw, gx = pull((cot_y, cot_l))
                return gw, gx, l_b.astype(jnp.float32)

            gw, gx, l_b = lax.cond(
                do_bwd,
                run_bwd,
                lambda: (
                    zeros_g,
                    jnp.zeros(mb_shape, dtype),
                    jnp.zeros((), jnp.float32),
                ),
            )
            grads = jax.tree_util.tree_map(lambda G, g: G + g, grads, gw)
            loss_sum = loss_sum + jnp.where(
                do_bwd & last, l_b / m, jnp.zeros((), jnp.float32)
            )

            # ---- exchanges: activations ride right, cotangents left ----
            act_recv = lax.ppermute(y, axis, right)
            cot_recv = lax.ppermute(gx, axis, left)
            jf_l = fwd_sched[t, s_left]
            jb_r = bwd_sched[t, s_right]
            in_act = masked_write(
                in_act, jnp.clip(jf_l, 0, m - 1) % in_buf, act_recv,
                (jf_l >= 0) & (s > 0),
            )
            gy = masked_write(
                gy, jnp.clip(jb_r, 0, m - 1) % gy_buf, cot_recv,
                (jb_r >= 0) & (s < p - 1),
            )
            return (in_act, gy, x_saved, grads, loss_sum), None

        zeros_g = jax.tree_util.tree_map(jnp.zeros_like, stage_params)
        init = (
            jnp.zeros((in_buf,) + mb_shape, dtype),
            jnp.zeros((gy_buf,) + mb_shape, dtype),
            jnp.zeros((x_buf,) + mb_shape, dtype),
            zeros_g,
            jnp.zeros((), jnp.float32),
        )
        (_, _, _, grads, loss_sum), _ = lax.scan(
            tick, init, jnp.arange(rows_f.shape[0])
        )
        return lax.psum(loss_sum, axis), grads

    return fn
