"""Tensor (intra-layer model) parallelism.

The reference ships tensor parallelism as a *user-level pattern*, not
machinery: ``MPLinear`` splits a Linear's input dimension across ranks and
partial-sum-allreduces the forward activations and backward input-gradients
(``examples/mnist/mnist_modelparallel.lua:30-61``). The framework deliverable
is the pattern built from its collectives.

TPU-native form: :class:`MPLinear` is a flax module whose kernel is split
along the input-feature axis over a named mesh axis. Inside ``shard_map``
each device holds its kernel slice and its input-feature slice; the forward
``psum`` over the tp axis reconstructs the full output (and, because psum's
transpose is psum, the backward gradient flow matches the reference's
``gradInput`` allreduce automatically — no monkey-patching needed under
autodiff).
"""

from __future__ import annotations

from typing import Any, Callable, Optional

import flax.linen as fnn
import jax
import jax.numpy as jnp
from jax import lax


class MPLinear(fnn.Module):
    """Input-dimension-split tensor-parallel Dense.

    Use inside ``shard_map`` with mesh axis ``axis``: the caller passes the
    local input-feature shard ``x_local [B, in_features/tp]``; the module
    holds the matching kernel shard and returns the full ``[B, features]``
    output (partial products psum-reduced over ``axis``); each rank
    contributes bias/tp to the sum so the full bias appears exactly once.
    """

    features: int
    axis: str = "tp"
    use_bias: bool = True
    dtype: Any = jnp.float32

    @fnn.compact
    def __call__(self, x_local):
        in_local = x_local.shape[-1]
        kernel = self.param(
            "kernel",
            fnn.initializers.lecun_normal(),
            (in_local, self.features),
            self.dtype,
        )
        partial = jnp.dot(x_local.astype(self.dtype), kernel)
        if self.use_bias:
            # Fold bias/tp into every rank's partial BEFORE the psum so (a)
            # all ranks see the biased output (the reference's single owner
            # contributes its bias to the allreduced sum) and (b) the bias
            # gradient is dout/tp on every rank, keeping replicated bias
            # copies bit-identical under training.
            bias = self.param(
                "bias", fnn.initializers.zeros, (self.features,), self.dtype
            )
            partial = partial + bias / lax.axis_size(self.axis)
        return lax.psum(partial, self.axis)


class MPLinearOutputSplit(fnn.Module):
    """Output-dimension-split Dense: each device computes its slice of the
    output features; compose with an input-split layer (Megatron-style
    column->row pairing) so no collective is needed between the two."""

    features_per_shard: int
    use_bias: bool = True
    dtype: Any = jnp.float32

    @fnn.compact
    def __call__(self, x):
        kernel = self.param(
            "kernel",
            fnn.initializers.lecun_normal(),
            (x.shape[-1], self.features_per_shard),
            self.dtype,
        )
        out = jnp.dot(x.astype(self.dtype), kernel)
        if self.use_bias:
            bias = self.param(
                "bias",
                fnn.initializers.zeros,
                (self.features_per_shard,),
                self.dtype,
            )
            out = out + bias
        return out


def shard_input_features(x, axis: str = "tp"):
    """Slice the trailing feature axis to this device's tp shard — the
    caller-side half of the MPLinear pattern (reference splits the input
    dim across ranks, mnist_modelparallel.lua:34-38)."""
    tp = lax.axis_size(axis)
    r = lax.axis_index(axis)
    n = x.shape[-1]
    if n % tp != 0:
        raise ValueError(f"feature dim {n} not divisible by tp={tp}")
    per = n // tp
    return lax.dynamic_slice_in_dim(x, r * per, per, axis=x.ndim - 1)
