"""Multi-axis mesh construction from communicators.

Factor a communicator's devices into named parallelism axes (dp / tp / sp /
...) — the TPU-native generalisation of the reference's 2-level intra/inter
communicator hierarchy to arbitrary strategy products. The last axis varies
fastest, so adjacent-ICI neighbors land on the innermost (most
bandwidth-hungry) axis, matching the scaling-book recipe of putting tp/sp
on the shortest ICI hops.
"""

from __future__ import annotations

from typing import Dict, Optional, Sequence, Tuple

import jax
import numpy as np
from jax.sharding import Mesh

from ..runtime.communicator import Communicator


def make_parallel_mesh(
    comm: Optional[Communicator] = None,
    axes: Optional[Dict[str, int]] = None,
) -> Mesh:
    """Build a named mesh over the communicator's devices.

    ``axes`` maps axis name -> size in declaration order (outermost first),
    e.g. ``{"dp": 2, "tp": 2, "sp": 2}`` on 8 devices. One axis may be -1
    (inferred). Sizes must multiply to the communicator size.
    """
    if comm is None:
        from .. import runtime_state

        comm = runtime_state.current_communicator()
    axes = dict(axes or {"dp": comm.size})
    sizes = list(axes.values())
    n = comm.size
    unknown = [i for i, s in enumerate(sizes) if s == -1]
    if len(unknown) > 1:
        raise ValueError("at most one axis size may be -1")
    if unknown:
        known = int(np.prod([s for s in sizes if s != -1]))
        if n % known != 0:
            raise ValueError(f"cannot infer axis: {n} devices over {known}")
        sizes[unknown[0]] = n // known
    if int(np.prod(sizes)) != n:
        raise ValueError(
            f"axes {dict(zip(axes, sizes))} do not cover {n} devices"
        )
    arr = np.array(comm.devices, dtype=object).reshape(sizes)
    return Mesh(arr, tuple(axes.keys()))
