"""Expert parallelism (MoE) over a mesh axis.

The reference predates MoE entirely (SURVEY.md §2.3 marks EP absent); this
is a capability extension in the modern taxonomy, built TPU-first:

- each device along the ``ep`` mesh axis owns ONE expert's parameters and
  a shard of the tokens;
- top-k routing (k=1 Switch-style, k=2 the GShard default) with a fixed
  per-expert **capacity** keeps every shape static (XLA requirement):
  token t goes to its k highest-scoring experts unless an expert's
  capacity is exhausted, in which case that route is dropped (its output
  contribution is zero — the standard overflow rule; first choices queue
  before second choices);
- dispatch/combine are einsums against a boolean ``[T, E, C]`` dispatch
  tensor (the Mesh-TensorFlow formulation), and the cross-device exchange
  is a single ``lax.all_to_all`` each way — the ICI-native analog of the
  all-to-all EP traffic in modern MoE stacks;
- everything is differentiable: gradients flow through the gate values
  and the expert parameters (the dispatch mask is constant wrt inputs).
"""

from __future__ import annotations

from typing import Callable

import jax
import jax.numpy as jnp
from jax import lax


def moe_dispatch_combine(
    x,
    router_logits,
    expert_fn: Callable,
    expert_params,
    axis: str = "ep",
    capacity: int | None = None,
    top_k: int = 1,
    renormalize: bool = True,
):
    """Route each token to its top-k experts across the ``axis`` devices.

    Parameters
    ----------
    x : ``[T, d]`` this device's token shard.
    router_logits : ``[T, E]`` routing scores (E = axis size).
    expert_fn : ``expert_fn(params, tokens[N, d]) -> [N, d]`` — THIS
        device's expert computation.
    expert_params : this device's expert parameter pytree.
    capacity : per-expert slots per source device (default:
        2 * ceil(k*T/E), the usual capacity-factor-2 headroom scaled by
        the routing multiplicity).
    top_k : experts per token. 1 = Switch-style; 2 = the GShard default.
        Capacity is charged in choice priority order: every token's first
        choice queues before any token's second choice, so under pressure
        primary routes survive and secondary routes drop first.
    renormalize : for ``top_k > 1``, rescale the selected gate
        probabilities to sum to 1 per token (GShard semantics). Ignored
        for ``top_k=1``, which keeps the raw softmax probability
        (Switch semantics, and round-2 behavior).

    Returns ``[T, d]`` combined outputs (dropped routes contribute zeros).
    """
    E = lax.axis_size(axis)
    T, d = x.shape
    k = top_k
    if not 1 <= k <= E:
        raise ValueError(f"top_k must be in [1, {E}], got {k}")
    if router_logits.shape != (T, E):
        raise ValueError(
            f"router_logits must be [T={T}, E={E}], got "
            f"{tuple(router_logits.shape)}"
        )
    C = capacity if capacity is not None else 2 * (-(-(k * T) // E))
    if C <= 0:
        raise ValueError(f"capacity must be positive, got {C}")

    gates = jax.nn.softmax(router_logits, axis=-1)  # [T, E]
    _, idxs = lax.top_k(router_logits, k)  # [T, k]
    onehots = jax.nn.one_hot(idxs, E, dtype=x.dtype)  # [T, k, E]
    gate_vals = jnp.einsum("te,tke->tk", gates, onehots)  # [T, k]
    if k > 1 and renormalize:
        gate_vals = gate_vals / jnp.maximum(
            jnp.sum(gate_vals, axis=-1, keepdims=True), 1e-9
        )

    # per-expert queue positions, choice-major: all first choices count
    # before any second choice (GShard's priority rule). Counted in int32,
    # NOT x.dtype: a bf16 cumsum cannot represent queue positions past 256
    # (257 rounds to 256), which would silently blend tokens into shared
    # capacity slots.
    oh_i = jax.nn.one_hot(idxs, E, dtype=jnp.int32)  # [T, k, E]
    oh_cm = oh_i.transpose(1, 0, 2).reshape(k * T, E)
    pos_cm = jnp.cumsum(oh_cm, axis=0) - oh_cm
    my_pos = (
        jnp.sum(pos_cm * oh_cm, axis=-1).reshape(k, T).T
    )  # [T, k] int32
    keep = (my_pos < C).astype(x.dtype)
    # per-choice dispatch [T, k, E, C]; slots are disjoint by construction
    disp_k = (
        onehots[:, :, :, None]
        * jax.nn.one_hot(my_pos, C, dtype=x.dtype)[:, :, None, :]
        * keep[:, :, None, None]
    )
    disp = jnp.sum(disp_k, axis=1)  # [T, E, C] dispatch mask
    comb = jnp.einsum("tkec,tk->tec", disp_k, gate_vals)  # gate-weighted

    # [E, C, d]: slot (e, c) holds the token bound for expert e
    expert_inputs = jnp.einsum("tec,td->ecd", disp, x)
    # exchange: dim 0 (expert) splits across devices, arrivals stack on a
    # new source dim -> [E_src, C, d] all bound for MY expert
    arrived = lax.all_to_all(
        expert_inputs, axis, split_axis=0, concat_axis=0, tiled=True
    )
    outs = expert_fn(expert_params, arrived.reshape(E * C, d)).reshape(
        E, C, d
    )
    # route results back to their source devices
    returned = lax.all_to_all(
        outs, axis, split_axis=0, concat_axis=0, tiled=True
    )
    # combine: scatter back to token order, gate-weighted per route
    return jnp.einsum("tec,ecd->td", comb, returned)


def moe_load_stats(router_logits, axis: str = "ep", top_k: int = 1):
    """(tokens_per_expert[E], aux_load_balance_loss) — the standard
    mean-gate x mean-assignment auxiliary loss that discourages expert
    collapse. ``tokens_per_expert`` counts every selected route (each
    token occupies capacity at k experts), but the aux loss uses the
    GShard dispatch fraction — FIRST choice only — for any ``top_k``, so
    its magnitude matches the standard formulation and load-balance
    coefficients tuned on GShard/Switch setups transfer unchanged."""
    E = lax.axis_size(axis)
    gates = jax.nn.softmax(router_logits, axis=-1)
    _, idxs = lax.top_k(router_logits, top_k)
    routes = jnp.sum(jax.nn.one_hot(idxs, E, dtype=gates.dtype), axis=1)
    first = jax.nn.one_hot(idxs[:, 0], E, dtype=gates.dtype)
    # global statistics across every device's token shard
    tokens_per_expert = lax.psum(jnp.sum(routes, axis=0), axis)
    me = lax.pmean(jnp.mean(gates, axis=0), axis)
    ce = lax.pmean(jnp.mean(first, axis=0), axis)
    return tokens_per_expert, E * jnp.sum(me * ce)
