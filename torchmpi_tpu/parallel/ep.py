"""Expert parallelism (MoE) over a mesh axis.

The reference predates MoE entirely (SURVEY.md §2.3 marks EP absent); this
is a capability extension in the modern taxonomy, built TPU-first:

- each device along the ``ep`` mesh axis owns ONE expert's parameters and
  a shard of the tokens;
- top-1 routing with a fixed per-expert **capacity** keeps every shape
  static (XLA requirement): token t goes to expert ``argmax(logits[t])``
  unless that expert's capacity is exhausted, in which case the token is
  dropped (its output contribution is zero — the standard Switch-style
  overflow rule);
- dispatch/combine are einsums against a boolean ``[T, E, C]`` dispatch
  tensor (the Mesh-TensorFlow formulation), and the cross-device exchange
  is a single ``lax.all_to_all`` each way — the ICI-native analog of the
  all-to-all EP traffic in modern MoE stacks;
- everything is differentiable: gradients flow through the gate values
  and the expert parameters (the dispatch mask is constant wrt inputs).
"""

from __future__ import annotations

from typing import Callable

import jax
import jax.numpy as jnp
from jax import lax


def moe_dispatch_combine(
    x,
    router_logits,
    expert_fn: Callable,
    expert_params,
    axis: str = "ep",
    capacity: int | None = None,
):
    """Route each token to its top-1 expert across the ``axis`` devices.

    Parameters
    ----------
    x : ``[T, d]`` this device's token shard.
    router_logits : ``[T, E]`` routing scores (E = axis size).
    expert_fn : ``expert_fn(params, tokens[N, d]) -> [N, d]`` — THIS
        device's expert computation.
    expert_params : this device's expert parameter pytree.
    capacity : per-expert slots per source device (default: 2 * ceil(T/E),
        the usual capacity-factor-2 headroom).

    Returns ``[T, d]`` combined outputs (dropped tokens contribute zeros).
    """
    E = lax.axis_size(axis)
    T, d = x.shape
    if router_logits.shape != (T, E):
        raise ValueError(
            f"router_logits must be [T={T}, E={E}], got "
            f"{tuple(router_logits.shape)}"
        )
    C = capacity if capacity is not None else 2 * (-(-T // E))
    if C <= 0:
        raise ValueError(f"capacity must be positive, got {C}")

    gates = jax.nn.softmax(router_logits, axis=-1)  # [T, E]
    expert_idx = jnp.argmax(router_logits, axis=-1)  # [T]
    onehot = jax.nn.one_hot(expert_idx, E, dtype=x.dtype)  # [T, E]
    gate_val = jnp.sum(gates * onehot, axis=-1)  # [T] top-1 prob

    # position of each token within its expert's queue; overflow dropped
    pos = jnp.cumsum(onehot, axis=0) - onehot  # [T, E] pre-count
    my_pos = jnp.sum(pos * onehot, axis=-1)  # [T]
    keep = my_pos < C
    # dispatch tensor [T, E, C]
    disp = (
        onehot[:, :, None]
        * jax.nn.one_hot(my_pos, C, dtype=x.dtype)[:, None, :]
        * keep[:, None, None].astype(x.dtype)
    )

    # [E, C, d]: slot (e, c) holds the token bound for expert e
    expert_inputs = jnp.einsum("tec,td->ecd", disp, x)
    # exchange: dim 0 (expert) splits across devices, arrivals stack on a
    # new source dim -> [E_src, C, d] all bound for MY expert
    arrived = lax.all_to_all(
        expert_inputs, axis, split_axis=0, concat_axis=0, tiled=True
    )
    outs = expert_fn(expert_params, arrived.reshape(E * C, d)).reshape(
        E, C, d
    )
    # route results back to their source devices
    returned = lax.all_to_all(
        outs, axis, split_axis=0, concat_axis=0, tiled=True
    )
    # combine: scatter back to token order, weighted by the gate prob
    y = jnp.einsum("tec,ecd->td", disp, returned)
    return y * gate_val[:, None]


def moe_load_stats(router_logits, axis: str = "ep"):
    """(tokens_per_expert[E], aux_load_balance_loss) — the standard
    mean-gate x mean-assignment auxiliary loss that discourages expert
    collapse."""
    E = lax.axis_size(axis)
    gates = jax.nn.softmax(router_logits, axis=-1)
    assign = jax.nn.one_hot(
        jnp.argmax(router_logits, axis=-1), E, dtype=gates.dtype
    )
    # global statistics across every device's token shard
    tokens_per_expert = lax.psum(jnp.sum(assign, axis=0), axis)
    me = lax.pmean(jnp.mean(gates, axis=0), axis)
    ce = lax.pmean(jnp.mean(assign, axis=0), axis)
    return tokens_per_expert, E * jnp.sum(me * ce)
