from .ep import moe_dispatch_combine, moe_load_stats
from .mesh import make_parallel_mesh
from .pp import (
    pipeline_1f1b_value_and_grad,
    pipeline_forward,
    pipeline_loss_fn,
)
from .ring_attention import full_self_attention, ring_self_attention
from .tp import MPLinear, MPLinearOutputSplit, shard_input_features

__all__ = [
    "make_parallel_mesh",
    "moe_dispatch_combine",
    "moe_load_stats",
    "pipeline_1f1b_value_and_grad",
    "pipeline_forward",
    "pipeline_loss_fn",
    "ring_self_attention",
    "full_self_attention",
    "MPLinear",
    "MPLinearOutputSplit",
    "shard_input_features",
]
