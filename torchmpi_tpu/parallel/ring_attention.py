"""Ring attention: sequence/context parallelism over a mesh axis.

Long-context capability absent from the 2017 reference (SURVEY.md §5) but
first-class here: the sequence axis is sharded over devices, and attention
is computed by rotating key/value blocks around the ring with ``ppermute``
(one ICI hop per step) while queries stay resident — communication overlaps
the per-block attention compute, and no device ever materialises the full
sequence. Flash-style streaming softmax (running max + normalizer) keeps
the math exact.

Two backends behind one function: the pure-XLA path (``backend='xla'``,
works on the CPU test mesh and lowers ppermute to ICI collective-permute
on TPU) and the Pallas kernel with explicit double-buffered K/V RDMA and
the streaming-softmax merge in-kernel
(``backend='pallas'``/``'pallas_interpret'``, ``ops/ring_attention_kernel
.py``). Oversized working sets auto-chunk over batch/heads (each chunk
rides its own ring); ``backend='auto'`` picks the kernel on real
multi-chip TPU whenever a single (batch, head) cell fits the VMEM
envelope, the XLA path otherwise.

Derived from the ring-attention pattern in the public pallas guide and the
scaling-book recipe: shift-K/V ring + online softmax.
"""

from __future__ import annotations

import math
from functools import partial
from typing import Optional

import jax
import jax.numpy as jnp
from jax import lax

NEG_INF = -1e30


def _block_attn(q, k, v, bias):
    """Scores and partial numerator/denominator for one (q-block, kv-block)
    pair with streaming-softmax bookkeeping. Score/accumulator math in
    float32 regardless of input dtype (flash-attention numerics)."""
    s = jnp.einsum(
        "...qhd,...khd->...hqk",
        q.astype(jnp.float32),
        k.astype(jnp.float32),
    ) / math.sqrt(q.shape[-1])
    if bias is not None:
        s = s + bias
    m = jnp.max(s, axis=-1)  # [..., h, q]
    p = jnp.exp(s - m[..., None])
    l = jnp.sum(p, axis=-1)  # [..., h, q]
    o = jnp.einsum("...hqk,...khd->...qhd", p, v.astype(jnp.float32))
    return o, m, l


def ring_self_attention(
    q,
    k,
    v,
    axis: str = "sp",
    causal: bool = False,
    axis_size: Optional[int] = None,
    backend: str = "xla",
):
    """Exact self-attention over a sequence sharded along ``axis``.

    Args: q/k/v of shape ``[batch, seq_local, heads, head_dim]`` — the local
    sequence shard. Returns the attention output for the local queries,
    identical (up to float error) to full attention over the gathered
    sequence.

    ``backend``: ``'xla'`` (ppermute ring); ``'auto'`` (the RDMA kernel
    on real multi-chip TPU when a single (batch, head) cell fits VMEM —
    larger working sets auto-chunk — else the XLA ring); or any
    combination of ``'pallas'`` with the suffix tokens ``_interpret``
    (interpret mode — CPU-mesh validation), ``_bidir`` (bidirectional
    forward: both ICI directions carry K/V chains, ~half the ring
    steps), and ``_full`` (RDMA backward kernel too — dK/dV accumulators
    ride the ring home with their blocks; default backward is the
    analytic XLA ring from the saved residuals). E.g.
    ``'pallas_interpret_bidir_full'``.

    Causal masking accounts for the global positions: the k/v block visiting
    at ring step s originated on rank ``(r - s) mod p``, so its global
    offset is known statically per step.
    """
    if backend != "xla":
        from ..ops.ring_attention_kernel import (
            _VMEM_BUDGET_BYTES,
            ring_attention,
            ring_attention_vmem_bytes,
        )

        tokens = set(backend.split("_"))
        if backend.startswith("pallas") and tokens <= {
            "pallas", "interpret", "full", "bidir"
        }:
            return ring_attention(
                q, k, v, axis, causal, axis_size,
                "interpret" in tokens,
                "full" in tokens,
                None,
                "bidir" in tokens,
            )
        if backend == "auto":
            from ..ops.ring_kernels import available

            # the kernel auto-chunks over batch/heads, so it is usable
            # whenever a single (batch, head) cell fits the envelope
            b, n, h, d = q.shape
            if (
                available()
                and ring_attention_vmem_bytes((1, n, 1, d), q.dtype)
                <= _VMEM_BUDGET_BYTES
            ):
                return ring_attention(q, k, v, axis, causal, axis_size, False)
        else:
            raise ValueError(f"unknown ring-attention backend {backend!r}")
    p = axis_size or lax.axis_size(axis)
    b, n_local, h, d = q.shape
    r = lax.axis_index(axis)
    perm = [(i, (i + 1) % p) for i in range(p)]

    q_pos = r * n_local + jnp.arange(n_local)  # global query positions

    def step(s, carry):
        o, m, l, kv = carry
        kb, vb = kv
        src = (r - s) % p  # which rank's shard we hold this step
        k_pos = src * n_local + jnp.arange(n_local)
        bias = None
        if causal:
            mask = q_pos[:, None] >= k_pos[None, :]  # [q, k]
            bias = jnp.where(mask, 0.0, NEG_INF)[None, None, :, :]
        ob, mb, lb = _block_attn(q, kb, vb, bias)
        # streaming softmax merge
        m_new = jnp.maximum(m, mb)
        alpha = jnp.exp(m - m_new)
        beta = jnp.exp(mb - m_new)
        l_new = l * alpha + lb * beta
        o_new = (
            o * alpha.transpose(0, 2, 1)[..., None]
            + ob * beta.transpose(0, 2, 1)[..., None]
        )
        # rotate k/v to the next rank (skip the final, unused rotation is
        # harmless and keeps the loop body uniform)
        kb = lax.ppermute(kb, axis, perm)
        vb = lax.ppermute(vb, axis, perm)
        return o_new, m_new, l_new, (kb, vb)

    o0 = jnp.zeros((b, n_local, h, d), jnp.float32)  # f32 accumulator
    m0 = jnp.full((b, h, n_local), NEG_INF, jnp.float32)
    l0 = jnp.zeros((b, h, n_local), jnp.float32)
    o, m, l, _ = lax.fori_loop(0, p, step, (o0, m0, l0, (k, v)))
    l = jnp.maximum(l, 1e-30)
    return (o / l.transpose(0, 2, 1)[..., None]).astype(q.dtype)


def full_self_attention(q, k, v, causal: bool = False):
    """Single-device reference attention (for parity tests)."""
    s = jnp.einsum("bqhd,bkhd->bhqk", q, k) / math.sqrt(q.shape[-1])
    if causal:
        n = q.shape[1]
        mask = jnp.tril(jnp.ones((n, n), bool))
        s = jnp.where(mask[None, None], s, NEG_INF)
    w = jax.nn.softmax(s, axis=-1)
    return jnp.einsum("bhqk,bkhd->bqhd", w, v)
