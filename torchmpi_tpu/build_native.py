"""Build the native runtime: ``python -m torchmpi_tpu.build_native``."""

from __future__ import annotations

import subprocess
import sys
from pathlib import Path


def main() -> int:
    csrc = Path(__file__).resolve().parent / "csrc"
    proc = subprocess.run(["make"], cwd=csrc)
    if proc.returncode == 0:
        from .runtime import native

        lib = native.get_lib()
        if lib is not None:
            print(f"built + loaded: {native._SO} ({lib.tpumpi_version().decode()})")
            return 0
    print("native build failed; pure-Python fallbacks remain active")
    return 1


if __name__ == "__main__":
    sys.exit(main())
