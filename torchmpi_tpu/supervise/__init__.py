"""Self-healing fleet: the verdict-driven recovery supervisor.

The stack's sensing half (flight recorder -> analyzer -> live
streaming verdicts) meets its acting half here: a policy engine that
maps each streaming verdict to a bounded remediation —

    rank-dead / hang      -> evict + live shrink (ElasticCoordinator)
    straggler             -> quarantine (evict + rejoin denylist)
    resize-incomplete     -> evict the ranks that never entered
    desync / resize-torn  -> checkpoint rollback (kill the world,
                             relaunch from the last registered
                             checkpoint_every artifact)
    overload              -> scale-up (live grow through the elastic
                             coordinator; the serving brownout ladder
                             holds the line at max world)
    underload             -> scale-down (retire the highest live rank)
    clean (persisting)    -> grow back (opt-in)

with hysteresis, jittered bounded retries, and an escalation ladder.
``launch --elastic --supervise`` runs it against the real job;
``SimFleet.attach_supervisor`` replays the identical decisions at
1k-10k simulated ranks, byte-identically per seed. See
:mod:`.core` (engine), :mod:`.policy` (the declarative table), and
:mod:`.checkpoints` (the last-good-checkpoint registry rollbacks
restore from).
"""

from .checkpoints import (  # noqa: F401
    describe_last,
    last_checkpoint,
    register_checkpoint,
)
from .core import Actuator, RecoverySupervisor  # noqa: F401
from .policy import (  # noqa: F401
    A_EVICT,
    A_GROW,
    A_QUARANTINE,
    A_ROLLBACK,
    A_SCALE_DOWN,
    A_SCALE_UP,
    PolicyRule,
    default_policy,
)

__all__ = [
    "Actuator", "RecoverySupervisor", "PolicyRule", "default_policy",
    "register_checkpoint", "last_checkpoint", "describe_last",
    "A_EVICT", "A_GROW", "A_QUARANTINE", "A_ROLLBACK",
    "A_SCALE_UP", "A_SCALE_DOWN",
]
