"""RecoverySupervisor: the verdict -> action policy engine.

The sensing half already exists — the PR 12 live plane streams
per-rank telemetry into a :class:`~..telemetry.live.FleetAggregator`
whose :meth:`~..telemetry.live.FleetAggregator.evaluate` names ONE
verdict per window. This module is the acting half: a deterministic
state machine that consumes those verdict documents and drives
remediation through an injected **actuator**, so the same engine runs

- in the launcher (``launch --elastic --supervise``): the actuator
  kills wedged workers, lets the elastic coordinator commit the live
  shrink, grows replacements, and — the last rung — kills the world so
  the launcher relaunches from the last registered checkpoint;
- in the fleet simulator (:meth:`~..sim.fleet.SimFleet
  .attach_supervisor`): the same decisions on the virtual clock at
  1k-10k ranks, byte-identical per seed;
- in tests: ``observe()`` is a plain synchronous call.

Safety properties (the policy table, :mod:`.policy`, carries the
numbers):

- **hysteresis** — a verdict acts only after persisting N consecutive
  aggregation windows;
- **bounded retries + jittered exponential backoff** per rung
  (deterministic: the jitter RNG is seeded);
- **escalation ladder** — evictions that fail to clear the verdict
  escalate to a checkpoint rollback; a rollback fires at most once per
  supervisor lifetime (the relaunch builds a fresh one);
- **quarantine** — stragglers are evicted AND denylisted for a
  cooldown: the grow-back rung discounts denylisted capacity from its
  target, so the supervisor will not replace a known-slow host until
  the cooldown lapses (operator-initiated grows are not vetoed);
- **dry-run** — every decision is journaled, nothing is actuated.

Every action lands in :attr:`RecoverySupervisor.journal`, in the
process flight recorder (comm ``supervisor``) when telemetry is
enabled, in the ``tm_supervisor_*`` metric lines the aggregator's
``/metrics`` serves, and in the ``/actions`` HTTP document.
"""

from __future__ import annotations

import random
import time
from typing import Any, Callable, Dict, List, Optional

from ..analysis import lockmon as _lockmon
from ..telemetry import flightrecorder as _flight
from . import checkpoints
from .policy import (
    A_EVICT,
    A_GROW,
    A_QUARANTINE,
    A_ROLLBACK,
    A_SCALE_DOWN,
    A_SCALE_UP,
    PolicyRule,
    default_policy,
)


class Actuator:
    """The remediation surface a supervisor drives. Subclasses return
    True when the action was applied (False/raise = failed attempt —
    it counts against the rung's bounded retries)."""

    def evict(self, ranks: List[int], reason: str) -> bool:
        raise NotImplementedError

    def grow(self, reason: str) -> bool:
        raise NotImplementedError

    def rollback(self, reason: str) -> bool:
        raise NotImplementedError

    # load-driven resizes default to the failure-driven primitives: an
    # actuator that can grow/evict can already scale, and one that wants
    # different mechanics (warm pools, draining) overrides these
    def scale_up(self, reason: str) -> bool:
        return self.grow(reason)

    def scale_down(self, ranks: List[int], reason: str) -> bool:
        return self.evict(ranks, reason)


class RecoverySupervisor:
    """Deterministic verdict->action engine (module docstring)."""

    def __init__(self, actuator: Actuator,
                 policy: Optional[Dict[str, PolicyRule]] = None,
                 clock: Optional[Callable[[], float]] = None,
                 dry_run: bool = False, seed: int = 0,
                 quarantine_cooldown_s: Optional[float] = None,
                 on_action: Optional[Callable[[dict], None]] = None):
        from .. import constants

        self.actuator = actuator
        self.policy = dict(policy) if policy is not None else default_policy()
        self.dry_run = bool(dry_run)
        self._clock = clock or time.time
        self._rng = random.Random(seed)
        self._on_action = on_action
        self._cooldown = float(
            constants.get("supervisor_quarantine_cooldown_s")
            if quarantine_cooldown_s is None else quarantine_cooldown_s
        )
        # scale-rung flap damping (read at construction, same contract
        # as default_policy: the launcher applies --set-constant first)
        self._scale_cooldown = float(
            constants.get("supervisor_scale_cooldown_s")
        )
        self._scale_max_world = int(
            constants.get("supervisor_scale_max_world")
        )
        self._scale_min_world = max(
            1, int(constants.get("supervisor_scale_min_world"))
        )
        self._last_scale_t = float("-inf")
        # one lock covers every mutable field: the observe loop (the
        # launcher's supervisor thread / the sim tick) mutates while the
        # aggregator's HTTP threads render /actions and /metrics — an
        # unlocked scrape mid-_act is a RuntimeError and an HTTP 500 on
        # a healthy fleet (the same rule as FleetAggregator._lock)
        self._lock = _lockmon.make_lock(
            "supervise/core.py:RecoverySupervisor._lock"
        )
        self.journal: List[dict] = []
        self.quarantined: Dict[int, float] = {}  # rank -> denylist until
        self.evicted: set = set()
        self.rolled_back = False
        self.counters: Dict[str, int] = {}
        self._verdict = "clean"
        self._windows = 0          # consecutive windows of _verdict
        self._world_high = 0       # largest fleet ever observed
        # per-verdict ladder state
        self._rung: Dict[str, int] = {}       # 0 = primary, 1 = escalated
        self._attempts: Dict[str, int] = {}   # attempts at current rung
        self._next_ok: Dict[str, float] = {}  # backoff gate

    # -- the decision step --------------------------------------------------
    def observe(self, doc: dict, now: Optional[float] = None) -> List[dict]:
        """Consume one verdict document (one aggregation window); returns
        the journal entries this window produced (possibly empty)."""
        now = self._clock() if now is None else float(now)
        with self._lock:
            verdict = doc.get("verdict", "clean")
            if verdict == self._verdict:
                self._windows += 1
            else:
                self._verdict, self._windows = verdict, 1
            self._world_high = max(
                self._world_high, len(doc.get("ranks", []))
            )
            for r in [r for r, t in self.quarantined.items() if now >= t]:
                del self.quarantined[r]
            if verdict == "clean" and (
                self._windows >= self._clean_hysteresis()
            ):
                # recovery held: the ladders reset (a LATER fault starts
                # a fresh bounded episode, not a continuation of the old
                # one) — including the evicted set, so a member that
                # REJOINS after the episode is targetable again
                self._rung.clear()
                self._attempts.clear()
                self._next_ok.clear()
                self.evicted.clear()
            rule = self.policy.get(verdict)
            if rule is None or self.rolled_back:
                return []
            if self._windows < rule.hysteresis:
                return []
            if now < self._next_ok.get(verdict, 0.0):
                return []
            return self._act(rule, verdict, doc, now)

    def _clean_hysteresis(self) -> int:
        rule = self.policy.get("clean")
        if rule is not None:
            return rule.hysteresis
        # scale rungs excluded: scale-down's deliberately long
        # hysteresis is capacity flap damping, not a bar recovery must
        # clear before fault ladders reset
        return max(
            (r.hysteresis for r in self.policy.values()
             if r.action not in (A_SCALE_UP, A_SCALE_DOWN)),
            default=1,
        )

    # -- acting -------------------------------------------------------------
    def _act(self, rule: PolicyRule, verdict: str, doc: dict,
             now: float) -> List[dict]:
        attempt = self._attempts.get(verdict, 0)
        rung = self._rung.get(verdict, 0)
        action = rule.action
        if rung == 0 and attempt >= rule.max_retries:
            if rule.escalate is None:
                return []  # rung exhausted, nowhere to go: hold
            rung = self._rung[verdict] = 1
            attempt = self._attempts[verdict] = 0
        if rung == 1:
            action = rule.escalate
            if attempt >= rule.max_retries:
                return []  # the LAST rung is bounded too: hold, don't
                # hammer a rollback path that keeps failing
        if action == A_GROW and not self._want_grow(doc):
            return []
        if action in (A_SCALE_UP, A_SCALE_DOWN):
            if now - self._last_scale_t < self._scale_cooldown:
                return []  # flap damping: one resize per cooldown, max
            world = len(doc.get("ranks", []))
            if action == A_SCALE_UP and self._scale_max_world and (
                world >= self._scale_max_world
            ):
                # at the ceiling the supervisor HOLDS: the serving
                # tier's brownout ladder degrades gracefully instead of
                # the fleet collapsing under a grow it cannot satisfy
                return []
            if action == A_SCALE_DOWN and (
                world - 1 < self._scale_min_world
            ):
                return []
        targets = self._targets(action, verdict, doc)
        entry = {
            "time": round(now, 6),
            "verdict": verdict,
            "windows": self._windows,
            "action": action,
            "ranks": targets,
            "attempt": attempt + 1,
            "escalated": rung == 1,
        }
        entry["result"] = self._perform(action, targets, verdict, now)
        self.journal.append(entry)
        key = f"{action}:{entry['result']}"
        self.counters[key] = self.counters.get(key, 0) + 1
        self._attempts[verdict] = attempt + 1
        backoff = min(
            rule.backoff_cap_s,
            rule.backoff_base_s * (2 ** attempt),
        ) * (0.5 + self._rng.random())  # +-50% jitter, seeded
        self._next_ok[verdict] = now + backoff
        self._record_flight(entry)
        if self._on_action is not None:
            try:
                self._on_action(entry)
            except Exception:  # noqa: BLE001 - reporting must not gate acting
                pass
        return [entry]

    def _perform(self, action: str, targets: List[int], verdict: str,
                 now: float) -> str:
        if self.dry_run:
            return "dry-run"
        try:
            if action in (A_EVICT, A_QUARANTINE):
                ok = True
                if targets:
                    ok = self.actuator.evict(targets, reason=verdict)
                if ok:
                    # a FAILED eviction leaves the targets fresh: the
                    # bounded retry must re-attempt the kill, not skip
                    # the ranks and exhaust the rung on no-ops
                    self.evicted.update(targets)
                    if action == A_QUARANTINE:
                        for r in targets:
                            self.quarantined[r] = now + self._cooldown
                return "applied" if ok else "failed"
            if action == A_GROW:
                return "applied" if self.actuator.grow(reason=verdict) \
                    else "failed"
            if action == A_SCALE_UP:
                ok = self.actuator.scale_up(reason=verdict)
                if ok:
                    self._last_scale_t = now
                return "applied" if ok else "failed"
            if action == A_SCALE_DOWN:
                ok = True
                if targets:
                    ok = self.actuator.scale_down(targets, reason=verdict)
                if ok:
                    self.evicted.update(targets)
                    self._last_scale_t = now
                    # a deliberate shrink lowers the observed high-water
                    # mark: grow-back must not fight scale-down by
                    # restoring capacity the load no longer needs
                    self._world_high = max(
                        self._scale_min_world,
                        self._world_high - len(targets),
                    )
                return "applied" if ok else "failed"
            if action == A_ROLLBACK:
                ok = self.actuator.rollback(reason=verdict)
                if ok:
                    self.rolled_back = True
                return "applied" if ok else "failed"
        except Exception:  # noqa: BLE001 - a failed actuation is a
            return "failed"  # counted attempt, never a supervisor crash
        return "failed"

    # -- target selection ---------------------------------------------------
    def _targets(self, action: str, verdict: str, doc: dict) -> List[int]:
        if action in (A_ROLLBACK, A_GROW, A_SCALE_UP):
            return []
        fresh = lambda rs: sorted(  # noqa: E731
            {int(r) for r in rs} - self.evicted
        )
        if action == A_SCALE_DOWN:
            # retire the HIGHEST live rank: the elastic world contracts
            # from the top, so the shrink commits without renumbering
            live = fresh(doc.get("ranks") or [])
            if len(live) <= self._scale_min_world:
                return []
            return [live[-1]]
        if verdict == "rank-dead":
            return fresh(doc.get("dead_ranks") or [])
        if verdict == "hang":
            dead = fresh(doc.get("dead_ranks") or [])
            if dead:
                return dead
            if self.evicted:
                # an eviction is already in flight this episode: the
                # survivors' stuck entries are expected evidence while
                # the shrink commits, NOT a fresh deadlock — killing the
                # "oldest waiter" here would behead a healthy rank that
                # is merely waiting out the resize. Hold (the attempt
                # still counts, so a hang that OUTLIVES the eviction
                # escalates to rollback, the designed ladder).
                return []
            stuck = doc.get("stuck") or []
            if not stuck:
                return []
            # a true deadlock names no corpse: evict the single oldest
            # waiter — the epoch bump un-wedges the rest, and the rung's
            # bounded retries keep this from decimating a healthy fleet
            oldest = min(
                stuck, key=lambda s: (float(s.get("t_issue") or 0.0),
                                      int(s.get("rank", 0))),
            )
            return fresh([int(oldest.get("rank", -1))])
        if verdict == "resize-incomplete":
            never = set()
            for info in (doc.get("resize") or {}).get("epochs", {}).values():
                never.update(int(r) for r in info.get("never_entered") or [])
            return fresh(never)
        if verdict == "straggler":
            ranking = (doc.get("stragglers") or {}).get("ranking") or []
            if not ranking:
                return []
            return fresh([int(ranking[0]["rank"])])
        return []

    def _want_grow(self, doc: dict) -> bool:
        target = self._world_high - len(self.quarantined)
        return len(doc.get("ranks", [])) < target

    # -- reporting ----------------------------------------------------------
    def _record_flight(self, entry: dict) -> None:
        if not _flight.enabled():
            return
        e = _flight.recorder.record(
            "supervisor", f"supervise.{entry['action']}",
            payload=f"ranks={entry['ranks']}",
            backend="supervisor",
            routing=f"verdict={entry['verdict']}",
            seq=len(self.journal) - 1,
        )
        if entry["result"] == "failed":
            _flight.FlightRecorder.fail(e)
        else:
            _flight.FlightRecorder.complete(e)

    def actions_doc(self, now: Optional[float] = None) -> dict:
        """The ``/actions`` HTTP document: journal + ladder state.
        Rendered under the lock — the observe loop mutates these."""
        now = self._clock() if now is None else float(now)
        with self._lock:
            return self._actions_doc_locked(now)

    def _actions_doc_locked(self, now: float) -> dict:
        return {
            "time": round(now, 6),
            "dry_run": self.dry_run,
            "verdict": self._verdict,
            "windows": self._windows,
            "rolled_back": self.rolled_back,
            "journal": list(self.journal),
            "evicted": sorted(self.evicted),
            "quarantined": {
                str(r): round(t, 6) for r, t in sorted(
                    self.quarantined.items()
                )
            },
            "counters": dict(sorted(self.counters.items())),
            "last_checkpoint": checkpoints.last_checkpoint(),
            "policy": {
                v: {
                    "action": r.action,
                    "hysteresis": r.hysteresis,
                    "max_retries": r.max_retries,
                    "escalate": r.escalate,
                }
                for v, r in sorted(self.policy.items())
            },
        }

    def prometheus_lines(self) -> List[str]:
        """``tm_supervisor_*`` gauge/counter lines for the aggregator's
        ``/metrics`` passthrough (under the lock, same reason as
        :meth:`actions_doc`)."""
        with self._lock:
            return self._prometheus_lines_locked()

    def _prometheus_lines_locked(self) -> List[str]:
        out = [
            "# HELP tm_supervisor_actions_total recovery actions taken "
            "by the supervisor, by action and result",
            "# TYPE tm_supervisor_actions_total counter",
        ]
        for key, n in sorted(self.counters.items()):
            action, _, result = key.partition(":")
            out.append(
                f'tm_supervisor_actions_total{{action="{action}",'
                f'result="{result}"}} {n}'
            )
        out += [
            "# HELP tm_supervisor_quarantined_ranks ranks currently on "
            "the rejoin denylist",
            "# TYPE tm_supervisor_quarantined_ranks gauge",
            f"tm_supervisor_quarantined_ranks {len(self.quarantined)}",
            "# HELP tm_supervisor_rolled_back 1 after the supervisor's "
            "checkpoint-rollback rung fired",
            "# TYPE tm_supervisor_rolled_back gauge",
            f"tm_supervisor_rolled_back {int(self.rolled_back)}",
            "# HELP tm_supervisor_verdict_windows consecutive windows "
            "the current verdict has persisted",
            "# TYPE tm_supervisor_verdict_windows gauge",
            f'tm_supervisor_verdict_windows{{verdict="{self._verdict}"}} '
            f"{self._windows}",
            "# HELP tm_supervisor_dry_run 1 when decisions are journaled "
            "but not actuated",
            "# TYPE tm_supervisor_dry_run gauge",
            f"tm_supervisor_dry_run {int(self.dry_run)}",
        ]
        return out
