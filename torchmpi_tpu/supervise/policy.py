"""The declarative recovery policy: verdict -> remediation, bounded.

One :class:`PolicyRule` per streaming verdict (the
:data:`~..telemetry.live.VERDICT_PRIORITY` names), each carrying the
four numbers that keep an autonomous supervisor SAFE:

- ``hysteresis`` — consecutive aggregation windows the verdict must
  persist before any action fires (a single noisy window acts on
  nobody);
- ``max_retries`` — bounded attempts per ladder rung;
- ``backoff_base_s`` / ``backoff_cap_s`` — jittered exponential backoff
  between attempts (base * 2^attempt, +-50% jitter, capped);
- ``escalate`` — the next rung when the bounded retries are exhausted
  and the verdict still stands (evictions that did not clear the
  verdict escalate to a checkpoint rollback).

The default table (:func:`default_policy`) is built from the
``supervisor_*`` constants so ``launch --set-constant`` deploys a
different temperament without code:

==================  =============  ==========================
verdict             action         escalation
==================  =============  ==========================
desync              rollback       (terminal)
resize-torn         rollback       (terminal)
hang                evict-shrink   rollback
rank-dead           evict-shrink   rollback
resize-incomplete   evict-shrink   rollback
straggler           quarantine     (none: advisory eviction)
overload            scale-up       (none: at max world the
                                   serving brownout ladder
                                   degrades instead)
ps-overload         (observe)      (none: admission control
                                   already sheds the load)
underload           scale-down     (none)
clean               grow-back      (opt-in via
                                   supervisor_grow_back)
==================  =============  ==========================

The scale rungs are the AMBITIOUS half of the ladder: every other rung
reacts to failure, these react to load (the serving tier's streaming
load verdicts). Flap damping is layered — asymmetric hysteresis
(``supervisor_scale_up_hysteresis`` fast, ``supervisor_scale_down_``
``hysteresis`` slow) plus a shared cooldown
(``supervisor_scale_cooldown_s``) between ANY two applied scale
actions, so an oscillating arrival trace cannot saw the world size.
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import Dict, Optional

from .. import constants

# action names (the journal/metrics vocabulary)
A_EVICT = "evict-shrink"
A_QUARANTINE = "quarantine"
A_ROLLBACK = "rollback"
A_GROW = "grow-back"
A_SCALE_UP = "scale-up"
A_SCALE_DOWN = "scale-down"


@dataclass(frozen=True)
class PolicyRule:
    action: str
    hysteresis: int
    max_retries: int
    backoff_base_s: float
    backoff_cap_s: float
    escalate: Optional[str] = None


def default_policy() -> Dict[str, PolicyRule]:
    """The shipped table, parameterized by the ``supervisor_*`` knobs
    (read at construction: the launcher builds the supervisor after
    applying ``--set-constant`` overrides)."""
    hyst = int(constants.get("supervisor_hysteresis_windows"))
    retries = int(constants.get("supervisor_max_retries"))
    base = float(constants.get("supervisor_backoff_base_s"))
    cap = float(constants.get("supervisor_backoff_cap_s"))

    def rule(action: str, escalate: Optional[str] = None,
             hysteresis: Optional[int] = None) -> PolicyRule:
        return PolicyRule(
            action=action,
            hysteresis=hyst if hysteresis is None else hysteresis,
            max_retries=retries,
            backoff_base_s=base,
            backoff_cap_s=cap,
            escalate=escalate,
        )

    table: Dict[str, PolicyRule] = {
        # a cross-rank collective divergence cannot be repaired by
        # membership surgery: the streams already disagree
        "desync": rule(A_ROLLBACK),
        # a torn resize means the redistribution sources are suspect
        "resize-torn": rule(A_ROLLBACK),
        "hang": rule(A_EVICT, escalate=A_ROLLBACK),
        "rank-dead": rule(A_EVICT, escalate=A_ROLLBACK),
        "resize-incomplete": rule(A_EVICT, escalate=A_ROLLBACK),
        "straggler": rule(A_QUARANTINE),
        # ps-overload is absent on purpose: BUSY/backoff admission
        # control is the load-shedding mechanism; killing servers under
        # load would amplify the storm
        #
        # the load rungs (serving tier): scale-up reacts faster than
        # scale-down by construction — asymmetric hysteresis is the
        # first line of flap damping, the supervisor's shared scale
        # cooldown the second
        "overload": rule(
            A_SCALE_UP,
            hysteresis=int(
                constants.get("supervisor_scale_up_hysteresis")
            ),
        ),
        "underload": rule(
            A_SCALE_DOWN,
            hysteresis=int(
                constants.get("supervisor_scale_down_hysteresis")
            ),
        ),
    }
    if bool(constants.get("supervisor_grow_back")):
        # grow back only after the fleet has been CLEAN for the same
        # hysteresis the destructive rungs require
        table["clean"] = rule(A_GROW)
    return table
