"""Last-good-checkpoint registry: the rollback artifact, by name.

Every failure path that ends in "restore from checkpoint" — the
exhausted single-fault contract, a history :class:`~..reshard.elastic.
DataLoss`, the supervisor's rollback rung — needs the artifact NAMED:
which checkpoint, at which step. This module is that single fact,
stdlib-only (the elastic layer imports it, and the elastic layer runs
without jax):

- :func:`register_checkpoint` — called by every checkpoint producer
  (``save_engine_sharded``, ``ElasticZero1.checkpoint_every``) after a
  save PUBLISHES (the atomic pointer swung, the artifact is readable).
  Records the fact in-process and, when ``TORCHMPI_TPU_CHECKPOINT_STATE``
  names a file, mirrors it there atomically — which is how the
  launcher-resident supervisor (a different process) learns what it can
  roll back to, and how a relaunched worker finds what to resume from.
- :func:`last_checkpoint` — the newest registered record (in-process
  first, the shared state file as fallback), or None.
- :func:`describe_last` — the human/exception fragment: DataLoss
  messages and the supervisor's rollback journal both embed it, so the
  operator never sees a bare "restore from checkpoint" again.

The state file holds one JSON object ``{"path", "step", "time"}``.
Replacement rule: a record for the SAME artifact path always wins (the
file on disk was just atomically replaced — the registry must follow,
including across a restart whose step counter started over); a record
for a DIFFERENT path only wins with a step at least as high (a late
async save of an older artifact must not roll the pointer back).
"""

from __future__ import annotations

import json
import os
import threading
import time
from pathlib import Path
from typing import Any, Dict, Optional

#: env var naming the cross-process state file (the launcher exports it
#: to every elastic worker; the supervisor reads the same path)
STATE_ENV = "TORCHMPI_TPU_CHECKPOINT_STATE"

_lock = threading.Lock()
_last: Optional[Dict[str, Any]] = None


def state_file() -> Optional[Path]:
    """The shared registry file, when the environment names one."""
    p = os.environ.get(STATE_ENV, "")
    return Path(p) if p else None


def register_checkpoint(path, step: int,
                        extra: Optional[Dict[str, Any]] = None) -> Dict[str, Any]:
    """Record ``path`` (already published) as the newest rollback
    artifact at ``step``. Returns the record. Never raises on I/O — a
    failed mirror write must not fail the save that just succeeded."""
    global _last
    rec = {
        "path": str(Path(path).resolve()),
        "step": int(step),
        "time": time.time(),
        **(extra or {}),
    }
    with _lock:
        if (
            _last is None
            or _last.get("path") == rec["path"]
            or int(_last.get("step", -1)) <= rec["step"]
        ):
            _last = rec
    sf = state_file()
    if sf is not None:
        try:
            prev = _read_file(sf)
            if (
                prev is not None
                and prev.get("path") != rec["path"]
                and int(prev.get("step", -1)) > rec["step"]
            ):
                return rec  # a newer DIFFERENT artifact is registered
            sf.parent.mkdir(parents=True, exist_ok=True)
            tmp = sf.with_name(sf.name + f".tmp.{os.getpid()}")
            tmp.write_text(json.dumps(rec))
            os.replace(tmp, sf)
        except OSError:
            pass
    return rec


def _read_file(sf: Path) -> Optional[Dict[str, Any]]:
    try:
        return json.loads(sf.read_text())
    except (OSError, ValueError):
        return None


def last_checkpoint() -> Optional[Dict[str, Any]]:
    """The newest registered checkpoint record: the in-process one or,
    when another process registered later (higher step) via the shared
    state file, that one."""
    with _lock:
        mine = dict(_last) if _last is not None else None
    sf = state_file()
    shared = _read_file(sf) if sf is not None else None
    if mine is None:
        return shared
    if shared is not None and int(shared.get("step", -1)) > int(
        mine.get("step", -1)
    ):
        return shared
    return mine


def describe_last() -> str:
    """The message fragment every restore-from-checkpoint error embeds:
    the artifact named, or the absence called out."""
    rec = last_checkpoint()
    if rec is None:
        return (
            "restore from checkpoint (none registered — arm "
            "checkpoint_every so a rollback artifact exists)"
        )
    return (
        f"restore from checkpoint {rec['path']} (step {rec['step']})"
    )


def _reset_for_tests() -> None:
    global _last
    with _lock:
        _last = None
