"""Named shard update rules (reference ``lib/parameterserver.cpp:119-213``):
``zero`` / ``copy`` / ``add`` applied server-side to the local shard."""

from __future__ import annotations

import numpy as np


def _rule_zero(shard: np.ndarray, incoming: np.ndarray) -> None:
    shard[...] = 0


def _rule_copy(shard: np.ndarray, incoming: np.ndarray) -> None:
    shard[...] = incoming


def _rule_add(shard: np.ndarray, incoming: np.ndarray) -> None:
    shard[...] += incoming


UPDATE_RULES = {
    "zero": _rule_zero,
    "copy": _rule_copy,
    "add": _rule_add,
}
