"""Parameter-server wire codec: quantized + chunked payload encoding.

The PS data path moves whole shard slices between client and server
processes; PR 2 gave the ring collectives a block-quantized wire format
(EQuARX-style) while the PS still shipped monolithic fp32 frames. This
module is the PS analog: a host-side (numpy) codec shared by the socket
transport's frame encode/decode and the in-process path's precision
simulation, with three encodings —

- ``full``: logical bytes verbatim;
- ``bf16``: round-to-nearest-even truncation to bfloat16 (uint16 on the
  wire, half the bytes), exact for values already representable;
- ``int8``: symmetric per-block quantization (``amax/127`` scale, one
  f32 scale per ``block`` elements — the same grid as
  ``collectives/primitives.quantize_blocks``), ~3.9x fewer bytes.

Server shards stay f32 **master copies**: decode always reconstructs f32
before an update rule touches a shard, so accumulation happens at full
precision and quantization error never compounds inside the server —
only per client<->server exchange (the 1-bit-SGD / QSGD framing: the
wire, not the state, is lossy).

Chunk container
---------------

A payload bigger than ``ps_chunk_bytes`` travels as a sequence of
self-describing chunks, each independently encoded::

    [_CHUNK_HDR: off u64, total u64, nelem u32, enc_nbytes u32, block u32]
    [enc_nbytes bytes]

so the sender quantizes/serializes chunk k+1 while chunk k is on the
wire (``sendmsg`` scatter-gather, no concat copy) and the receiver
``recv_into``s each chunk and dequantizes it into the preallocated
logical buffer while the next chunk is still in flight. Chunk sizes are
deterministic from (nelem, wire, block), so the total wire length is
known before the first byte is sent (the frame header needs it).

The decoded payload is applied as ONE atomic message per frame: applying
chunk-by-chunk would let a concurrent trigger read a torn shard (the
mailbox's per-shard apply atomicity is a coherence contract the prefetch
path relies on), and a connection torn mid-stream would leave a partial
non-idempotent 'add' that a channel replay then double-applies. The
pipeline overlap therefore covers encode -> wire -> decode; the final
vectorized rule apply is one numpy op, cheap next to the dequantize it
overlaps with.
"""

from __future__ import annotations

import struct
from typing import Iterator, List, Tuple

import numpy as np

# wire codes carried in the frame header (u8)
WIRE_FULL = 0
WIRE_BF16 = 1
WIRE_INT8 = 2

WIRE_NAMES = {WIRE_FULL: "full", WIRE_BF16: "bf16", WIRE_INT8: "int8"}
WIRE_CODES = {v: k for k, v in WIRE_NAMES.items()}

# per-chunk header: logical element offset, total logical elements of the
# frame payload (same in every chunk; lets the receiver preallocate on
# first-chunk arrival), this chunk's logical element count, its encoded
# byte length, and the quantization block size (embedded so a receiver
# with a different ``wire_quant_block_size`` constant still decodes
# correctly — the sender's grid is authoritative).
_CHUNK_HDR = struct.Struct(">QQIII")
CHUNK_HDR_SIZE = _CHUNK_HDR.size

# smallest positive scale: a zero block must not divide by zero and its
# dequantized zeros stay exactly zero (same epsilon as the collective
# quantizer)
_EPS = np.float32(1e-30)


def wire_code(name: str) -> int:
    try:
        return WIRE_CODES[name]
    except KeyError:
        raise ValueError(
            f"unknown parameterserver wire dtype {name!r} "
            f"(have {sorted(WIRE_CODES)})"
        ) from None


def resolve_ps_wire(arr_dtype, explicit: str = None) -> int:
    """Effective wire code for a payload of ``arr_dtype``: quantized
    encodings engage only for float32 (f64 PS instances ship verbatim —
    the reference instantiates Float/Double and the lossy formats target
    the f32 gradient/parameter traffic)."""
    from .. import constants

    name = explicit or constants.get("parameterserver_wire_dtype")
    if np.dtype(arr_dtype) != np.float32:
        return WIRE_FULL
    return wire_code(name)


# ---------------------------------------------------------------------------
# scalar span codecs (one contiguous f32 span -> encoded bytes and back)
# ---------------------------------------------------------------------------


def _bf16_encode(x: np.ndarray) -> np.ndarray:
    """f32 -> bf16 bits (uint16) with round-to-nearest-even."""
    bits = np.ascontiguousarray(x, np.float32).view(np.uint32)
    rounded = bits + 0x7FFF + ((bits >> 16) & 1)
    return (rounded >> 16).astype(np.uint16)


def _bf16_decode(u16: np.ndarray) -> np.ndarray:
    return (u16.astype(np.uint32) << 16).view(np.float32)


def _int8_encode(x: np.ndarray, block: int) -> Tuple[np.ndarray, np.ndarray]:
    """f32 span -> (int8 values zero-padded to whole blocks, f32 scales)."""
    flat = np.ascontiguousarray(x, np.float32).reshape(-1)
    n = flat.shape[0]
    pad = -n % block
    if pad:
        flat = np.concatenate([flat, np.zeros(pad, np.float32)])
    b = flat.reshape(-1, block)
    scale = np.maximum(np.abs(b).max(axis=1), _EPS) / np.float32(127.0)
    q = np.clip(np.rint(b / scale[:, None]), -127, 127).astype(np.int8)
    return q.reshape(-1), scale.astype(np.float32)


def _int8_decode(buf, n: int, block: int) -> np.ndarray:
    if n <= 0:
        return np.empty(0, np.float32)
    nblocks = -(-n // block)
    q = np.frombuffer(buf, np.int8, count=nblocks * block)
    scale = np.frombuffer(buf, np.float32, count=nblocks,
                          offset=nblocks * block)
    # big-endian wire scales on a little-endian host: frombuffer with the
    # explicit byte order
    out = (q.reshape(-1, block).astype(np.float32)
           * scale.reshape(-1, 1)).reshape(-1)
    return out[:n]


def enc_nbytes(n: int, wire: int, block: int, itemsize: int = 4) -> int:
    """Encoded byte length of an ``n``-element span (deterministic: the
    frame header carries the total payload length before any chunk is
    encoded)."""
    if wire == WIRE_FULL:
        return n * itemsize
    if wire == WIRE_BF16:
        return n * 2
    nblocks = -(-n // block) if n > 0 else 0
    return nblocks * block + nblocks * 4


def encode_span(x: np.ndarray, wire: int, block: int) -> List:
    """Encode one contiguous span; returns a list of buffers (kept apart
    for scatter-gather sends — no concat copy)."""
    if wire == WIRE_FULL:
        return [memoryview(np.ascontiguousarray(x).reshape(-1)).cast("B")]
    if wire == WIRE_BF16:
        return [memoryview(_bf16_encode(x)).cast("B")]
    q, scale = _int8_encode(x, block)
    return [memoryview(q).cast("B"), memoryview(scale).cast("B")]


def decode_span(buf, n: int, wire: int, block: int,
                logical_dtype=np.float32) -> np.ndarray:
    """Inverse of :func:`encode_span` (``buf``: bytes-like of the encoded
    span). Returns a 1-D array of ``n`` logical elements."""
    if wire == WIRE_FULL:
        return np.frombuffer(buf, np.dtype(logical_dtype), count=n)
    if wire == WIRE_BF16:
        return _bf16_decode(np.frombuffer(buf, np.uint16, count=n))
    return _int8_decode(buf, n, block)


def roundtrip(x: np.ndarray, wire: int, block: int) -> np.ndarray:
    """decode(encode(x)) — the value a receiver reconstructs. Used by the
    in-process path to honor ``parameterserver_wire_dtype`` (so a
    single-process run exhibits the same exchange precision as the
    socket transport: convergence evidence transfers) and by the delta
    bookkeeping to track the client's exact reconstruction."""
    if wire == WIRE_FULL:
        return np.asarray(x, np.float32)
    enc = b"".join(bytes(m) for m in encode_span(x, wire, block))
    flat = decode_span(enc, int(np.asarray(x).size), wire, block)
    return flat.reshape(np.asarray(x).shape)


# ---------------------------------------------------------------------------
# chunk container
# ---------------------------------------------------------------------------


def plan_chunks(n: int, wire: int, block: int, chunk_bytes: int,
                itemsize: int = 4) -> List[Tuple[int, int]]:
    """Split an ``n``-element payload into [(offset, nelem)] chunks whose
    encoded size approximates ``chunk_bytes`` (block-aligned for int8 so
    every chunk quantizes on its own grid). ``chunk_bytes <= 0`` or a
    payload that fits one chunk yields a single chunk.

    The encoded-size policy (how many elements fit ``chunk_bytes``)
    lives here; the span math is the schedule IR's shared chunk rule
    (:func:`~..schedule.pipeline.split_spans`), so the PS wire, the
    reshard executor and the pipelined plan families cut payloads
    identically."""
    from ..schedule.pipeline import split_spans

    if n <= 0:
        return [(0, 0)]  # the empty-shard frame still carries one header
    if chunk_bytes <= 0:
        return [(0, n)]
    per_elem = max(1, enc_nbytes(block, wire, block, itemsize) // block)
    elems = max(1, chunk_bytes // per_elem)
    return list(split_spans(
        n, elems, align=block if wire == WIRE_INT8 else 1
    ))


def container_nbytes(n: int, wire: int, block: int, chunk_bytes: int,
                     itemsize: int = 4) -> Tuple[int, int]:
    """(total payload bytes incl. chunk headers, nchunks) for the frame
    header — computed before any chunk is encoded."""
    chunks = plan_chunks(n, wire, block, chunk_bytes, itemsize)
    total = sum(
        CHUNK_HDR_SIZE + enc_nbytes(cn, wire, block, itemsize)
        for _, cn in chunks
    )
    return total, len(chunks)


def iter_encoded_chunks(
    arr: np.ndarray, wire: int, block: int, chunk_bytes: int
) -> Iterator[List]:
    """Lazily yield per-chunk buffer lists ([hdr, enc...]) so the caller
    interleaves encode with socket writes: quantize/serialize of chunk
    k+1 overlaps the wire I/O of chunk k."""
    flat = np.ascontiguousarray(arr).reshape(-1)
    n = flat.shape[0]
    itemsize = flat.dtype.itemsize
    for off, cn in plan_chunks(n, wire, block, chunk_bytes, itemsize):
        enc = encode_span(flat[off:off + cn], wire, block)
        hdr = _CHUNK_HDR.pack(
            off, n, cn, enc_nbytes(cn, wire, block, itemsize), block
        )
        yield [hdr] + enc


def read_chunk_header(buf) -> Tuple[int, int, int, int, int]:
    """(off, total, nelem, enc_nbytes, block) from a chunk header blob."""
    return _CHUNK_HDR.unpack_from(buf, 0)


def encode_frame_payload(
    arr: np.ndarray, wire: int, block: int, chunk_bytes: int
) -> Tuple[List, int, int]:
    """Materialize a whole chunk container: (flat buffer list for
    scatter-gather send, total byte length, nchunks). Used where the
    encode happens away from the socket (trigger replies built on the
    server thread so delta bookkeeping can record the exact encoded
    reconstruction)."""
    parts: List = []
    nchunks = 0
    total = 0
    for bufs in iter_encoded_chunks(arr, wire, block, chunk_bytes):
        nchunks += 1
        for b in bufs:
            total += len(memoryview(b).cast("B"))
        parts.extend(bufs)
    return parts, total, nchunks


def decode_parts(parts: List, wire: int,
                 logical_dtype=np.float32) -> np.ndarray:
    """Decode a buffer list produced by :func:`encode_frame_payload`
    back to the logical array — the receiver-side reconstruction, used
    by the delta bookkeeping to track what the client now holds (so the
    next delta is computed against the client's EXACT state and
    quantization error never compounds across fetches)."""
    out = None
    i = 0
    while i < len(parts):
        off, total, cn, nb, block = read_chunk_header(parts[i])
        i += 1
        if out is None:
            out = np.empty(total, np.dtype(logical_dtype))
        if wire == WIRE_INT8:
            q = np.frombuffer(parts[i], np.int8)
            scale = np.frombuffer(parts[i + 1], np.float32)
            i += 2
            dec = (q.reshape(-1, block).astype(np.float32)
                   * scale.reshape(-1, 1)).reshape(-1)[:cn]
        else:
            dec = decode_span(parts[i], cn, wire, block, logical_dtype)
            i += 1
        out[off:off + cn] = dec
    return out if out is not None else np.empty(0, np.dtype(logical_dtype))


def decode_container(payload, nchunks: int, wire: int,
                     logical_dtype=np.float32) -> np.ndarray:
    """Decode a fully-materialized chunk container (used for payloads
    that arrived as one blob, e.g. multi-frame items); the streaming
    receive path in ``transport._read_payload`` decodes chunk-by-chunk
    instead. ``wire`` is the frame header's wire byte (authoritative for
    every chunk; the per-chunk block size still comes from each chunk
    header). ``nchunks`` is advisory — the container is self-describing
    and is consumed to exhaustion."""
    mv = memoryview(payload)
    out = None
    pos = 0
    end = len(mv)
    while pos < end:
        off, total, cn, nb, block = read_chunk_header(mv[pos:])
        pos += CHUNK_HDR_SIZE
        if out is None:
            out = np.empty(total, np.dtype(logical_dtype))
        out[off:off + cn] = decode_span(
            mv[pos:pos + nb], cn, wire, block, logical_dtype
        )
        pos += nb
    return out if out is not None else np.empty(0, np.dtype(logical_dtype))


__all__ = [
    "WIRE_FULL",
    "WIRE_BF16",
    "WIRE_INT8",
    "WIRE_NAMES",
    "WIRE_CODES",
    "CHUNK_HDR_SIZE",
    "wire_code",
    "resolve_ps_wire",
    "enc_nbytes",
    "encode_span",
    "decode_span",
    "roundtrip",
    "plan_chunks",
    "container_nbytes",
    "iter_encoded_chunks",
    "read_chunk_header",
    "decode_container",
    "encode_frame_payload",
    "decode_parts",
]
