"""Event-multiplexed socket server core for the PS transport listener.

The pre-fabric listener was thread-per-connection: an accept loop spawned
one blocking reader thread per client, so the server's thread count grew
O(clients) and topped out at tens of connections — the endpoint-
concurrency wall the TensorFlow+CUDA-aware-MPI characterization hits
once the wire itself is fast. This module replaces that with ONE event
loop thread multiplexing every connection through ``selectors`` (epoll
on Linux):

- all sockets are non-blocking; each connection owns an **incremental
  frame state machine** (:class:`Conn`) that fills preallocated buffers
  with ``recv_into`` exactly like the blocking ``_recv_exact_into``
  path did — header, rule/dtype, then either a raw payload or the PR 5
  chunk containers, dequantized chunk-by-chunk into the preallocated
  logical array as each chunk completes (decode still overlaps wire
  I/O, now across *all* connections at once);
- completed frames are handed to the listener's dispatch callback on
  the loop thread, preserving per-connection wire order (the mailbox-
  order contract the dedup tables rely on);
- replies are **queued**, never sent from pool threads: a pool worker
  enqueues the encoded reply buffers and wakes the loop via a self-
  pipe; the loop flushes with non-blocking sends and only registers
  write-interest while a connection's queue is non-empty, so one
  dead/slow client can never wedge a shared apply worker.

Thread census with the fabric: 1 loop thread + the shared apply pool +
the global server thread — O(pools), independent of client count.
"""

from __future__ import annotations

import os
import selectors
import socket
import threading
from collections import deque
from typing import Callable, List, Optional

import numpy as np

from ..analysis import lockmon as _lockmon
from . import wire as _wire


class ConnectionClosed(Exception):
    """Peer closed / protocol violation: tear down this connection only."""


# parser phases
_PH_HEAD = 0       # filling the frame header
_PH_META = 1       # filling rule + dtype bytes
_PH_RAW = 2        # filling an unchunked payload
_PH_CHUNK_HDR = 3  # filling a chunk-container header
_PH_CHUNK_BODY = 4  # filling one chunk's encoded span

# one readiness event parses at most this many complete frames before
# yielding back to the selector: a blasting client cannot starve its
# neighbours (epoll is level-triggered — buffered bytes re-arm it)
_FRAMES_PER_WAKE = 64


def _transport():
    # late import: transport imports this module at its top level
    from . import transport as T

    return T


class Conn:
    """One multiplexed connection: incremental frame parser + thread-safe
    outbound write queue. Socket I/O happens ONLY on the event-loop
    thread; any thread may enqueue replies via :meth:`queue_write`.

    The parsed frame tuple is ``(kind, inst, rank, client, seq, oseq,
    fp, rule, dtype, wire, nchunks, payload, trace, span)`` — payload
    already decoded to logical bytes for chunked/quantized frames,
    exactly what the blocking ``_recv_frame`` produced, plus the frame's
    causal trace context (zeros when unstamped).
    """

    __slots__ = (
        "sock", "fd", "out", "out_lock", "want_write", "closed",
        "busy_floor",
        "_phase", "_buf", "_view", "_got",
        "_kind", "_inst", "_rank", "_client", "_seq", "_oseq", "_fp",
        "_wirec", "_nchunks", "_rl", "_dl", "_pl", "_trace", "_span",
        "_rule", "_dtype", "_dt",
        "_payload_left", "_out_arr", "_out_mv", "_chunk_meta", "_scratch",
    )

    def __init__(self, sock: socket.socket):
        self.sock = sock
        self.fd = sock.fileno()
        self.out: "deque[memoryview]" = deque()
        self.out_lock = _lockmon.make_lock("eventloop.py:Conn.out_lock")
        self.want_write = False
        self.closed = False
        # admission-control order fence: the lowest BUSY-rejected UPDATE
        # seq on this connection (see _Listener._handle_frame)
        self.busy_floor: Optional[int] = None
        self._scratch = bytearray()
        self._start_header()

    # -- parser -----------------------------------------------------------
    def _start_header(self) -> None:
        T = _transport()
        self._phase = _PH_HEAD
        self._buf = bytearray(T._HEADER.size)
        self._view = memoryview(self._buf)
        self._got = 0

    def _begin(self, buf: bytearray, phase: int) -> None:
        self._phase = phase
        self._buf = buf
        self._view = memoryview(buf)
        self._got = 0

    def _begin_view(self, view: memoryview, phase: int) -> None:
        self._phase = phase
        self._buf = None
        self._view = view
        self._got = 0

    def _begin_payload(self):
        """Transition out of the header/meta phases; returns a completed
        frame tuple for empty payloads, else None."""
        if self._pl == 0:
            return self._emit(b"")
        if self._nchunks == 0:
            self._begin(bytearray(self._pl), _PH_RAW)
            return None
        self._dt = np.dtype(self._dtype or "<f4")
        self._payload_left = self._pl
        self._out_arr = None
        self._out_mv = None
        self._begin(bytearray(_wire.CHUNK_HDR_SIZE), _PH_CHUNK_HDR)
        return None

    def _emit(self, payload):
        frame = (
            self._kind, self._inst, self._rank, self._client, self._seq,
            self._oseq, self._fp, self._rule, self._dtype, self._wirec,
            self._nchunks, payload, self._trace, self._span,
        )
        self._out_arr = None
        self._out_mv = None
        self._start_header()
        return frame

    def _advance(self):
        """One phase transition after the current view filled; returns a
        completed frame tuple or None."""
        T = _transport()
        if self._phase == _PH_HEAD:
            (magic, kind, inst, rank, client, seq, oseq, fp, token, wirec,
             nchunks, rl, dl, pl, trace, span) = T._HEADER.unpack(self._buf)
            if magic != T._MAGIC:
                raise ConnectionClosed(
                    f"bad parameter-server frame magic 0x{magic:x}"
                )
            if token != T._auth_token():
                raise ConnectionClosed(
                    "parameter-server frame failed authentication"
                )
            (self._kind, self._inst, self._rank, self._client, self._seq,
             self._oseq, self._fp, self._wirec, self._nchunks,
             self._trace, self._span) = (
                kind, inst, rank, client, seq, oseq, fp, wirec, nchunks,
                trace, span)
            self._rl, self._dl, self._pl = rl, dl, pl
            self._rule = self._dtype = ""
            if rl or dl:
                self._begin(bytearray(rl + dl), _PH_META)
                return None
            return self._begin_payload()
        if self._phase == _PH_META:
            self._rule = bytes(self._buf[: self._rl]).decode()
            self._dtype = bytes(self._buf[self._rl:]).decode()
            return self._begin_payload()
        if self._phase == _PH_RAW:
            return self._emit(self._buf)
        if self._phase == _PH_CHUNK_HDR:
            off, total, cn, nb, block = _wire.read_chunk_header(self._buf)
            self._payload_left -= _wire.CHUNK_HDR_SIZE + nb
            self._chunk_meta = (off, cn, nb, block)
            if self._out_arr is None:
                self._out_arr = np.empty(total, self._dt)
                self._out_mv = memoryview(self._out_arr).cast("B")
            if self._wirec == _wire.WIRE_FULL:
                it = self._dt.itemsize
                self._begin_view(
                    self._out_mv[off * it:off * it + nb], _PH_CHUNK_BODY
                )
            else:
                if len(self._scratch) < nb:
                    self._scratch = bytearray(nb)
                self._begin_view(
                    memoryview(self._scratch)[:nb], _PH_CHUNK_BODY
                )
            if nb == 0:
                return self._chunk_done()
            return None
        # _PH_CHUNK_BODY
        return self._chunk_done()

    def _chunk_done(self):
        off, cn, nb, block = self._chunk_meta
        if self._wirec != _wire.WIRE_FULL:
            self._out_arr[off:off + cn] = _wire.decode_span(
                memoryview(self._scratch)[:nb], cn, self._wirec, block,
                self._dt,
            )
        if self._payload_left > 0:
            self._begin(bytearray(_wire.CHUNK_HDR_SIZE), _PH_CHUNK_HDR)
            return None
        return self._emit(memoryview(self._out_arr).cast("B"))

    def feed(self) -> List[tuple]:
        """Drain readable bytes into the state machine; returns the list
        of frames completed by this readiness event. Raises
        :class:`ConnectionClosed` on EOF / protocol violation."""
        frames: List[tuple] = []
        while len(frames) < _FRAMES_PER_WAKE:
            need = len(self._view) - self._got
            if need > 0:
                try:
                    n = self.sock.recv_into(self._view[self._got:], need)
                except (BlockingIOError, InterruptedError):
                    return frames
                except OSError as e:
                    raise ConnectionClosed(str(e)) from None
                if n == 0:
                    raise ConnectionClosed(
                        "peer closed parameter-server connection"
                    )
                self._got += n
                if self._got < len(self._view):
                    return frames  # short read: kernel buffer drained
            frame = self._advance()
            # a zero-size phase (empty payload, 0-byte chunk) may chain
            # several transitions before new bytes are needed
            while frame is None and len(self._view) == self._got == 0:
                frame = self._advance()
            if frame is not None:
                frames.append(frame)
        return frames

    # -- writes -----------------------------------------------------------
    def queue_write(self, bufs) -> None:
        """Enqueue reply buffers (any thread). Dropped if the connection
        already closed — the peer is gone, matching the old behavior of
        swallowing a send on a broken socket."""
        views = [
            b if isinstance(b, memoryview) else memoryview(bytes(b))
            for b in bufs
        ]
        with self.out_lock:
            if self.closed:
                return
            self.out.extend(v.cast("B") for v in views if len(v))

    def try_send_direct(self, bufs) -> bool:
        """Optimistic reply fast path (any thread): when nothing is
        queued, write straight to the non-blocking socket instead of
        paying the wake-pipe + loop-iteration hop. Any unsent remainder
        is queued; returns True when fully sent (no loop wake needed).
        Safe against the loop's flush: EVERY send on this socket happens
        under ``out_lock`` and queued bytes always precede new ones."""
        with self.out_lock:
            if self.closed:
                return True  # peer gone: drop, like queue_write
            if self.out or self.want_write:
                self.out.extend(
                    memoryview(b).cast("B")
                    if isinstance(b, memoryview)
                    else memoryview(bytes(b)).cast("B")
                    for b in bufs if len(b)
                )
                return False
            for i, b in enumerate(bufs):
                view = (
                    b if isinstance(b, memoryview) else memoryview(bytes(b))
                ).cast("B")
                if not len(view):
                    continue
                sent = 0
                while sent < len(view):
                    try:
                        sent += self.sock.send(view[sent:])
                    except (BlockingIOError, InterruptedError):
                        self.out.append(view[sent:])
                        self.out.extend(
                            (v if isinstance(v, memoryview)
                             else memoryview(bytes(v))).cast("B")
                            for v in bufs[i + 1:] if len(v)
                        )
                        return False
                    except OSError:
                        return True  # broken: the loop reaps the conn
            return True

    def flush(self) -> bool:
        """Non-blocking drain of the write queue (loop thread only).
        True when fully drained; False when the kernel buffer filled
        (caller registers write-interest). Raises ConnectionClosed on a
        broken socket. The lock is held across the send — sends are
        non-blocking, and it serializes against ``try_send_direct``."""
        while True:
            with self.out_lock:
                if not self.out:
                    return True
                buf = self.out[0]
                try:
                    n = self.sock.send(buf)
                except (BlockingIOError, InterruptedError):
                    return False
                except OSError as e:
                    raise ConnectionClosed(str(e)) from None
                if n < len(buf):
                    self.out[0] = buf[n:]
                else:
                    self.out.popleft()


class EventLoop:
    """One thread multiplexing accept + read + write over all listener
    connections. Frame dispatch (``handle_frame(conn, frame)``) runs on
    the loop thread and must not block — the listener posts mailbox
    messages and offloads waits to its pool, exactly the split the old
    per-connection readers had."""

    def __init__(
        self,
        server_sock: socket.socket,
        handle_frame: Callable[[Conn, tuple], None],
        on_open: Optional[Callable[[Conn], None]] = None,
        on_close: Optional[Callable[[Conn], None]] = None,
        name: str = "tm-ps-loop",
    ):
        self._srv = server_sock
        self._handle = handle_frame
        self._on_open = on_open
        self._on_close = on_close
        self._sel = selectors.DefaultSelector()
        self._rpipe, self._wpipe = os.pipe()
        os.set_blocking(self._rpipe, False)
        os.set_blocking(self._wpipe, False)
        self._sel.register(self._srv, selectors.EVENT_READ, "accept")
        self._sel.register(self._rpipe, selectors.EVENT_READ, "wake")
        self._plock = _lockmon.make_lock("eventloop.py:EventLoop._plock")
        self._pending_write: set = set()
        self._conns: set = set()
        self._stop = threading.Event()
        self._thread = threading.Thread(
            target=self._run, name=name, daemon=True
        )

    def start(self) -> None:
        self._thread.start()

    def connection_count(self) -> int:
        return len(self._conns)  # racy read; stats only

    def send(self, conn: Conn, bufs) -> None:
        """Thread-safe reply send: straight to the socket when the
        connection's queue is empty (the common case — saves the
        wake-pipe + loop-iteration hop per reply), else enqueue + wake
        the loop to flush in order."""
        if conn.try_send_direct(bufs):
            return
        with self._plock:
            self._pending_write.add(conn)
        self._wake()

    def _wake(self) -> None:
        try:
            os.write(self._wpipe, b"x")
        except (BlockingIOError, OSError):
            pass  # pipe full (already pending) or closing

    def stop(self) -> None:
        self._stop.set()
        self._wake()
        if self._thread.is_alive():
            self._thread.join(timeout=5)

    # -- loop internals ---------------------------------------------------
    def _accept(self) -> None:
        while True:
            try:
                s, _ = self._srv.accept()
            except (BlockingIOError, InterruptedError):
                return
            except OSError:
                return  # listener socket closing
            s.setblocking(False)
            try:
                s.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
            except OSError:
                pass
            conn = Conn(s)
            self._conns.add(conn)
            self._sel.register(s, selectors.EVENT_READ, conn)
            if self._on_open is not None:
                self._on_open(conn)

    def _close_conn(self, conn: Conn) -> None:
        if conn.closed:
            return
        with conn.out_lock:
            conn.closed = True
            conn.out.clear()
        try:
            self._sel.unregister(conn.sock)
        except (KeyError, ValueError, OSError):
            pass
        try:
            conn.sock.close()
        except OSError:
            pass
        self._conns.discard(conn)
        with self._plock:
            self._pending_write.discard(conn)
        if self._on_close is not None:
            self._on_close(conn)

    def _flush_conn(self, conn: Conn) -> None:
        if conn.closed:
            return
        try:
            drained = conn.flush()
        except ConnectionClosed:
            self._close_conn(conn)
            return
        if not drained and not conn.want_write:
            conn.want_write = True
            self._sel.modify(
                conn.sock,
                selectors.EVENT_READ | selectors.EVENT_WRITE,
                conn,
            )
        elif drained and conn.want_write:
            conn.want_write = False
            self._sel.modify(conn.sock, selectors.EVENT_READ, conn)

    def _run(self) -> None:
        try:
            while not self._stop.is_set():
                try:
                    events = self._sel.select(timeout=0.5)
                except OSError:
                    if self._stop.is_set():
                        return
                    continue
                with self._plock:
                    pend, self._pending_write = self._pending_write, set()
                for conn in pend:
                    self._flush_conn(conn)
                for key, mask in events:
                    data = key.data
                    if data == "wake":
                        try:
                            while os.read(self._rpipe, 4096):
                                pass
                        except (BlockingIOError, OSError):
                            pass
                        continue
                    if data == "accept":
                        self._accept()
                        continue
                    conn = data
                    if conn.closed:
                        continue
                    if mask & selectors.EVENT_WRITE:
                        self._flush_conn(conn)
                    if mask & selectors.EVENT_READ and not conn.closed:
                        try:
                            frames = conn.feed()
                        except ConnectionClosed:
                            self._close_conn(conn)
                            continue
                        for frame in frames:
                            try:
                                self._handle(conn, frame)
                            except Exception:  # noqa: BLE001
                                # a dispatch bug must not kill the shared
                                # loop; the old per-conn reader died alone
                                self._close_conn(conn)
                                break
        finally:
            for conn in list(self._conns):
                self._close_conn(conn)
            for fd in (self._rpipe, self._wpipe):
                try:
                    os.close(fd)
                except OSError:
                    pass
            try:
                self._sel.close()
            except OSError:
                pass
