"""Cross-process parameter-server transport (sockets).

The reference PS is inherently multi-process: clients ``Isend`` a rule name
and ``Ssend`` shard slices to *remote* servers, whose polling thread
``Iprobe``s per-instance tags (``lib/parameterserver.cpp:309-400,404-541``).
The TPU rebuild's wire protocol is transport-abstracted (mailboxes); this
module plugs a TCP transport into the same mailbox interface so a
:class:`~torchmpi_tpu.parameterserver.ParameterServer` spans the processes
of a multi-controller JAX job (``start(coordinator_address=...)``).

Design:

- every process runs one **listener** (accept loop + per-connection handler
  threads) serving the shard ranks whose devices live in this process;
- requests are length-prefixed binary frames (no pickle on the wire):
  ``kind`` (UPDATE | TRIGGER), instance id, server rank, client, rule,
  dtype, payload bytes — the tag-namespace parity of
  ``instance * kSentinelTag + {rule, clientChunk, serverChunk, trigger}``
  (``parameterserver.cpp:296-301``);
- an UPDATE is acked only after the server thread *applied* the rule (the
  Ssend happens-before guarantee, strengthened to applied — matching the
  in-process transport); a TRIGGER replies with the shard bytes;
- clients keep one persistent connection per peer process, PIPELINED:
  senders hold the channel lock only to put a frame on the wire; every
  frame carries a channel-monotone seq which the listener ECHOES on the
  reply, and the demux matches replies by that seq — the listener
  applies a connection's frames concurrently (worker pool) and may
  reply out of order, so one slow shard apply does not head-of-line
  block the others;
- addresses are exchanged once via ``multihost_utils.process_allgather``
  (the runtime's coordination service), the analog of MPI's out-of-band
  bootstrap.
"""

from __future__ import annotations

import os
import socket
import struct
import threading
import time
from collections import OrderedDict, deque
from concurrent.futures import Future
from typing import Dict, List, Optional, Tuple, Union

import numpy as np

from .. import constants, telemetry as _telemetry
from ..analysis import lockmon as _lockmon
from ..schedule import pipeline as _sched_pipeline
from ..telemetry import flightrecorder as _flight
from ..telemetry import tracecontext as _tracecontext
from . import wire as _wire

_MAGIC = 0x7E5B
_KIND_UPDATE = 1
_KIND_TRIGGER = 2
_KIND_ACK = 3
_KIND_SHARD = 4
_KIND_ERROR = 5
_KIND_BARRIER = 6
# admission-control reject: the listener's pending-frame budget is
# exhausted; rule carries the retry-after hint (milliseconds). The
# client channel replays the frame after a jittered backoff — overload
# degrades to bounded queue depth + retry latency, never to unbounded
# queueing or accept failures.
_KIND_BUSY = 9
# host-blob allgather frame: rule = tag, client = origin process,
# payload = opaque bytes. Powers host-staged collectives (the DCN hop of
# use_staged_collectives) without touching device links.
_KIND_GATHER = 8
# one frame carrying updates for SEVERAL shard ranks owned by the same
# peer: payload = u32 count, then count x (u32 rank, u64 nbytes) headers,
# then the concatenated slice bytes. One round trip (and one applied-ack)
# per peer instead of one per rank — the frame-level analog of the
# reference's chunked Isend fan-out (parameterserver.cpp:309-353).
_KIND_UPDATE_MULTI = 7
# inference-serving RPC pair (torchmpi_tpu.serve): REQUEST rides the
# same admission/BUSY machinery as UPDATE/TRIGGER (budget exhaustion ->
# BUSY + retry-after, never unbounded queueing); rank carries the QoS
# level, rule the request tag. REPLY mirrors SHARD but keeps a distinct
# kind so serving traffic is separable from shard fetches in telemetry
# and never confuses the PS client decode path. rule on a REPLY is the
# status ("ok", or "shed:<retry_ms>" when the server's brownout ladder
# drops the request).
_KIND_REQUEST = 10
_KIND_REPLY = 11
_MULTI_COUNT = struct.Struct(">I")
_MULTI_ITEM = struct.Struct(">IQ")
# the `rank` header field of a multi frame (dedup key sentinel: the frame
# is deduped as a unit, not per rank)
_MULTI_RANK = 0xFFFFFFFF

# bound on retained poison records (_failed). The record protecting a
# failed seq must outlive its reconnect replay; now that SINGLE update
# failures are poisoned too (not just partially-applied multis), a tight
# cap could be churned through before the replay arrives and the evicted
# seq would be answered from the _applied high-water mark — a false ACK.
# Entries are one small string each; failures are rare and fatal to the
# owning client anyway, so a generous cap costs nothing.
_FAILED_CAP = 4096

# telemetry: RPC latency / retry / poison / replay series for the
# cross-process PS path (one branch per call site when disabled)
_KIND_NAMES = {
    _KIND_UPDATE: "update",
    _KIND_TRIGGER: "trigger",
    _KIND_BARRIER: "barrier",
    _KIND_GATHER: "gather",
    _KIND_UPDATE_MULTI: "update_multi",
    _KIND_REQUEST: "request",
    _KIND_REPLY: "reply",
}
_MET = None


def _metric_handles():
    global _MET
    if _MET is None:
        m = _telemetry.metrics
        _MET = (
            m.counter(
                "tm_ps_requests_total",
                "PS transport frames submitted, by kind",
            ),
            m.histogram(
                "tm_ps_rpc_latency_seconds",
                "submit-to-reply latency per PS transport frame, by kind",
            ),
            m.counter(
                "tm_ps_reconnects_total",
                "peer-channel reconnect attempts (broken connections)",
            ),
            m.counter(
                "tm_ps_replayed_frames_total",
                "un-answered frames replayed after a reconnect",
            ),
            m.counter(
                "tm_ps_poisoned_frames_total",
                "frames recorded as failed so replays re-report the error",
            ),
            m.counter(
                "tm_ps_replay_answered_total",
                "listener-side replayed frames answered from the "
                "dedup/poison/in-flight tables, by outcome",
            ),
            m.histogram(
                "tm_ps_chunk_pipeline_depth",
                "chunks per chunked PS frame (the encode/wire/decode "
                "pipeline depth of that transfer), by kind",
                buckets=(1, 2, 4, 8, 16, 32, 64, 128),
            ),
            m.counter(
                "tm_ps_delta_fetches_total",
                "delta-encoded fetch outcomes, by reply (full/delta/same)",
            ),
            m.counter(
                "tm_ps_busy_retries_total",
                "BUSY/retry-after replies honored client-side with "
                "jittered backoff",
            ),
            m.gauge(
                "tm_ps_dead_marks_active",
                "peer processes currently skipped by replica-chain "
                "failover routing (dead-marks inside their "
                "ps_dead_peer_retry_s window)",
            ),
            m.counter(
                "tm_ps_dead_mark_expiries_total",
                "dead-marks whose retry window elapsed (the peer is "
                "re-probed; each expiry closes one bounded split-brain "
                "window)",
            ),
            m.counter(
                "tm_ps_read_routes_total",
                "fetches routed per serving lane (owner/replica/shm), "
                "by lane and read policy",
            ),
            m.counter(
                "tm_ps_read_fallbacks_total",
                "fetch routing fallbacks to the owner, by reason "
                "(stale/dead/shm)",
            ),
            m.counter(
                "tm_ps_read_shm_retries_total",
                "seqlock torn-read retries on the shared-memory fetch "
                "lane (writer raced the read)",
            ),
            m.histogram(
                "tm_ps_read_latency_seconds",
                "fetch latency per serving lane, by lane",
            ),
        )
    return _MET


# server-side fabric series (connection lifecycle, admission control,
# queue-vs-apply attribution), labelled by listener port
_SRV_MET = None


def _srv_metric_handles():
    global _SRV_MET
    if _SRV_MET is None:
        m = _telemetry.metrics
        _SRV_MET = (
            m.counter(
                "tm_ps_busy_rejected_total",
                "frames rejected by the listener's admission budget, "
                "by listener",
            ),
            m.gauge(
                "tm_ps_connections_open",
                "currently open listener connections, by listener",
            ),
            m.counter(
                "tm_ps_accepts_total",
                "connections accepted, by listener",
            ),
            m.counter(
                "tm_ps_disconnects_total",
                "connections closed (peer EOF, protocol error, broken "
                "socket), by listener",
            ),
            m.histogram(
                "tm_ps_server_queue_seconds",
                "admission-to-apply-start wait per admitted PS frame "
                "(time spent queued for a pool worker), by kind",
            ),
            m.histogram(
                "tm_ps_server_apply_seconds",
                "apply time per admitted PS frame (mailbox apply wait, "
                "incl. chain forwarding), by kind",
            ),
            m.counter(
                "tm_ps_replica_forward_failures_total",
                "replica-chain forwards that failed; the chain degrades "
                "to head-only for that successor",
            ),
            m.counter(
                "tm_ps_read_stale_redirects_total",
                "fetches a chain member refused because its applied "
                "high-water had not covered the client's session floor "
                "(client re-fetches at the owner), by listener",
            ),
        )
    return _SRV_MET


class _StaleRead(Exception):
    """A chain member refused a fetch because its applied high-water had
    not covered the client's read-your-writes session floor (reply rule
    ``stale:<hw>``). Internal routing signal: ``Transport.trigger``
    catches it and redirects toward the owner — it never escapes to
    callers."""

    def __init__(self, proc: int, rule: str):
        super().__init__(f"peer {proc} stale for session floor ({rule})")
        self.proc = proc
        self.rule = rule


def busy_backoff_s(attempts: int, hint_ms: int = 0, rng=None) -> float:
    """The client channel's BUSY backoff: base * 2^(attempts-1) capped
    at 2s, +-50% jitter. One definition shared by the live channel and
    the fleet simulator, so the modeled overload behavior IS the
    deployed policy (``hint_ms`` is the server's retry-after hint; 0
    falls back to the ``ps_busy_retry_ms`` knob)."""
    import random

    base = (hint_ms or constants.get("ps_busy_retry_ms")) / 1000.0
    delay = min(2.0, base * (1 << min(max(attempts, 1) - 1, 6)))
    return delay * (rng or random).uniform(0.5, 1.5)


def admission_decision(pending: int, budget: int, busy_floor, seq: int,
                       update_kind: bool):
    """The listener's admission-control policy as a pure function:
    ``(admit, new_busy_floor)`` for a frame arriving with ``pending``
    frames already admitted against ``budget``. The per-connection
    ``busy_floor`` keeps rejections order-safe for pipelined updates:
    once an UPDATE is rejected, every later UPDATE on that connection is
    rejected too until the first rejected seq is retried. Shared by
    ``_Listener._admit`` and the fleet simulator's modeled servers."""
    if budget <= 0:
        return True, busy_floor
    forced = update_kind and busy_floor is not None and seq > busy_floor
    if pending >= budget or forced:
        if update_kind and busy_floor is None:
            busy_floor = seq
        return False, busy_floor
    if update_kind and busy_floor is not None and seq <= busy_floor:
        busy_floor = None
    return True, busy_floor


# frame: magic u16, kind u8, inst u32, rank u32, client u32, seq u64,
#        oseq u64, fp u32, token u32, wire u8, nchunks u32,
#        rule_len u16, dtype_len u16, payload_len u64, trace u64,
#        span u64
#
# - seq: per-channel monotone sequence on EVERY frame; echoed on the
#   reply (the client demux correlates by it — the server replies out
#   of order), and for UPDATE/BARRIER/GATHER frames also the dedup key
#   ((inst, rank, client, seq) / per-origin high-water) so a reconnect
#   retry after a lost ACK cannot double-apply or double-count.
# - oseq: ORIGIN sequence, nonzero only under shard replication: a
#   channel-independent per-(inst, rank, client) monotone update id
#   assigned by the originating client's Transport. It is the dedup
#   identity that survives failover — the same update re-issued to a
#   replica (a different channel, fresh channel seqs) or chain-forwarded
#   by the head carries the same oseq, so the replica's applied
#   high-water answers duplicates with an ACK instead of re-applying.
#   0 = dedup by the channel seq (the non-replicated fast path).
# - fp: instance fingerprint (shape/dtype/size/owners); catches
#   process-local instance-id desync loudly instead of applying updates
#   to the wrong tensor.
# - token: optional shared secret (TORCHMPI_TPU_PS_TOKEN) so a stray
#   network peer can't read or mutate parameters.
# - wire: payload encoding (wire.WIRE_FULL/BF16/INT8). On an UPDATE it
#   describes the payload; on a TRIGGER it REQUESTS the reply encoding;
#   on a SHARD reply it describes the reply payload. ``dtype`` always
#   names the LOGICAL dtype — the decoded value, never the wire bytes.
# - nchunks: > 0 means the payload region is a chunk container
#   (``wire.py``): nchunks x [chunk header | encoded span], streamed so
#   encode/decode of chunk k+1 overlaps the wire I/O of chunk k. 0 means
#   the payload is one raw blob (control frames, multi-frame containers).
# - trace: causal trace id (telemetry.tracecontext); 0 = unstamped (no
#   ambient trace / tracing off). Replays and BUSY re-sends reuse the
#   retained encoded frame, so origin context survives by construction.
# - span: the sender's span id for THIS hop; the receiver records its
#   local work with ``parent=span``, and replies echo (trace, span)
#   unchanged. Chain-forwarded ``fwd:`` frames re-stamp span with the
#   forwarding hop's span while keeping trace — one trace per update,
#   one span per link of the chain.
_HEADER = struct.Struct(">HBIIIQQIIBIHHQQQ")


# Auto-derived per-job frame secret (see _init_job_token): 0 only until
# the transport bootstraps or in single-process runs (no listener peers).
_job_token_value = 0


def _auth_token() -> int:
    tok = os.environ.get("TORCHMPI_TPU_PS_TOKEN", "")
    if not tok:
        return _job_token_value
    import zlib

    return zlib.crc32(tok.encode()) & 0xFFFFFFFF


def _init_job_token() -> None:
    """Derive a shared per-job frame secret from the runtime's coordination
    service (process 0 broadcasts random bytes at transport bootstrap), so
    the PS listener is never open unauthenticated by default — previously
    auth was opt-in via TORCHMPI_TPU_PS_TOKEN and any network peer could
    read or mutate parameters. The env token still overrides (stable
    secrets across restarts). Ordering: runs BEFORE the address exchange,
    so no peer can learn this listener's address until every process holds
    the secret."""
    global _job_token_value
    if os.environ.get("TORCHMPI_TPU_PS_TOKEN", ""):
        return
    import jax

    if jax.process_count() <= 1:
        return
    import zlib

    from jax.experimental import multihost_utils

    seed = np.frombuffer(os.urandom(16), np.uint8)
    tok = multihost_utils.broadcast_one_to_all(
        seed, is_source=jax.process_index() == 0
    )
    _job_token_value = zlib.crc32(bytes(np.asarray(tok))) & 0xFFFFFFFF


def instance_fingerprint(shape, dtype, size: int, owners,
                         rotation: int = 0, replication: int = 1) -> int:
    import zlib

    desc = f"{tuple(shape)}|{np.dtype(dtype).str}|{size}|{tuple(owners)}"
    if rotation:
        # shard ranges depend on the remainder rotation (byte-aware
        # placement): a rotation disagreement means a ranges disagreement
        # and must fail as loudly as any other layout desync
        desc += f"|rot{rotation}"
    if replication > 1:
        # chain layout disagreement (one process replicating, another
        # not) would silently skip forwarding: fail as loudly as any
        # other layout desync
        desc += f"|rep{replication}"
    return zlib.crc32(desc.encode()) & 0xFFFFFFFF


def _recv_exact_into(sock: socket.socket, view: memoryview) -> None:
    """Fill ``view`` from the socket with ``recv_into`` — no intermediate
    chunk allocation, no bytes-concat copy."""
    got = 0
    n = len(view)
    while got < n:
        r = sock.recv_into(view[got:], n - got)
        if not r:
            raise ConnectionError("peer closed parameter-server connection")
        got += r


def _recv_exact(sock: socket.socket, n: int) -> bytearray:
    """One preallocated buffer, filled in place (the old implementation
    recv'd fresh chunk objects and copied them into a growing bytearray;
    this is the recv_into rewrite that kills the per-frame copy even on
    the non-chunked control path). Returns a bytearray — bytes-compatible
    for every consumer here (struct.unpack_from, np.frombuffer, decode)."""
    buf = bytearray(n)
    _recv_exact_into(sock, memoryview(buf))
    return buf


_Buffers = Union[bytes, bytearray, List]


def _send_buffers(sock: socket.socket, buffers: _Buffers) -> None:
    """sendall for a scatter-gather buffer list (``sendmsg``, partial
    sends handled) or a single blob."""
    if isinstance(buffers, (bytes, bytearray, memoryview)):
        sock.sendall(buffers)
        return
    if not hasattr(sock, "sendmsg"):
        # platforms without scatter-gather sockets (win32): one concat
        # per frame, the pre-chunking behavior
        sock.sendall(b"".join(bytes(memoryview(b).cast("B"))
                              for b in buffers))
        return
    views = [memoryview(b).cast("B") if not isinstance(b, memoryview) else b
             for b in buffers]
    while views:
        # bounded iovec count per call (IOV_MAX); the loop drains the rest
        sent = sock.sendmsg(views[:64])
        while views and sent >= len(views[0]):
            sent -= len(views[0])
            views.pop(0)
        if sent and views:
            views[0] = views[0][sent:]


def _frame_header(
    kind: int,
    inst: int = 0,
    rank: int = 0,
    client: int = 0,
    seq: int = 0,
    fp: int = 0,
    wire: int = 0,
    nchunks: int = 0,
    rule: str = "",
    dtype: str = "",
    payload_len: int = 0,
    oseq: int = 0,
    trace: int = 0,
    span: int = 0,
):
    rule_b, dtype_b = rule.encode(), dtype.encode()
    header = _HEADER.pack(
        _MAGIC, kind, inst, rank, client, seq, oseq, fp, _auth_token(),
        wire, nchunks, len(rule_b), len(dtype_b), payload_len, trace, span,
    )
    return header, rule_b, dtype_b


def _frame_bytes(
    kind: int,
    inst: int = 0,
    rank: int = 0,
    client: int = 0,
    seq: int = 0,
    fp: int = 0,
    rule: str = "",
    dtype: str = "",
    payload: bytes = b"",
    wire: int = 0,
    nchunks: int = 0,
    oseq: int = 0,
    trace: int = 0,
    span: int = 0,
) -> bytes:
    header, rule_b, dtype_b = _frame_header(
        kind, inst, rank, client, seq, fp, wire, nchunks, rule, dtype,
        len(payload), oseq, trace, span,
    )
    return header + rule_b + dtype_b + payload


def _send_frame(
    sock: socket.socket,
    kind: int,
    inst: int = 0,
    rank: int = 0,
    client: int = 0,
    seq: int = 0,
    fp: int = 0,
    rule: str = "",
    dtype: str = "",
    payload: _Buffers = b"",
    wire: int = 0,
    nchunks: int = 0,
    oseq: int = 0,
    trace: int = 0,
    span: int = 0,
) -> None:
    if isinstance(payload, list):
        total = sum(len(memoryview(b).cast("B")) for b in payload)
        header, rule_b, dtype_b = _frame_header(
            kind, inst, rank, client, seq, fp, wire, nchunks, rule, dtype,
            total, oseq, trace, span,
        )
        _send_buffers(sock, [header, rule_b, dtype_b] + payload)
    else:
        sock.sendall(
            _frame_bytes(
                kind, inst, rank, client, seq, fp, rule, dtype, payload,
                wire, nchunks, oseq, trace, span,
            )
        )


def _reply_bufs(
    kind: int,
    inst: int = 0,
    rank: int = 0,
    client: int = 0,
    seq: int = 0,
    fp: int = 0,
    rule: str = "",
    dtype: str = "",
    payload: _Buffers = b"",
    wire: int = 0,
    nchunks: int = 0,
    trace: int = 0,
    span: int = 0,
):
    """Encode a reply frame as a buffer list for the event loop's write
    queue (never sent inline: pool threads enqueue, the loop flushes).
    ``(trace, span)`` echo the request's context — a reply closes the
    request span, it does not open a new one."""
    if isinstance(payload, list):
        total = sum(len(memoryview(b).cast("B")) for b in payload)
        header, rule_b, dtype_b = _frame_header(
            kind, inst, rank, client, seq, fp, wire, nchunks, rule, dtype,
            total, trace=trace, span=span,
        )
        return [header, rule_b, dtype_b, *payload]
    header, rule_b, dtype_b = _frame_header(
        kind, inst, rank, client, seq, fp, wire, nchunks, rule, dtype,
        len(payload), trace=trace, span=span,
    )
    return [header, rule_b, dtype_b, payload]


def _recv_head(sock: socket.socket):
    header = _recv_exact(sock, _HEADER.size)
    (magic, kind, inst, rank, client, seq, oseq, fp, token, wire, nchunks,
     rl, dl, pl, trace, span) = _HEADER.unpack(header)
    if magic != _MAGIC:
        raise ConnectionError(
            f"bad parameter-server frame magic 0x{magic:x}"
        )
    if token != _auth_token():
        raise ConnectionError("parameter-server frame failed authentication")
    rule = _recv_exact(sock, rl).decode() if rl else ""
    dtype = _recv_exact(sock, dl).decode() if dl else ""
    return kind, inst, rank, client, seq, fp, rule, dtype, wire, nchunks, pl


def _read_payload(
    sock: socket.socket, pl: int, wire: int, nchunks: int, dtype_str: str
):
    """Read (and decode) a frame payload.

    Unchunked (``nchunks == 0``): one recv_into-filled buffer, returned
    raw (control frames; multi containers are decoded by
    :func:`_parse_multi_payload`).

    Chunked: stream the container — recv_into each chunk's encoded bytes
    into a reusable scratch buffer and dequantize it into the
    preallocated logical array immediately, so decode of chunk k overlaps
    the wire I/O of chunk k+1 and the last byte's arrival leaves almost
    no decode work. WIRE_FULL chunks recv_into the logical array
    directly (zero staging copy). Returns a memoryview of the logical
    bytes (np.frombuffer-compatible, like the raw path)."""
    if nchunks == 0:
        return _recv_exact(sock, pl)
    dt = np.dtype(dtype_str or "<f4")
    out: Optional[np.ndarray] = None
    out_mv: Optional[memoryview] = None
    hdr = bytearray(_wire.CHUNK_HDR_SIZE)
    hdr_mv = memoryview(hdr)
    scratch = bytearray()
    for _ in range(nchunks):
        _recv_exact_into(sock, hdr_mv)
        off, total, cn, nb, block = _wire.read_chunk_header(hdr)
        if out is None:
            out = np.empty(total, dt)
            out_mv = memoryview(out).cast("B")
        if wire == _wire.WIRE_FULL:
            _recv_exact_into(
                sock, out_mv[off * dt.itemsize:off * dt.itemsize + nb]
            )
            continue
        if len(scratch) < nb:
            scratch = bytearray(nb)
        view = memoryview(scratch)[:nb]
        _recv_exact_into(sock, view)
        out[off:off + cn] = _wire.decode_span(view, cn, wire, block, dt)
    if out is None:
        return b""
    return memoryview(out).cast("B")


def _recv_frame(sock: socket.socket):
    """Read one frame; chunked / quantized payloads are reassembled and
    decoded transparently — the returned payload is always LOGICAL bytes
    of ``dtype`` (the 9-tuple shape every caller and test relies on)."""
    kind, inst, rank, client, seq, fp, rule, dtype, wire, nchunks, pl = (
        _recv_head(sock)
    )
    payload = (
        _read_payload(sock, pl, wire, nchunks, dtype) if pl else b""
    )
    return kind, inst, rank, client, seq, fp, rule, dtype, payload


def _enable_keepalive(sock: socket.socket) -> None:
    """Kernel-level liveness detection for blocking PS sockets: probe after
    30s idle, every 15s, declare dead after 3 misses (~75s). Distinguishes
    a dead/partitioned peer (error) from a live-but-slow apply (fine)."""
    sock.setsockopt(socket.SOL_SOCKET, socket.SO_KEEPALIVE, 1)
    for opt, val in (
        ("TCP_KEEPIDLE", 30),
        ("TCP_KEEPINTVL", 15),
        ("TCP_KEEPCNT", 3),
    ):
        if hasattr(socket, opt):  # linux names; best-effort elsewhere
            try:
                sock.setsockopt(socket.IPPROTO_TCP, getattr(socket, opt), val)
            except OSError:
                pass


def _parse_multi_payload(payload, dt: np.dtype, wire: int = 0):
    """Decode a _KIND_UPDATE_MULTI payload into [(rank, values)]. With a
    non-full frame wire byte each item's bytes are a chunk container
    (encoded per item so the per-rank slices quantize on independent
    grids); decoded values are always the logical dtype."""
    (count,) = _MULTI_COUNT.unpack_from(payload, 0)
    off = _MULTI_COUNT.size
    metas = []
    for _ in range(count):
        r, nb = _MULTI_ITEM.unpack_from(payload, off)
        off += _MULTI_ITEM.size
        metas.append((r, nb))
    mv = memoryview(payload)
    items = []
    for r, nb in metas:
        if wire == _wire.WIRE_FULL:
            items.append(
                (r, np.frombuffer(
                    payload, dt, count=nb // dt.itemsize, offset=off
                ))
            )
        else:
            items.append(
                (r, _wire.decode_container(mv[off:off + nb], 0, wire, dt))
            )
        off += nb
    return items


class _Listener:
    """Event-multiplexed listener serving this process's shard ranks.

    One :class:`~.eventloop.EventLoop` thread multiplexes EVERY client
    connection (non-blocking sockets, per-connection incremental frame
    state machines); mailbox posting happens on the loop thread in wire
    order, applied-waits and replies run on the shared apply pool, and
    replies are queued back through the loop — so the server's thread
    count is O(pools), independent of how many clients connect. The
    pre-fabric design (accept loop + one blocking reader thread per
    connection) topped out at tens of clients; see ``eventloop.py``.

    Admission control: at most ``ps_pending_frame_budget`` decoded
    frames may be in the apply stage at once; beyond that, new
    UPDATE/TRIGGER frames get a BUSY/retry-after reply the client
    channel honors with jittered backoff. A per-connection BUSY *floor*
    keeps rejections order-safe: once an UPDATE is rejected, every
    later pipelined UPDATE on that connection is rejected too until the
    first rejected seq is retried, so retried updates can never apply
    out of their assignment order.
    """

    def __init__(self, lookup_instance):
        self._lookup = lookup_instance
        self._sock = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
        self._sock.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
        # bind the advertised interface when one is configured (defense
        # in depth alongside the frame token); 0.0.0.0 otherwise so
        # cluster peers on any fabric can reach us
        bind_host = os.environ.get("TORCHMPI_TPU_PS_HOST", "0.0.0.0")
        try:
            self._sock.bind((bind_host, 0))
        except OSError:
            self._sock.bind(("0.0.0.0", 0))
        self._sock.listen(max(1, int(constants.get("ps_listen_backlog"))))
        self._sock.setblocking(False)
        self.port = self._sock.getsockname()[1]
        # UPDATE dedup: last applied seq per (inst, rank, client) — a
        # reconnect retry after a lost ACK must not double-apply. The
        # in-progress table closes the remaining window: a retry arriving
        # while the FIRST apply is still running (applied-seq not yet
        # recorded) waits for that apply instead of re-posting it.
        self._applied: Dict[Tuple[int, int, int], int] = {}
        self._inflight: Dict[Tuple[Tuple[int, int, int], int], threading.Event] = {}
        # poisoned multi frames: a PARTIALLY-applied _KIND_UPDATE_MULTI
        # (one item applied, another failed) must never be re-applied by
        # a reconnect retry whose ERROR response was lost — the retry is
        # answered from this record instead (bounded FIFO; failures are
        # rare and fatal to the client anyway)
        self._failed: Dict[Tuple[Tuple[int, int, int], int], str] = {}
        self._applied_lock = _lockmon.make_lock(
            "transport.py:_Listener._applied_lock"
        )
        # subset barrier bookkeeping: tag -> per-origin ARRIVAL COUNTERS
        # (not a set): a fast peer's next barrier frame with the same tag
        # can land before this process finishes waiting on the current
        # one; counting generations keeps that early arrival banked for
        # the next wait instead of silently discarding it.
        self._barrier_seen: Dict[str, Dict[int, int]] = {}
        # BARRIER dedup: last applied barrier seq per origin. A channel
        # replay of a barrier whose original delivery landed (ACK lost on
        # the broken connection) must not increment the arrival counter a
        # second time — barrier_wait banks surplus generations, so the
        # double-count would let a LATER barrier with the same tag pass
        # before that origin actually arrives. Seqs are channel-monotone
        # (shared counter with UPDATEs), so (origin, seq) identifies the
        # frame and a high-water mark per origin suffices.
        self._barrier_applied: Dict[int, int] = {}
        # host-blob allgather bookkeeping: tag -> origin -> payload QUEUE
        # (generations, same banking rationale as the barrier counters)
        # plus the replay-dedup high-water mark per origin.
        self._gather_seen: Dict[str, Dict[int, "deque[bytes]"]] = {}
        self._gather_applied: Dict[int, int] = {}
        self._barrier_cv = _lockmon.make_condition(
            "transport.py:_Listener._barrier_cv"
        )
        self._stop = threading.Event()
        # admission control + connection-lifecycle counters (ints under
        # one small lock; mirrored into telemetry series when enabled)
        self._pending_lock = _lockmon.make_lock(
            "transport.py:_Listener._pending_lock"
        )
        self._pending_frames = 0
        self._busy_rejects = 0
        self._accepts = 0
        self._disconnects = 0
        # inference-serving hook (torchmpi_tpu.serve): when set, REQUEST
        # frames are admitted through the same budget as updates and
        # answered by ``handler(rule, qos, payload, pending) ->
        # (status_rule, result)`` on the apply pool; result may be an
        # ndarray, bytes, or None. Unset, REQUEST frames get ERROR.
        self.request_handler = None
        # ONE listener-wide pool for applied-waits and replies, sized
        # from the expected in-flight frames (the PS pool size bounds
        # concurrent applies; 2x covers waits stacked behind them). A
        # per-connection pool multiplied threads on reconnect churn: a
        # flapping peer left dozens of idle pools behind (ADVICE r5).
        # Invariant that keeps the bounded pool deadlock-free: pool
        # tasks block only on SERVER-thread progress (apply events /
        # trigger futures), never on other pool tasks — which is why
        # replay waiters (_await_other_apply, which block on a FINISHER
        # task's event) run on their own short-lived threads instead.
        from concurrent.futures import ThreadPoolExecutor

        self._pool = ThreadPoolExecutor(
            max_workers=max(
                4, constants.get("parameterserver_thread_pool_size") * 2
            ),
            thread_name_prefix="tm-ps-apply",
        )
        from .eventloop import EventLoop

        self._loop = EventLoop(
            self._sock, self._handle_frame,
            on_open=self._on_open, on_close=self._on_close,
        )
        # listener health producer: queue depth (frames waiting for a
        # pool worker), admitted-frame backlog, and connection lifecycle
        # counts, read at snapshot time only. A weakref keeps the
        # collector from pinning a closed listener; a rebootstrapped
        # transport's listener re-registers over it.
        import weakref

        ref = weakref.ref(self)

        def _listener_stats() -> dict:
            listener = ref()
            if listener is None:
                return {"alive": False}
            q = getattr(listener._pool, "_work_queue", None)
            return {
                "alive": not listener._stop.is_set(),
                "queue_depth": q.qsize() if q is not None else None,
                "pool_workers": len(getattr(listener._pool, "_threads", ())),
                "connections": listener._loop.connection_count(),
                "accepted": listener._accepts,
                "disconnected": listener._disconnects,
                "busy_rejected": listener._busy_rejects,
                "pending_frames": listener._pending_frames,
                "port": listener.port,
            }

        _telemetry.metrics.register_collector("ps_listener", _listener_stats)
        self._loop.start()

    # -- connection lifecycle (loop thread) ---------------------------------
    def _on_open(self, conn) -> None:
        self._accepts += 1
        if _telemetry.enabled():
            met = _srv_metric_handles()
            met[2].inc(listener=str(self.port))
            met[1].set(
                self._loop.connection_count(), listener=str(self.port)
            )

    def _on_close(self, conn) -> None:
        self._disconnects += 1
        if _telemetry.enabled():
            met = _srv_metric_handles()
            met[3].inc(listener=str(self.port))
            met[1].set(
                self._loop.connection_count(), listener=str(self.port)
            )

    def _submit(self, fn, *args) -> None:
        """Schedule reply work on the shared pool; during close() the
        pool may already be shut down while a reader drains its socket —
        drop the work instead of killing the reader with RuntimeError."""
        try:
            self._pool.submit(fn, *args)
        except RuntimeError:
            if not self._stop.is_set():
                raise

    def barrier_arrived(self, tag: str, origin: int) -> None:
        with self._barrier_cv:
            counts = self._barrier_seen.setdefault(tag, {})
            counts[origin] = counts.get(origin, 0) + 1
            self._barrier_cv.notify_all()

    def barrier_wait(self, tag: str, expect: set, timeout=None) -> bool:
        def _ready() -> bool:
            counts = self._barrier_seen.get(tag, {})
            return all(counts.get(o, 0) >= 1 for o in expect)

        with self._barrier_cv:
            ok = self._barrier_cv.wait_for(_ready, timeout)
            if ok:
                # consume ONE generation per origin; surplus arrivals stay
                # banked for the caller's next barrier with this tag
                counts = self._barrier_seen.get(tag, {})
                for o in expect:
                    counts[o] -= 1
                    if counts[o] <= 0:
                        counts.pop(o, None)
                if not counts:
                    self._barrier_seen.pop(tag, None)
            return ok

    def _fresh_seq(self, applied: Dict[int, int], client: int, seq: int) -> bool:
        """Replay dedup for out-of-band frames (BARRIER/GATHER): True iff
        ``seq`` advances ``client``'s high-water mark in ``applied``. Seqs
        are channel-monotone (shared counter with UPDATEs), so a channel
        replay of an already-delivered frame is recognised by its seq; a
        re-banked arrival would satisfy a LATER wait with the same tag
        spuriously. Takes the condition's lock itself."""
        with self._barrier_cv:
            if applied.get(client, 0) >= seq:
                return False
            applied[client] = seq
            return True

    def gather_arrived(self, tag: str, origin: int, payload: bytes) -> None:
        with self._barrier_cv:
            per = self._gather_seen.setdefault(tag, {})
            per.setdefault(origin, deque()).append(payload)
            self._barrier_cv.notify_all()

    def gather_wait(self, tag: str, expect: set, timeout=None):
        """Collect one payload per origin in ``expect`` (None on timeout)."""

        def _ready() -> bool:
            per = self._gather_seen.get(tag, {})
            return all(per.get(o) for o in expect)

        with self._barrier_cv:
            if not self._barrier_cv.wait_for(_ready, timeout):
                return None
            per = self._gather_seen.get(tag, {})
            out = {o: per[o].popleft() for o in expect}
            for o in list(per):
                if not per[o]:
                    per.pop(o)
            if not per:
                self._gather_seen.pop(tag, None)
            return out

    def _admit(self, conn, kind: int, seq: int) -> bool:
        """Admission control (loop thread): True admits the frame into
        the apply stage; False means the caller must reply BUSY. The
        per-connection ``busy_floor`` keeps rejections order-safe for
        pipelined updates (see class docstring)."""
        budget = constants.get("ps_pending_frame_budget")
        update_kind = kind in (_KIND_UPDATE, _KIND_UPDATE_MULTI)
        with self._pending_lock:
            pending = self._pending_frames
        admit, conn.busy_floor = admission_decision(
            pending, budget, conn.busy_floor, seq, update_kind
        )
        if not admit:
            self._busy_rejects += 1
            if _telemetry.enabled():
                _srv_metric_handles()[0].inc(listener=str(self.port))
        return admit

    def _make_finisher(self, reply, fl):
        """Wrap ``reply`` so the frame's admission slot is released and
        its server-side flight entry completed exactly once, whichever
        pool path answers it."""
        done = [False]

        def finish(rkind: int, rseq: int, **kw) -> None:
            if not done[0]:
                done[0] = True
                with self._pending_lock:
                    self._pending_frames -= 1
                if fl is not None:
                    if rkind == _KIND_ERROR:
                        _flight.FlightRecorder.fail(fl)
                    else:
                        _flight.FlightRecorder.complete(fl)
            reply(rkind, rseq, **kw)

        return finish

    def _server_types(self):
        """Cached (_Message, _CancelToken) from ``.server`` — imported
        lazily (the module cycle forbids a top-level import) but only
        ONCE, not per frame on the single event-loop thread."""
        types = self.__dict__.get("_server_types_cache")
        if types is None:
            from .server import _CancelToken, _Message

            types = self.__dict__["_server_types_cache"] = (
                _Message, _CancelToken,
            )
        return types

    def _handle_frame(self, conn, frame) -> None:
        """One decoded frame, dispatched on the EVENT-LOOP thread. Frames
        are POSTED in wire order here (per-(inst, rank) apply order is
        mailbox order, so a client's updates to one shard still apply in
        its program order), but the applied-WAITS and replies run on the
        LISTENER-WIDE worker pool (``self._pool``): replies are
        correlated by the echoed frame seq, not FIFO, so one slow shard
        apply never head-of-line-blocks every later frame on the
        connection — the per-instance independence of the reference's
        Iprobe dispatch (``parameterserver.cpp:404-541``). Replies are
        QUEUED through the loop, never sent from pool threads, so a
        dead client cannot wedge a shared worker."""
        (kind, inst_id, rank, client, seq, oseq, fp, rule, dtype,
         wire, nchunks, payload, trace, tspan) = frame
        loop = self._loop
        # the server-side span for this frame's local work: child of the
        # sender's span (parent=tspan), deterministic so replays re-derive
        # the same id. Zero stays zero — unstamped frames cost one branch.
        srv_span = (
            _tracecontext.fnv1a64(trace, "ps:server", self.port, seq)
            if trace else 0
        )

        def reply(rkind: int, rseq: int, **kw) -> None:
            # replies echo the request's (trace, span): the closing edge
            # of the request span, not a new node
            loop.send(
                conn,
                _reply_bufs(rkind, seq=rseq, trace=trace, span=tspan, **kw),
            )

        if kind == _KIND_BARRIER:
            # subset barrier: record (tag, origin) and ack receipt; a
            # replayed frame (seq already applied) is ACKed without
            # re-counting the arrival. Control frames bypass admission
            # control — they are cheap and correctness-critical.
            if not seq or self._fresh_seq(
                self._barrier_applied, client, seq
            ):
                self.barrier_arrived(rule, client)
            reply(_KIND_ACK, seq)
            return
        if kind == _KIND_GATHER:
            # host-blob allgather contribution, same replay dedup
            if not seq or self._fresh_seq(
                self._gather_applied, client, seq
            ):
                self.gather_arrived(rule, client, payload)
            reply(_KIND_ACK, seq)
            return
        if kind == _KIND_REQUEST:
            # serving RPC: rides the UPDATE/TRIGGER admission budget so
            # inference load and training load shed against the same
            # bound (the serve tier's own brownout ladder sits above
            # this, inside the handler)
            if not self._admit(conn, kind, seq):
                reply(
                    _KIND_BUSY, seq,
                    rule=str(constants.get("ps_busy_retry_ms")),
                )
                return
            fl = None
            if _flight.enabled():
                fl = _flight.recorder.record(
                    f"ps:server:{self.port}", "request",
                    payload=f"{len(payload)}B", backend="socket",
                    routing=f"qos={rank},client={client}",
                    trace=trace, span=srv_span, parent=tspan,
                )
            with self._pending_lock:
                self._pending_frames += 1
            finish = self._make_finisher(reply, fl)
            self._submit(
                self._finish_request, finish, seq, inst_id, rank, client,
                rule, payload, time.monotonic(),
            )
            return
        if kind not in (_KIND_UPDATE, _KIND_UPDATE_MULTI, _KIND_TRIGGER):
            reply(_KIND_ERROR, seq, rule=f"bad kind {kind}")
            return
        # chain-forward frames (a replica pump relaying an update the
        # chain head ALREADY admitted) bypass admission: re-admitting at
        # every hop multiplies the rejection probability and inverts
        # priority — a BUSYed forward blocks the single in-order pump
        # while the originating update holds its slot upstream, so
        # replication traffic would starve behind the very client
        # traffic it carries. Forwarded frames still occupy pending
        # slots, so CLIENT traffic sheds first at a loaded replica —
        # backpressure points at the right party. Depth stays bounded:
        # each forward maps 1:1 to an update admitted under the head's
        # own budget.
        forwarded = kind == _KIND_UPDATE and rule.startswith("fwd:")
        if forwarded:
            rule = rule[4:]
        if not forwarded and not self._admit(conn, kind, seq):
            reply(
                _KIND_BUSY, seq,
                rule=str(constants.get("ps_busy_retry_ms")),
            )
            return
        inst = self._lookup(inst_id)
        if inst is None:
            reply(
                _KIND_ERROR, seq,
                rule=f"unknown parameter-server instance {inst_id}",
            )
            return
        if fp and fp != inst.fingerprint:
            # instance-id desync (processes created PSs in different
            # orders): fail loudly, never apply to the wrong tensor
            reply(
                _KIND_ERROR, seq,
                rule=(
                    f"instance {inst_id} fingerprint mismatch "
                    "(parameter servers must be created in the "
                    "same order on every process)"
                ),
            )
            return
        timeout = constants.get("deadlock_timeout_seconds") or None
        # the frame is now in the apply stage: it holds one admission
        # slot and one server-side flight entry until its reply goes out
        t_admit = time.monotonic()
        fl = None
        if _flight.enabled():
            fl = _flight.recorder.record(
                f"ps:server:{self.port}",
                _KIND_NAMES.get(kind, str(kind)),
                payload=f"{len(payload)}B",
                backend="socket",
                routing=(
                    f"inst={inst_id},rank={rank},client={client}"
                    + (",fwd=1" if forwarded else "")
                ),
                trace=trace, span=srv_span, parent=tspan,
            )
        with self._pending_lock:
            self._pending_frames += 1
        finish = self._make_finisher(reply, fl)
        _Message, _CancelToken = self._server_types()
        # dedup identity: the origin seq under replication (it survives
        # failover to a replica), the channel seq otherwise
        dseq = oseq or seq
        if kind in (_KIND_UPDATE, _KIND_UPDATE_MULTI):
            dkey = (inst_id, rank, client)
            ikey = (dkey, dseq)
            owner = True
            pending: Optional[threading.Event] = None
            poisoned = None
            replay_applied = False
            with self._applied_lock:
                # applied / poisoned / inflight are decided in ONE
                # critical section: were the applied-check and the
                # inflight registration split, the original apply could
                # complete (recording seq and popping its inflight
                # entry) between them, and a reconnect retry would
                # register itself as a fresh owner and re-post a
                # non-idempotent rule.
                #
                # _failed is consulted BEFORE the _applied high-water
                # check: seqs are channel-monotone, so a LATER update's
                # success advances the mark past a failed seq — the
                # replay of the failed frame must be re-answered with
                # its recorded ERROR, never a false ACK (ADVICE r5).
                if dseq:
                    poisoned = self._failed.get(ikey)
                    if poisoned is None:
                        if self._applied.get(dkey, 0) >= dseq:
                            replay_applied = True
                        else:
                            pending = self._inflight.get(ikey)
                            if pending is None:
                                self._inflight[ikey] = threading.Event()
                            else:
                                owner = False
            if poisoned is not None:
                # retry of a failed frame whose ERROR response was lost
                # (single UPDATE, or a partially-applied multi):
                # re-report from the record, never re-apply (multi items
                # that succeeded would double)
                if _telemetry.enabled():
                    _metric_handles()[5].inc(outcome="poisoned")
                finish(_KIND_ERROR, seq, rule=poisoned)
                return
            if replay_applied:
                # retry of an already-applied update: ack only
                if _telemetry.enabled():
                    _metric_handles()[5].inc(outcome="acked")
                finish(_KIND_ACK, seq, inst=inst_id, rank=rank)
                return
            if not owner:
                # a reconnect retry racing the FIRST apply (its seq not
                # yet recorded): wait for that apply and report ITS
                # outcome — re-posting would apply a non-idempotent rule
                # ('add') twice. Own thread, NOT the pool: this wait
                # completes only when the owner's _finish_update (a pool
                # task) sets the event — parked on a pool worker it
                # could starve the very task it waits for.
                if _telemetry.enabled():
                    _metric_handles()[5].inc(outcome="waited")
                threading.Thread(
                    target=self._await_other_apply,
                    args=(finish, dkey, dseq, seq, pending, inst_id,
                          rank, timeout),
                    name="tm-ps-replay-wait", daemon=True,
                ).start()
                return
            try:
                dt = np.dtype(dtype)
                if kind == _KIND_UPDATE_MULTI:
                    items = _parse_multi_payload(payload, dt, wire)
                    owned = wire != _wire.WIRE_FULL
                else:
                    items = [(rank, np.frombuffer(payload, dt))]
                    # a decoded container is a fresh buffer with no
                    # other referents: safe to hand to the mailbox
                    # without the defensive copy
                    owned = nchunks > 0
            except Exception as e:  # noqa: BLE001 - bad wire payload
                if dseq:
                    with self._applied_lock:
                        done_ev = self._inflight.pop(ikey, None)
                    if done_ev is not None:
                        done_ev.set()
                finish(_KIND_ERROR, seq, rule=f"bad update payload: {e}")
                return
            # posting happens HERE, on the loop thread, so the per-rank
            # mailboxes see this connection's updates in wire order;
            # only the waits/replies are offloaded
            posted = []
            try:
                for r, values in items:
                    ev = threading.Event()
                    token = _CancelToken()
                    msg = _Message(
                        "update", client=client, rule=rule,
                        payload=values if owned else values.copy(),
                        done=ev, cancelled=token, oseq=oseq,
                        trace=trace, span=srv_span,
                    )
                    inst.post(r, msg)
                    posted.append((ev, token, msg, r))
            except Exception as e:  # noqa: BLE001 - e.g. bad rank
                # PARTIALLY-posted frame (an out-of-range rank makes
                # inst.post raise): withdraw what we can, reply ERROR,
                # and release the inflight slot — leaking it would hang
                # the channel replay's not-owner wait forever
                self._submit(
                    self._abort_partial_post, finish, kind, ikey,
                    seq, posted, f"update post failed: {e}",
                )
                return
            self._submit(
                self._finish_update, finish, kind, dkey, ikey, dseq, seq,
                inst_id, rank, posted, timeout, t_admit,
            )
        else:  # _KIND_TRIGGER
            if oseq:
                # read-your-writes session floor: a replica-routed fetch
                # carries the client's last-acked origin seq (minus the
                # ps_read_staleness allowance). A member whose applied
                # high-water has not covered it must not serve — the
                # stale reply redirects the client to the owner, which
                # is fresh by construction (it is the write point).
                with self._applied_lock:
                    hw = self._applied.get((inst_id, rank, client), 0)
                if hw < oseq:
                    if _telemetry.enabled():
                        _srv_metric_handles()[7].inc(
                            listener=str(self.port)
                        )
                    finish(
                        _KIND_SHARD, seq, inst=inst_id, rank=rank,
                        rule=f"stale:{hw}", dtype="<f4",
                    )
                    return
            f: Future = Future()
            delta_base = None
            delta_origin = 0
            if rule.startswith("delta:"):
                # delta-encoded fetch: the client names the version of
                # its cached copy (and its origin process — two
                # processes may share a client id, e.g. the default
                # client=0, and must not overwrite each other's
                # reconstruction snapshots); the server thread answers
                # with 'same' / a delta against its recorded
                # reconstruction / a fresh full shard
                fields = rule.split(":")
                try:
                    delta_base = int(fields[1])
                    if len(fields) > 2:
                        delta_origin = int(fields[2])
                except (IndexError, ValueError) as e:
                    # malformed rule must still release the admission
                    # slot + flight entry it already holds — raising
                    # here would leak them and wedge the budget shut
                    finish(
                        _KIND_ERROR, seq,
                        rule=f"bad delta trigger rule {rule!r}: {e}",
                    )
                    return
            try:
                inst.post(
                    rank,
                    _Message(
                        "trigger", client=client, reply=f,
                        delta=delta_base, wire=wire,
                        origin=delta_origin,
                    ),
                )
            except Exception as e:  # noqa: BLE001 - e.g. bad rank
                finish(_KIND_ERROR, seq, rule=f"trigger post failed: {e}")
                return
            self._submit(
                self._finish_trigger, finish, f, seq, inst_id, rank,
                timeout, wire, t_admit,
            )

    def _abort_partial_post(
        self, reply, kind, ikey, seq, posted, failure
    ) -> None:
        try:
            applied_any = False
            for ev, token, msg, _r in posted:
                if token.cancel():
                    continue  # never started: exactly withdrawn
                ev.wait()  # applying or applied: let it finish
                applied_any = True
            if kind == _KIND_UPDATE_MULTI and ikey[1] and applied_any:
                # items that DID apply must never re-apply on a replay
                # whose ERROR response was lost: poison the (key, seq)
                if _telemetry.enabled():
                    _metric_handles()[4].inc(site="partial_post")
                with self._applied_lock:
                    while len(self._failed) >= _FAILED_CAP:
                        self._failed.pop(next(iter(self._failed)))
                    self._failed[ikey] = failure
            reply(_KIND_ERROR, seq, rule=failure)
        finally:
            if ikey[1]:
                with self._applied_lock:
                    done_ev = self._inflight.pop(ikey, None)
                if done_ev is not None:
                    done_ev.set()

    def _await_other_apply(
        self, reply, dkey, dseq, seq, pending, inst_id, rank, timeout
    ) -> None:
        pending.wait(timeout)
        with self._applied_lock:
            done = self._applied.get(dkey, 0) >= dseq
        if done:
            reply(_KIND_ACK, seq, inst=inst_id, rank=rank)
        else:
            reply(
                _KIND_ERROR, seq,
                rule="original update apply did not complete",
            )

    def _finish_update(
        self, reply, kind, dkey, ikey, dseq, seq, inst_id, rank, posted,
        timeout, t_admit=None,
    ) -> None:
        try:
            t_start = time.monotonic()
            failure: Optional[str] = None
            with _telemetry.span(
                "ps.server.apply", kind=_KIND_NAMES.get(kind, str(kind)),
                rank=rank,
            ):
                for ev, token, msg, _r in posted:
                    if not ev.wait(timeout):
                        # atomically withdraw: if the server has not
                        # STARTED applying, it never will (serve_once
                        # CAS-checks the token) and the failure report is
                        # exact; if it is mid-apply, wait for it to finish
                        # and report the true outcome instead of lying.
                        if token.cancel():
                            failure = "remote update apply timed out"
                            continue
                        ev.wait()  # apply in progress: completes
                    if msg.error is not None:
                        failure = f"update apply failed: {msg.error}"
            if _telemetry.enabled() and t_admit is not None:
                met = _srv_metric_handles()
                kname = _KIND_NAMES.get(kind, str(kind))
                met[4].observe(t_start - t_admit, kind=kname)
                met[5].observe(time.monotonic() - t_start, kind=kname)
            if failure is not None:
                # A frame is acked/deduped as a UNIT. The error is fatal
                # client-side (the pool never resends on _KIND_ERROR) —
                # but the ERROR response itself can be lost to a
                # connection drop, and the reconnect RESEND must be
                # answered from the record: for a multi frame re-applying
                # would double the items that succeeded; for a single
                # UPDATE a LATER update's success advances the _applied
                # high-water mark past this seq, and an unpoisoned replay
                # would then be answered with a false ACK (ADVICE r5).
                if dseq:
                    if _telemetry.enabled():
                        _metric_handles()[4].inc(site="apply_failed")
                    with self._applied_lock:
                        while len(self._failed) >= _FAILED_CAP:
                            self._failed.pop(next(iter(self._failed)))
                        self._failed[ikey] = failure
                reply(_KIND_ERROR, seq, rule=failure)
                return
            with self._applied_lock:
                if dseq:
                    # max(): concurrent applies of two updates to the same
                    # (inst, rank, client) finish on different pool
                    # workers — a plain store could regress the
                    # high-water mark
                    self._applied[dkey] = max(
                        self._applied.get(dkey, 0), dseq
                    )
            reply(_KIND_ACK, seq, inst=inst_id, rank=rank)
        finally:
            if dseq:
                with self._applied_lock:
                    done_ev = self._inflight.pop(ikey, None)
                if done_ev is not None:
                    done_ev.set()

    def _finish_trigger(
        self, reply, fut, seq, inst_id, rank, timeout, req_wire: int = 0,
        t_admit=None,
    ) -> None:
        t_start = time.monotonic()
        try:
            with _telemetry.span("ps.server.apply", kind="trigger",
                                 rank=rank):
                shard = fut.result(timeout)
        except Exception as e:  # noqa: BLE001 - reported to the client
            reply(_KIND_ERROR, seq, rule=str(e))
            return
        if _telemetry.enabled() and t_admit is not None:
            met = _srv_metric_handles()
            met[4].observe(t_start - t_admit, kind="trigger")
            met[5].observe(time.monotonic() - t_start, kind="trigger")
        from ..utils.tracing import wire_stats

        if isinstance(shard, dict):
            # delta-mode reply prebuilt on the server thread (the encode
            # happened there so the per-client reconstruction bookkeeping
            # records EXACTLY what goes on the wire)
            wire_stats.record(
                "ps_fetch", _wire.WIRE_NAMES.get(shard["wire"], "full"),
                shard["logical_nbytes"], shard["total_len"],
            )
            reply(
                _KIND_SHARD, seq, inst=inst_id, rank=rank,
                rule=shard["rule"], dtype=shard["dtype"],
                payload=shard["parts"], wire=shard["wire"],
                nchunks=shard["nchunks"],
            )
            return
        wire_eff = req_wire if shard.dtype == np.float32 else _wire.WIRE_FULL
        chunk_bytes = constants.get("ps_chunk_bytes")
        if wire_eff == _wire.WIRE_FULL and (
            chunk_bytes <= 0 or shard.nbytes <= chunk_bytes
        ):
            wire_stats.record("ps_fetch", "full", shard.nbytes, shard.nbytes)
            reply(
                _KIND_SHARD, seq, inst=inst_id, rank=rank,
                dtype=shard.dtype.str, payload=shard.tobytes(),
            )
            return
        block = constants.get("wire_quant_block_size")
        parts, total, nchunks = _wire.encode_frame_payload(
            shard, wire_eff, block, chunk_bytes
        )
        wire_stats.record(
            "ps_fetch", _wire.WIRE_NAMES.get(wire_eff, "full"),
            shard.nbytes, total,
        )
        if _telemetry.enabled() and nchunks:
            _metric_handles()[6].observe(nchunks, kind="trigger")
        reply(
            _KIND_SHARD, seq, inst=inst_id, rank=rank,
            dtype=shard.dtype.str, payload=parts, wire=wire_eff,
            nchunks=nchunks,
        )

    def _finish_request(
        self, finish, seq, inst_id, rank, client, rule, payload, t_admit,
    ) -> None:
        """Answer one serving REQUEST on the apply pool. ``rank`` is the
        QoS level the client put in the header's rank field (serving
        frames address no shard). The handler sees the listener's live
        admitted-frame backlog so its brownout ladder can key off queue
        pressure without a second bookkeeping path."""
        handler = self.request_handler
        if handler is None:
            finish(_KIND_ERROR, seq, rule="no request handler registered")
            return
        t_start = time.monotonic()
        try:
            with self._pending_lock:
                pending = self._pending_frames
            with _telemetry.span("ps.server.apply", kind="request",
                                 rank=rank):
                status, result = handler(rule, rank, payload, pending)
        except Exception as e:  # noqa: BLE001 - reported to the client
            finish(_KIND_ERROR, seq, rule=f"request handler failed: {e}")
            return
        if _telemetry.enabled():
            met = _srv_metric_handles()
            met[4].observe(t_start - t_admit, kind="request")
            met[5].observe(time.monotonic() - t_start, kind="request")
        if result is None:
            finish(_KIND_REPLY, seq, inst=inst_id, rank=rank, rule=status)
        elif isinstance(result, np.ndarray):
            finish(
                _KIND_REPLY, seq, inst=inst_id, rank=rank, rule=status,
                dtype=result.dtype.str, payload=result.tobytes(),
            )
        else:
            finish(
                _KIND_REPLY, seq, inst=inst_id, rank=rank, rule=status,
                payload=bytes(result),
            )

    def close(self):
        self._stop.set()
        self._loop.stop()  # joins the loop; closes every connection
        try:
            self._sock.close()
        except OSError:
            pass
        self._pool.shutdown(wait=False)


class _Waiter:
    """One in-flight request: the raw frame — a scatter-gather buffer
    list, retained fully encoded so a reconnect can replay it in
    original order — and the completion slot. ``t0``/``kind`` are
    telemetry fields (set only when telemetry is enabled)."""

    __slots__ = ("event", "frame", "reply", "error", "t0", "kind", "flight",
                 "busy")

    def __init__(self, frame: _Buffers):
        self.event = threading.Event()
        self.frame = frame
        self.reply = None
        self.error: Optional[Exception] = None
        self.t0: Optional[float] = None
        self.kind: int = 0
        # flight-recorder entry for this RPC (set only when the recorder
        # is on); completed/failed by complete()
        self.flight: Optional[list] = None
        # BUSY/retry-after rejections received for this frame (drives the
        # exponential backoff of the channel's busy resender)
        self.busy: int = 0


class _PeerChannel:
    """One persistent connection to a peer, PIPELINED: a sender holds the
    channel lock only while assigning its seq and putting the frame on
    the wire — never for the round trip — so many requests ride the
    connection concurrently. A demux reader thread matches each reply to
    its waiter by the ECHOED frame seq: the listener posts a
    connection's frames in wire order but applies them concurrently, so
    replies arrive out of order and the seq is the request id.

    Reconnects are CHANNEL-level, not caller-level: on a broken
    connection the channel reconnects once and replays every un-answered
    frame in original order. Caller-side retries would be wrong here —
    two pipelined updates of one (inst, rank, client) could be resent in
    swapped order, and the server's monotone seq dedup would then drop
    the earlier one as "already applied" (silent update loss). In-order
    replay preserves exactly the assignment-order == wire-order
    invariant the dedup was designed around; replayed frames whose
    original apply DID land are answered from the dedup/in-flight
    tables, never re-applied."""

    def __init__(self, addresses: Dict[int, Tuple[str, int]], proc: int):
        self.addresses = addresses
        self.proc = proc
        self.lock = _lockmon.make_lock("transport.py:_PeerChannel.lock")
        # seq -> waiter, in submission (== seq) order: replies are matched
        # by the echoed seq (the server replies OUT of order now that
        # applies run concurrently), while reconnect replay still walks
        # the insertion order
        self.pending: "OrderedDict[int, _Waiter]" = OrderedDict()
        self.sock: Optional[socket.socket] = None
        self.gen = 0  # connection generation (stale-reader guard)
        self.seq = 0
        # replay attempts since the last successful reply; bounds the
        # reconnect loop to ONE outstanding replay (the old pool's "one
        # reconnect attempt" budget)
        self._unacked_replays = 0
        # liveness marker for the waiter watchdog: monotonic time of the
        # last reply (or connect). A pipelined waiter may legitimately
        # queue for many windows behind slow-but-live applies; only a
        # connection with NO traffic for a full window is wedged.
        self._last_reply = time.monotonic()
        # BUSY/retry-after backoff state: rejected seqs bank here and a
        # lazy resender thread replays them (in seq order, preserving
        # the server's order fence) once the jittered due time passes.
        # Guarded by _busy_cv, NEVER nested inside self.lock.
        self._busy_cv = _lockmon.make_condition(
            "transport.py:_PeerChannel._busy_cv"
        )
        self._busy_seqs: set = set()
        self._busy_due = 0.0
        self._busy_thread: Optional[threading.Thread] = None
        # monotonic time of the last BUSY reject from this peer — the
        # adaptive read policy's backpressure signal (stale value just
        # means the owner recovered; reads drift back to it)
        self.last_busy = 0.0
        self.closed = False

    def _connect(self) -> socket.socket:
        host, port = self.addresses[self.proc]
        last_err: Optional[Exception] = None
        for candidate in (host, "localhost"):
            try:
                sock = socket.create_connection((candidate, port), timeout=30)
                sock.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
                # The 30s above bounds only the CONNECT. The established
                # socket's RECV blocks indefinitely: slow applies are
                # bounded by the waiter liveness check (deadlock
                # watchdog), dead peers by TCP keepalive (~75s) — a recv
                # timeout would tear down a healthy pipelined connection
                # that simply had no traffic. SENDs, which happen under
                # the channel lock, get the watchdog as SO_SNDTIMEO: a
                # peer that stops reading while the TCP buffer is full
                # would otherwise block sendall forever WITH the lock
                # held — wedging the reader's demux and the _kick escape
                # hatch along with it.
                _enable_keepalive(sock)
                sock.settimeout(None)
                wd = constants.get("deadlock_timeout_seconds") or 0
                if wd > 0:
                    # struct timeval layout is platform-specific (Windows
                    # wants a DWORD of milliseconds); a wrong-size value
                    # can raise or set a garbage timeout, so degrade to
                    # no send-timeout rather than break connect
                    try:
                        import sys as _sys

                        if _sys.platform == "win32":
                            sock.setsockopt(
                                socket.SOL_SOCKET, socket.SO_SNDTIMEO,
                                struct.pack("@L", int(wd) * 1000),
                            )
                        else:
                            sock.setsockopt(
                                socket.SOL_SOCKET, socket.SO_SNDTIMEO,
                                struct.pack("@ll", int(wd), 0),
                            )
                    except OSError:
                        pass
                return sock
            except OSError as e:  # try localhost fallback (single-host test)
                last_err = e
        raise ConnectionError(
            f"cannot reach parameter-server peer process {self.proc} at "
            f"{host}:{port}: {last_err}"
        )

    def _connected_locked(self) -> socket.socket:
        """Ensure a live connection + reader (caller holds ``self.lock``)."""
        if self.sock is None:
            self.sock = self._connect()
            self.gen += 1
            self._last_reply = time.monotonic()  # fresh liveness window
            threading.Thread(
                target=self._read_loop,
                args=(self.sock, self.gen),
                name=f"tm-ps-reader-{self.proc}",
                daemon=True,
            ).start()
        return self.sock

    def _read_loop(self, sock: socket.socket, gen: int) -> None:
        while True:
            try:
                frame = _recv_frame(sock)
            except Exception as e:  # noqa: BLE001 - includes auth/magic
                self._on_broken(gen, e)
                return
            rseq = frame[4]  # server echoes the request seq
            if frame[0] == _KIND_BUSY:
                # admission-control reject: the frame was NOT applied.
                # Keep the waiter pending and schedule a jittered-backoff
                # replay — overload degrades to retry latency, and the
                # BUSY itself counts as traffic for the silence watchdog
                # (the server is alive, just shedding).
                self._on_busy(rseq, frame[6])
                continue
            with self.lock:
                w = self.pending.pop(rseq, None)
                self._unacked_replays = 0  # traffic flows: reset budget
                self._last_reply = time.monotonic()
            if w is not None:
                w.reply = frame
                w.event.set()

    def _on_busy(self, rseq: int, hint: str) -> None:
        try:
            hint_ms = int(hint)
        except (TypeError, ValueError):
            hint_ms = 0
        self.last_busy = time.monotonic()
        with self.lock:
            self._unacked_replays = 0
            self._last_reply = time.monotonic()
            w = self.pending.get(rseq)
            if w is None:
                return  # already failed/answered
            w.busy += 1
            attempts = w.busy
        if _telemetry.enabled():
            _metric_handles()[8].inc()
        due = time.monotonic() + busy_backoff_s(attempts, hint_ms)
        with self._busy_cv:
            self._busy_seqs.add(rseq)
            self._busy_due = max(self._busy_due, due)
            if self._busy_thread is None or not self._busy_thread.is_alive():
                self._busy_thread = threading.Thread(
                    target=self._busy_resend_loop,
                    name=f"tm-ps-busy-{self.proc}", daemon=True,
                )
                self._busy_thread.start()
            self._busy_cv.notify_all()

    def _busy_resend_loop(self) -> None:
        """Replays BUSY-rejected frames after their backoff, in seq order
        (the server's per-connection order fence admits the lowest
        rejected seq first). Lives only while the channel does."""
        while True:
            with self._busy_cv:
                while not self._busy_seqs and not self.closed:
                    self._busy_cv.wait()
                if self.closed:
                    return
                now = time.monotonic()
                if now < self._busy_due:
                    self._busy_cv.wait(self._busy_due - now)
                    continue
                seqs = sorted(self._busy_seqs)
                self._busy_seqs.clear()
            rebank = None
            with self.lock:
                if self.closed:
                    return
                try:
                    sock = self._connected_locked()
                    for s in seqs:
                        w = self.pending.get(s)
                        if w is not None:
                            _send_buffers(sock, w.frame)
                except (ConnectionError, OSError) as e:
                    if self.sock is not None:
                        # mid-send break on a live socket: that socket's
                        # reader observes the break and _on_broken
                        # replays every pending frame (these included)
                        pass
                    elif self._unacked_replays >= 1:
                        self._fail_pending_locked(ConnectionError(
                            f"parameter-server peer {self.proc} "
                            f"unreachable during BUSY retry: {e}"
                        ))
                    else:
                        # connect itself failed: no reader exists to
                        # recover these frames — re-bank them for one
                        # more backoff window, charged against the same
                        # replay budget _on_broken uses
                        self._unacked_replays += 1
                        rebank = seqs
            if rebank is not None:
                due = time.monotonic() + (
                    constants.get("ps_busy_retry_ms") / 1000.0
                )
                with self._busy_cv:
                    self._busy_seqs.update(rebank)
                    self._busy_due = max(self._busy_due, due)

    def _fail_pending_locked(self, err: Exception) -> None:
        while self.pending:
            _, w = self.pending.popitem(last=False)
            w.error = err
            w.event.set()

    def _on_broken(self, gen: int, err: Exception) -> None:
        """Reader-side failure path: reconnect once and replay the
        un-answered frames in order, or fail them all."""
        with self.lock:
            if gen != self.gen or self.closed:
                return  # a newer connection already took over
            if self.sock is not None:
                try:
                    self.sock.close()
                except OSError:
                    pass
                self.sock = None
            if not self.pending:
                return  # nothing outstanding: reconnect lazily
            if self._unacked_replays >= 1:
                # the previous replay produced no reply before breaking
                # again: peer is gone, stop looping
                self._fail_pending_locked(
                    ConnectionError(
                        f"parameter-server peer {self.proc} unreachable "
                        f"after replay: {err}"
                    )
                )
                return
            self._unacked_replays += 1
            if _telemetry.enabled():
                met = _metric_handles()
                met[2].inc()  # reconnects
                met[3].inc(len(self.pending))  # replayed frames
            try:
                sock = self._connected_locked()
                for w in self.pending.values():
                    _send_buffers(sock, w.frame)
            except (ConnectionError, OSError) as e2:
                if self.sock is not None:
                    try:
                        self.sock.close()
                    except OSError:
                        pass
                    self.sock = None
                self._fail_pending_locked(
                    ConnectionError(
                        f"parameter-server peer {self.proc} reconnect "
                        f"failed: {e2}"
                    )
                )

    def _kick(self) -> None:
        """Force the failure/replay path (used by waiter timeouts)."""
        with self.lock:
            if self.sock is not None:
                try:
                    self.sock.close()
                except OSError:
                    pass

    def request(
        self,
        kind: int,
        inst: int,
        rank: int,
        client: int,
        fp: int = 0,
        rule: str = "",
        payload_arr: Optional[np.ndarray] = None,
        payload_raw: bytes = b"",
        dtype_str: str = "",
        wire: Optional[int] = None,
        oseq: int = 0,
        trace: int = 0,
        span: int = 0,
        parent: int = 0,
    ):
        """Pipelined request/response."""
        return self.complete(
            self.submit(
                kind, inst, rank, client, fp=fp, rule=rule,
                payload_arr=payload_arr, payload_raw=payload_raw,
                dtype_str=dtype_str, wire=wire, oseq=oseq,
                trace=trace, span=span, parent=parent,
            )
        )

    def submit(
        self,
        kind: int,
        inst: int,
        rank: int,
        client: int,
        fp: int = 0,
        rule: str = "",
        payload_arr: Optional[np.ndarray] = None,
        payload_raw: bytes = b"",
        dtype_str: str = "",
        wire: Optional[int] = None,
        oseq: int = 0,
        trace: int = 0,
        span: int = 0,
        parent: int = 0,
    ) -> _Waiter:
        """Put one frame on the wire and return its waiter WITHOUT waiting
        for the reply — fan-out callers (allgather_blob, barrier) submit to
        every peer first, then :meth:`complete` each, so P-1 exchanges cost
        ~1 round trip instead of P-1 serialized ones.

        ``payload_arr`` frames go through the PS wire codec: the payload
        is encoded per ``parameterserver_wire_dtype`` (int8 block-quant /
        bf16 / full) and split into ``ps_chunk_bytes`` chunks, each
        quantized-then-sent in turn so serialization of chunk k+1
        overlaps the wire I/O of chunk k (``sendmsg`` scatter-gather, no
        concat copy). ``wire`` overrides the encoding (TRIGGERs use it to
        request a reply encoding; explicit WIRE_FULL pins a frame
        verbatim).

        EVERY frame draws a seq from the per-peer counter UNDER the channel
        lock together with the send — assignment order == wire order, so
        the server's dedup can never confuse concurrent sends with
        retries, and replies (now out-of-order: the server applies
        concurrently) are correlated back by the echoed seq."""
        wire_eff = int(wire) if wire is not None else 0
        nchunks = 0
        chunk_iter = None
        total_len = len(payload_raw)
        block = 0
        if payload_arr is not None:
            arr = np.ascontiguousarray(payload_arr)
            dtype_str = arr.dtype.str
            if wire is None:
                wire_eff = _wire.resolve_ps_wire(arr.dtype)
            chunk_bytes = constants.get("ps_chunk_bytes")
            if arr.size == 0:
                wire_eff = _wire.WIRE_FULL  # empty shard: nothing to encode
            if wire_eff == _wire.WIRE_FULL and (
                chunk_bytes <= 0 or arr.nbytes <= chunk_bytes
            ):
                payload_raw = arr.tobytes()  # small fp32 frame: legacy path
                total_len = len(payload_raw)
            else:
                block = constants.get("wire_quant_block_size")
                n = int(arr.size)
                total_len, nchunks = _wire.container_nbytes(
                    n, wire_eff, block, chunk_bytes, arr.dtype.itemsize
                )
                chunk_iter = _wire.iter_encoded_chunks(
                    arr, wire_eff, block, chunk_bytes
                )
            from ..utils.tracing import wire_stats

            wire_stats.record(
                "ps_update", _wire.WIRE_NAMES.get(wire_eff, "full"),
                arr.nbytes, total_len,
            )
        if not trace:
            # explicit (trace, span) wins — forwarding hops carry the
            # origin trace; otherwise the ambient context stamps the frame
            ctx = _tracecontext.current()
            if ctx is not None:
                trace, parent = ctx.trace_id, ctx.span_id
        with self.lock:
            if self.closed:
                raise ConnectionError("parameter-server transport closed")
            self.seq += 1
            seq = self.seq
            if trace and not span:
                # this RPC-send hop's span, derived after the seq draw so
                # every frame on the channel gets a distinct id
                span = _tracecontext.fnv1a64(trace, "ps", self.proc, seq)
            header, rule_b, dtype_b = _frame_header(
                kind, inst, rank, client, seq, fp, wire_eff, nchunks,
                rule, dtype_str, total_len, oseq, trace, span,
            )
            w = _Waiter([header, rule_b, dtype_b])
            if _telemetry.enabled():
                w.t0 = time.monotonic()
                w.kind = kind
                met = _metric_handles()
                met[0].inc(kind=_KIND_NAMES.get(kind, str(kind)))
                if nchunks:
                    met[6].observe(
                        nchunks, kind=_KIND_NAMES.get(kind, str(kind))
                    )
            sock = self._connected_locked()  # raises if unreachable
            if _flight.enabled():
                # recorded only once the channel is live (a connect
                # failure raises out of submit — no RPC ever existed, so
                # no entry may be left 'issued' for the watchdog to flag);
                # the entry reuses the wire seq (per-peer monotone), so a
                # recorder line maps 1:1 to the frame on the wire; stuck
                # at 'issued' past the watchdog timeout = the hang signal
                w.flight = _flight.recorder.record(
                    f"ps:{self.proc}", _KIND_NAMES.get(kind, str(kind)),
                    payload=f"{total_len}B:{dtype_str or 'raw'}",
                    wire=_wire.WIRE_NAMES.get(wire_eff, str(wire_eff)),
                    backend="socket",
                    routing=f"inst={inst},rank={rank},client={client}",
                    seq=seq,
                    trace=trace, span=span, parent=parent,
                )
            self.pending[seq] = w
            sock_ok = True

            def _try_send(bufs) -> None:
                nonlocal sock_ok
                if not sock_ok:
                    return
                try:
                    _send_buffers(sock, bufs)
                except OSError:
                    # leave w in pending and close: the reader's replay
                    # path resends the (fully encoded) frame in order on
                    # the next connection. Encoding continues below so the
                    # retained frame is complete.
                    sock_ok = False
                    try:
                        sock.close()
                    except OSError:
                        pass

            if chunk_iter is None:
                if payload_raw:
                    w.frame.append(payload_raw)
                _try_send(w.frame)
            else:
                # pipelined chunk stream: encode chunk k+1 while the
                # kernel drains chunk k; the header rides with chunk 0.
                # Driven by the schedule IR's shared ChunkPipeline so
                # every chunk gets a (frame-id, chunk_idx) flight
                # sub-entry on the rank-local "chunks" stream.
                pending_bufs = list(w.frame)

                def send_stage(idx: int, bufs) -> None:
                    nonlocal pending_bufs
                    w.frame.extend(bufs)
                    _try_send(pending_bufs + bufs)
                    pending_bufs = []

                _sched_pipeline.ChunkPipeline(
                    f"ps:{self.proc}:{seq}",
                    _KIND_NAMES.get(kind, str(kind)),
                    nbytes_of=lambda bufs: sum(
                        len(memoryview(b).cast("B")) for b in bufs
                    ),
                ).run(chunk_iter, send_stage)
        return w

    def complete(self, w: _Waiter):
        """Wait for a submitted frame's reply and decode it."""
        timeout = constants.get("deadlock_timeout_seconds") or None
        # The watchdog bounds CONNECTION silence, not this waiter's queue
        # position: a pipelined request may legitimately wait many
        # windows behind slow-but-live applies (the server handles a
        # connection's frames sequentially), and that was never a
        # deadlock under the old lock-step pool either. Only when NO
        # reply lands for a full window is the peer wedged: then force
        # one reconnect+replay, and give it one more silent window
        # before declaring it dead.
        kicked = False
        while not w.event.wait(timeout):
            with self.lock:
                silent = time.monotonic() - self._last_reply
            if silent < (timeout or 0):
                continue  # traffic is flowing; we're just queued
            if not kicked:
                kicked = True
                self._kick()
                continue
            if w.flight is not None:
                _flight.FlightRecorder.fail(w.flight)
            raise ConnectionError(
                f"parameter-server peer {self.proc} sent nothing for "
                f"{int(silent)}s (watchdog {timeout}s, after replay)"
            )
        if w.error is not None:
            if w.flight is not None:
                _flight.FlightRecorder.fail(w.flight)
            raise w.error
        if w.t0 is not None and _telemetry.enabled():
            _metric_handles()[1].observe(
                time.monotonic() - w.t0,
                kind=_KIND_NAMES.get(w.kind, str(w.kind)),
            )
        rkind, _, _, _, _, _, rrule, rdtype, rpayload = w.reply
        if rkind == _KIND_ERROR:
            if w.flight is not None:
                _flight.FlightRecorder.fail(w.flight)
            raise RuntimeError(f"parameter-server peer error: {rrule}")
        if w.flight is not None:
            _flight.FlightRecorder.complete(w.flight)
        if rkind == _KIND_SHARD:
            return np.frombuffer(rpayload, np.dtype(rdtype)).copy()
        if rkind == _KIND_REPLY:
            # serving RPC: (status_rule, decoded result). rrule carries
            # "ok" / "shed:<retry_ms>"; the result array is absent on a
            # shed reply.
            if rdtype:
                return rrule, np.frombuffer(
                    rpayload, np.dtype(rdtype)
                ).copy()
            return rrule, (bytes(rpayload) if rpayload else None)
        return None  # ACK

    def close(self) -> None:
        with self.lock:
            self.closed = True
            if self.sock is not None:
                try:
                    self.sock.close()
                except OSError:
                    pass
                self.sock = None
            self._fail_pending_locked(
                ConnectionError("parameter-server transport closed")
            )
        with self._busy_cv:
            self._busy_cv.notify_all()  # release the busy resender


class _PeerPool:
    """Pipelined persistent channels, one per peer process."""

    def __init__(self, addresses: Dict[int, Tuple[str, int]]):
        self.addresses = addresses
        self._channels: Dict[int, _PeerChannel] = {
            p: _PeerChannel(addresses, p) for p in addresses
        }

    def request(self, proc: int, kind: int, inst: int, rank: int,
                client: int, **kw):
        return self._channels[proc].request(kind, inst, rank, client, **kw)

    def submit(self, proc: int, kind: int, inst: int, rank: int,
               client: int, **kw):
        return self._channels[proc].submit(kind, inst, rank, client, **kw)

    def complete(self, proc: int, waiter):
        return self._channels[proc].complete(waiter)

    def close(self):
        for ch in self._channels.values():
            ch.close()


class Transport:
    """Process-wide PS transport: listener + peer pool + address book."""

    def __init__(self, lookup_instance):
        import jax

        self.process_index = jax.process_index()
        self.listener = _Listener(lookup_instance)
        # token FIRST, then addresses: peers cannot reach the listener
        # before the exchange publishes its address, and by then every
        # process holds the job secret
        _init_job_token()
        host = os.environ.get("TORCHMPI_TPU_PS_HOST") or socket.gethostname()
        addresses = self._exchange_addresses(host, self.listener.port)
        self.pool = _PeerPool(addresses)
        # delta-fetch client cache: (inst, rank, client) ->
        # (serving proc, version, reconstruction). One in-flight delta
        # round trip per key (the per-key lock): overlapping deltas
        # against one snapshot would fork the client/server
        # reconstruction agreement. The key is CHAIN-CONSISTENT (no
        # proc): replica-aware routing may serve consecutive fetches of
        # one shard from different chain members, and a per-proc key
        # would let a replica-served delta poison the owner's recorded
        # reconstruction. The serving proc lives in the VALUE instead —
        # a fetch routed to a different member sends base=-1 (full,
        # self-healing), because snapshot agreement is per member.
        self._delta_cache: Dict[Tuple[int, int, int],
                                Tuple[int, int, np.ndarray]] = {}
        self._delta_locks: Dict[Tuple[int, int, int],
                                threading.Lock] = {}
        self._delta_guard = _lockmon.make_lock(
            "transport.py:Transport._delta_guard"
        )
        # replication failover state: processes observed dead (a channel
        # raised ConnectionError after its replay budget) are skipped
        # when routing down a shard's replica chain — but only for
        # ps_dead_peer_retry_s, after which they are re-probed (a
        # permanent mark would let one transient stall split the brain:
        # this client routing to the replica forever while other clients
        # still talk to the recovered head). Per-(inst, rank, client)
        # origin-seq counters give every replicated update a
        # channel-independent dedup identity that survives re-issue to a
        # replica (see the oseq header field).
        self._dead_procs: Dict[int, float] = {}
        self._dead_expired: set = set()
        self._dead_lock = _lockmon.make_lock(
            "transport.py:Transport._dead_lock"
        )
        self._oseq: Dict[Tuple[int, int, int], int] = {}
        self._oseq_lock = _lockmon.make_lock(
            "transport.py:Transport._oseq_lock"
        )
        # read-path state (PS read routing; see trigger()):
        # - _acked: (inst, rank, client) -> highest origin seq this
        #   process has been ACKED for — the read-your-writes session
        #   floor a replica-routed fetch must have applied (guarded by
        #   _oseq_lock, same lifecycle as _oseq);
        # - _read_rr: (inst, rank) -> round-robin cursor spreading
        #   fetches over the replica chain under ps_read_policy=replica;
        # - _shm_readers / _shm_failed / _read_versions: the zero-copy
        #   shared-memory lane's attach cache, the peers known to be on
        #   another host (never retried), and the shard version each
        #   shm-served fetch observed (consulted by serve's
        #   version_vector, which otherwise only sees the delta cache).
        self._read_rr: Dict[Tuple[int, int], int] = {}
        self._read_lock = _lockmon.make_lock(
            "transport.py:Transport._read_lock"
        )
        self._acked: Dict[Tuple[int, int, int], int] = {}
        self._shm_readers: Dict[Tuple[int, int, int], object] = {}
        self._shm_failed: set = set()
        self._read_versions: Dict[Tuple[int, int, int], int] = {}

    @staticmethod
    def _exchange_addresses(host: str, port: int) -> Dict[int, Tuple[str, int]]:
        import jax
        from jax.experimental import multihost_utils

        n = jax.process_count()
        # fixed-width byte matrix: "host:port" padded to 256
        me = f"{host}:{port}".encode()[:256].ljust(256, b"\0")
        mine = np.frombuffer(me, np.uint8)
        # reshape defensively: a single-process allgather comes back flat
        # (256,), not (1, 256) — indexing row p would slice one BYTE
        gathered = np.asarray(
            multihost_utils.process_allgather(mine)
        ).reshape(n, -1)
        out: Dict[int, Tuple[str, int]] = {}
        for p in range(n):
            s = bytes(gathered[p]).rstrip(b"\0").decode()
            h, _, pt = s.rpartition(":")
            out[p] = (h, int(pt))
        return out

    def next_oseq(self, inst: int, rank: int, client: int) -> int:
        """Channel-independent monotone update id per (inst, rank,
        client) — the dedup identity a replicated update keeps across
        failover re-issues and chain forwarding."""
        with self._oseq_lock:
            v = self._oseq.get((inst, rank, client), 0) + 1
            self._oseq[(inst, rank, client)] = v
            return v

    def _record_acked(self, inst: int, rank: int, client: int,
                      oseq: int) -> None:
        """Advance the read-your-writes session floor: ``oseq`` was
        ACKED (applied at its serving chain member), so any later fetch
        by this client must observe at least it."""
        if not oseq:
            return
        k = (inst, rank, client)
        with self._oseq_lock:
            if oseq > self._acked.get(k, 0):
                self._acked[k] = oseq

    def _session_floor(self, inst: int, rank: int, client: int) -> int:
        """The origin seq a NON-owner chain member must have applied to
        serve this client's fetch: last-acked minus the
        ``ps_read_staleness`` allowance (0 = nothing written yet, or
        everything written is inside the allowed lag — any member may
        serve). The owner never needs a floor: it is the write point."""
        with self._oseq_lock:
            acked = self._acked.get((inst, rank, client), 0)
        return max(0, acked - int(constants.get("ps_read_staleness")))

    def _dead_marks_gauge(self, ttl: float, now: float) -> None:
        if not _telemetry.enabled():
            return
        # snapshot: another thread's _mark_dead may mutate the dict
        # mid-iteration (the transport is shared across client threads
        # and the replica pump)
        active = sum(
            1 for t in list(self._dead_procs.values())
            if not ttl or now - t < ttl
        )
        _metric_handles()[9].set(active)

    def _mark_dead(self, proc: int) -> None:
        with self._dead_lock:
            self._dead_procs[proc] = time.monotonic()
            self._dead_expired.discard(proc)
        self._dead_marks_gauge(
            constants.get("ps_dead_peer_retry_s"), time.monotonic()
        )

    def _alive_chain(self, chain) -> List[int]:
        ttl = constants.get("ps_dead_peer_retry_s")
        now = time.monotonic()
        alive = []
        for p in chain:
            t = self._dead_procs.get(p)
            if t is None:
                alive.append(p)
            elif ttl and now - t >= ttl:
                # the retry window elapsed: route to the peer again.
                # Counting the expiry (once per mark) makes the bounded
                # split-brain window PR 8 documented OBSERVABLE in
                # ps_health instead of invisible until a partition
                # scenario trips it. The lock-free pre-check keeps
                # long-expired marks off the hot path; under the lock,
                # the timestamp re-check drops the count if a racing
                # _mark_dead re-marked the peer (counting then would
                # pre-claim the FRESH mark's expiry).
                if p not in self._dead_expired:
                    with self._dead_lock:
                        first = (
                            self._dead_procs.get(p) == t
                            and p not in self._dead_expired
                        )
                        if first:
                            self._dead_expired.add(p)
                    if first:
                        if _telemetry.enabled():
                            _metric_handles()[10].inc()
                        # the gauge moves only on transitions (mark /
                        # expiry), not on every routing call
                        self._dead_marks_gauge(ttl, now)
                alive.append(p)
        return alive if alive else list(chain)  # last resort: retry all

    def update(
        self, proc: int, inst: int, rank: int, client: int, rule: str,
        payload: np.ndarray, fp: int = 0, chain=None, oseq: int = 0,
    ) -> None:
        """Apply ``rule`` to shard ``rank`` on its owner. With a replica
        ``chain`` (length > 1), the update carries an origin seq and
        fails over down the chain: a dead head is marked and the SAME
        update (same oseq) is re-issued to the next live replica — whose
        applied high-water dedups the re-issue if the head's chain
        forward already delivered it, so failover never loses or
        double-applies an update."""
        if chain is None or len(chain) <= 1:
            self.pool.request(
                proc, _KIND_UPDATE, inst, rank, client,
                fp=fp, rule=rule, payload_arr=payload, oseq=oseq,
            )
            self._record_acked(inst, rank, client, oseq)
            return
        if not oseq:
            oseq = self.next_oseq(inst, rank, client)
        last: Optional[Exception] = None
        for p in self._alive_chain(chain):
            try:
                self.pool.request(
                    p, _KIND_UPDATE, inst, rank, client,
                    fp=fp, rule=rule, payload_arr=payload, oseq=oseq,
                )
                self._record_acked(inst, rank, client, oseq)
                return
            except ConnectionError as e:
                self._mark_dead(p)
                last = e
        raise ConnectionError(
            f"all replicas of shard {rank} (chain {list(chain)}) "
            f"unreachable: {last}"
        )

    def forward_update(
        self, proc: int, inst: int, rank: int, client: int, rule: str,
        payload: np.ndarray, fp: int = 0, oseq: int = 0,
        trace: int = 0, parent: int = 0,
    ) -> None:
        """Chain-forward an APPLIED update to the next replica, keeping
        the original (client, oseq) dedup identity. Called by the
        server-side replica pump in apply order. The ``fwd:`` rule tag
        exempts the frame from the successor's admission budget (it was
        admitted once, at the chain head — see the listener's bypass
        note), so a loaded replica sheds client traffic, never the
        replication stream that keeps it consistent. ``(trace, parent)``
        carry the ORIGIN trace and the forwarding hop's apply span, so
        the chain stays one causal trace with one span per link."""
        self.pool.request(
            proc, _KIND_UPDATE, inst, rank, client,
            fp=fp, rule=f"fwd:{rule}", payload_arr=payload, oseq=oseq,
            trace=trace, parent=parent,
        )

    def update_multi(
        self, proc: int, inst: int, rank_slices, client: int, rule: str,
        fp: int = 0,
    ) -> None:
        """One frame carrying updates for every shard rank this peer owns
        (``rank_slices`` = [(rank, 1-D array)], all one dtype): one round
        trip + one applied-ack per peer instead of one per rank — the
        frame-level analog of the reference's per-chunk Isend fan-out
        (``parameterserver.cpp:309-353``). Each item is wire-encoded
        independently (its own quantization grid); the frame travels
        unchunked — ``server.py`` routes oversized slices through
        per-rank chunked UPDATE frames instead."""
        arrs = [np.ascontiguousarray(a) for _, a in rank_slices]
        wire_eff = _wire.resolve_ps_wire(arrs[0].dtype)
        if wire_eff == _wire.WIRE_FULL:
            blobs = [a.tobytes() for a in arrs]
        else:
            block = constants.get("wire_quant_block_size")
            blobs = []
            for a in arrs:
                if a.size == 0:
                    blobs.append(b"")
                    continue
                parts, _, _ = _wire.encode_frame_payload(
                    a, wire_eff, block, 0
                )
                blobs.append(b"".join(bytes(p) for p in parts))
        payload = b"".join(
            [_MULTI_COUNT.pack(len(rank_slices))]
            + [
                _MULTI_ITEM.pack(r, len(b))
                for (r, _), b in zip(rank_slices, blobs)
            ]
            + blobs
        )
        from ..utils.tracing import wire_stats

        wire_stats.record(
            "ps_update_multi", _wire.WIRE_NAMES.get(wire_eff, "full"),
            sum(a.nbytes for a in arrs), len(payload),
        )
        self.pool.request(
            proc, _KIND_UPDATE_MULTI, inst, _MULTI_RANK, client,
            fp=fp, rule=rule, wire=wire_eff,
            payload_raw=payload, dtype_str=arrs[0].dtype.str,
        )

    # bounded client-side reconstruction cache: long-running jobs churn
    # PS instances, and each key pins a shard-sized array — evicted keys
    # self-heal with a full fetch (mirrors the server's snapshot cap)
    _DELTA_CACHE_CAP = 256

    def _delta_lock_for(self, key) -> threading.Lock:
        with self._delta_guard:
            lock = self._delta_locks.get(key)
            if lock is None:
                lock = self._delta_locks[key] = _lockmon.make_lock(
                    "transport.py:Transport._delta_locks[]"
                )
            return lock

    def _delta_cache_store(self, key, entry) -> None:
        with self._delta_guard:
            while (
                len(self._delta_cache) >= self._DELTA_CACHE_CAP
                and key not in self._delta_cache
            ):
                # evict the array only — the per-key lock stays (tiny,
                # and replacing a lock another thread still holds would
                # briefly allow two concurrent deltas on one key)
                self._delta_cache.pop(next(iter(self._delta_cache)))
            self._delta_cache[key] = entry

    def _read_candidates(
        self, owner: int, inst: int, rank: int, chain, policy: str,
        prefer: Optional[int] = None,
    ) -> List[int]:
        """The ordered chain members a fetch of ``rank`` tries, per the
        read policy. ``owner``: the legacy availability walk — head
        first, live replicas only as failover. ``replica``: rotate the
        live chain round-robin so concurrent fetches of one shard land
        on distinct endpoints. ``adaptive``: owner-preferred, spreading
        only while the owner shows backpressure (a BUSY inside the last
        second, or a dead-mark). ``prefer`` pins the first candidate (a
        member already chosen by :meth:`route_read` so a caller's
        fan-out grouping and the actual routing agree)."""
        if chain is None or len(chain) <= 1:
            return [owner]
        alive = self._alive_chain(chain)
        if policy == "replica":
            spread = True
        elif policy == "adaptive":
            spread = self._owner_pressured(owner)
        else:
            spread = False
        if not spread or len(alive) <= 1:
            return alive
        if prefer is not None and prefer in alive:
            rot = alive.index(prefer)
        else:
            with self._read_lock:
                i = self._read_rr.get((inst, rank), 0)
                self._read_rr[(inst, rank)] = i + 1
            rot = i % len(alive)
        return alive[rot:] + alive[:rot]

    def route_read(self, owner: int, inst: int, rank: int, chain,
                   policy=None) -> int:
        """The chain member the NEXT fetch of ``rank`` would be served
        by under ``policy`` (advances the round-robin cursor). Callers
        fanning out many fetches group their issue threads by this, so
        the issue-all-then-wait overlap lands on distinct endpoints;
        they pass the result back to :meth:`trigger` as ``prefer``."""
        policy = str(policy or constants.get("ps_read_policy"))
        return self._read_candidates(owner, inst, rank, chain, policy)[0]

    def _owner_pressured(self, owner: int) -> bool:
        ch = self.pool._channels.get(owner)
        if ch is not None and time.monotonic() - ch.last_busy < 1.0:
            return True
        ttl = constants.get("ps_dead_peer_retry_s")
        t = self._dead_procs.get(owner)
        return t is not None and (
            not ttl or time.monotonic() - t < ttl
        )

    def _shm_fetch(
        self, owner: int, inst: int, rank: int, client: int,
    ) -> Optional[np.ndarray]:
        """The zero-copy lane: seqlock-read shard ``rank`` from the
        owner's shared-memory segment, if the owner is on THIS host and
        has published. None = lane unavailable or spin budget exhausted
        (caller falls back to the socket path). Owner publishes before
        acking, so this lane is read-your-writes with no session floor."""
        if owner in self._shm_failed:
            return None
        key = (owner, inst, rank)
        reader = self._shm_readers.get(key)
        if reader is None:
            from . import shmlane as _shm

            addr = self.pool.addresses.get(owner)
            if addr is None or not _shm.is_local_host(addr[0]):
                self._shm_failed.add(owner)  # permanent: host won't move
                return None
            with self._read_lock:
                reader = self._shm_readers.get(key)
                if reader is None:
                    reader = _shm.ShmReader(
                        _shm.segment_name(addr[1], inst, rank)
                    )
                    self._shm_readers[key] = reader
        before = reader.retries
        res = reader.read()
        if _telemetry.enabled() and reader.retries > before:
            _metric_handles()[13].inc(reader.retries - before)
        if res is None:
            return None
        arr, version = res
        with self._read_lock:
            k = (inst, rank, client)
            if version > self._read_versions.get(k, 0):
                self._read_versions[k] = version
        return arr

    def trigger(
        self, proc: int, inst: int, rank: int, client: int, fp: int = 0,
        logical_dtype=np.float32, chain=None, read_policy=None,
        prefer=None,
    ) -> np.ndarray:
        """Fetch shard ``rank``. Lanes, in preference order:

        1. **shm** (``ps_shm_lane``): same-host owner segment, seqlock
           read, no sockets — read-your-writes by publish-before-ack;
        2. **socket**, routed by ``read_policy`` (default the
           ``ps_read_policy`` knob) over the replica ``chain`` via
           :meth:`_read_candidates`. Non-owner members receive this
           client's session floor (:meth:`_session_floor`) and answer
           ``stale:<hw>`` instead of serving a view older than the
           client's own acked writes — the client then redirects to the
           owner (the "forward to the owner" of the session contract,
           executed client-side so the redirect rides the existing
           failover machinery). Dead members are marked (PR 8 walk) and
           skipped for ``ps_dead_peer_retry_s``.

        Last resort is always one direct owner attempt (even through a
        dead-mark — it may have recovered): a stale or dying replica set
        must never fail a fetch the owner can still serve."""
        policy = str(read_policy or constants.get("ps_read_policy"))
        want_t = _telemetry.enabled()
        t0 = time.monotonic() if want_t else 0.0
        if constants.get("ps_shm_lane"):
            arr = self._shm_fetch(proc, inst, rank, client)
            if arr is not None:
                if want_t:
                    _metric_handles()[11].inc(lane="shm", policy=policy)
                    _metric_handles()[14].observe(
                        time.monotonic() - t0, lane="shm"
                    )
                return arr
            if want_t:
                _metric_handles()[12].inc(reason="shm")
        floor = self._session_floor(inst, rank, client)
        last: Optional[Exception] = None
        owner_tried = False
        for p in self._read_candidates(
            proc, inst, rank, chain, policy, prefer=prefer,
        ):
            need = 0 if p == proc or policy == "owner" else floor
            try:
                arr = self._trigger_one(
                    p, inst, rank, client, fp, logical_dtype,
                    need_oseq=need,
                )
            except _StaleRead:
                # the member's applied high-water hasn't covered this
                # client's session floor: redirect toward the owner
                if want_t:
                    _metric_handles()[12].inc(reason="stale")
                continue
            except ConnectionError as e:
                self._mark_dead(p)
                last = e
                if want_t and p != proc:
                    _metric_handles()[12].inc(reason="dead")
                owner_tried = owner_tried or p == proc
                continue
            if want_t:
                lane = "owner" if p == proc else "replica"
                _metric_handles()[11].inc(lane=lane, policy=policy)
                _metric_handles()[14].observe(
                    time.monotonic() - t0, lane=lane
                )
            return arr
        if not owner_tried:
            # every candidate was stale/dead and none was the owner (or
            # the owner sat dead-marked outside the candidate walk):
            # one direct re-probe — the owner needs no session floor
            try:
                arr = self._trigger_one(
                    proc, inst, rank, client, fp, logical_dtype
                )
                if want_t:
                    _metric_handles()[11].inc(lane="owner", policy=policy)
                    _metric_handles()[14].observe(
                        time.monotonic() - t0, lane="owner"
                    )
                return arr
            except ConnectionError as e:
                self._mark_dead(proc)
                last = e
        raise ConnectionError(
            f"all replicas of shard {rank} "
            f"(chain {list(chain) if chain else [proc]}) "
            f"unreachable: {last}"
        )

    def _trigger_one(
        self, proc: int, inst: int, rank: int, client: int, fp: int = 0,
        logical_dtype=np.float32, need_oseq: int = 0,
    ) -> np.ndarray:
        wire_req = _wire.resolve_ps_wire(logical_dtype)
        if not constants.get("parameterserver_delta_encoding"):
            w = self.pool.submit(
                proc, _KIND_TRIGGER, inst, rank, client, fp=fp,
                wire=wire_req, oseq=need_oseq,
            )
            arr = self.pool.complete(proc, w)
            if need_oseq and w.reply[6].startswith("stale:"):
                raise _StaleRead(proc, w.reply[6])
            return arr
        # delta-encoded fetch: ship only the since-last-fetch difference
        # against the per-client version vector. Unchanged shard -> empty
        # 'same' reply (the big win for prefetch loops between sparse
        # updates); changed -> a delta, which quantizes on small scales
        # (tighter int8 error than a full-shard fetch); version mismatch
        # or server-side eviction -> a fresh full shard, self-healing.
        # The cache key is chain-consistent (no proc); the base version
        # is offered only to the member that RECORDED the matching
        # reconstruction — snapshot agreement is per member, so a fetch
        # routed elsewhere goes base=-1 (full reply, re-anchoring the
        # cache at the new member).
        key = (inst, rank, client)
        with self._delta_lock_for(key):
            cached = self._delta_cache.get(key)
            if cached is not None and cached[0] == proc:
                base, recon = cached[1], cached[2]
            else:
                base, recon = -1, None
            w = self.pool.submit(
                proc, _KIND_TRIGGER, inst, rank, client, fp=fp,
                rule=f"delta:{base}:{self.process_index}", wire=wire_req,
                oseq=need_oseq,
            )
            arr = self.pool.complete(proc, w)
            rrule = w.reply[6]
            if need_oseq and rrule.startswith("stale:"):
                raise _StaleRead(proc, rrule)
            if _telemetry.enabled():
                outcome = rrule.split(":", 1)[0] or "legacy"
                _metric_handles()[7].inc(reply=outcome)
            if rrule.startswith("same:"):
                version = int(rrule.split(":")[1])
                self._delta_cache_store(key, (proc, version, recon))
                return recon.copy()
            if rrule.startswith("delta:"):
                _, _, version = rrule.split(":")
                new = recon + arr
                self._delta_cache_store(key, (proc, int(version), new))
                return new.copy()
            if rrule.startswith("full:"):
                version = int(rrule.split(":")[1])
                self._delta_cache_store(key, (proc, version, arr.copy()))
                return arr
            return arr  # peer predates delta mode: plain shard reply

    def barrier(self, procs, tag: str, timeout=None) -> None:
        """Barrier among the process subset ``procs`` (all must call with
        the same tag): send a BARRIER frame to every peer, then wait until
        one arrived from each. Replaces job-global sync for parameter
        servers living on sub-communicators."""
        procs = set(int(p) for p in procs)
        me = self.process_index
        waiters = [
            (p, self.pool.submit(p, _KIND_BARRIER, 0, 0, me, rule=tag))
            for p in sorted(procs - {me})
        ]
        for p, w in waiters:
            self.pool.complete(p, w)
        expect = procs - {me}
        if expect and not self.listener.barrier_wait(tag, expect, timeout):
            raise RuntimeError(
                f"parameter-server barrier {tag!r} timed out waiting for "
                f"{sorted(expect)}"
            )

    def allgather_blob(
        self, procs, tag: str, payload: bytes, timeout=None
    ) -> Dict[int, bytes]:
        """Host allgather of opaque bytes among the process subset
        ``procs`` (all must call with the same tag): send the local
        payload to every peer, collect one from each. The host-wire
        exchange behind staged collectives — the analog of the
        reference's staged-via-pinned-CPU MPI hop
        (``lib/detail/collectives_cuda.cpp:390-683``), which moves
        cross-node data over the host fabric precisely because no
        inter-group device link is assumed."""
        procs = set(int(p) for p in procs)
        me = self.process_index
        # fan-out: all frames on the wire first, THEN collect the acks —
        # P-1 peers cost ~1 round trip, not P-1 serialized ones
        waiters = [
            (p, self.pool.submit(p, _KIND_GATHER, 0, 0, me,
                                 rule=tag, payload_raw=payload))
            for p in sorted(procs - {me})
        ]
        for p, w in waiters:
            self.pool.complete(p, w)
        out = {me: payload}
        expect = procs - {me}
        if expect:
            got = self.listener.gather_wait(tag, expect, timeout)
            if got is None:
                raise RuntimeError(
                    f"host allgather {tag!r} timed out waiting for "
                    f"{sorted(expect)}"
                )
            out.update(got)
        return out

    def set_request_handler(self, handler) -> None:
        """Install the serving-tier REQUEST handler on this process's
        listener (see :attr:`_Listener.request_handler`); ``None``
        uninstalls it."""
        self.listener.request_handler = handler

    def serve_request(
        self, proc: int, rule: str, payload, qos: int = 0,
    ):
        """One serving RPC to ``proc``'s request handler: returns
        ``(status_rule, result)`` where result is an ndarray (array
        reply), bytes (opaque reply) or None. BUSY backoff/replay is the
        channel's, same as every other frame kind. Request payloads ship
        verbatim (no wire codec): the handler contract is raw bytes in,
        so inference inputs are never quantized by the PS wire dtype."""
        if isinstance(payload, np.ndarray):
            raw = np.ascontiguousarray(payload).tobytes()
        else:
            raw = bytes(payload) if payload else b""
        return self.pool.request(
            proc, _KIND_REQUEST, 0, int(qos), self.process_index,
            rule=rule, payload_raw=raw,
        )

    def close(self):
        self.pool.close()
        self.listener.close()


_transport: Optional[Transport] = None
_transport_lock = _lockmon.make_lock("transport.py:_transport_lock")


def ensure_transport() -> Transport:
    """Bootstrap the process-wide transport on first cross-process PS use
    (the reference bootstraps per-instance inside barriers,
    ``parameterserver.cpp:677-745``)."""
    global _transport
    with _transport_lock:
        if _transport is None:
            from .server import _server

            _transport = Transport(_server.get_instance)
        return _transport


def shutdown_transport() -> None:
    global _transport
    with _transport_lock:
        if _transport is not None:
            _transport.close()
            _transport = None
