"""Zero-copy shared-memory fetch lane for co-located PS clients.

The socket fetch path costs two syscalls, an event-loop dispatch, an
apply-pool hop, and a wire encode/decode per shard — pure overhead when
client and shard owner share a host. This module gives same-host fetches
a lane that bypasses all of it: the owner publishes every applied shard
into a per-(instance, rank) ``multiprocessing.shared_memory`` segment,
and clients read it through a **seqlock**:

- the publisher bumps a version counter to ODD, memcpys the shard bytes
  plus the shard's delta version, then bumps the counter to EVEN;
- a reader snapshots the counter (odd = write in progress, retry), reads
  the payload, and re-reads the counter — any mismatch means the read
  raced a publish (torn) and is retried; after ``ps_shm_spin_limit``
  attempts the caller falls back to the socket path.

Freshness contract: :meth:`ShmPublisher.publish` is called by the server
thread right after each apply, BEFORE the update's ack is released — so
a client that has been acked for a write always observes it through the
owner's segment (read-your-writes by construction, no session floor
needed on this lane).

Segment names are derived from the owner's listener port (unique per
host), so clients compute them from the address book with no extra
exchange. Python 3.10's ``SharedMemory`` registers every attach with the
resource tracker (which would spuriously unlink publisher-owned segments
at reader-process exit); readers unregister themselves, and the
publisher owns unlink.
"""

from __future__ import annotations

import socket
import struct
import time
from typing import Dict, Optional, Tuple

import numpy as np

from .. import constants

# header: magic u32 | seqlock counter u64 | shard version u64 |
# payload nbytes u64 | dtype str (8 bytes, NUL-padded). Little-endian,
# fixed offsets; payload starts at _HDR_SIZE.
_MAGIC = 0x544D5053  # "TMPS"
_HDR = struct.Struct("<IQQQ8s")
_HDR_SIZE = 64  # padded: payload lands cache-line aligned


def segment_name(port: int, inst: int, rank: int) -> str:
    """The shm segment name for shard ``rank`` of instance ``inst``
    owned by the listener on ``port`` — derivable by any co-located
    client from the bootstrap address book."""
    return f"tmps{int(port)}i{int(inst)}r{int(rank)}"


def is_local_host(host: str) -> bool:
    """Whether ``host`` (an address-book entry) names THIS machine —
    the gate for attempting the shm lane at all."""
    return host in ("127.0.0.1", "localhost", "0.0.0.0",
                    socket.gethostname())


def _unregister_tracker(shm) -> None:
    # attach-side resource_tracker registration (fixed only in 3.12's
    # track=False): without this, a reader process exiting would unlink
    # segments the PUBLISHER still serves from
    try:
        from multiprocessing import resource_tracker

        resource_tracker.unregister(shm._name, "shared_memory")
    except Exception:  # noqa: BLE001 - best-effort, platform-dependent
        pass


class ShmPublisher:
    """Owner-side segment set for one instance's locally-owned shards.

    Created by whoever runs the instance (``ParameterServer`` when
    ``ps_shm_lane`` is on; benches/tests arm it directly) and handed to
    :meth:`_Instance.attach_shm`; the server thread calls
    :meth:`publish` after every apply. ``close`` unlinks everything."""

    def __init__(self, port: int, inst: int):
        self.port = int(port)
        self.inst = int(inst)
        self._segs: Dict[int, "object"] = {}  # rank -> SharedMemory
        self._counters: Dict[int, int] = {}

    def publish(self, rank: int, shard: np.ndarray, version: int) -> None:
        """Seqlock-write ``shard`` (+ its delta ``version``) into the
        rank's segment, creating it on first publish."""
        from multiprocessing import shared_memory

        arr = np.ascontiguousarray(shard)
        seg = self._segs.get(rank)
        if seg is None:
            name = segment_name(self.port, self.inst, rank)
            size = _HDR_SIZE + max(1, arr.nbytes)
            try:
                seg = shared_memory.SharedMemory(
                    name=name, create=True, size=size
                )
            except FileExistsError:
                # stale segment from a dead predecessor on this port:
                # take it over (same name => same (port, inst, rank))
                seg = shared_memory.SharedMemory(name=name)
                if seg.size < size:
                    seg.close()
                    shared_memory.SharedMemory(name=name).unlink()
                    seg = shared_memory.SharedMemory(
                        name=name, create=True, size=size
                    )
            self._segs[rank] = seg
            self._counters[rank] = 0
        c = self._counters[rank] + 1  # odd: write in progress
        buf = seg.buf
        _HDR.pack_into(
            buf, 0, _MAGIC, c, int(version), arr.nbytes,
            arr.dtype.str.encode()[:8],
        )
        buf[_HDR_SIZE:_HDR_SIZE + arr.nbytes] = arr.tobytes()
        c += 1  # even: payload + version consistent
        _HDR.pack_into(
            buf, 0, _MAGIC, c, int(version), arr.nbytes,
            arr.dtype.str.encode()[:8],
        )
        self._counters[rank] = c

    def close(self) -> None:
        """Unlink every segment (readers mid-read keep their mapping
        alive until they drop it; new attaches fail over to sockets)."""
        for seg in self._segs.values():
            try:
                # a same-process reader's tracker unregistration (see
                # _unregister_tracker) may have dropped OUR registration
                # too (one tracker per process); re-register so unlink's
                # own unregister finds it instead of spamming stderr
                from multiprocessing import resource_tracker

                resource_tracker.register(seg._name, "shared_memory")
            except Exception:  # noqa: BLE001 - best-effort
                pass
            try:
                seg.close()
                seg.unlink()
            except Exception:  # noqa: BLE001 - already unlinked / torn down
                pass
        self._segs.clear()
        self._counters.clear()

    def __del__(self):  # best-effort: never leak /dev/shm entries
        self.close()


class ShmReader:
    """Client-side seqlock reader for one (owner port, inst, rank)
    segment. ``read()`` returns ``(array copy, shard version)`` or
    ``None`` (unpublished / persistently torn — caller uses the socket
    path). Attach failures are retried at most once per
    ``_ATTACH_RETRY_S`` so an unarmed publisher costs one failed open
    per window, not per fetch."""

    _ATTACH_RETRY_S = 1.0

    def __init__(self, name: str):
        self.name = name
        self._shm = None
        self._next_attach = 0.0
        self.retries = 0  # torn-read retries observed (telemetry drain)

    def _attached(self):
        if self._shm is not None:
            return self._shm
        now = time.monotonic()
        if now < self._next_attach:
            return None
        from multiprocessing import shared_memory

        try:
            shm = shared_memory.SharedMemory(name=self.name)
        except (FileNotFoundError, OSError):
            self._next_attach = now + self._ATTACH_RETRY_S
            return None
        _unregister_tracker(shm)
        self._shm = shm
        return shm

    def read(self) -> Optional[Tuple[np.ndarray, int]]:
        shm = self._attached()
        if shm is None:
            return None
        buf = shm.buf
        spins = max(1, int(constants.get("ps_shm_spin_limit")))
        for _ in range(spins):
            try:
                magic, c1, version, nbytes, dt = _HDR.unpack_from(buf, 0)
            except struct.error:
                return None
            if magic != _MAGIC or c1 == 0:
                return None  # never published
            if c1 & 1:
                self.retries += 1
                continue  # publish in progress
            if _HDR_SIZE + nbytes > shm.size:
                return None  # header torn beyond plausibility
            payload = bytes(buf[_HDR_SIZE:_HDR_SIZE + nbytes])
            c2 = _HDR.unpack_from(buf, 0)[1]
            if c1 != c2:
                self.retries += 1
                continue  # raced a publish: torn payload, retry
            try:
                dtype = np.dtype(dt.rstrip(b"\0").decode())
            except (TypeError, ValueError):
                return None
            return np.frombuffer(payload, dtype).copy(), int(version)
        return None  # spin budget exhausted: socket fallback

    def close(self) -> None:
        if self._shm is not None:
            try:
                self._shm.close()
            except Exception:  # noqa: BLE001
                pass
            self._shm = None
