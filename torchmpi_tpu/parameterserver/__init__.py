"""Host-side sharded parameter server (reference N10 + L6/L7)."""

from .rules import UPDATE_RULES
from .server import ParameterServer, free_all
from .update import DownpourUpdate, EASGDUpdate, Update

__all__ = [
    "ParameterServer",
    "free_all",
    "UPDATE_RULES",
    "Update",
    "DownpourUpdate",
    "EASGDUpdate",
]
