"""Host-side sharded parameter server (reference N10 + L6/L7)."""

from .rules import UPDATE_RULES
from .server import ParameterServer, free_all, shard_range
from .tensors import PSGroup, synchronize_gradients_with_parameterserver
from .update import DownpourUpdate, EASGDUpdate, Update

__all__ = [
    "ParameterServer",
    "PSGroup",
    "free_all",
    "shard_range",
    "UPDATE_RULES",
    "Update",
    "DownpourUpdate",
    "EASGDUpdate",
    "synchronize_gradients_with_parameterserver",
]
