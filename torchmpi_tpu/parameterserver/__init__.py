"""Host-side sharded parameter server (reference N10 + L6/L7)."""

from __future__ import annotations


def free_all() -> None:
    """Free every live parameter server (called from stop())."""
    from . import server

    server.free_all()


from .server import ParameterServer, free_all  # noqa: E402,F811
from .rules import UPDATE_RULES  # noqa: E402
from .update import DownpourUpdate, EASGDUpdate, Update  # noqa: E402

__all__ = [
    "ParameterServer",
    "free_all",
    "UPDATE_RULES",
    "Update",
    "DownpourUpdate",
    "EASGDUpdate",
]
