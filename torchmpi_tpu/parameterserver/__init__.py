"""Host-side sharded parameter server (reference N10 + L6/L7).

The data path speaks the quantized + chunk-pipelined wire protocol of
:mod:`.wire` (``parameterserver_wire_dtype`` / ``ps_chunk_bytes``
constants), supports delta-encoded fetches
(``parameterserver_delta_encoding``) and client-side double-buffered
prefetch (:meth:`ParameterServer.prefetch`, ``ps_prefetch``)."""

from . import wire
from .rules import UPDATE_RULES
from .server import ParameterServer, free_all, shard_range
from .tensors import PSGroup, synchronize_gradients_with_parameterserver
from .update import DownpourUpdate, EASGDUpdate, Update

__all__ = [
    "ParameterServer",
    "PSGroup",
    "free_all",
    "shard_range",
    "UPDATE_RULES",
    "Update",
    "DownpourUpdate",
    "EASGDUpdate",
    "synchronize_gradients_with_parameterserver",
    "wire",
]
