"""Pytree-level parameter-server helpers with a global cache.

Analog of ``torchmpi/parameterserver/init.lua`` (L6): per-tensor PS
instances cached by identity (``cache.parameterServers``), list-wise
``initTensors`` / ``prefetchTensors`` / ``integrateTensors`` /
``sendTensors`` operations (``parameterserver/init.lua:128-219``), plus the
DSGD gradient synchronization pattern from
``examples/mnist/mnist_parameterserver_dsgd.lua:63-89``.

Pytree convention: parameters are **rank-stacked** ([p, ...] leaves, rank
r's replica at index r) — the single-controller representation of the
reference's per-process tensors. Every rank acts as a PS client: sends
contribute each rank's block, fetches return one (possibly different,
staleness included) center snapshot per rank.
"""

from __future__ import annotations

from typing import Callable, Dict, List, Optional, Sequence

import jax
import jax.numpy as jnp
import numpy as np
from jax import tree_util

from ..runtime.communicator import Communicator
from ..runtime.handles import SyncHandle
from .server import ParameterServer


def _comm(comm: Optional[Communicator]) -> Communicator:
    if comm is not None:
        return comm
    from .. import runtime_state

    return runtime_state.current_communicator()


class PSGroup:
    """One ParameterServer per pytree leaf (the ``cache.parameterServers``
    registry, ``torchmpi/cache.lua:19-35``), initialised from rank 0's
    replica (``initTensors`` default init, ``parameterserver/init.lua:
    128-151``)."""

    def __init__(self, params, comm: Optional[Communicator] = None):
        self.comm = _comm(comm)
        self.p = self.comm.size
        leaves, self.treedef = tree_util.tree_flatten(params)
        self.servers: List[ParameterServer] = []
        for leaf in leaves:
            if not getattr(leaf, "is_fully_addressable", True):
                # the PSGroup/Update convenience layer works on
                # process-local rank-stacked replicas (the reference's
                # per-rank Lua tables); a global array spanning
                # controllers cannot be host-fetched. Multi-controller PS
                # drives per-process clients against ParameterServer
                # directly — the tests/test_multiprocess.py pattern.
                raise ValueError(
                    "PSGroup leaves must be process-local (rank-stacked "
                    "host replicas); for multi-controller jobs use "
                    "ParameterServer with per-process clients instead"
                )
            arr = np.asarray(leaf)
            if arr.shape[0] != self.p:
                raise ValueError(
                    f"PSGroup expects rank-stacked leaves [p={self.p}, ...]; "
                    f"got {arr.shape}"
                )
            self.servers.append(ParameterServer(arr[0], comm=self.comm))
        self._prefetched: Optional[List[List[SyncHandle]]] = None

    # ------------------------------------------------------------------
    def send_tensors(
        self,
        values,
        rule: str = "add",
        local_update: Optional[Callable] = None,
        scale: Optional[float] = None,
        client_ranks: Optional[Sequence[int]] = None,
    ) -> List[SyncHandle]:
        """Every client rank sends its block of each leaf
        (``sendTensors``, ``parameterserver/init.lua:187-219``).
        ``local_update`` preprocesses each block before sending (Downpour's
        ``t:mul(-lr)``)."""
        leaves = tree_util.tree_leaves(values)
        ranks = list(range(self.p)) if client_ranks is None else list(client_ranks)
        handles = []
        batch_add = rule == "add" and len(ranks) > 1
        for srv, leaf in zip(self.servers, leaves):
            arr = np.asarray(leaf)
            if batch_add:
                # 'add' is linear and order-independent: pre-sum the client
                # blocks on the host and make ONE server trip per leaf
                # instead of one per rank — the vectorized analog of the
                # reference's chunked Isend fan-out amortization
                # (parameterserver.cpp:309-353). local_update keeps its
                # per-block contract (it may not be linear, e.g. clipping).
                if local_update is None:
                    total = arr[np.asarray(ranks)].sum(axis=0)
                else:
                    total = np.sum(
                        [np.asarray(local_update(arr[r])) for r in ranks],
                        axis=0,
                    )
                handles.append(
                    srv.send(total, rule="add", client=ranks[0], scale=scale)
                )
                continue
            for r in ranks:
                block = arr[r]
                if local_update is not None:
                    block = local_update(block)
                handles.append(srv.send(block, rule=rule, client=r, scale=scale))
        return handles

    def prefetch_tensors(
        self, client_ranks: Optional[Sequence[int]] = None
    ) -> List[SyncHandle]:
        """Issue async fetches of every leaf for every client rank
        (``prefetchTensors``, ``parameterserver/init.lua:159-170``)."""
        ranks = list(range(self.p)) if client_ranks is None else list(client_ranks)
        self._prefetch_ranks = ranks
        self._prefetched = [
            [srv.receive(client=r) for r in ranks] for srv in self.servers
        ]
        return [h for per_srv in self._prefetched for h in per_srv]

    def integrate_tensors_stacked(
        self, params, fold: Callable, client_ranks=None
    ):
        """Vectorized integration: ``fold(fetched, blocks)`` receives the
        WHOLE ``[k, *leaf_shape]`` stack of fetches and the matching
        client blocks per leaf and returns ``(new_blocks, extra)`` —
        ONE stacked numpy op per leaf instead of a per-rank python loop
        (the O(bytes) analog of the reference's chunked fan-out,
        ``parameterserver.cpp:309-353``). Returns
        ``(params, ranks, extras)`` with ``extras[i]`` = leaf i's fold
        extra (schedules use it for e.g. EASGD's elastic differences).
        Ranks that did not prefetch keep their block unchanged."""
        ranks, stacks = self.wait_prefetched_stacked(
            client_ranks=client_ranks
        )
        idx = np.asarray(ranks)
        leaves = list(tree_util.tree_leaves(params))
        extras = []
        for i, fetched in enumerate(stacks):
            arr = np.array(leaves[i])  # mutable host copy
            new_blocks, extra = fold(fetched, arr[idx])
            arr[idx] = new_blocks
            leaves[i] = jnp.asarray(arr)
            extras.append(extra)
        return (
            tree_util.tree_unflatten(self.treedef, leaves),
            ranks,
            extras,
        )

    def integrate_tensors(self, params, fn: Callable, client_ranks=None):
        """Per-block integration: ``new_block = fn(fetched, block)`` per
        (leaf, client rank) (``integrateTensors``,
        ``parameterserver/init.lua:173-184``) — the compat wrapper over
        :meth:`integrate_tensors_stacked` for folds that are not
        vectorizable.

        If no prefetch is outstanding (e.g. the first integration of a
        schedule whose first prefetch lands *after* it — the reference's
        counter arithmetic allows this and falls back to the init-time
        buffers), a synchronous fetch is issued now."""

        def fold(fetched, blocks):
            return (
                np.stack(
                    [
                        np.asarray(fn(fetched[j], blocks[j]))
                        for j in range(len(fetched))
                    ]
                ),
                None,
            )

        params, _, _ = self.integrate_tensors_stacked(
            params, fold, client_ranks=client_ranks
        )
        return params

    def wait_prefetched_stacked(self, client_ranks=None):
        """Wait the outstanding prefetches (issuing synchronous ones when
        none are pending, like :meth:`integrate_tensors`) and return
        ``(ranks, stacks)`` where ``stacks[i]`` is a ``[k, *leaf_shape]``
        numpy array of the k client fetches of leaf i. This is the
        vectorized integration primitive: schedules fold a whole leaf in
        ONE stacked numpy op instead of a per-rank python loop (O(bytes),
        not O(ranks x leaves) interpreter trips)."""
        if self._prefetched is None:
            self.prefetch_tensors(client_ranks=client_ranks)
        ranks = list(self._prefetch_ranks)
        stacks = [
            np.stack([np.asarray(h.wait()) for h in per_srv])
            for per_srv in self._prefetched
        ]
        self._prefetched = None
        return ranks, stacks

    def receive_full(self, client: int = 0, read_policy=None):
        """Synchronously fetch the full center value of every leaf —
        all fetches issued first, then waited, so the per-leaf round
        trips overlap on the pipelined transport instead of serializing
        (one leaf's wire time hides the next leaf's).

        The overlap only pays when the issues land on distinct
        endpoints: under ``ps_read_policy=replica`` (or an explicit
        ``read_policy``) each server's fan-out groups its fetch threads
        by the ROUTED chain member — per-leaf round-robin cursors
        stagger across leaves, so concurrent leaf fetches interleave
        over the whole chain instead of queueing owner-ordered at the
        heads."""
        handles = [
            srv.receive(client=client, read_policy=read_policy)
            for srv in self.servers
        ]
        leaves = [h.wait() for h in handles]
        return tree_util.tree_unflatten(self.treedef, leaves)

    def prefetch_full(self, client: int = 0,
                      read_policy=None) -> List[SyncHandle]:
        """Instance-level prefetch of every leaf (double-buffered per
        server, see :meth:`ParameterServer.prefetch`): the next
        :meth:`receive_full` consumes these in-flight fetches. Routing
        spreads across replica chains exactly as in
        :meth:`receive_full`."""
        return [
            srv.prefetch(client=client, read_policy=read_policy)
            for srv in self.servers
        ]

    def free(self) -> None:
        for srv in self.servers:
            srv.free()


def synchronize_gradients_with_parameterserver(
    grads,
    ps_group: Optional[PSGroup] = None,
    comm: Optional[Communicator] = None,
    average: bool = True,
):
    """Synchronous DSGD gradient exchange through the parameter server
    (``mnist_parameterserver_dsgd.lua:63-89``): rank 0 zeroes the center,
    every rank adds its gradients, every rank receives, divide by size.
    Returns ``(synced_grads, ps_group)`` — pass the group back in to reuse
    the cached servers."""
    comm = _comm(comm)
    p = comm.size
    if ps_group is None:
        ps_group = PSGroup(grads, comm=comm)

    # rank 0 zeroes; handle-wait + barrier gives everyone the happens-before
    for h in ps_group.send_tensors(grads, rule="zero", client_ranks=[0]):
        h.wait()
    # everyone accumulates
    for h in ps_group.send_tensors(grads, rule="add"):
        h.wait()
    # everyone receives the sum
    leaves = tree_util.tree_leaves(grads)
    out = []
    for srv, leaf in zip(ps_group.servers, leaves):
        center = srv.receive().wait()
        if average:
            center = center / p
        out.append(jnp.broadcast_to(jnp.asarray(center), np.asarray(leaf).shape))
    return tree_util.tree_unflatten(ps_group.treedef, out), ps_group
