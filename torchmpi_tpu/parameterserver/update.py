"""Parameter-server update schedules: Update base, Downpour, EASGD.

Analog of ``torchmpi/parameterserver/{update,downpourupdate,easgdupdate}.lua``
(L7). The base class owns the step-counted schedule:

- ``__shard`` at ``init_delay``: create the PS group on the *sharding*
  communicator level (``update.lua:49-55``).
- ``__fetch`` at ``init_delay + update_frequency + prefetch`` then every
  ``update_frequency``: issue async prefetches (``update.lua:58-65``;
  ``prefetch`` must be in [0, update_frequency], ``update.lua:29-30``).
- ``__integrate`` / ``__send``: subclass-defined.
- Mixed PS × data-parallel: when the sharding and dataparallel communicator
  levels differ, only each DP group's root integrates, and integrated
  parameters are broadcast within DP groups afterwards
  (``update.lua:82-113``).

State convention: ``update(step, params, grads) -> params`` on rank-stacked
pytrees; each rank's replica evolves independently between integrations —
exactly the per-process divergence the reference's async modes exhibit.
"""

from __future__ import annotations

from typing import Callable, List, Optional, Sequence

import jax
import jax.numpy as jnp
import numpy as np
from jax import tree_util

from ..runtime.communicator import Communicator
from ..runtime.handles import SyncHandle
from .tensors import PSGroup


def _wait_all(handles: List[SyncHandle]) -> List:
    return [h.wait() for h in handles]


class Update:
    def __init__(
        self,
        comm: Optional[Communicator] = None,
        sharding_level: Optional[int] = None,
        dataparallel_level: Optional[int] = None,
        update_frequency: int = 10,
        init_delay: int = 100,
        prefetch: int = 0,
    ):
        if not 0 <= prefetch <= update_frequency:
            raise ValueError(
                f"prefetch must be in [0, {update_frequency}]"
            )
        from .. import runtime_state

        self._state = runtime_state
        self.comm = comm
        self.sharding_level = sharding_level
        self.dataparallel_level = dataparallel_level
        self.update_frequency = update_frequency
        self.init_delay = init_delay
        self.prefetch = prefetch

        # schedule counters (update.lua:38-42)
        self.init_parameterserver = init_delay
        self.next_prefetch = init_delay + update_frequency + prefetch
        self.next_integration = init_delay + update_frequency

        self.ps: Optional[PSGroup] = None
        self.handles_send: List[SyncHandle] = []
        self.handles_prefetch: List[SyncHandle] = []

    # ------------------------------------------------------------------
    def _sharding_comm(self) -> Communicator:
        if self.sharding_level is not None:
            return self._state.stack().at(self.sharding_level)
        return self.comm or self._state.current_communicator()

    def _dataparallel_comm(self) -> Optional[Communicator]:
        if self.dataparallel_level is None:
            return None
        return self._state.stack().at(self.dataparallel_level)

    def _integrating_ranks(self) -> Optional[List[int]]:
        """Ranks that fetch/integrate: all, unless a distinct dataparallel
        communicator exists — then only each DP intra-group's root
        (update.lua:86-95)."""
        dp = self._dataparallel_comm()
        if dp is None:
            return None  # all ranks
        return [
            r for r in range(dp.size) if dp.member(r).intra_rank == 0
        ]

    # ------------------------------------------------------------------
    def _shard(self, step: int, params) -> None:
        if step == self.init_parameterserver:
            self.ps = PSGroup(params, comm=self._sharding_comm())

    def _fetch(self, step: int) -> None:
        if step == self.next_prefetch and self.ps is not None:
            _wait_all(self.handles_send)
            self.handles_send = []
            if not self.handles_prefetch:
                # nothing in flight; otherwise the eager post-integration
                # prefetch (ps_prefetch) already issued this fetch and
                # only the schedule counter advances
                self.handles_prefetch = self.ps.prefetch_tensors(
                    client_ranks=self._integrating_ranks()
                )
            self.next_prefetch += self.update_frequency

    def _integrate(self, step: int, params):
        raise NotImplementedError

    def _send(self, step: int, params, grads) -> None:
        raise NotImplementedError

    def update(self, step: int, params, grads):
        """One schedule tick (``Update.update``, update.lua:77-115). Runs
        shard -> fetch -> integrate -> send unconditionally like the
        reference (subclass accumulation happens even before sharding)."""
        self._shard(step, params)

        integrated = False
        self._fetch(step)
        params, integrated = self._integrate(step, params)
        if (
            integrated
            and self.prefetch == 0
            and self.ps is not None
            and not self.handles_prefetch
        ):
            from .. import constants

            if constants.get("ps_prefetch"):
                # eager client-side prefetch: with a zero prefetch
                # distance the scheduled fetch lands at the integration
                # step itself (no overlap at all) — issue the NEXT fetch
                # right now instead, so it rides the wire during the
                # coming update_frequency steps of compute and the next
                # integration consumes data already in flight. Cost: the
                # fetched center excludes sends after this tick (one
                # interval of extra staleness — the Downpour trade;
                # disable via constants ps_prefetch=False for exact
                # fetch-at-integration semantics).
                self.handles_prefetch = self.ps.prefetch_tensors(
                    client_ranks=self._integrating_ranks()
                )
        self._send(step, params, grads)

        # Mixed PS x DP: broadcast integrated params within DP groups
        # (update.lua:104-112).
        dp = self._dataparallel_comm()
        if dp is not None and integrated:
            from ..collectives import eager

            params = tree_util.tree_map(
                lambda w: eager.run_group_broadcast(w, dp, root=0), params
            )
        return params

    def free(self) -> None:
        if self.ps is not None:
            self.ps.free()
            self.ps = None


class DownpourUpdate(Update):
    """Downpour SGD (``downpourupdate.lua``): accumulate gradients locally,
    every ``send_frequency`` steps send the accumulated (locally scaled,
    e.g. multiplied by -lr) gradients with the ``add`` rule; integration
    copies the fetched center into the local replica."""

    def __init__(
        self,
        local_update: Callable = None,
        send_frequency: int = 1,
        **kw,
    ):
        super().__init__(**kw)
        self.send_frequency = send_frequency
        self.next_send = self.init_delay + send_frequency
        self.local_update = local_update or (lambda t: t)
        self._accum = None

    def _send(self, step: int, params, grads) -> None:
        # accumulate every step (downpourupdate.lua:47-52)
        if self._accum is None:
            self._accum = tree_util.tree_map(jnp.asarray, grads)
        else:
            self._accum = tree_util.tree_map(
                lambda a, g: a + g, self._accum, grads
            )
        if step == self.next_send and self.ps is not None:
            self.handles_send = self.ps.send_tensors(
                self._accum, rule="add", local_update=self.local_update
            )
            _wait_all(self.handles_send)
            self.handles_send = []
            self._accum = tree_util.tree_map(jnp.zeros_like, self._accum)
            self.next_send += self.send_frequency

    def _integrate(self, step: int, params):
        if step == self.next_integration and self.ps is not None:
            _wait_all(self.handles_prefetch)
            self.handles_prefetch = []
            # Downpour integration copies the fetched center over the
            # replica — one stacked scatter per leaf, no per-rank loop.
            params, _, _ = self.ps.integrate_tensors_stacked(
                params,
                lambda fetched, blocks: (fetched, None),
                client_ranks=self._integrating_ranks(),
            )
            self.next_integration += self.update_frequency
            return params, True
        return params, False


class EASGDUpdate(Update):
    """Elastic-averaging SGD (``easgdupdate.lua``): at each integration,
    with alpha = beta / size, the replica moves toward the fetched center
    (``x += alpha (center - x)``) and the elastic difference
    ``-alpha (center - x_old)`` is sent back with ``add`` at the next send
    step (the center moves toward the replica)."""

    def __init__(self, beta: float = 0.9, **kw):
        super().__init__(**kw)
        self.beta = beta
        self.next_send = self.next_integration
        self._elastic = None  # per-leaf rank-stacked elastic differences

    def _send(self, step: int, params, grads) -> None:
        if step == self.next_send and self.ps is not None and self._elastic is not None:
            self.handles_send = self.ps.send_tensors(self._elastic, rule="add")
            self.next_send += self.update_frequency

    def _integrate(self, step: int, params):
        if step == self.next_integration and self.ps is not None:
            _wait_all(self.handles_prefetch)
            self.handles_prefetch = []
            comm = self._sharding_comm()
            alpha = self.beta / comm.size

            # easgdupdate.lua:68-77 per client: old = fetched - x;
            # x += alpha*old; elastic sent later = -alpha*old — ONE
            # stacked numpy op per leaf across every integrating rank
            # (round-2 verdict weak #4: the old per-rank fold + python
            # re-stack was O(ranks x leaves) interpreter trips).
            def fold(fetched, blocks):
                old = fetched - blocks
                return blocks + alpha * old, -alpha * old

            params, ranks, olds = self.ps.integrate_tensors_stacked(
                params, fold, client_ranks=self._integrating_ranks()
            )
            idx = np.asarray(ranks)
            elastic = []
            for leaf, e in zip(tree_util.tree_leaves(params), olds):
                full = np.zeros(np.asarray(leaf).shape, np.asarray(leaf).dtype)
                full[idx] = e
                elastic.append(jnp.asarray(full))
            self._elastic = tree_util.tree_unflatten(
                self.ps.treedef, elastic
            )
            self.next_integration += self.update_frequency
            return params, True
        return params, False
