"""Update schedules (Downpour / EASGD) — land with the PS milestone."""

from __future__ import annotations


class Update:
    def __init__(self, *a, **k):
        raise NotImplementedError("lands with the parameter-server milestone")


class DownpourUpdate(Update):
    pass


class EASGDUpdate(Update):
    pass
