"""Sharded parameter server core (to be implemented; see SURVEY.md §7.5)."""

from __future__ import annotations


class ParameterServer:
    def __init__(self, *a, **k):
        raise NotImplementedError("parameter server lands in a later milestone")


def free_all() -> None:
    pass
